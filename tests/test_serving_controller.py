"""Traffic-driven control plane (mxnet_tpu/serving/controller.py +
Router fleet membership): dynamic ``add_replica``/``remove_replica``
with drain semantics, the ScalePolicy hysteresis decision function,
FleetController observe-decide-act ticks with contained failures,
rolling upgrades with breaker-gated automatic rollback, and the
control-plane fault sites / telemetry.

The drain invariant proved here is the fleet-change extension of the
router's zero-lost-future contract: a replica leaving the fleet —
drained clean, drain-deadline expired, or breaker-tripped — never
strands a submitted future; anything still in flight fails over to the
survivors. Bitwise comparisons follow the test_serving.py discipline
(matched batch buckets = the same compiled executable).
"""
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import fault, serving, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn
from mxnet_tpu.serving.controller import (
    FleetController, FleetSignals, ScalePolicy, UpgradeRolledBack,
    rolling_upgrade,
)
from mxnet_tpu.serving.health import CLOSED, OPEN
from mxnet_tpu.serving.router import Router

pytestmark = pytest.mark.serving


def make_net(seed=0, units=4):
    net = nn.Dense(units, in_units=8)
    net.initialize()
    rs = np.random.RandomState(seed)
    net.weight.set_data(mx.nd.array(
        rs.randn(units, 8).astype(np.float32)))
    net.bias.set_data(mx.nd.array(rs.randn(units).astype(np.float32)))
    net.hybridize()
    return net


def make_server(name, seed=0, slo_ms=60, **kw):
    return serving.Server(make_net(seed=seed), batch_buckets=(2, 4),
                          shape_buckets=[(8,)], slo_ms=slo_ms,
                          name=name, **kw)


def make_router(n=2, seed=0, slo_ms=60, **kw):
    return Router([make_server(f"rep{i}", seed=seed, slo_ms=slo_ms)
                   for i in range(n)], slo_ms=slo_ms, **kw)


def traffic(n=16):
    return [np.random.RandomState(300 + i).randn(8).astype(np.float32)
            for i in range(n)]


def oracle(xs, seed=0):
    """Single-replica reference over the same grid (matched buckets)."""
    srv = make_server("oracle", seed=seed).start()
    try:
        return [srv.submit(x).result(timeout=30) for x in xs]
    finally:
        srv.stop()


class _SlowBlock(mx.gluon.Block):
    """Eager block with a controlled dispatch latency — keeps requests
    IN FLIGHT long enough for drain tests to observe them."""

    def __init__(self, delay_s=0.15, **kw):
        super().__init__(**kw)
        self.delay_s = delay_s

    def forward(self, x):
        time.sleep(self.delay_s)
        return x * 2


def make_slow_server(name, delay_s=0.15, slo_ms=2000):
    return serving.Server(_SlowBlock(delay_s), batch_buckets=(2, 4),
                          shape_buckets=[(8,)], slo_ms=slo_ms,
                          name=name)


@pytest.fixture(autouse=True)
def _fast_retry(monkeypatch):
    monkeypatch.setenv("MXNET_COMM_RETRY_DELAY", "0.01")


# ---------------------------------------------------------------------------
# dynamic fleet membership: add_replica / remove_replica / drain
# ---------------------------------------------------------------------------

class TestFleetMembership:
    def test_add_replica_serves_bit_identical(self):
        xs = traffic(8)
        refs = oracle(xs)
        with make_router(2) as router:
            newcomer = make_server("rep2")
            router.add_replica(newcomer)
            assert router.fleet_size() == 3
            assert newcomer.is_running     # started + warmed at admission
            outs = [router.submit(x).result(timeout=30) for x in xs]
        assert all(np.array_equal(a, b) for a, b in zip(outs, refs))

    def test_add_replica_validates_grid_and_name(self):
        with make_router(2) as router:
            bad_grid = serving.Server(make_net(), batch_buckets=(2, 4, 8),
                                      shape_buckets=[(8,)], slo_ms=60,
                                      name="odd")
            with pytest.raises(MXNetError, match="different bucket grid"):
                router.add_replica(bad_grid)
            with pytest.raises(MXNetError, match="already in the fleet"):
                router.add_replica(make_server("rep0"))
            assert router.fleet_size() == 2

    def test_remove_unknown_and_last_replica_refused(self):
        with make_router(2) as router:
            with pytest.raises(MXNetError, match="no replica named"):
                router.remove_replica("ghost")
            router.remove_replica("rep0")
            with pytest.raises(MXNetError, match="last"):
                router.remove_replica("rep1")
            assert router.fleet_size() == 1

    def test_remove_with_drain_resolves_every_inflight_future(self):
        """The drain invariant: a replica leaving mid-traffic strands
        nothing — queued work finishes or fails over, every future
        resolves with a result."""
        reps = [make_slow_server(f"slow{i}") for i in range(2)]
        router = Router(reps, slo_ms=2000)
        router.start()
        try:
            xs = traffic(12)
            futs = [router.submit(x) for x in xs]
            time.sleep(0.05)           # some dispatches now in flight
            srv = router.remove_replica("slow0", drain=True, timeout=10)
            assert not srv.is_running
            outs = [f.result(timeout=30) for f in futs]
            assert all(np.array_equal(o, x * 2)
                       for o, x in zip(outs, xs))
            assert router.fleet_size() == 1
        finally:
            router.stop(drain=False, timeout=30)

    def test_drain_deadline_expiry_fails_over_not_hangs(self):
        """A replica wedged in dispatch cannot stall its own removal:
        the drain deadline expires, the stuck flight is evicted and
        retried on the survivors, and remove_replica returns."""
        reps = [make_slow_server("wedge", delay_s=1.2),
                make_slow_server("healthy", delay_s=0.01)]
        router = Router(reps, slo_ms=8000, dispatch_timeout_s=30)
        router.start()
        try:
            xs = traffic(4)
            futs = [router.submit(x) for x in xs]
            deadline = time.time() + 10
            while not any(r["name"] == "wedge" and r["inflight"] > 0
                          for r in router.stats()["replicas"]):
                assert time.time() < deadline, "nothing routed at wedge"
                time.sleep(0.01)
            t0 = time.monotonic()
            wedge = router.remove_replica("wedge", drain=True,
                                          timeout=0.2)
            assert time.monotonic() - t0 < 1.0     # bounded, not 1.2 s
            outs = [f.result(timeout=30) for f in futs]
            assert all(np.array_equal(o, x * 2)
                       for o, x in zip(outs, xs))
            # the wedged scheduler exits once its dispatch returns —
            # wait it out so the leak guard sees a clean house
            deadline = time.time() + 10
            while wedge.is_running and time.time() < deadline:
                time.sleep(0.05)
            assert not wedge.is_running
        finally:
            router.stop(drain=False, timeout=30)

    def test_draining_replica_takes_no_new_work(self):
        with make_router(2) as router:
            with router._cond:
                target = next(r for r in router._replicas
                              if r.server.name == "rep0")
                target.draining = True
            ok0 = next(r["ok"] for r in router.stats()["replicas"]
                       if r["name"] == "rep0")
            for x in traffic(8):
                router.submit(x).result(timeout=30)
            assert next(r["ok"] for r in router.stats()["replicas"]
                        if r["name"] == "rep0") == ok0
            assert router.fleet_size() == 1
            assert router.fleet_size(include_draining=True) == 2

    def test_drained_replica_breaker_state_discarded(self):
        """Re-admitting a previously-tripped replica starts a FRESH
        breaker (and a fresh stable index): the drain retired the old
        health record along with the membership."""
        with make_router(2) as router:
            rep0 = next(r for r in router.replicas()
                        if r["name"] == "rep0")
            rep0["breaker"].record_hang()          # hang trips OPEN
            assert rep0["breaker"].state == OPEN
            old_index = rep0["index"]
            srv = router.remove_replica("rep0", drain=True, timeout=5,
                                        stop_server=False)
            router.add_replica(srv)
            fresh = next(r for r in router.replicas()
                         if r["name"] == "rep0")
            assert fresh["state"] == CLOSED
            assert fresh["breaker"].n_trips == 0
            assert fresh["index"] > old_index      # ids never reused
            router.submit(traffic(1)[0]).result(timeout=30)

    def test_predicted_wait_zero_on_idle_fleet(self):
        """The autoscaler signal is ARMED like predicted-wait shedding:
        an idle fleet that just served a burst reports 0.0, not the
        raw two-fleet-batch estimate (which would scale up a fleet
        with nothing queued)."""
        with make_router(2) as router:
            for x in traffic(12):
                router.submit(x).result(timeout=30)
            assert router.predicted_wait() == 0.0

    def test_stats_expose_fleet_shape(self):
        with make_router(2) as router:
            st = router.stats()
            assert st["fleet_size"] == 2
            assert all(r["draining"] is False for r in st["replicas"])
            snap = router.replicas()
            assert [r["index"] for r in snap] == [0, 1]
            assert {r["name"] for r in snap} == {"rep0", "rep1"}


# ---------------------------------------------------------------------------
# ScalePolicy: the pure decision function, fake clock
# ---------------------------------------------------------------------------

def signals(n=2, queue=0, inflight=0, shed=0, wait=0.0, slo=0.1,
            max_batch=4):
    return FleetSignals(n_replicas=n, queue_depth=queue,
                        inflight=inflight, shed_delta=shed,
                        predicted_wait_s=wait, slo_s=slo,
                        max_batch=max_batch)


class TestScalePolicy:
    def _policy(self, **kw):
        self.now = [0.0]
        kw.setdefault("min_replicas", 1)
        kw.setdefault("max_replicas", 4)
        kw.setdefault("up_cooldown_s", 2.0)
        kw.setdefault("down_utilization", 0.25)
        kw.setdefault("down_hold_s", 10.0)
        kw.setdefault("down_cooldown_s", 5.0)
        return ScalePolicy(time_fn=lambda: self.now[0], **kw)

    def test_shed_scales_up(self):
        p = self._policy()
        assert p.desired(signals(n=2, shed=3)) == 3
        assert p.last_reason == "shed"

    def test_predicted_wait_scales_up(self):
        p = self._policy(up_wait_factor=0.5)
        assert p.desired(signals(n=2, wait=0.06, slo=0.1)) == 3
        assert p.last_reason == "predicted_wait"
        p2 = self._policy(up_wait_factor=0.5)
        assert p2.desired(signals(n=2, wait=0.04, slo=0.1)) == 2

    def test_up_cooldown_limits_one_step_per_window(self):
        p = self._policy(up_cooldown_s=2.0)
        assert p.desired(signals(n=2, shed=1)) == 3
        self.now[0] = 1.0
        assert p.desired(signals(n=3, shed=1)) == 3     # cooling down
        self.now[0] = 2.5
        assert p.desired(signals(n=3, shed=1)) == 4

    def test_bounds_always_win(self):
        p = self._policy(max_replicas=2)
        assert p.desired(signals(n=2, shed=5)) == 2     # at max
        p2 = self._policy(min_replicas=2, down_hold_s=0.0,
                          down_cooldown_s=0.0)
        assert p2.desired(signals(n=2)) == 2            # at min

    def test_scale_down_needs_sustained_quiet(self):
        p = self._policy(down_hold_s=10.0, down_cooldown_s=0.0)
        assert p.desired(signals(n=3)) == 3             # hold starts
        self.now[0] = 5.0
        assert p.desired(signals(n=3)) == 3             # still holding
        self.now[0] = 10.5
        assert p.desired(signals(n=3)) == 2
        assert p.last_reason == "idle"

    def test_pressure_resets_the_hold_clock(self):
        p = self._policy(down_hold_s=10.0, down_cooldown_s=0.0)
        p.desired(signals(n=3))                         # hold starts
        self.now[0] = 9.0
        p.desired(signals(n=3, shed=1))                 # pressure!
        self.now[0] = 12.0
        assert p.desired(signals(n=3)) == 3             # clock restarted
        self.now[0] = 22.5
        assert p.desired(signals(n=3)) == 2

    def test_busy_fleet_is_not_quiet(self):
        p = self._policy(down_hold_s=0.0, down_cooldown_s=0.0)
        # utilization 8/(3*4) = 0.67 >= 0.25: not quiet
        assert p.desired(signals(n=3, inflight=8)) == 3
        assert p.last_reason == "steady"

    def test_down_cooldown_one_step_per_window(self):
        p = self._policy(down_hold_s=0.0, down_cooldown_s=5.0)
        self.now[0] = 0.1
        assert p.desired(signals(n=4)) == 3
        self.now[0] = 2.0
        assert p.desired(signals(n=3)) == 3             # cooling down
        self.now[0] = 5.5
        assert p.desired(signals(n=3)) == 2

    def test_validation(self):
        with pytest.raises(MXNetError, match="min_replicas"):
            ScalePolicy(min_replicas=0)
        with pytest.raises(MXNetError, match="max_replicas"):
            ScalePolicy(min_replicas=3, max_replicas=2)
        with pytest.raises(MXNetError, match="up_wait_factor"):
            ScalePolicy(up_wait_factor=0.0)
        with pytest.raises(MXNetError, match="cooldowns"):
            ScalePolicy(up_cooldown_s=-1.0)

    def test_utilization_property(self):
        assert signals(n=2, inflight=8, max_batch=4).utilization == 1.0
        assert signals(n=0, inflight=8).utilization == 0.0


# ---------------------------------------------------------------------------
# FleetController: observe-decide-act, contained failures, fault site
# ---------------------------------------------------------------------------

class TestFleetController:
    def _controller(self, router, **kw):
        spawned = []

        def factory(i):
            srv = make_server(f"auto{i}")
            spawned.append(srv)
            return srv
        kw.setdefault("policy", ScalePolicy(1, 4, up_cooldown_s=0.0))
        ctl = FleetController(router, factory, interval_s=0.05, **kw)
        ctl._test_spawned = spawned
        return ctl

    def test_shed_pressure_scales_up(self):
        with make_router(2) as router:
            ctl = self._controller(router)
            assert ctl.tick() is None                  # steady
            router.n_shed += 1                         # a shed happened
            assert ctl.tick() == "up"
            assert router.fleet_size() == 3
            assert ctl.n_scale_up == 1
            assert ctl.scale_events[-1]["reason"] == "shed"
            # the spawned replica actually serves
            out = router.submit(traffic(1)[0]).result(timeout=30)
            assert out is not None

    def test_factory_failure_contained_and_retried(self):
        with make_router(2) as router:
            calls = [0]

            def flaky(i):
                calls[0] += 1
                if calls[0] == 1:
                    raise RuntimeError("spawn infra hiccup")
                return make_server(f"auto{i}")
            ctl = FleetController(
                router, flaky, interval_s=0.05,
                policy=ScalePolicy(1, 4, up_cooldown_s=0.0))
            router.n_shed += 1
            assert ctl.tick() is None                  # contained
            assert ctl.n_scale_failed == 1
            assert router.fleet_size() == 2
            router.n_shed += 1
            assert ctl.tick() == "up"                  # retried, won
            assert router.fleet_size() == 3 and calls[0] == 2

    def test_scale_down_drains_idlest_replica(self):
        clock = [0.0]
        with make_router(3) as router:
            ctl = self._controller(
                router, policy=ScalePolicy(
                    1, 4, down_hold_s=0.0, down_cooldown_s=0.0,
                    time_fn=lambda: clock[0]))
            clock[0] = 1.0
            assert ctl.tick() == "down"
            assert router.fleet_size() == 2
            assert ctl.n_scale_down == 1
            # ties on inflight=0 break to the NEWEST (highest index)
            assert {r["name"] for r in router.replicas()} \
                == {"rep0", "rep1"}

    def test_failed_scale_up_does_not_burn_the_cooldown(self):
        """The up-cooldown paces SUCCESSFUL additions: a failed spawn
        un-stamps it, so the very next tick retries instead of
        shedding through a whole cooldown window."""
        with make_router(2) as router:
            calls = [0]

            def flaky(i):
                calls[0] += 1
                if calls[0] == 1:
                    raise RuntimeError("spawn infra hiccup")
                return make_server(f"auto{i}")
            ctl = FleetController(
                router, flaky, interval_s=0.05,
                policy=ScalePolicy(1, 4, up_cooldown_s=3600.0))
            router.n_shed += 1
            assert ctl.tick() is None              # failed, contained
            router.n_shed += 1
            assert ctl.tick() == "up"              # no cooldown wait
            assert router.fleet_size() == 3

    def test_controller_scale_fault_site_contained(self):
        with make_router(2) as router:
            ctl = self._controller(router)
            router.n_shed += 1
            with fault.inject("controller.scale=once"):
                assert ctl.tick() is None
            assert ctl.n_scale_failed == 1
            assert router.fleet_size() == 2            # fleet untouched
            router.n_shed += 1
            assert ctl.tick() == "up"                  # next tick wins

    def test_thread_lifecycle_and_leak_registry(self):
        from mxnet_tpu.serving.controller import live_controllers
        with make_router(2) as router:
            ctl = self._controller(router)
            with ctl:
                assert ctl.is_running
                assert ctl in live_controllers()
                time.sleep(0.15)                       # a few ticks
            assert not ctl.is_running
            assert ctl not in live_controllers()
            assert ctl.n_ticks >= 1
            st = ctl.stats()
            assert st["fleet_size"] == 2 and not st["running"]

    def test_validation(self):
        with make_router(2) as router:
            with pytest.raises(MXNetError, match="interval"):
                FleetController(router, make_server, interval_s=0.0)

    def test_controller_telemetry_exported(self):
        was = telemetry.enabled()
        telemetry.reset()
        telemetry.enable()
        try:
            with make_router(2) as router:
                ctl = self._controller(router)
                router.n_shed += 1
                ctl.tick()
                with fault.inject("controller.scale=once"):
                    router.n_shed += 1
                    ctl.tick()
            text = telemetry.prom_text()
            # labeled per router: a multi-router process must not
            # overwrite one shared series (the scrape-fed controller
            # filters by this label)
            assert 'mxnet_controller_fleet_size{router="' in text
            assert '"} 3' in text
            assert 'mxnet_controller_scale_total{direction="up",' \
                'outcome="ok"} 1' in text
            assert 'mxnet_controller_scale_total{direction="up",' \
                'outcome="failed"} 1' in text
            assert "mxnet_controller_scale_seconds" in text
        finally:
            telemetry.reset()
            if not was:
                telemetry.disable()


# ---------------------------------------------------------------------------
# rolling upgrades: one-at-a-time swap, bake, automatic rollback
# ---------------------------------------------------------------------------

class TestRollingUpgrade:
    def test_upgrade_flips_fleet_to_new_model(self):
        xs = traffic(8)
        refs_v2 = oracle(xs, seed=1)
        with make_router(2) as router:
            out = rolling_upgrade(router, lambda s: make_net(seed=1),
                                  bake_s=0.05)
            assert out["version"] == 1
            assert sorted(out["upgraded"]) == ["rep0", "rep1"]
            assert [r["server"].model_version
                    for r in router.replicas()] == [1, 1]
            got = [router.submit(x).result(timeout=30) for x in xs]
        assert all(np.array_equal(a, b) for a, b in zip(got, refs_v2))

    def test_upgrade_under_traffic_loses_nothing(self):
        xs = traffic(8)
        refs = {1: oracle(xs, seed=0), 2: oracle(xs, seed=1)}
        with make_router(2) as router:
            stop = threading.Event()
            futs = []

            def feed():
                i = 0
                while not stop.is_set():
                    futs.append((i % len(xs),
                                 router.submit(xs[i % len(xs)])))
                    i += 1
                    time.sleep(0.004)
            t = threading.Thread(target=feed)
            t.start()
            try:
                time.sleep(0.1)
                rolling_upgrade(router, lambda s: make_net(seed=1),
                                bake_s=0.1)
                time.sleep(0.1)
            finally:
                stop.set()
                t.join()
            for idx, f in futs:
                got = f.result(timeout=30)     # zero lost futures
                assert any(np.array_equal(got, refs[v][idx])
                           for v in (1, 2))

    def test_broken_build_rolls_back_swapped_replicas(self):
        xs = traffic(6)
        refs_v1 = oracle(xs, seed=0)
        with make_router(2) as router:
            calls = [0]

            def poisoned(server):
                calls[0] += 1
                if calls[0] == 2:              # AFTER rep0 swapped
                    raise RuntimeError("bad weights blob")
                return make_net(seed=1)
            with pytest.raises(UpgradeRolledBack, match="rolled"):
                rolling_upgrade(router, poisoned, bake_s=0.05)
            # every replica back on the OLD model and version
            assert [r["server"].model_version
                    for r in router.replicas()] == [0, 0]
            got = [router.submit(x).result(timeout=30) for x in xs]
        assert all(np.array_equal(a, b) for a, b in zip(got, refs_v1))

    def test_upgrade_fault_site_aborts_rollout(self):
        with make_router(2) as router:
            with fault.inject("serving.upgrade=once"):
                with pytest.raises(UpgradeRolledBack):
                    rolling_upgrade(router,
                                    lambda s: make_net(seed=1),
                                    bake_s=0.05)
            assert [r["server"].model_version
                    for r in router.replicas()] == [0, 0]
            router.submit(traffic(1)[0]).result(timeout=30)

    def test_breaker_trip_during_bake_rolls_back(self):
        """The bake watches the router's own health evidence: tripping
        the freshly-upgraded replica's breaker mid-bake rolls the whole
        rollout back."""
        with make_router(2) as router:
            errs = []

            def run():
                try:
                    rolling_upgrade(router, lambda s: make_net(seed=1),
                                    bake_s=5.0)
                except BaseException as e:   # noqa: BLE001
                    errs.append(e)
            t = threading.Thread(target=run)
            t.start()
            try:
                first = router.replicas()[0]
                deadline = time.time() + 10
                while first["server"].model_version == 0:
                    assert time.time() < deadline, "swap never happened"
                    time.sleep(0.01)
                first["breaker"].record_hang()         # trips OPEN
            finally:
                t.join(timeout=30)
            assert len(errs) == 1
            assert isinstance(errs[0], UpgradeRolledBack)
            assert "breaker" in str(errs[0].__cause__)
            assert [r["server"].model_version
                    for r in router.replicas()] == [0, 0]

    def test_upgrade_telemetry_outcomes(self):
        was = telemetry.enabled()
        telemetry.reset()
        telemetry.enable()
        try:
            with make_router(2) as router:
                rolling_upgrade(router, lambda s: make_net(seed=1),
                                bake_s=0.02)
                calls = [0]

                def poisoned(server):
                    calls[0] += 1
                    if calls[0] == 2:
                        raise RuntimeError("boom")
                    return make_net(seed=2)
                with pytest.raises(UpgradeRolledBack):
                    rolling_upgrade(router, poisoned, bake_s=0.02)
            text = telemetry.prom_text()
            assert 'mxnet_serving_upgrade_total{outcome="ok"} 3' in text
            assert 'mxnet_serving_upgrade_total{' \
                'outcome="rolled_back"} 1' in text
            assert 'mxnet_serving_upgrade_total{' \
                'outcome="aborted"} 1' in text
        finally:
            telemetry.reset()
            if not was:
                telemetry.disable()

    def test_degraded_fleet_refuses_upgrade_before_swapping(self):
        """A breaker already non-CLOSED would fail its bake instantly
        and blame pre-existing unhealth on the new build — the rollout
        is refused up front, typed, with nothing swapped."""
        with make_router(2) as router:
            rep0 = next(r for r in router.replicas()
                        if r["name"] == "rep0")
            rep0["breaker"].record_hang()
            calls = [0]

            def factory(server):
                calls[0] += 1
                return make_net(seed=1)
            with pytest.raises(MXNetError, match="fleet not healthy"):
                rolling_upgrade(router, factory, bake_s=0.02)
            assert calls[0] == 0                   # nothing built
            assert [r["server"].model_version
                    for r in router.replicas()] == [0, 0]

    def test_no_upgradable_replicas_raises(self):
        with make_router(2) as router:
            with router._cond:
                for r in router._replicas:
                    r.draining = True
            with pytest.raises(MXNetError, match="no replicas"):
                rolling_upgrade(router, lambda s: make_net(seed=1))


# ---------------------------------------------------------------------------
# fault-site registry
# ---------------------------------------------------------------------------

def test_control_plane_fault_sites_registered():
    assert "controller.scale" in fault.SITES
    assert "serving.upgrade" in fault.SITES
    # parse accepts them (the chaos harness depends on it)
    fault.parse_spec("controller.scale=once;serving.upgrade=nth:2")
