"""MoE / expert-parallelism tests (GShard construction; no upstream-MXNet
counterpart — capability addition, SURVEY §2.4 parallelism zoo).

Oracle: a per-token python loop applying the same top-k routing and
per-expert SwiGLU with unlimited capacity.
"""
import numpy as onp
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import autograd, parallel as par
from mxnet_tpu.gluon.model_zoo.nlp import MoEMLP, moe_sharding_rules


def _oracle(tokens, router_w, gu_w, down_w, top_k):
    """Unlimited-capacity reference: loop tokens, apply top-k experts."""
    n, u = tokens.shape
    logits = tokens @ router_w.T
    probs = onp.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    out = onp.zeros_like(tokens)
    for i in range(n):
        top = onp.argsort(-probs[i])[:top_k]
        gates = probs[i][top]
        gates = gates / gates.sum()
        for g, e in zip(gates, top):
            gu = tokens[i] @ gu_w[e]
            h = gu.shape[-1] // 2
            silu = gu[:h] / (1.0 + onp.exp(-gu[:h]))
            act = silu * gu[h:]
            out[i] += g * (act @ down_w[e])
    return out


class TestMoECorrectness:
    @pytest.mark.parametrize("top_k", [1, 2])
    def test_matches_per_token_oracle(self, top_k):
        rs = onp.random.RandomState(0)
        B, L, U, H, E = 2, 6, 8, 16, 4
        layer = MoEMLP(U, H, num_experts=E, top_k=top_k,
                       capacity_factor=8.0)  # ample capacity: no drops
        layer.initialize()
        x = mx.nd.array(rs.randn(B, L, U).astype("float32"))
        out = layer(x).asnumpy()
        params = {p.name: p.data().asnumpy()
                  for p in layer.collect_params().values()}
        router_w = params[layer.router.weight.name]
        gu_w = params[layer.gate_up_weight.name]
        down_w = params[layer.down_weight.name]
        want = _oracle(x.asnumpy().reshape(-1, U), router_w, gu_w, down_w,
                       top_k).reshape(B, L, U)
        onp.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)

    def test_capacity_drops_tokens(self):
        rs = onp.random.RandomState(1)
        layer = MoEMLP(8, 16, num_experts=2, top_k=1, capacity_factor=0.25)
        layer.initialize()
        x = mx.nd.array(rs.randn(2, 8, 8).astype("float32"))
        out = layer(x).asnumpy()
        assert onp.isfinite(out).all()
        # with capacity 2 per expert over 16 tokens, most rows are dropped
        assert (onp.abs(out).sum(axis=-1) == 0).sum() >= 8

    def test_gradients_flow(self):
        rs = onp.random.RandomState(2)
        layer = MoEMLP(8, 16, num_experts=4, top_k=2)
        layer.initialize()
        x = mx.nd.array(rs.randn(2, 4, 8).astype("float32"))
        with autograd.record():
            loss = (layer(x) ** 2).sum()
        loss.backward()
        for p in layer.collect_params().values():
            g = p.grad()
            assert onp.isfinite(g.asnumpy()).all(), p.name
        assert onp.abs(layer.gate_up_weight.grad().asnumpy()).max() > 0


class TestExpertParallel:
    def test_trainstep_ep_sharding(self):
        """dp x ep mesh: expert weights shard over ep, training works, and
        the loss matches the same model trained on a single device."""
        from mxnet_tpu import gluon
        from mxnet_tpu.gluon import loss as gloss
        from jax.sharding import PartitionSpec as P

        rs = onp.random.RandomState(3)
        x = mx.nd.array(rs.randn(4, 4, 8).astype("float32"))
        y = mx.nd.array(rs.randn(4, 4, 8).astype("float32"))

        def run(n_dev, axes, rules):
            onp.random.seed(0)
            mx.random.seed(0)
            layer = MoEMLP(8, 16, num_experts=4, top_k=2,
                           capacity_factor=8.0)
            layer.initialize()
            mesh = par.make_mesh(axes, devices=jax.devices()[:n_dev])
            step = par.TrainStep(layer, gloss.L2Loss(), "sgd", mesh=mesh,
                                 rules=rules,
                                 optimizer_params={"learning_rate": 0.1})
            losses = [float(step(x, y)[0].asnumpy()) for _ in range(3)]
            return losses, step, layer

        l1, _, _ = run(1, {"dp": 1}, None)
        l8, step8, layer8 = run(8, {"dp": 2, "ep": 4},
                                moe_sharding_rules())
        onp.testing.assert_allclose(l8, l1, rtol=1e-4)
        spec = layer8.gate_up_weight.data().data.sharding.spec
        assert spec == P("ep", None, None), spec
