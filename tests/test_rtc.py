"""mx.rtc user-kernel tests (reference: tests/python/gpu/test_rtc.py —
CudaModule compile/launch round trip, here over Pallas interpret mode)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import rtc
from mxnet_tpu.base import MXNetError


def _axpy(x_ref, y_ref, o_ref, *, alpha):
    o_ref[...] = alpha * x_ref[...] + y_ref[...]


def _scale_block(x_ref, o_ref):
    import jax.experimental.pallas as pl

    i = pl.program_id(0)
    o_ref[...] = x_ref[...] * (i + 1)


class TestPallasModule:
    def test_axpy_launch(self):
        mod = rtc.PallasModule({"axpy": _axpy})
        rs = onp.random.RandomState(0)
        x = mx.nd.array(rs.randn(16, 128).astype("float32"))
        y = mx.nd.array(rs.randn(16, 128).astype("float32"))
        k = mod.get_kernel("axpy",
                           out_shapes=[("o", "float32", (16, 128))],
                           alpha=2.5)
        out, = k.launch([x, y])
        onp.testing.assert_allclose(out.asnumpy(),
                                    2.5 * x.asnumpy() + y.asnumpy(),
                                    rtol=1e-5, atol=1e-6)
        # second launch reuses the compiled executable
        out2, = k([x, y])
        assert len(k._cache) == 1
        onp.testing.assert_allclose(out2.asnumpy(), out.asnumpy())

    def test_grid_kernel(self):
        from jax.experimental import pallas as pl

        def blocky(x_ref, o_ref):
            # each program scales its own 2-row band by its program id
            i = pl.program_id(0)
            band = pl.ds(2 * i, 2)
            o_ref[band, :] = x_ref[band, :] * (i + 1).astype("float32")

        mod = rtc.PallasModule({"blocky": blocky})
        k = mod.get_kernel("blocky", grid=(4,),
                           out_shapes=[("o", "float32", (8, 128))])
        x = mx.nd.ones((8, 128))
        out, = k.launch([x])
        want = onp.repeat(onp.arange(1.0, 5.0), 2)[:, None] * \
            onp.ones((8, 128))
        onp.testing.assert_allclose(out.asnumpy(), want)

    def test_unknown_kernel_and_missing_outs(self):
        mod = rtc.PallasModule({"axpy": _axpy})
        with pytest.raises(MXNetError, match="not in module"):
            mod.get_kernel("nope", out_shapes=[("o", "float32", (4,))])
        with pytest.raises(MXNetError, match="out_shapes"):
            mod.get_kernel("axpy", out_shapes=[])

    def test_cuda_module_guidance(self):
        with pytest.raises(MXNetError, match="PallasModule"):
            rtc.CudaModule("extern C __global__ void k() {}")

    def test_single_function_module(self):
        mod = rtc.PallasModule(_axpy)
        assert mod.exports == ["_axpy"]
