"""ONNX export/import tests (reference: tests/python-pytest/onnx/
test_onnxruntime*, mx2onnx/onnx2mx converter suites).

Oracle = numerical round-trip: a gluon net exported to ONNX and imported
back must produce the same outputs; the wire codec must survive an
encode→decode cycle field-for-field.
"""
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.contrib import onnx as onnx_mxnet
from mxnet_tpu.contrib.onnx import onnx_pb as pb
from mxnet_tpu.gluon import nn


def _export_block(net, x, tmp_path, name):
    net.hybridize()
    net(x)
    prefix = str(tmp_path / name)
    net.export(prefix)
    onnx_file = prefix + ".onnx"
    onnx_mxnet.export_model(prefix + "-symbol.json",
                            prefix + "-0000.params",
                            input_shapes=[tuple(x.shape)],
                            onnx_file_path=onnx_file)
    return onnx_file


class TestCodec:
    def test_tensor_roundtrip(self):
        for dtype in ("float32", "int64", "int32", "float16", "bool"):
            a = (onp.random.RandomState(0).randn(3, 4) * 5).astype(dtype)
            t = pb.TensorProto.from_array(a, name="w")
            back = pb.dec_tensor(t.encode())
            assert back.name == "w" and list(back.dims) == [3, 4]
            onp.testing.assert_array_equal(back.to_array(), a)

    def test_typed_data_fallback(self):
        # writers that use float_data/int64_data instead of raw_data
        t = pb.TensorProto(name="f", dims=(2, 2), data_type=pb.FLOAT)
        enc = (pb._f_varint(1, 2) + pb._f_varint(1, 2)
               + pb._f_varint(2, pb.FLOAT) + pb._f_str(8, "f")
               + b"".join(pb._tag(4, 5) + __import__("struct").pack("<f", v)
                          for v in (1.0, 2.0, 3.0, 4.0)))
        back = pb.dec_tensor(enc)
        onp.testing.assert_allclose(back.to_array(),
                                    [[1.0, 2.0], [3.0, 4.0]])

    def test_model_roundtrip_fields(self):
        node = pb.NodeProto("Relu", ["x"], ["y"], name="r",
                            attrs={"axis": 1, "alpha": 0.5, "mode": "nn",
                                   "axes": [1, 2], "scales": [1.0, 2.0]})
        g = pb.GraphProto(
            nodes=[node],
            inputs=[pb.ValueInfoProto("x", pb.FLOAT, (1, "N", 3))],
            outputs=[pb.ValueInfoProto("y", pb.FLOAT, (1, 3))],
            initializers=[pb.TensorProto.from_array(
                onp.ones((2,), onp.float32), name="w")])
        m = pb.ModelProto(g, opset=13)
        back = pb.dec_model(m.encode())
        assert back.producer_name == "mxnet_tpu" and back.opset == 13
        bg = back.graph
        assert bg.input[0].shape == [1, "N", 3]
        assert bg.node[0].op_type == "Relu"
        assert bg.node[0].attribute["axis"] == 1
        assert bg.node[0].attribute["alpha"] == pytest.approx(0.5)
        assert bg.node[0].attribute["mode"] == "nn"
        assert bg.node[0].attribute["axes"] == [1, 2]
        assert bg.node[0].attribute["scales"] == [1.0, 2.0]
        assert bg.initializer[0].name == "w"


class TestRoundTrip:
    def test_mlp(self, tmp_path):
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu"), nn.Dropout(0.5),
                nn.Dense(4))
        net.initialize()
        x = mx.nd.array(onp.random.RandomState(0).randn(2, 8)
                        .astype("float32"))
        want = net(x).asnumpy()
        f = _export_block(net, x, tmp_path, "mlp")

        meta = onnx_mxnet.get_model_metadata(f)
        assert meta["input_tensor_data"][0][1] == (2, 8)

        sym, arg, aux = onnx_mxnet.import_model(f)
        assert not aux
        from mxnet_tpu.gluon import SymbolBlock  # noqa: F401  (API parity)
        net2 = onnx_mxnet.import_to_gluon(f)
        got = net2(x).asnumpy()
        onp.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_convnet_with_bn(self, tmp_path):
        net = nn.HybridSequential()
        net.add(nn.Conv2D(8, kernel_size=3, padding=1, strides=2),
                nn.BatchNorm(), nn.Activation("relu"),
                nn.MaxPool2D(pool_size=2),
                nn.GlobalAvgPool2D(), nn.Flatten(), nn.Dense(5))
        net.initialize()
        x = mx.nd.array(onp.random.RandomState(1).randn(2, 3, 16, 16)
                        .astype("float32"))
        net(x)  # settle + give BN stats a step
        want = net(x).asnumpy()
        f = _export_block(net, x, tmp_path, "conv")

        sym, arg, aux = onnx_mxnet.import_model(f)
        # BN running stats come back as AUX params, like upstream
        assert any("running" in k or "moving" in k for k in aux), aux
        net2 = onnx_mxnet.import_to_gluon(f)
        got = net2(x).asnumpy()
        onp.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_embedding_transformerish(self, tmp_path):
        class Tiny(nn.HybridSequential):
            pass

        net = nn.HybridSequential()
        net.add(nn.Embedding(32, 12), nn.LayerNorm(),
                nn.Dense(6, flatten=False))
        net.initialize()
        x = mx.nd.array(onp.random.RandomState(2).randint(0, 32, (2, 5)),
                        dtype="float32")
        want = net(x).asnumpy()
        f = _export_block(net, x, tmp_path, "emb")
        net2 = onnx_mxnet.import_to_gluon(f)
        got = net2(x).asnumpy()
        onp.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_unsupported_op_raises(self, tmp_path):
        s = mx.sym.var("data")
        y = mx.sym.gamma(s) if hasattr(mx.sym, "gamma") else None
        if y is None:
            pytest.skip("no un-mapped op available")
        with pytest.raises(mx.base.MXNetError, match="no converter"):
            onnx_mxnet.export_model(y, {}, [(2, 2)],
                                    onnx_file_path=str(tmp_path / "x.onnx"))


def test_gemm_transb0_import(tmp_path):
    """Regression: Gemm(transB=0) — the layout non-MXNet exporters emit —
    must import (weight gets pre-transposed into FC layout)."""
    w = onp.random.RandomState(0).randn(8, 4).astype("float32")
    b = onp.random.RandomState(1).randn(4).astype("float32")
    g = pb.GraphProto(
        nodes=[pb.NodeProto("Gemm", ["x", "w", "b"], ["y"], name="g",
                            attrs={"transB": 0})],
        inputs=[pb.ValueInfoProto("x", pb.FLOAT, (2, 8))],
        outputs=[pb.ValueInfoProto("y", pb.FLOAT, (2, 4))],
        initializers=[pb.TensorProto.from_array(w, "w"),
                      pb.TensorProto.from_array(b, "b")])
    f = str(tmp_path / "gemm.onnx")
    with open(f, "wb") as fh:
        fh.write(pb.ModelProto(g).encode())
    net = onnx_mxnet.import_to_gluon(f)
    x = onp.random.RandomState(2).randn(2, 8).astype("float32")
    got = net(mx.nd.array(x)).asnumpy()
    onp.testing.assert_allclose(got, x @ w + b, rtol=1e-5, atol=1e-5)
