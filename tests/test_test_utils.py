"""mx.test_utils oracle-surface tests (reference: the module is itself the
test infrastructure — these verify the oracles catch what they must)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import test_utils as tu


class TestAssertAlmostEqual:
    def test_pass_and_locate_failure(self):
        a = onp.zeros((3, 4), "float32")
        b = a.copy()
        tu.assert_almost_equal(a, b)
        b[1, 2] = 1.0
        with pytest.raises(AssertionError, match=r"\(1, 2\)"):
            tu.assert_almost_equal(a, b)

    def test_dtype_scaled_tolerance(self):
        a = mx.nd.ones((4,)).astype("bfloat16")
        b = mx.nd.array([1.004, 1.0, 1.0, 1.0]).astype("bfloat16")
        tu.assert_almost_equal(a, b)  # within bf16-class tolerance
        with pytest.raises(AssertionError):
            tu.assert_almost_equal(onp.ones(4, "float64"),
                                   onp.ones(4, "float64") + 1e-4)


class TestNumericGradient:
    def test_composite_function(self):
        tu.check_numeric_gradient(
            lambda x, y: (x * y + (x ** 2)).sum(),
            [onp.random.RandomState(0).randn(3, 2),
             onp.random.RandomState(1).randn(3, 2)])

    def test_catches_wrong_gradient(self):
        import mxnet_tpu.autograd as ag

        class Bad(ag.Function):
            def forward(self, x):
                return x * x

            def backward(self, dy):
                return dy  # wrong: should be 2x*dy

        def f(x):
            return Bad()(x).sum()

        with pytest.raises(AssertionError):
            tu.check_numeric_gradient(f, [onp.array([1.0, 2.0])])


class TestConsistency:
    def test_op_across_contexts(self):
        res = tu.check_consistency(
            lambda x: mx.nd.softmax(x),
            [onp.random.RandomState(2).randn(4, 5).astype("float32")])
        assert len(res) == 2

    def test_rand_helpers(self):
        onp.random.seed(0)
        assert len(tu.rand_shape_nd(4, 6)) == 4
        arr = tu.rand_ndarray((2, 3))
        assert arr.shape == (2, 3)
