"""mx.library native custom-op tests (reference:
tests/python/unittest/test_extensions.py — MXLoadLib + lib_api.h custom
ops, built from example/extensions/lib_custom_op).

A real C library is compiled at test time (g++ is part of the toolchain)
and its ops must work through mx.nd, inside hybridized blocks, and under
jit via pure_callback.
"""
import os
import subprocess
import shutil

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError

_C_SRC = r"""
#include <cstdint>
#include <cstring>

extern "C" {

int mxlib_num_ops(void) { return 2; }

const char* mxlib_op_name(int op) {
    return op == 0 ? "my_gemm_relu" : "my_l2norm";
}

int mxlib_op_num_inputs(int op) { return op == 0 ? 2 : 1; }

int mxlib_op_infer_shape(int op, int nin, const int64_t** in_shapes,
                         const int* in_ndims, int64_t* out_shape,
                         int* out_ndim) {
    if (op == 0) {                       // (M,K) x (K,N) -> (M,N)
        if (nin != 2 || in_ndims[0] != 2 || in_ndims[1] != 2) return 1;
        if (in_shapes[0][1] != in_shapes[1][0]) return 2;
        out_shape[0] = in_shapes[0][0];
        out_shape[1] = in_shapes[1][1];
        *out_ndim = 2;
        return 0;
    }
    out_shape[0] = 1;                    // scalar-ish (1,)
    *out_ndim = 1;
    return 0;
}

int mxlib_op_compute(int op, int nin, const float** in,
                     const int64_t** in_shapes, const int* in_ndims,
                     float* out) {
    if (op == 0) {
        int64_t m = in_shapes[0][0], k = in_shapes[0][1],
                n = in_shapes[1][1];
        for (int64_t i = 0; i < m; ++i)
            for (int64_t j = 0; j < n; ++j) {
                float acc = 0.f;
                for (int64_t kk = 0; kk < k; ++kk)
                    acc += in[0][i * k + kk] * in[1][kk * n + j];
                out[i * n + j] = acc > 0.f ? acc : 0.f;   // fused relu
            }
        return 0;
    }
    int64_t total = 1;
    for (int d = 0; d < in_ndims[0]; ++d) total *= in_shapes[0][d];
    float acc = 0.f;
    for (int64_t i = 0; i < total; ++i) acc += in[0][i] * in[0][i];
    out[0] = acc;
    return 0;
}

}  // extern "C"
"""


@pytest.fixture(scope="module")
def libpath(tmp_path_factory):
    if shutil.which("g++") is None:
        pytest.skip("no g++ in environment")
    d = tmp_path_factory.mktemp("libcustom")
    src = d / "ops.cc"
    src.write_text(_C_SRC)
    so = d / "libcustom.so"
    subprocess.run(["g++", "-O2", "-shared", "-fPIC", str(src),
                    "-o", str(so)], check=True)
    return str(so)


class TestLibrary:
    def test_load_and_compute(self, libpath):
        names = mx.library.load(libpath)
        assert names == ["my_gemm_relu", "my_l2norm"]
        assert libpath in mx.library.loaded_libs()
        rs = onp.random.RandomState(0)
        a = mx.nd.array(rs.randn(3, 4).astype("float32"))
        b = mx.nd.array(rs.randn(4, 5).astype("float32"))
        got = mx.nd.my_gemm_relu(a, b).asnumpy()
        want = onp.maximum(a.asnumpy() @ b.asnumpy(), 0.0)
        onp.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
        nrm = mx.nd.my_l2norm(a).asnumpy()
        onp.testing.assert_allclose(
            nrm, [(a.asnumpy() ** 2).sum()], rtol=1e-5)

    def test_under_jit_and_hybridize(self, libpath):
        mx.library.load(libpath, verbose=False)
        from mxnet_tpu.gluon import nn

        class Net(nn.HybridSequential):
            def hybrid_forward(self, F, x):
                return F.my_l2norm(F.relu(x))

        net = Net()
        x = mx.nd.array(onp.array([[-1.0, 2.0], [3.0, -4.0]], "float32"))
        want = net(x).asnumpy()
        net.hybridize()
        got = net(x).asnumpy()
        onp.testing.assert_allclose(got, want, rtol=1e-5)
        onp.testing.assert_allclose(got, [13.0], rtol=1e-5)

    def test_bad_shapes_and_missing_lib(self, libpath):
        mx.library.load(libpath, verbose=False)
        with pytest.raises(MXNetError, match="infer_shape failed"):
            mx.nd.my_gemm_relu(mx.nd.ones((2, 3)), mx.nd.ones((4, 5)))
        with pytest.raises(MXNetError, match="not found"):
            mx.library.load("/nonexistent/lib.so")


def test_colliding_op_name_rejected(tmp_path):
    if shutil.which("g++") is None:
        pytest.skip("no g++")
    src = tmp_path / "bad.cc"
    src.write_text(_C_SRC.replace('"my_gemm_relu"', '"relu"'))
    so = tmp_path / "bad.so"
    subprocess.run(["g++", "-O2", "-shared", "-fPIC", str(src),
                    "-o", str(so)], check=True)
    with pytest.raises(MXNetError, match="collides"):
        mx.library.load(str(so))
