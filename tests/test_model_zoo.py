"""Vision model-zoo tests (reference strategy: tests/python/unittest/
test_gluon_model_zoo.py — build each family, forward a small batch)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.gluon.model_zoo import vision


def _forward(net, hw=64, classes=10, batch=2):
    net.initialize()
    x = mx.nd.random.uniform(shape=(batch, 3, hw, hw))
    y = net(x)
    assert y.shape == (batch, classes)
    assert np.isfinite(y.asnumpy()).all()


def test_resnet_thumbnail():
    net = vision.resnet18_v1(classes=10, thumbnail=True)
    _forward(net, hw=32)


def test_resnet_v2_thumbnail():
    net = vision.resnet18_v2(classes=10, thumbnail=True)
    _forward(net, hw=32)


def test_resnet_bottleneck():
    net = vision.resnet50_v1(classes=10, thumbnail=True)
    _forward(net, hw=32)


def test_mobilenet_v1():
    _forward(vision.mobilenet0_25(classes=10), hw=64)


def test_mobilenet_v2():
    _forward(vision.mobilenet_v2_0_25(classes=10), hw=64)


def test_mobilenet_v3():
    _forward(vision.mobilenet_v3_small(classes=10), hw=64)


def test_squeezenet():
    _forward(vision.squeezenet1_1(classes=10), hw=64)


def test_vgg():
    _forward(vision.vgg11(classes=10), hw=64)


def test_alexnet():
    _forward(vision.alexnet(classes=10), hw=224, batch=1)


def test_densenet():
    _forward(vision.densenet121(classes=10), hw=224, batch=1)


def test_inception():
    _forward(vision.inception_v3(classes=10), hw=299, batch=1)


def test_get_model_registry():
    net = vision.get_model("resnet18_v1", classes=10, thumbnail=True)
    _forward(net, hw=32)
    with pytest.raises(mx.MXNetError):
        vision.get_model("resnet999")
    # every registered name constructs without forward
    assert len(vision._models) >= 36


def test_zoo_hybridize_matches_eager():
    net = vision.resnet18_v1(classes=10, thumbnail=True)
    net.initialize()
    x = mx.nd.random.uniform(shape=(2, 3, 32, 32))
    y_eager = net(x).asnumpy()
    net.hybridize()
    y_jit = net(x).asnumpy()
    np.testing.assert_allclose(y_eager, y_jit, rtol=2e-5, atol=2e-5)


def test_zoo_save_load_roundtrip(tmp_path):
    net = vision.mobilenet_v2_0_25(classes=10)
    net.initialize()
    x = mx.nd.random.uniform(shape=(1, 3, 64, 64))
    y0 = net(x).asnumpy()
    f = str(tmp_path / "m.params")
    net.save_parameters(f)
    net2 = vision.mobilenet_v2_0_25(classes=10)
    net2.load_parameters(f)
    np.testing.assert_allclose(y0, net2(x).asnumpy(), rtol=1e-6, atol=1e-6)
