"""Driver entry-point regression tests.

Round-1 verdict: the driver imports ``__graft_entry__`` and calls
``dryrun_multichip(8)`` directly — without setting JAX_PLATFORMS /
XLA_FLAGS — so the env bootstrap must live inside the function. These
tests invoke it exactly that way, in a subprocess with a scrubbed env.
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _scrubbed_env():
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    # Restore the container's original PYTHONPATH (stashed by the root
    # conftest before its CPU re-exec) so the subprocess sees the same
    # sitecustomize/plugin registration the real driver does.
    orig = env.pop("MXNET_TPU_ORIG_PYTHONPATH", None)
    if orig is not None:
        env["PYTHONPATH"] = orig
    return env


@pytest.mark.slow
def test_dryrun_multichip_driver_pattern():
    """The exact driver invocation: import module, call dryrun_multichip(8)."""
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g\n"
         "g.dryrun_multichip(8)\n"],
        cwd=REPO, env=_scrubbed_env(), capture_output=True, text=True,
        timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "OK" in proc.stdout


def test_acquire_devices_in_initialized_session():
    """In-process path: jax is already initialized (conftest CPU mesh)."""
    import jax

    sys.path.insert(0, REPO)
    try:
        import __graft_entry__ as g
    finally:
        sys.path.pop(0)
    devices = g._acquire_devices(len(jax.devices()))
    assert len(devices) == len(jax.devices())
