"""Row-sparse embedding gradients (VERDICT round-2 #6 / SURVEY §7.3.5).

The TPU-native lazy path: Embedding(sparse_grad=True) logs (rows, dY)
through a trace-scoped custom-VJP side channel; TrainStep runs the REAL
optimizer on only the touched rows (static-shape dedupe, scatter
mode='drop'). Pinned here:
- the step's jaxpr contains no (vocab, dim) scatter-add (the dense
  embedding cotangent) while the dense-grad step does;
- lazy semantics: untouched rows and their optimizer state do not move
  (dense Adam would decay every row's state);
- numerical parity with the dense path for SGD (linear update);
- duplicate-token accumulation; dedupe_rows; kvstore row_sparse_pull.
"""
import numpy as onp
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import parallel as par
from mxnet_tpu.gluon import loss as gloss, nn
from mxnet_tpu.parallel.sparse_grad import dedupe_rows

V, D = 64, 8


class _TinyLM(nn.HybridSequential):
    def __init__(self, sparse):
        super().__init__()
        self.add(nn.Embedding(V, D, sparse_grad=sparse))
        self.add(nn.Dense(4, flatten=False))


def _build_step(sparse, optimizer="sgd", **opt_kw):
    onp.random.seed(0)
    mx.random.seed(0)
    net = _TinyLM(sparse)
    net.initialize()
    mesh = par.make_mesh({"dp": 1}, devices=jax.devices()[:1])
    step = par.TrainStep(net, gloss.L2Loss(), optimizer, mesh=mesh,
                         optimizer_params={"learning_rate": 0.1, **opt_kw})
    return net, step


def _batch():
    rs = onp.random.RandomState(1)
    tok = mx.nd.array(onp.array([[1, 5, 5, 9], [2, 5, 1, 60]],
                                dtype=onp.int32))
    y = mx.nd.array(rs.randn(2, 4, 4).astype(onp.float32))
    return tok, y


def test_dedupe_rows():
    rows = jnp.array([7, 3, 7, 7, 1], jnp.int32)
    vals = jnp.asarray(onp.arange(10, dtype=onp.float32).reshape(5, 2))
    uniq, summed = dedupe_rows(rows, vals, 100)
    got = {int(r): tuple(map(float, s)) for r, s in zip(uniq, summed)
           if int(r) < 100}
    assert got == {1: (8.0, 9.0), 3: (2.0, 3.0),
                   7: (0.0 + 4.0 + 6.0, 1.0 + 5.0 + 7.0)}
    # surplus slots carry the sentinel
    assert sorted(int(r) for r in uniq)[-2:] == [100, 100]


def test_sgd_parity_with_dense():
    """scatter-add is linear, so lazy SGD == dense SGD exactly."""
    tok, y = _batch()
    net_d, step_d = _build_step(False)
    loss_d, _ = step_d(tok, y)
    net_s, step_s = _build_step(True)
    loss_s, _ = step_s(tok, y)
    assert float(loss_s.asnumpy()) == pytest.approx(
        float(loss_d.asnumpy()), rel=1e-6)
    wd = list(net_d.collect_params().values())[0].data().asnumpy()
    ws = list(net_s.collect_params().values())[0].data().asnumpy()
    onp.testing.assert_allclose(ws, wd, rtol=1e-5, atol=1e-6)


def test_adam_is_lazy():
    """Dense Adam moves EVERY row (state decay); lazy Adam must leave
    untouched rows and their state bit-identical."""
    tok, y = _batch()
    net, step = _build_step(True, optimizer="adam")
    emb_p = list(net.collect_params().values())[0]
    w0 = emb_p.data().asnumpy().copy()
    for _ in range(3):
        step(tok, y)
    w1 = emb_p.data().asnumpy()
    touched = sorted(set(tok.asnumpy().astype(int).ravel().tolist()))
    untouched = [r for r in range(V) if r not in touched]
    onp.testing.assert_array_equal(w1[untouched], w0[untouched])
    assert not onp.allclose(w1[touched], w0[touched])


def test_no_dense_grad_in_jaxpr():
    """The sparse step must contain no (V, D) scatter-add — that op IS
    the dense embedding cotangent. The dense step has one."""

    def jaxpr_of(sparse):
        net, step = _build_step(sparse, optimizer="adam")
        tok, y = _batch()
        step(tok, y)  # build + cache
        entry = list(step._cache.values())[0]
        # retrace the cached step_fn abstractly for inspection
        import numpy as np

        from mxnet_tpu import random_state
        from mxnet_tpu.base import execution_platform
        from mxnet_tpu.parallel.mesh import use_mesh

        param_vals = tuple(p.data().data for p in step._params)
        state_vals = tuple(s.data for s in step._state_leaf_nds)
        with random_state.preserved_stream():
            key = random_state.get_state_key()
        with execution_platform("cpu"), use_mesh(step.mesh):
            return jax.make_jaxpr(
                lambda *a: entry["jitted"].__wrapped__(*a))(
                param_vals, state_vals, np.int32(1), np.float32(0.1),
                key, tok.data, y.data)

    def count_vd_scatter_add(jaxpr):
        n = 0

        def walk(jx):
            nonlocal n
            for eqn in jx.eqns:
                for val in eqn.params.values():
                    items = val if isinstance(val, (tuple, list)) else (val,)
                    for it in items:
                        sub = getattr(it, "jaxpr", it)
                        if hasattr(sub, "eqns"):
                            walk(sub)
                if eqn.primitive.name == "scatter-add":
                    for ov in eqn.outvars:
                        if tuple(getattr(ov.aval, "shape", ())) == (V, D):
                            n += 1
        walk(jaxpr.jaxpr)
        return n

    assert count_vd_scatter_add(jaxpr_of(True)) == 0
    assert count_vd_scatter_add(jaxpr_of(False)) >= 1


def test_duplicate_rows_accumulate():
    """Row 5 appears 3x in the batch; its SGD delta must be the sum."""
    tok, y = _batch()
    net, step = _build_step(True)
    emb_p = list(net.collect_params().values())[0]
    w0 = emb_p.data().asnumpy().copy()
    step(tok, y)
    # dense oracle
    net_d, step_d = _build_step(False)
    emb_d = list(net_d.collect_params().values())[0]
    step_d(tok, y)
    onp.testing.assert_allclose(emb_p.data().asnumpy()[5],
                                emb_d.data().asnumpy()[5],
                                rtol=1e-5, atol=1e-6)


def test_kvstore_row_sparse_pull():
    from mxnet_tpu import kvstore as kv_mod
    from mxnet_tpu.ndarray.sparse import RowSparseNDArray

    kv = kv_mod.create("local")
    table = mx.nd.array(onp.arange(V * D, dtype=onp.float32).reshape(V, D))
    kv.init("emb", table)
    out = RowSparseNDArray(data=jnp.zeros((0,)), ctx=mx.cpu())
    rows = mx.nd.array(onp.array([3, 7, 3], dtype=onp.int64))
    kv.row_sparse_pull("emb", out=out, row_ids=rows)
    # factored payload: O(rows) values, correct contents
    idx = out.indices.asnumpy()
    vals = out.values.asnumpy()
    # aux-array contract: sorted, in-range, exact nnz (dup collapsed)
    assert list(idx) == [3, 7]
    assert vals.shape == (2, D)
    by_row = {int(i): v for i, v in zip(idx, vals)}
    onp.testing.assert_allclose(by_row[3], table.asnumpy()[3])
    onp.testing.assert_allclose(by_row[7], table.asnumpy()[7])
    # densification on demand matches the table on those rows
    dense = out.asnumpy()
    onp.testing.assert_allclose(dense[7], table.asnumpy()[7])
    assert (dense[0] == 0).all()


def test_tied_weight_sharing_raises():
    """Weight tying + sparse_grad would silently drop the head's dense
    cotangent; TrainStep must refuse (review finding, round 3)."""
    from mxnet_tpu.base import MXNetError

    from mxnet_tpu.gluon.block import HybridBlock

    class Tied(HybridBlock):
        def __init__(self):
            super().__init__()
            with self.name_scope():
                # true weight tying: same prefix -> the Dense reuses the
                # Embedding's weight Parameter object (LlamaModel's
                # tie_weights pattern)
                self.embed = nn.Embedding(V, D, sparse_grad=True,
                                          prefix="tok_")
                self.head = nn.Dense(V, flatten=False, use_bias=False,
                                     params=self.embed.params,
                                     prefix="tok_")

        def hybrid_forward(self, F, x):
            return self.head(self.embed(x))

    net = Tied()
    net.initialize()
    mesh = par.make_mesh({"dp": 1}, devices=jax.devices()[:1])
    step = par.TrainStep(net, gloss.L2Loss(), "sgd", mesh=mesh,
                         optimizer_params={"learning_rate": 0.1})
    tok, _ = _batch()
    y = mx.nd.array(onp.zeros((2, 4, V), dtype=onp.float32))
    with pytest.raises(MXNetError, match="row_sparse"):
        step(tok, y)
