"""Request tracing + flight recorder (mxnet_tpu/tracing.py): the span
layer (mint/adopt/ambient, batch flow linkage), the bounded recorder
ring and its crash dumps, exemplar round-trips through the Prometheus
text codec, the exporter's /varz + /traces endpoints under concurrent
scrapes, and tools/latency_report.py's per-stage decomposition.

The cross-PROCESS half (span context in the wire frame header, worker
spans piggybacked on result frames) lives in
tests/test_serving_worker.py::TestRealWorkerProcess — it needs a real
subprocess. Here everything is in-process and tier-1 fast.
"""
import json
import os
import sys
import threading
import urllib.request

import numpy as np
import pytest

from mxnet_tpu import serving, telemetry, tracing

pytestmark = pytest.mark.tracing

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")
if FIXTURES not in sys.path:
    sys.path.insert(0, FIXTURES)

import worker_factory  # noqa: E402  (the fixtures dir is the point)


@pytest.fixture(autouse=True)
def _clean_ring():
    tracing.reset()
    yield
    tracing.reset()


# ---------------------------------------------------------------------------
# span layer
# ---------------------------------------------------------------------------

class TestSpanLayer:
    def test_default_off_and_inert(self):
        assert not tracing.enabled()
        assert tracing.ambient() is None
        tracing.note("dropped on the floor")        # no ambient: no-op
        tracing.record_event("shed", reason="x")    # disabled: no-op
        assert tracing.recorder().events() == []
        assert tracing.recorder().traces() == []

    def test_trace_finish_hands_record_to_ring(self):
        tracing.enable()
        tr = tracing.new_trace("request", router="r0")
        sp = tr.begin("router.queue", router="r0")
        sp.end(outcome="ok")
        tr.finish("ok")
        recs = tracing.recorder().traces()
        assert len(recs) == 1
        rec = recs[0]
        assert rec["trace_id"] == tr.trace_id
        assert rec["status"] == "ok"
        names = [s["name"] for s in rec["spans"]]
        assert "router.queue" in names and "request" in names
        # every span carries the ids that make a dump self-describing
        for s in rec["spans"]:
            assert s["trace_id"] == tr.trace_id
            assert s["span_id"] and s["proc"] and s["pid"] == os.getpid()

    def test_finish_first_wins(self):
        tracing.enable()
        tr = tracing.new_trace("request")
        tr.finish("ok")
        tr.finish("ReplicaFault")       # late loser must not re-record
        assert tr.status == "ok"
        assert len(tracing.recorder().traces()) == 1

    def test_span_end_is_idempotent(self):
        tracing.enable()
        tr = tracing.new_trace("request")
        sp = tr.begin("dispatch")
        sp.end(outcome="ok")
        sp.end(outcome="error")         # racing second end: dropped
        tr.finish("ok")
        spans = [s for s in tr.export_spans() if s["name"] == "dispatch"]
        assert len(spans) == 1
        assert spans[0]["tags"]["outcome"] == "ok"

    def test_wire_adopt_round_trip(self):
        tracing.enable()
        tr = tracing.new_trace("request")
        ctx = tr.wire()
        assert ctx["id"] == tr.trace_id
        assert ctx["parent"] == tr.root.span_id
        child = tracing.adopt(ctx, worker="w0")
        assert child is not None
        assert child.trace_id == tr.trace_id
        assert child.remote_parent == tr.root.span_id

    @pytest.mark.parametrize("bad", [
        None, "just-a-string", 42, {}, {"id": 7}, {"parent": "p"}])
    def test_adopt_malformed_degrades_to_untraced(self, bad):
        assert tracing.adopt(bad) is None

    def test_ambient_nests_and_is_thread_local(self):
        tracing.enable()
        tr = tracing.new_trace("request")
        seen = {}

        def other_thread():
            seen["other"] = tracing.ambient()

        with tracing.active(tr, tr.root):
            inner = tr.begin("router.attempt")
            with tracing.active(tr, inner):
                assert tracing.ambient() == (tr, inner)
                t = threading.Thread(target=other_thread)
                t.start()
                t.join()
            assert tracing.ambient() == (tr, tr.root)
        assert tracing.ambient() is None
        assert seen["other"] is None    # context never leaks threads

    def test_note_lands_inside_the_ambient_span(self):
        tracing.enable()
        tr = tracing.new_trace("request")
        sp = tr.begin("dispatch")
        with tracing.active(tr, sp):
            tracing.note("fault injected: serving.replica.0")
        sp.end()
        d = [s for s in tr.export_spans() if s["name"] == "dispatch"][0]
        assert "fault injected" in d["notes"][0][1]

    def test_batch_span_links_waits_and_fans_out(self):
        tracing.enable()
        traces = [tracing.new_trace("request") for _ in range(3)]
        waits = [t.begin("batch.wait") for t in traces]
        bsp = tracing.begin_batch(
            list(zip(traces, waits)), wait_tags={"bucket": 4},
            replica="rep0")
        assert bsp is not None
        assert bsp.tags["batch"] == 3
        # every wait span ended at dispatch start, carrying a flow id
        # that terminates at the batch span
        assert sorted(bsp.flows_in) == sorted(
            w.flow_out for w in waits)
        tracing.end_batch(bsp, outcome="ok")
        for t in traces:
            t.finish("ok")
        # the shared dispatch span is copied into EVERY sibling trace
        # (self-contained dumps), keeping the owning trace's id
        for t in traces:
            ds = [s for s in t.export_spans() if s["name"] == "dispatch"]
            assert len(ds) == 1
            assert ds[0]["span_id"] == bsp.span_id
            assert ds[0]["trace_id"] == traces[0].trace_id

    def test_chrome_export_flows_and_dedup(self):
        tracing.enable()
        traces = [tracing.new_trace("request") for _ in range(2)]
        waits = [t.begin("batch.wait") for t in traces]
        bsp = tracing.begin_batch(list(zip(traces, waits)))
        tracing.end_batch(bsp)
        for t in traces:
            t.finish("ok")
        evs = tracing.chrome_trace_events()
        xs = [e for e in evs if e["ph"] == "X"]
        # the fanned-out dispatch span appears ONCE despite living in
        # two trace records
        assert sum(1 for e in xs if e["name"] == "dispatch") == 1
        starts = [e for e in evs if e["ph"] == "s"]
        finishes = [e for e in evs if e["ph"] == "f"]
        assert {e["id"] for e in starts} == {e["id"] for e in finishes}
        assert len(starts) == 2         # one flow per co-batched wait


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

class TestFlightRecorder:
    def test_rings_are_bounded(self):
        rec = tracing.FlightRecorder(trace_capacity=4, event_capacity=3)
        for i in range(10):
            rec.record_trace({"trace_id": f"t{i}", "spans": []})
            rec.record_event("shed", seq=i)
        assert [t["trace_id"] for t in rec.traces()] == \
            ["t6", "t7", "t8", "t9"]
        assert [e["seq"] for e in rec.events()] == [7, 8, 9]
        assert rec.n_traces == 10 and rec.n_events == 10

    def test_dump_jsonl_round_trips(self):
        tracing.enable()
        tracing.record_event("breaker", replica="rep0",
                             from_state="closed", to_state="open")
        tr = tracing.new_trace("request")
        tr.finish("ok")
        lines = [json.loads(x) for x in
                 tracing.dump_jsonl().splitlines()]
        evs = [x for x in lines if "event" in x]
        trs = [x for x in lines if "trace_id" in x and "spans" in x]
        assert evs[0]["event"] == "breaker"
        assert evs[0]["to_state"] == "open"
        assert trs[0]["trace_id"] == tr.trace_id

    def test_dump_writes_through_atomic_write(self, tmp_path):
        tracing.enable()
        tr = tracing.new_trace("request")
        tr.finish("ok")
        path = str(tmp_path / "flight.jsonl")
        tracing.dump(path)
        with open(path) as f:
            lines = [json.loads(x) for x in f if x.strip()]
        assert any(x.get("trace_id") == tr.trace_id for x in lines)
        assert not [p for p in os.listdir(tmp_path)
                    if p != "flight.jsonl"]     # no temp litter

    def test_maybe_dump_weaves_pid_and_records_itself(
            self, tmp_path, monkeypatch):
        base = str(tmp_path / "traces.jsonl")
        monkeypatch.setenv("MXNET_TRACING_OUT", base)
        assert tracing.maybe_dump("test") is None   # disabled: no-op
        tracing.enable()
        tr = tracing.new_trace("request")
        tr.finish("ok")
        path = tracing.maybe_dump("breaker_open")
        assert path == str(tmp_path / f"traces.{os.getpid()}.jsonl")
        with open(path) as f:
            lines = [json.loads(x) for x in f if x.strip()]
        dumps = [x for x in lines if x.get("event") == "dump"]
        assert dumps and dumps[0]["reason"] == "breaker_open"

    def test_maybe_dump_without_env_is_none(self):
        tracing.enable()
        assert tracing.dump_path() is None
        assert tracing.maybe_dump("test") is None


# ---------------------------------------------------------------------------
# exemplars through the Prometheus text codec
# ---------------------------------------------------------------------------

class TestExemplars:
    def _scrape_with_exemplar(self):
        telemetry.record_serving_request(0.012, outcome="ok",
                                         trace_id="00ab00cd00ef0001")
        telemetry.record_serving_request(0.013, outcome="ok")
        return telemetry.prom_text()

    def test_exemplar_on_the_latency_bucket(self):
        telemetry.enable()
        try:
            telemetry.reset()
            text = self._scrape_with_exemplar()
        finally:
            telemetry.disable()
            telemetry.reset()
        ex_lines = [ln for ln in text.splitlines() if " # {" in ln]
        assert ex_lines, "no exemplar line in prom_text"
        assert any('trace_id="00ab00cd00ef0001"' in ln
                   and "_bucket" in ln for ln in ex_lines)

    def test_parse_emit_parse_is_lossless(self):
        telemetry.enable()
        try:
            telemetry.reset()
            text = self._scrape_with_exemplar()
        finally:
            telemetry.disable()
            telemetry.reset()
        p1 = telemetry.parse_prom_text(text)
        p2 = telemetry.parse_prom_text(telemetry.emit_prom_text(p1))
        assert p1 == p2
        exs = [s.get("exemplar")
               for fam in p1.values() for s in fam["samples"]
               if s.get("exemplar")]
        assert exs and exs[0]["labels"] == {
            "trace_id": "00ab00cd00ef0001"}

    def test_prom_value_ignores_exemplars(self):
        # the autoscaler's scrape path must read the same totals
        # whether or not requests were traced
        telemetry.enable()
        try:
            telemetry.reset()
            text = self._scrape_with_exemplar()
        finally:
            telemetry.disable()
            telemetry.reset()
        parsed = telemetry.parse_prom_text(text)
        fam = parsed["mxnet_serving_request_seconds"]
        cnt = [s for s in fam["samples"]
               if s["name"].endswith("_count")]
        assert cnt and cnt[0]["value"] == 2.0
        buckets = [s for s in fam["samples"]
                   if s["name"].endswith("_bucket")
                   and s.get("exemplar")]
        assert buckets and isinstance(buckets[0]["value"], float)
        # the scrape-fed controller reads counters from this same text
        assert telemetry.prom_value(
            parsed, "mxnet_serving_requests_total",
            {"outcome": "ok"}) == 2.0


# ---------------------------------------------------------------------------
# in-process end to end: ingress-less router path + exporter endpoints
# ---------------------------------------------------------------------------

def _traffic(n, dim=8):
    return [np.random.RandomState(300 + i).randn(dim).astype(np.float32)
            for i in range(n)]


class TestEndToEnd:
    def test_router_request_yields_one_connected_trace(self):
        tracing.enable()
        telemetry.enable()
        srv = serving.Server(
            worker_factory.tiny_net(), batch_buckets=(2, 4),
            shape_buckets=[(8,)], slo_ms=200, name="tr_rep0")
        router = serving.Router([srv], slo_ms=200).start()
        try:
            telemetry.reset()
            futs = [router.submit(x) for x in _traffic(4)]
            for f in futs:
                f.result(timeout=60)
            recs = tracing.recorder().traces()
            assert len(recs) == 4
            for rec in recs:
                assert rec["status"] == "ok"
                names = {s["name"] for s in rec["spans"]}
                assert {"request", "router.queue", "router.attempt",
                        "batch.wait", "dispatch"} <= names
                # the attempt chain shares the trace id (the batch
                # dispatch span may carry a co-batched sibling's)
                for s in rec["spans"]:
                    if s["name"] == "router.attempt":
                        assert s["trace_id"] == rec["trace_id"]
                        assert s["tags"]["outcome"] == "ok"
                        assert s["tags"]["replica"] == "tr_rep0"
            # the traced requests put exemplars on the router histogram
            assert 'trace_id="' in telemetry.prom_text()
        finally:
            router.stop(timeout=30)
            telemetry.disable()
            telemetry.reset()

    def test_untraced_router_request_allocates_no_trace(self):
        srv = serving.Server(
            worker_factory.tiny_net(), batch_buckets=(2, 4),
            shape_buckets=[(8,)], slo_ms=200, name="off_rep0")
        router = serving.Router([srv], slo_ms=200).start()
        try:
            router.submit(_traffic(1)[0]).result(timeout=60)
            assert tracing.recorder().traces() == []
            assert tracing.recorder().events() == []
        finally:
            router.stop(timeout=30)

    def test_exporter_varz_and_traces_under_concurrent_scrapes(self):
        tracing.enable()
        telemetry.enable()
        exporter = telemetry.start_exporter()
        try:
            telemetry.reset()
            telemetry.record_serving_request(
                0.01, trace_id="00aa00bb00cc0001")
            tr = tracing.new_trace("request")
            tr.finish("ok")
            base = exporter.url.rsplit("/metrics", 1)[0]
            results, errors = [], []

            def scrape(path, n=8):
                try:
                    for _ in range(n):
                        with urllib.request.urlopen(
                                base + path, timeout=10) as r:
                            results.append(
                                (path, r.status,
                                 r.read().decode("utf-8")))
                except Exception as e:  # noqa: BLE001 - reraised below
                    errors.append((path, e))

            threads = [threading.Thread(target=scrape, args=(p,))
                       for p in ("/metrics", "/varz", "/traces",
                                 "/metrics", "/varz", "/traces")]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert not errors, errors
            assert all(st == 200 for _, st, _ in results)
            by = {}
            for path, _st, body in results:
                by.setdefault(path, []).append(body)
            assert any('trace_id="00aa00bb00cc0001"' in b
                       for b in by["/metrics"])
            varz = json.loads(by["/varz"][0])
            assert "mxnet_serving_request_seconds" in varz["metrics"]
            got = [json.loads(ln) for ln in
                   by["/traces"][0].splitlines() if ln.strip()]
            assert any(x.get("trace_id") == tr.trace_id for x in got)
        finally:
            exporter.stop()
            telemetry.disable()
            telemetry.reset()


# ---------------------------------------------------------------------------
# tools/latency_report.py: per-stage decomposition from a dump
# ---------------------------------------------------------------------------

class TestLatencyReport:
    def _report_mod(self):
        sys.path.insert(0, os.path.join(
            os.path.dirname(__file__), os.pardir, "tools"))
        try:
            import latency_report
        finally:
            sys.path.pop(0)
        return latency_report

    def test_stage_split_from_traces_alone(self, tmp_path):
        lr = self._report_mod()
        tracing.enable()
        for i in range(8):
            tr = tracing.new_trace("request")
            for name, dur in (("ingress.decode", 100),
                              ("router.queue", 400),
                              ("batch.wait", 1600),
                              ("dispatch", 800),
                              ("wire.return", 200),
                              ("ingress.reply", 100)):
                tr.add_raw(name, ts=tracing.now_us(), dur=dur)
            tr.finish("ok")
        tracing.record_event("failover", reason="replica_error")
        path = str(tmp_path / "dump.jsonl")
        tracing.dump(path)

        traces, events = lr.load_traces([path])
        assert len(traces) == 8 and len(events) == 1
        rep = lr.report(traces, events)
        assert rep["traces"] == 8
        assert rep["statuses"] == {"ok": 8}
        assert rep["events"] == {"failover": 1}
        # the serving_bench stage-8 rollup, measured instead of derived
        assert rep["serving_ingress_overhead_framing_ms"] == \
            pytest.approx(0.2)
        assert rep["serving_ingress_overhead_socket_ms"] == \
            pytest.approx(0.2)
        assert rep["serving_ingress_overhead_scheduling_ms"] == \
            pytest.approx(2.0)
        stages = {r["stage"]: r for r in rep["stages"]}
        assert stages["batch.wait"]["n"] == 8
        assert stages["batch.wait"]["p50_ms"] == pytest.approx(1.6)

    def test_failover_retries_are_summed_per_request(self, tmp_path):
        lr = self._report_mod()
        tracing.enable()
        tr = tracing.new_trace("request")
        tr.add_raw("router.attempt", ts=tracing.now_us(), dur=1000)
        tr.add_raw("router.attempt", ts=tracing.now_us(), dur=3000)
        tr.finish("ok")
        path = str(tmp_path / "dump.jsonl")
        tracing.dump(path)
        traces, events = lr.load_traces([path])
        stages = lr.stage_latencies(traces)
        assert stages["router.attempt"] == [4.0]  # the request paid both

    def test_bad_lines_are_skipped_not_fatal(self, tmp_path):
        lr = self._report_mod()
        path = tmp_path / "torn.jsonl"
        path.write_text(
            '{"trace_id": "t1", "status": "ok", "spans": '
            '[{"name": "dispatch", "dur": 500}]}\n'
            "{torn line from a crash dum\n")
        traces, events = lr.load_traces([str(path)])
        assert len(traces) == 1 and events == []
