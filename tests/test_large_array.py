"""Large-array / int64-indexing tier (reference: tests/nightly/
test_large_array.py + test_large_vector.py — upstream's guard that ops
survive tensors whose element COUNT or flat indices exceed int32).

Default-run tests here stay modest (hundreds of MB at most, CPU-friendly)
and cover int64 index VALUES. The multi-GB tier (> 2^31 ELEMENTS / flat
offsets, 3-9 GB transients) is marked ``slow`` and guarded by a
free-memory check; run with
``pytest -m slow tests/test_large_array.py`` (the nightly-tier analogue).

jax note: x64 is enabled globally (conftest), so shapes/indices carry
int64 precision end to end; XLA's default index type is s32 per-buffer,
which is exactly the class of bug this tier exists to catch.
"""
import os

import numpy as onp
import pytest

import mxnet_tpu as mx

LARGE_X = 100_000_000        # vector length for the default tier (400 MB f32)
SMALL_Y = 50


class TestInt64Indices:
    def test_int64_index_values_roundtrip(self):
        """Indices above 2^31 as VALUES (take/embedding-style lookups
        must not truncate them to int32)."""
        big = onp.array([2**31 + 7, 2**33 + 1, 5], dtype=onp.int64)
        nd = mx.nd.array(big, dtype="int64")
        assert nd.dtype == onp.int64
        onp.testing.assert_array_equal(nd.asnumpy(), big)
        # arithmetic stays int64 (no silent i32 wrap)
        got = (nd + 1).asnumpy()
        onp.testing.assert_array_equal(got, big + 1)

    def test_arange_beyond_int32(self):
        a = mx.nd.arange(2**31 - 2, 2**31 + 3, dtype="int64")
        onp.testing.assert_array_equal(
            a.asnumpy(), onp.arange(2**31 - 2, 2**31 + 3, dtype=onp.int64))

class TestLargeVector:
    def test_large_vector_elementwise_and_reduce(self):
        x = mx.nd.ones((LARGE_X,), dtype="float32")
        y = (x * 2 + 1).sum()
        assert float(y.asnumpy()) == 3.0 * LARGE_X

    def test_large_matrix_rowwise_op(self):
        x = mx.nd.ones((LARGE_X // SMALL_Y, SMALL_Y))
        out = mx.nd.broadcast_add(x, mx.nd.arange(SMALL_Y))
        assert out.shape == (LARGE_X // SMALL_Y, SMALL_Y)
        got = out[123].asnumpy()
        onp.testing.assert_allclose(got, 1.0 + onp.arange(SMALL_Y))

    def test_large_dot_shape(self):
        a = mx.nd.ones((LARGE_X // 10_000, 100))
        b = mx.nd.ones((100, 50))
        out = mx.nd.dot(a, b)
        assert out.shape == (LARGE_X // 10_000, 50)
        assert float(out[0, 0].asnumpy()) == 100.0


@pytest.mark.slow
class TestBeyond2G:
    """> 2^31 ELEMENTS in one tensor (the upstream nightly threshold).
    ~4.3 GB at int16 — bench-host sized, skipped if the host is small."""

    def _skip_if_small_host(self, need_gb=16):
        free_kb = 0
        try:
            with open("/proc/meminfo") as f:
                for line in f:
                    if line.startswith("MemAvailable"):
                        free_kb = int(line.split()[1])
                        break
        except OSError:
            return  # no /proc: let the test try
        if free_kb < (need_gb << 20):
            pytest.skip(f"needs ~{need_gb} GB free host memory")

    def test_flat_offset_beyond_int32(self):
        """A (3, 2^30) int8 array's last element sits at flat element
        offset ~3.2e9 > 2^31 — reads there must address correctly."""
        self._skip_if_small_host()
        n = 2**30
        x = mx.nd.zeros((3, n), dtype="int8")
        x[2, n - 1] = 7
        assert int(x[2, n - 1].asnumpy()) == 7
        assert int(x[2, n - 2].asnumpy()) == 0
        assert int(x.astype("float32").sum().asnumpy()) == 7

    def test_over_2g_elements(self):
        self._skip_if_small_host()
        n = 2**31 + 8
        x = mx.nd.ones((n,), dtype="int16")
        x[n - 1] = 3
        assert int(x[n - 1].asnumpy()) == 3
        assert int(x[0].asnumpy()) == 1
        # halve the transient: int64 promotion of 2^30-element slices
        # instead of the whole 2^31-element tensor at once
        s = sum(int(x[i * (n // 4):(i + 1) * (n // 4)].astype("int64")
                    .sum().asnumpy()) for i in range(4))
        assert s == n + 2
