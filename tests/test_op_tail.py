"""Round-4 op-name tail: sampling/pdf families, optimizer updates,
im2col/col2im, legacy aliases, triangular linalg, indexing legacy ops.

Oracles: scipy densities for pdf ops, distribution moments for samplers,
adjointness for im2col/col2im, single-tensor update math for optimizers.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


class TestSampleOps:
    def test_sample_poisson_exponential_moments(self):
        lam = nd.array([1.0, 4.0])
        s = nd.sample_poisson(lam, shape=(4000,)).asnumpy()
        onp.testing.assert_allclose(s.mean(axis=1), [1.0, 4.0], atol=0.2)
        e = nd.sample_exponential(lam, shape=(4000,)).asnumpy()
        onp.testing.assert_allclose(e.mean(axis=1), [1.0, 0.25], atol=0.1)

    def test_sample_negative_binomial_moments(self):
        s = nd.sample_negative_binomial(
            nd.array([5.0]), nd.array([0.5]), shape=(4000,)).asnumpy()
        onp.testing.assert_allclose(s.mean(), 5.0, atol=0.4)
        g = nd.sample_generalized_negative_binomial(
            nd.array([3.0]), nd.array([0.2]), shape=(4000,)).asnumpy()
        onp.testing.assert_allclose(g.mean(), 3.0, atol=0.4)

    def test_random_poisson_under_rbg_default(self):
        """jax.random.poisson only supports threefry; the op derives a
        threefry key from the (rbg-default) global key."""
        s = nd.random_poisson(lam=2.0, shape=(4000,)).asnumpy()
        onp.testing.assert_allclose(s.mean(), 2.0, atol=0.25)

    def test_pdf_ops_match_scipy(self):
        st = pytest.importorskip("scipy.stats")
        x = nd.array([[1.0, 2.0]])
        got = nd.random_pdf_normal(x, nd.array([0.0]), nd.array([1.0]))
        onp.testing.assert_allclose(got.asnumpy()[0], st.norm.pdf([1, 2]),
                                    atol=1e-5)
        got = nd.random_pdf_poisson(x, nd.array([2.0]))
        onp.testing.assert_allclose(got.asnumpy()[0],
                                    st.poisson.pmf([1, 2], 2.0), atol=1e-5)
        got = nd.random_pdf_gamma(x, nd.array([2.0]), nd.array([1.5]))
        onp.testing.assert_allclose(
            got.asnumpy()[0], st.gamma.pdf([1, 2], 2.0, scale=1 / 1.5),
            atol=1e-5)
        got = nd.random_pdf_negative_binomial(x, nd.array([5.0]),
                                              nd.array([0.5]))
        onp.testing.assert_allclose(got.asnumpy()[0],
                                    st.nbinom.pmf([1, 2], 5, 0.5), atol=1e-5)
        got = nd.random_pdf_exponential(x, nd.array([1.5]), is_log=True)
        onp.testing.assert_allclose(got.asnumpy()[0],
                                    st.expon.logpdf([1, 2], scale=1 / 1.5),
                                    atol=1e-5)

    def test_pdf_dirichlet(self):
        st = pytest.importorskip("scipy.stats")
        sample = nd.array([[[0.2, 0.3, 0.5]]])
        alpha = nd.array([[2.0, 3.0, 4.0]])
        got = nd.random_pdf_dirichlet(sample, alpha)
        want = st.dirichlet.pdf([0.2, 0.3, 0.5], [2.0, 3.0, 4.0])
        onp.testing.assert_allclose(got.asnumpy().ravel(), [want], rtol=1e-4)


class TestOptimizerTail:
    def test_ftml_update_moves_against_gradient(self):
        w = nd.array([1.0, -2.0])
        g = nd.array([0.5, -0.5])
        d, v, z = nd.zeros(2), nd.zeros(2), nd.zeros(2)
        new_w, d1, v1, z1 = nd.ftml_update(w, g, d, v, z, lr=0.1, t=1)
        dw = new_w.asnumpy() - w.asnumpy()
        assert dw[0] < 0 < dw[1]

    def test_mp_nag_matches_fp32_nag(self):
        w32 = nd.array([1.0, -2.0])
        g = nd.array([0.1, 0.2])
        m = nd.zeros(2)
        ref_w, ref_m = nd.nag_mom_update(w32, g, m, lr=0.1, momentum=0.9)
        got = nd.mp_nag_mom_update(w32.astype("float16"), g, nd.zeros(2),
                                   nd.array([1.0, -2.0]), lr=0.1,
                                   momentum=0.9)
        onp.testing.assert_allclose(got[2].asnumpy(), ref_w.asnumpy(),
                                    rtol=1e-6)
        assert got[0].dtype == onp.float16

    def test_mp_lamb_matches_lamb(self):
        w = nd.array([1.0, -2.0])
        g = nd.array([0.1, 0.2])
        upd, m1, v1 = nd.lamb_update_phase1(w, g, nd.zeros(2), nd.zeros(2),
                                            t=1)
        upd_mp, _, _ = nd.mp_lamb_update_phase1(
            w.astype("float16"), g, nd.zeros(2), nd.zeros(2), w, t=1)
        onp.testing.assert_allclose(upd_mp.asnumpy(), upd.asnumpy(),
                                    rtol=1e-5)
        new_w = nd.lamb_update_phase2(w, upd, nd.array([1.0]),
                                      nd.array([1.0]), lr=0.01)
        got_w, got_w32 = nd.mp_lamb_update_phase2(
            w.astype("float16"), upd, nd.array([1.0]), nd.array([1.0]), w,
            lr=0.01)
        onp.testing.assert_allclose(got_w32.asnumpy(), new_w.asnumpy(),
                                    rtol=1e-5)


class TestIm2Col:
    def test_round_trip_shapes_and_adjoint(self):
        rs = onp.random.RandomState(0)
        x = nd.array(rs.randn(2, 3, 6, 6).astype("f"))
        col = nd.im2col(x, kernel=(3, 3), stride=(1, 1), pad=(1, 1))
        assert col.shape == (2, 27, 36)
        c = nd.array(rs.randn(*col.shape).astype("f"))
        back = nd.col2im(c, output_size=(6, 6), kernel=(3, 3), stride=(1, 1),
                         pad=(1, 1))
        assert back.shape == x.shape
        # adjointness: <im2col(x), c> == <x, col2im(c)>
        lhs = float((col * c).sum().asnumpy())
        rhs = float((x * back).sum().asnumpy())
        onp.testing.assert_allclose(lhs, rhs, rtol=1e-4)

    def test_col2im_reconstructs_average(self):
        # stride=kernel (no overlap): col2im(im2col(x)) == x exactly
        x = nd.array(onp.arange(16, dtype="f").reshape(1, 1, 4, 4))
        col = nd.im2col(x, kernel=(2, 2), stride=(2, 2))
        back = nd.col2im(col, output_size=(4, 4), kernel=(2, 2),
                         stride=(2, 2))
        onp.testing.assert_allclose(back.asnumpy(), x.asnumpy())


class TestLegacyAndMisc:
    def test_v1_aliases(self):
        x = nd.ones((1, 3, 8, 8))
        w = nd.ones((4, 3, 1, 1))
        y = nd.Convolution_v1(x, w, kernel=(1, 1), num_filter=4,
                              no_bias=True)
        assert y.shape == (1, 4, 8, 8)
        p = nd.Pooling_v1(x, kernel=(2, 2), stride=(2, 2))
        assert p.shape == (1, 3, 4, 4)

    def test_crop(self):
        x = nd.array(onp.arange(36, dtype="f").reshape(1, 1, 6, 6))
        y = nd.Crop(x, offset=(1, 2), h_w=(3, 3))
        onp.testing.assert_allclose(y.asnumpy()[0, 0, 0], [8, 9, 10])
        ref = nd.zeros((1, 1, 2, 2))
        y = nd.Crop(x, ref, center_crop=True)
        assert y.shape == (1, 1, 2, 2)

    def test_softmax_cross_entropy_matches_manual(self):
        rs = onp.random.RandomState(0)
        logits = rs.randn(4, 5).astype("f")
        labels = onp.array([0, 2, 4, 1], "f")
        got = float(nd.softmax_cross_entropy(
            nd.array(logits), nd.array(labels)).asnumpy())
        p = onp.exp(logits - logits.max(1, keepdims=True))
        p /= p.sum(1, keepdims=True)
        want = -onp.log(p[onp.arange(4), labels.astype(int)]).sum()
        onp.testing.assert_allclose(got, want, rtol=1e-5)

    def test_mish(self):
        x = nd.array([0.0, 1.0, -1.0])
        got = nd.mish(x).asnumpy()
        want = x.asnumpy() * onp.tanh(onp.log1p(onp.exp(x.asnumpy())))
        onp.testing.assert_allclose(got, want, rtol=1e-5)

    def test_kl_sparse_reg_backward_adds_penalty(self):
        from mxnet_tpu import autograd
        x = nd.array(onp.full((4, 3), 0.5, "f"))
        x.attach_grad()
        with autograd.record():
            y = nd.IdentityAttachKLSparseReg(x, sparseness_target=0.2,
                                             penalty=0.01)
            loss = y.sum()
        loss.backward()
        # identity grad (1) + penalty*(-rho/0.5 + (1-rho)/0.5)
        want = 1.0 + 0.01 * (-0.2 / 0.5 + 0.8 / 0.5)
        onp.testing.assert_allclose(x.grad.asnumpy(),
                                    onp.full((4, 3), want), rtol=1e-5)

    def test_triangular_pack_unpack(self):
        v = nd.array([1.0, 2.0, 3.0, 4.0, 5.0, 6.0])
        t = nd.linalg_maketrian(v)
        onp.testing.assert_allclose(
            t.asnumpy(), [[1, 0, 0], [2, 3, 0], [4, 5, 6]])
        onp.testing.assert_allclose(nd.linalg_extracttrian(t).asnumpy(),
                                    v.asnumpy())
        u = nd.linalg_maketrian(v, lower=False)
        onp.testing.assert_allclose(
            nd.linalg_extracttrian(u, lower=False).asnumpy(), v.asnumpy())

    def test_indexing_legacy_ops(self):
        l = nd.array([[1.0, 2.0], [3.0, 4.0]])
        r = nd.array([1.0, 0.0])
        onp.testing.assert_allclose(
            nd.choose_element_0index(l, r).asnumpy(), [2.0, 3.0])
        filled = nd.fill_element_0index(l, nd.array([9.0, 8.0]), r)
        onp.testing.assert_allclose(filled.asnumpy(), [[1, 9], [8, 4]])
        idx = nd.array([[0], [1]]).astype("int32")
        got = nd.scatter_set_nd(l, nd.array([5.0]), idx, shape=(2, 2))
        onp.testing.assert_allclose(got.asnumpy(), [[1, 5], [3, 4]])

    def test_cast_storage(self):
        a = nd.array([[1.0, 0.0], [0.0, 2.0]])
        c = nd.cast_storage(a, "csr")
        assert c.stype == "csr"
        assert nd.cast_storage(c, "default").stype == "default"
        rs = nd.cast_storage(a, "row_sparse")
        assert rs.stype == "row_sparse"


class TestROIPooling:
    """reference roi_pooling.cc bin rules (floor/ceil edges, overlap)."""

    @staticmethod
    def _ref(data, rois, ph, pw, scale):
        R = rois.shape[0]
        C, H, W = data.shape[1:]
        out = onp.zeros((R, C, ph, pw), data.dtype)
        for r, roi in enumerate(rois):
            b = int(roi[0])
            x1, y1, x2, y2 = [int(onp.floor(v * scale + 0.5))
                              for v in roi[1:]]
            rh = max(y2 - y1 + 1, 1)
            rw = max(x2 - x1 + 1, 1)
            for i in range(ph):
                for j in range(pw):
                    hs = max(y1 + int(onp.floor(i * rh / ph)), 0)
                    he = min(y1 + int(onp.ceil((i + 1) * rh / ph)), H)
                    ws = max(x1 + int(onp.floor(j * rw / pw)), 0)
                    we = min(x1 + int(onp.ceil((j + 1) * rw / pw)), W)
                    patch = data[b, :, hs:he, ws:we]
                    out[r, :, i, j] = patch.max(axis=(1, 2)) \
                        if patch.size else 0.0
        return out

    def test_matches_oracle(self):
        rs = onp.random.RandomState(0)
        data = rs.rand(2, 3, 12, 12).astype("f")
        rois = onp.array([[0, 0, 0, 7, 7], [1, 2, 2, 11, 9],
                          [0, 3, 1, 6, 6]], "f")
        for (ph, pw), scale in (((3, 3), 1.0), ((2, 4), 0.5)):
            got = nd.ROIPooling(nd.array(data), nd.array(rois),
                                pooled_size=(ph, pw),
                                spatial_scale=scale).asnumpy()
            onp.testing.assert_allclose(
                got, self._ref(data, rois, ph, pw, scale), rtol=1e-5)


class TestUpsamplingAndGroupedDeconv:
    def test_topk_mask(self):
        x = nd.array([[3.0, 1.0, 2.0], [0.0, 5.0, 4.0]])
        m = nd.topk(x, k=2, ret_typ="mask")
        onp.testing.assert_allclose(m.asnumpy(), [[1, 0, 1], [0, 1, 1]])

    def test_grouped_deconvolution_matches_per_group(self):
        rs = onp.random.RandomState(0)
        x = nd.array(rs.randn(2, 4, 5, 5).astype("f"))
        w = nd.array(rs.randn(4, 2, 3, 3).astype("f"))
        got = nd.Deconvolution(x, w, kernel=(3, 3), stride=(2, 2),
                               pad=(1, 1), num_filter=4, num_group=2)
        outs = []
        for gi in range(2):
            xg = nd.array(x.asnumpy()[:, gi * 2:(gi + 1) * 2])
            wg = nd.array(w.asnumpy()[gi * 2:(gi + 1) * 2])
            outs.append(nd.Deconvolution(
                xg, wg, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                num_filter=2).asnumpy())
        onp.testing.assert_allclose(got.asnumpy(),
                                    onp.concatenate(outs, axis=1),
                                    rtol=1e-4, atol=1e-4)

    def test_bilinear_upsampling_constant_preserving(self):
        """UpSampling bilinear = depthwise deconv with the caller's
        kernel (reference upsampling.cc); the standard bilinear-init
        kernel must reproduce a constant image in the interior."""
        scale, c_ch = 2, 3
        k = 2 * scale - scale % 2
        f = (k + 1) // 2
        ctr = (2 * f - 1 - f % 2) / (2.0 * f)
        og = onp.ogrid[:k, :k]
        filt = (1 - abs(og[0] / f - ctr)) * (1 - abs(og[1] / f - ctr))
        w = onp.zeros((c_ch, 1, k, k), "f")
        w[:, 0] = filt
        x = nd.ones((1, c_ch, 4, 4))
        y = nd.UpSampling(x, nd.array(w), scale=scale,
                          sample_type="bilinear", num_args=2)
        assert y.shape == (1, c_ch, 8, 8)
        assert onp.allclose(y.asnumpy()[0, :, 2:6, 2:6], 1.0, atol=1e-5)

    def test_nearest_multi_input_concat(self):
        a, b = nd.ones((1, 2, 3, 3)), nd.zeros((1, 1, 3, 3))
        out = nd.UpSampling(a, b, scale=2, sample_type="nearest",
                            num_args=2)
        assert out.shape == (1, 3, 6, 6)
        # different-resolution inputs upsample to the COMMON output size
        # (reference: per-input factor toward data[0].shape * scale)
        a, b = nd.ones((1, 2, 4, 4)), nd.zeros((1, 1, 2, 2))
        out = nd.UpSampling(a, b, scale=2, sample_type="nearest",
                            num_args=2)
        assert out.shape == (1, 3, 8, 8)


class TestContribTail:
    """Round-4 contrib tail: fft/count_sketch/adaptive pool/matching."""

    def test_quadratic_allclose_index_copy(self):
        onp.testing.assert_allclose(
            nd.quadratic(nd.array([1.0, 2.0]), a=1, b=2, c=3).asnumpy(),
            [6.0, 11.0])
        assert float(nd.contrib.allclose(
            nd.array([1.0]), nd.array([1.0 + 1e-9])).asnumpy()) == 1.0
        assert float(nd.contrib.allclose(
            nd.array([1.0]), nd.array([2.0])).asnumpy()) == 0.0
        old = nd.array(onp.zeros((4, 3), "f"))
        new = nd.array(onp.ones((2, 3), "f"))
        got = nd.index_copy(old, nd.array([1, 3]).astype("int32"), new)
        onp.testing.assert_allclose(got.asnumpy()[:, 0], [0, 1, 0, 1])

    def test_fft_ifft_roundtrip(self):
        rs = onp.random.RandomState(0)
        x = nd.array(rs.randn(2, 8).astype("f"))
        f = nd.fft(x)
        assert f.shape == (2, 16)  # interleaved (re, im)
        bak = nd.ifft(f) / 8  # reference ifft scales by n
        onp.testing.assert_allclose(bak.asnumpy(), x.asnumpy(), atol=1e-4)

    def test_count_sketch_matches_oracle(self):
        rs = onp.random.RandomState(1)
        d = nd.array(rs.rand(3, 6).astype("f"))
        hv = [0, 1, 0, 2, 1, 3]
        sv = [1, -1, 1, 1, -1, 1]
        cs = nd.count_sketch(d, nd.array(onp.array(hv, "f")),
                             nd.array(onp.array(sv, "f")), out_dim=4)
        want = onp.zeros((3, 4), "f")
        for i, (hh, ss) in enumerate(zip(hv, sv)):
            want[:, hh] += ss * d.asnumpy()[:, i]
        onp.testing.assert_allclose(cs.asnumpy(), want, rtol=1e-5)

    def test_adaptive_avg_pooling(self):
        x = onp.arange(32, dtype="f").reshape(1, 2, 4, 4)
        p = nd.AdaptiveAvgPooling2D(nd.array(x), output_size=(2, 2))
        want = x.reshape(1, 2, 2, 2, 2, 2).mean(axis=(3, 5))
        onp.testing.assert_allclose(p.asnumpy(), want)
        # uneven bins + global (default) size
        assert nd.AdaptiveAvgPooling2D(
            nd.array(onp.random.rand(1, 1, 7, 5).astype("f")),
            output_size=(3, 2)).shape == (1, 1, 3, 2)
        assert nd.AdaptiveAvgPooling2D(
            nd.array(x)).shape == (1, 2, 1, 1)

    def test_bipartite_matching_greedy(self):
        sc = nd.array(onp.array([[0.9, 0.1], [0.8, 0.7]], "f"))
        rm, cm = nd.bipartite_matching(sc, threshold=0.05)
        onp.testing.assert_allclose(rm.asnumpy(), [0, 1])
        onp.testing.assert_allclose(cm.asnumpy(), [0, 1])
        # threshold excludes weak pairs
        rm, cm = nd.bipartite_matching(sc, threshold=0.85)
        onp.testing.assert_allclose(rm.asnumpy(), [0, -1])
        # ascending = smallest-first
        rm, cm = nd.bipartite_matching(
            nd.array(onp.array([[0.3, 0.2], [0.1, 0.25]], "f")),
            is_ascend=True, threshold=0.5)
        onp.testing.assert_allclose(rm.asnumpy(), [1, 0])
