"""Typed op-attribute system (VERDICT #9; reference: dmlc::Parameter —
typed param structs with range validation and doc flow)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError


def test_bad_choice_raises_named_error():
    x = mx.nd.array(onp.ones((1, 2, 4, 4), onp.float32))
    with pytest.raises(MXNetError, match="Pooling.*pool_type.*'max'"):
        mx.nd.Pooling(x, kernel=(2, 2), pool_type="maxx")


def test_out_of_range_raises():
    x = mx.nd.array(onp.ones((2, 4), onp.float32))
    rngkey = None
    with pytest.raises(MXNetError, match="Dropout.*p=1.5.*range"):
        mx.nd.Dropout(x, p=1.5, mode="always")


def test_bad_type_raises():
    x = mx.nd.array(onp.ones((1, 2, 4, 4), onp.float32))
    w = mx.nd.array(onp.ones((3, 2, 3, 3), onp.float32))
    with pytest.raises(MXNetError, match="Convolution.*num_filter"):
        mx.nd.Convolution(x, w, kernel=(3, 3), num_filter="three",
                          no_bias=True)


def test_negative_pad_raises():
    x = mx.nd.array(onp.ones((1, 2, 4, 4), onp.float32))
    w = mx.nd.array(onp.ones((3, 2, 3, 3), onp.float32))
    with pytest.raises(MXNetError, match="Convolution.*pad"):
        mx.nd.Convolution(x, w, kernel=(3, 3), num_filter=3,
                          pad=(-1, 0), no_bias=True)


def test_docs_flow_into_wrapper():
    doc = mx.nd.Convolution.__doc__
    assert "Attributes" in doc
    assert "kernel" in doc and "Spatial kernel size" in doc
    assert "num_filter" in doc and "range [1, inf]" in doc
    assert "NHWC" in doc  # layout choices rendered


def test_valid_calls_unaffected():
    x = mx.nd.array(onp.ones((1, 2, 4, 4), onp.float32))
    w = mx.nd.array(onp.ones((3, 2, 3, 3), onp.float32))
    out = mx.nd.Convolution(x, w, kernel=(3, 3), num_filter=3, pad=(1, 1),
                            no_bias=True)
    assert out.shape == (1, 3, 4, 4)
