"""Flash attention kernel tests.

The Pallas kernel runs in interpret mode on the CPU oracle (SURVEY.md §4:
CPU is the reference device); the scan path is exercised natively. On real
TPU the same tests validate the compiled kernel.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu.pallas_kernels import flash_attention, flash_attention_scan
from mxnet_tpu.ops.attention import _sdpa_reference

pytestmark = pytest.mark.pallas

SCALE = 1.0 / np.sqrt(64)


def _qkv(lq=256, lk=256, d=64, seed=0):
    rs = np.random.RandomState(seed)
    mk = lambda l: jnp.asarray(rs.randn(2, 4, l, d).astype("float32"))
    return mk(lq), mk(lk), mk(lk)


class TestScanPath:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        q, k, v = _qkv()
        ref = _sdpa_reference(q, k, v, None, SCALE, causal)
        out = flash_attention_scan(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_unaligned_length(self):
        q, k, v = _qkv(lq=100, lk=100)
        ref = _sdpa_reference(q, k, v, None, SCALE, True)
        out = flash_attention_scan(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_cross_lengths(self, causal):
        q, k, v = _qkv(lq=128, lk=384)
        ref = _sdpa_reference(q, k, v, None, SCALE, causal)
        out = flash_attention_scan(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)


class TestPallasKernel:
    @pytest.mark.parametrize("causal", [False, True])
    def test_interpret_matches_reference(self, causal):
        q, k, v = _qkv()
        ref = _sdpa_reference(q, k, v, None, SCALE, causal)
        out = flash_attention(q, k, v, causal=causal, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_interpret_causal_cross_lengths(self):
        q, k, v = _qkv(lq=128, lk=384)
        ref = _sdpa_reference(q, k, v, None, SCALE, True)
        out = flash_attention(q, k, v, causal=True, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_causal_lq_gt_lk_no_nan(self):
        """Advisor finding: causal with lq > lk leaves top query rows fully
        masked; they must emit zeros, never 0/0 NaN (kernel and scan)."""
        q, k, v = _qkv(lq=384, lk=128)
        out_k = np.asarray(flash_attention(q, k, v, causal=True,
                                           interpret=True))
        out_s = np.asarray(flash_attention_scan(q, k, v, causal=True))
        assert np.isfinite(out_k).all()
        assert np.isfinite(out_s).all()
        # bottom-right alignment: the first lq-lk query rows see no keys
        np.testing.assert_allclose(out_k[:, :, :384 - 128], 0.0)
        np.testing.assert_allclose(out_s[:, :, :384 - 128], 0.0)
        # visible rows still match the dense reference
        ref = np.asarray(_sdpa_reference(q, k, v, None, SCALE, True))
        np.testing.assert_allclose(out_k[:, :, 384 - 128 + 1:],
                                   ref[:, :, 384 - 128 + 1:],
                                   rtol=1e-5, atol=1e-5)

    def test_flash_supported_rejects_causal_lq_gt_lk(self):
        # flash_shape_supported is the platform-independent predicate, so
        # this regression is covered on the CPU test mesh too (plain
        # flash_supported would short-circuit False on platform != tpu)
        from mxnet_tpu.pallas_kernels import flash_shape_supported

        q, k, v = _qkv(lq=384, lk=128)
        assert not flash_shape_supported(q, k, v, causal=True)
        assert flash_shape_supported(q, k, v, causal=False)
        assert flash_shape_supported(k, q, q, causal=True)  # lq < lk ok

    def test_gradients_match(self):
        q, k, v = _qkv(lq=128, lk=128)

        def loss_ref(q, k, v):
            return jnp.sum(_sdpa_reference(q, k, v, None, SCALE, True) ** 2)

        def loss_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=True,
                                           interpret=True) ** 2)

        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gr, gf):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    @pytest.mark.parametrize("causal", [False, True])
    def test_backward_cross_lengths(self, causal):
        """The Pallas dq/dk/dv kernels (round-2: real kernels, not scan
        recompute) against the dense reference with lq != lk."""
        q, k, v = _qkv(lq=128, lk=384)
        g = jnp.asarray(np.random.RandomState(7)
                        .randn(*q.shape).astype("float32"))

        _, vjp_f = jax.vjp(lambda a, b, c: flash_attention(
            a, b, c, causal=causal, interpret=True), q, k, v)
        _, vjp_r = jax.vjp(lambda a, b, c: _sdpa_reference(
            a, b, c, None, SCALE, causal), q, k, v)
        for a, b, name in zip(vjp_f(g), vjp_r(g), "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4,
                                       err_msg=f"d{name} causal={causal}")

    def test_backward_bf16_finite_and_close(self):
        q, k, v = _qkv(lq=256, lk=256)
        qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
        g = jnp.ones(q.shape, jnp.bfloat16)
        _, vjp_b = jax.vjp(lambda a, b, c: flash_attention(
            a, b, c, causal=True, interpret=True), qb, kb, vb)
        _, vjp_f = jax.vjp(lambda a, b, c: _sdpa_reference(
            a, b, c, None, SCALE, True), q, k, v)
        for a, b, name in zip(vjp_b(g), vjp_f(jnp.ones_like(q)), "qkv"):
            a = np.asarray(a, dtype=np.float32)
            assert np.isfinite(a).all(), f"d{name} has non-finite values"
            np.testing.assert_allclose(a, np.asarray(b), rtol=0.1, atol=0.1,
                                       err_msg=f"d{name} bf16")

    @pytest.mark.parametrize("causal", [False, True])
    def test_blhd_layout_matches_bhld(self, causal):
        """blhd (projection-native, transpose-free) must equal bhld in both
        directions — fwd values and dq/dk/dv."""
        q, k, v = _qkv(lq=256, lk=256)
        g = jnp.asarray(np.random.RandomState(3)
                        .randn(*q.shape).astype("float32"))
        t = lambda x: jnp.transpose(x, (0, 2, 1, 3))

        o_ref, vjp_ref = jax.vjp(lambda a, b, c: flash_attention(
            a, b, c, causal=causal, interpret=True), q, k, v)
        o_new, vjp_new = jax.vjp(lambda a, b, c: flash_attention(
            a, b, c, causal=causal, interpret=True, layout="blhd"),
            t(q), t(k), t(v))
        np.testing.assert_allclose(np.asarray(t(o_new)), np.asarray(o_ref),
                                   rtol=1e-5, atol=1e-5)
        for a, b, name in zip(vjp_new(t(g)), vjp_ref(g), "qkv"):
            np.testing.assert_allclose(np.asarray(t(a)), np.asarray(b),
                                       rtol=1e-5, atol=1e-5,
                                       err_msg=f"d{name} causal={causal}")


class TestAttentionDropout:
    """In-kernel attention-probability dropout (round 5, VERDICT r4 #2).

    The mask is a stateless position hash, so every dispatch route
    (Pallas kernels in any block/grouping geometry, the scan fallback,
    the dense reference) must produce BITWISE-identical drop decisions
    for the same seed — which makes exact oracle comparison possible.
    """

    def test_kernel_matches_dense_oracle(self):
        q, k, v = _qkv()
        seed = jnp.asarray(7, jnp.uint32)
        ref = _sdpa_reference(q, k, v, None, SCALE, False,
                              dropout=0.25, seed=seed)
        out = flash_attention(q, k, v, causal=False, interpret=True,
                              dropout=0.25, seed=seed)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_scan_matches_dense_oracle(self):
        q, k, v = _qkv(lq=128, lk=384)
        seed = jnp.asarray(11, jnp.uint32)
        ref = _sdpa_reference(q, k, v, None, SCALE, True,
                              dropout=0.1, seed=seed)
        out = flash_attention_scan(q, k, v, causal=True,
                                   dropout=0.1, seed=seed)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_streaming_kernel_matches_dense_oracle(self):
        # lq=512, lk=1024 -> nk=2: exercises the streaming fwd kernel's
        # per-(qi, ki) mask tiles against the whole-matrix oracle
        q, k, v = _qkv(lq=512, lk=1024)
        seed = jnp.asarray(3, jnp.uint32)
        ref = _sdpa_reference(q, k, v, None, SCALE, False,
                              dropout=0.2, seed=seed)
        out = flash_attention(q, k, v, causal=False, interpret=True,
                              dropout=0.2, seed=seed)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_gradients_match_dense_oracle(self):
        q, k, v = _qkv(lq=256, lk=256)
        seed = jnp.asarray(5, jnp.uint32)

        def loss_flash(a, b, c):
            return jnp.sum(flash_attention(a, b, c, interpret=True,
                                           dropout=0.25, seed=seed) ** 2)

        def loss_ref(a, b, c):
            return jnp.sum(_sdpa_reference(a, b, c, None, SCALE, False,
                                           dropout=0.25, seed=seed) ** 2)

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)

    def test_streaming_bwd_gradients_match(self):
        # nq=nk=2 -> split dkdv/dq backward kernels regenerate the mask
        # per streamed tile
        q, k, v = _qkv(lq=1024, lk=1024)
        seed = jnp.asarray(13, jnp.uint32)

        def loss_flash(a, b, c):
            return jnp.sum(flash_attention(a, b, c, interpret=True,
                                           dropout=0.1, seed=seed) ** 2)

        def loss_ref(a, b, c):
            return jnp.sum(_sdpa_reference(a, b, c, None, SCALE, False,
                                           dropout=0.1, seed=seed) ** 2)

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)

    def test_keep_rate_and_seed_sensitivity(self):
        q, k, v = _qkv()
        o1 = flash_attention(q, k, v, interpret=True, dropout=0.5,
                             seed=jnp.asarray(1, jnp.uint32))
        o2 = flash_attention(q, k, v, interpret=True, dropout=0.5,
                             seed=jnp.asarray(2, jnp.uint32))
        assert not np.allclose(np.asarray(o1), np.asarray(o2))
        # expectation preserved: mean over many elements ~ no-dropout mean
        o0 = flash_attention(q, k, v, interpret=True)
        np.testing.assert_allclose(np.asarray(o1).mean(),
                                   np.asarray(o0).mean(), atol=0.02)

    def test_dropout_requires_seed(self):
        q, k, v = _qkv()
        with pytest.raises(ValueError, match="seed"):
            flash_attention(q, k, v, dropout=0.1)


class TestFlashBackwardReachability:
    """ISSUE 11 satellite: audit that the Pallas flash-attention
    BACKWARD kernels (_bwd_dkdv_kernel / _bwd_dq_kernel via
    _flash_bwd_pallas) are actually reached from the model-zoo attention
    paths — training attention must not re-materialize the score matrix
    in backward. (The dense _sdpa_reference path is reached only when a
    mask is given or the shape/platform gate fails, by design.)"""

    def test_zoo_attention_backward_hits_pallas_bwd(self, monkeypatch):
        """Grad through the zoo MultiHeadAttention with the flash gate
        forced (interpret mode = the CPU oracle of the TPU route) runs
        the Pallas backward kernels — counted at _flash_bwd_pallas."""
        import importlib

        import mxnet_tpu as mx
        from mxnet_tpu import autograd, pallas_kernels
        from mxnet_tpu.gluon.model_zoo.nlp.attention import \
            MultiHeadAttention

        # the package attr `flash_attention` is the FUNCTION; get the
        # module (where the vjp resolves _flash_bwd_pallas) explicitly
        fa_mod = importlib.import_module(
            "mxnet_tpu.pallas_kernels.flash_attention")

        calls = []
        real_bwd = fa_mod._flash_bwd_pallas

        def counting_bwd(*args, **kw):
            calls.append(1)
            return real_bwd(*args, **kw)

        monkeypatch.setattr(fa_mod, "_flash_bwd_pallas", counting_bwd)
        # force the flash route on CPU: gate open + interpret kernels
        monkeypatch.setattr(pallas_kernels, "flash_supported",
                            lambda *a, **k: True)
        real_flash = pallas_kernels.flash_attention
        monkeypatch.setattr(
            pallas_kernels, "flash_attention",
            lambda q, k, v, **kw: real_flash(
                q, k, v, **{**kw, "interpret": True}))

        attn = MultiHeadAttention(32, 2, causal=True)
        attn.initialize()
        x = mx.nd.array(np.random.RandomState(0)
                        .randn(1, 128, 32).astype(np.float32))
        with autograd.record():
            out = attn(x)
            loss = (out ** 2).sum()
        loss.backward()
        assert calls, ("zoo attention backward never reached the Pallas "
                       "bwd kernels")
        for p in attn.collect_params().values():
            g = p.list_grad()[0].asnumpy()
            assert np.isfinite(g).all() and np.abs(g).sum() > 0

    def test_flash_gate_covers_zoo_training_shapes(self):
        """The BERT/Llama zoo attention shapes (post head-split bhld)
        pass the flash shape gate — fwd AND bwd run on the kernels on
        TPU, not the score-materializing dense path."""
        from mxnet_tpu.pallas_kernels import flash_shape_supported

        zoo_shapes = [
            (8, 12, 512, 64),    # BERT-base seq-512
            (4, 32, 2048, 128),  # Llama-proxy seq-2048
        ]
        for b, h, l, d in zoo_shapes:
            q = jnp.zeros((b, h, l, d), jnp.bfloat16)
            assert flash_shape_supported(q, q, q, causal=True), (b, h, l, d)

    def test_sdp_attention_with_mask_keeps_dense_path(self, monkeypatch):
        """Masked attention cannot take the flash kernel (documented
        fallback): it routes to the dense reference even with the gate
        forced open."""
        from mxnet_tpu import pallas_kernels
        from mxnet_tpu.ops.attention import sdp_attention

        monkeypatch.setattr(pallas_kernels, "flash_supported",
                            lambda *a, **k: True)
        called = []
        real_flash = pallas_kernels.flash_attention
        monkeypatch.setattr(
            pallas_kernels, "flash_attention",
            lambda *a, **kw: called.append(1) or real_flash(*a, **kw))
        q = jnp.asarray(np.random.RandomState(0)
                        .randn(1, 2, 128, 16).astype(np.float32))
        mask = jnp.ones((1, 1, 128, 128), jnp.float32)
        out = sdp_attention(None, q, q, q, mask)
        assert not called
        assert np.isfinite(np.asarray(out)).all()
