"""Sparse surface tests (reference: tests/python/unittest/test_sparse_ndarray.py).

Dense-backed semantics per SURVEY.md §7.3.5: the API round-trips and the
views (indices/indptr/values) match scipy-style expectations."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError
from mxnet_tpu.ndarray import sparse


def _dense():
    d = onp.zeros((4, 5), "float32")
    d[0, 1] = 1.0
    d[0, 4] = 2.0
    d[2, 0] = 3.0
    return d


class TestCSR:
    def test_from_dense_and_views(self):
        a = mx.nd.array(_dense()).tostype("csr")
        assert a.stype == "csr" and isinstance(a, sparse.CSRNDArray)
        onp.testing.assert_array_equal(a.indices.asnumpy(), [1, 4, 0])
        onp.testing.assert_array_equal(a.indptr.asnumpy(), [0, 2, 2, 3, 3])
        onp.testing.assert_allclose(a.values.asnumpy(), [1.0, 2.0, 3.0])
        onp.testing.assert_allclose(a.asnumpy(), _dense())

    def test_from_aux_arrays(self):
        a = sparse.csr_matrix(([1.0, 2.0, 3.0], [1, 4, 0],
                               [0, 2, 2, 3, 3]), shape=(4, 5))
        onp.testing.assert_allclose(a.asnumpy(), _dense())

    def test_tostype_round_trip(self):
        a = mx.nd.array(_dense()).tostype("csr")
        back = a.tostype("default")
        assert back.stype == "default"
        onp.testing.assert_allclose(back.asnumpy(), _dense())

    def test_csr_requires_2d(self):
        with pytest.raises(MXNetError, match="2-D"):
            mx.nd.ones((2, 3, 4)).tostype("csr")

    def test_dot_with_dense(self):
        a = sparse.csr_matrix(_dense())
        b = mx.nd.array(onp.arange(10.0).reshape(5, 2).astype("float32"))
        out = sparse.dot(a, b)
        onp.testing.assert_allclose(out.asnumpy(), _dense() @ b.asnumpy())


class TestFactoredCSR:
    """Round-4 upgrade (VERDICT r3 #7): CSR keeps factored
    values/indices/indptr, and dot() runs the O(nnz) segment-sum kernel."""

    def _factored(self):
        return sparse.csr_matrix(
            ([1.0, 2.0, 3.0], [1, 4, 0], [0, 2, 2, 3, 3]), shape=(4, 5))

    def test_factored_views_no_densify(self):
        a = self._factored()
        assert a._vals is not None and a._data is None
        onp.testing.assert_array_equal(a.indices.asnumpy(), [1, 4, 0])
        onp.testing.assert_array_equal(a.indptr.asnumpy(), [0, 2, 2, 3, 3])
        onp.testing.assert_allclose(a.values.asnumpy(), [1.0, 2.0, 3.0])
        assert a._data is None  # views served from factored parts
        assert a.shape == (4, 5)

    def test_factored_dot_matches_dense(self):
        a = self._factored()
        b = mx.nd.array(onp.arange(10.0).reshape(5, 2).astype("float32"))
        out = sparse.dot(a, b)
        assert a._data is None  # the kernel consumed factored parts
        onp.testing.assert_allclose(out.asnumpy(), _dense() @ b.asnumpy())

    def test_factored_dot_transpose_a(self):
        a = self._factored()
        b = mx.nd.array(onp.arange(8.0).reshape(4, 2).astype("float32"))
        out = sparse.dot(a, b, transpose_a=True)
        assert a._data is None
        onp.testing.assert_allclose(out.asnumpy(), _dense().T @ b.asnumpy())

    def test_hlo_never_materializes_dense(self):
        """Gate: a jitted logreg step over the factored parts has NO
        intermediate the size of the dense (M, K) matrix."""
        import jax
        import jax.numpy as jnp

        from mxnet_tpu.ndarray.sparse import csr_matmul

        M, K, NNZ = 64, 100_000, 512
        rs = onp.random.RandomState(0)
        vals = jnp.asarray(rs.randn(NNZ).astype("float32"))
        cols = jnp.asarray(rs.randint(0, K, NNZ).astype("int32"))
        rows = jnp.asarray(onp.sort(rs.randint(0, M, NNZ)).astype("int32"))
        y = jnp.asarray(rs.choice([-1.0, 1.0], M).astype("float32"))
        w = jnp.zeros((K, 1), "float32")

        def loss(w, vals, cols, rows, y):
            logits = csr_matmul(vals, cols, rows, M, K, w)[:, 0]
            return jnp.mean(jnp.log1p(jnp.exp(-y * logits)))

        jaxpr = jax.make_jaxpr(jax.grad(loss))(w, vals, cols, rows, y)
        dense_size = M * K

        def walk(jx):
            for eqn in jx.eqns:
                for v in list(eqn.outvars) + list(eqn.invars):
                    aval = getattr(v, "aval", None)
                    if aval is not None and hasattr(aval, "shape"):
                        size = 1
                        for d in aval.shape:
                            size *= d
                        assert size < dense_size, (
                            f"dense-sized intermediate {aval.shape} "
                            f"in {eqn.primitive}")
                for sub in eqn.params.values():
                    if hasattr(sub, "jaxpr"):
                        walk(sub.jaxpr)

        walk(jaxpr.jaxpr)

    def test_logreg_trains_on_sparse(self):
        """End-to-end: LibSVMIter -> factored CSR batches -> logistic
        regression whose grads flow through the segment-sum matmul."""
        import os
        import tempfile

        import jax
        import jax.numpy as jnp

        from mxnet_tpu import io as mxio
        from mxnet_tpu.ndarray.sparse import csr_matmul

        # synthetic separable problem, written as libsvm text
        rs = onp.random.RandomState(3)
        DIM, N, B = 50, 64, 16
        w_true = rs.randn(DIM).astype("float32")
        path = os.path.join(tempfile.gettempdir(), "t_libsvm.txt")
        with open(path, "w") as f:
            for _ in range(N):
                nnz = rs.randint(3, 8)
                idx = onp.sort(rs.choice(DIM, nnz, replace=False))
                v = rs.randn(nnz).astype("float32")
                label = 1.0 if float(v @ w_true[idx]) > 0 else 0.0
                f.write(str(label) + " " +
                        " ".join(f"{i}:{x:.5f}" for i, x in zip(idx, v))
                        + "\n")

        it = mxio.LibSVMIter(data_libsvm=path, data_shape=(DIM,),
                             batch_size=B)

        def loss_fn(w, vals, cols, rows, y):
            logits = csr_matmul(vals, cols, rows, B, DIM, w[:, None])[:, 0]
            p = jax.nn.sigmoid(logits)
            return -jnp.mean(y * jnp.log(p + 1e-7)
                             + (1 - y) * jnp.log(1 - p + 1e-7))

        grad_fn = jax.jit(jax.value_and_grad(loss_fn),
                          static_argnums=())
        w = jnp.zeros((DIM,), "float32")
        first = last = None
        for _ in range(6):
            it.reset()
            for batch in it:
                csr = batch.data[0]
                vals = csr._vals
                cols = csr._cols
                rows = csr._row_ids()
                yb = jnp.asarray(batch.label[0].asnumpy())
                lv, g = grad_fn(w, vals, cols, rows, yb)
                w = w - 0.5 * g
                if first is None:
                    first = float(lv)
                last = float(lv)
        assert last < first * 0.7, (first, last)


class TestLibSVMIter:
    def _write(self, path, n=10, dim=8):
        rs = onp.random.RandomState(1)
        rows = []
        with open(path, "w") as f:
            for i in range(n):
                idx = onp.sort(rs.choice(dim, 3, replace=False))
                v = onp.round(rs.randn(3), 3)
                f.write(f"{i % 2}.0 " +
                        " ".join(f"{j}:{x}" for j, x in zip(idx, v)) + "\n")
                rows.append((idx, v))
        return rows

    def test_batches_and_views(self, tmp_path):
        path = str(tmp_path / "d.libsvm")
        rows = self._write(path, n=10, dim=8)
        it = mx.io.LibSVMIter(data_libsvm=path, data_shape=(8,),
                              batch_size=4)
        b = next(it)
        csr = b.data[0]
        assert isinstance(csr, sparse.CSRNDArray) and csr.shape == (4, 8)
        dense = csr.asnumpy()
        for r in range(4):
            want = onp.zeros(8, "float32")
            idx, v = rows[r]
            want[idx] = v
            onp.testing.assert_allclose(dense[r], want, rtol=1e-5)
        onp.testing.assert_allclose(b.label[0].asnumpy(), [0, 1, 0, 1])

    def test_round_batch_pad(self, tmp_path):
        path = str(tmp_path / "d.libsvm")
        self._write(path, n=10, dim=8)
        it = mx.io.LibSVMIter(data_libsvm=path, data_shape=(8,),
                              batch_size=4)
        batches = list(it)
        assert len(batches) == 3
        assert batches[-1].pad == 2  # 10 rows -> last batch wraps 2
        it.reset()
        assert len(list(it)) == 3


class TestRowSparse:
    def test_views_and_retain(self):
        a = mx.nd.array(_dense()).tostype("row_sparse")
        assert a.stype == "row_sparse"
        onp.testing.assert_array_equal(a.indices.asnumpy(), [0, 2])
        onp.testing.assert_allclose(a.values.asnumpy(),
                                    _dense()[[0, 2]])
        kept = a.retain(mx.nd.array([0.0]))
        want = _dense().copy()
        want[2] = 0
        onp.testing.assert_allclose(kept.asnumpy(), want)

    def test_from_values_indices(self):
        vals = onp.ones((2, 3), "float32")
        a = sparse.row_sparse_array((vals, [1, 3]), shape=(5, 3))
        want = onp.zeros((5, 3), "float32")
        want[[1, 3]] = 1.0
        onp.testing.assert_allclose(a.asnumpy(), want)

    def test_zeros_and_bad_stype(self):
        z = sparse.zeros("row_sparse", (3, 2))
        assert z.stype == "row_sparse" and float(z.asnumpy().sum()) == 0
        with pytest.raises(MXNetError, match="storage type"):
            mx.nd.ones((2, 2)).tostype("bogus")


class TestKVStoreRowSparsePull:
    def test_row_sparse_pull_writes_requested_rows(self):
        # round 3: row_sparse_pull gathers ONLY the requested rows
        # (round 2 pulled the whole table — the dense-backed facade)
        from mxnet_tpu import kvstore as kv

        store = kv.create("local")
        store.init("emb", mx.nd.ones((6, 2)))
        out = mx.nd.zeros((6, 2))
        store.row_sparse_pull("emb", out, row_ids=mx.nd.array([0.0, 3.0]))
        got = out.asnumpy()
        onp.testing.assert_allclose(got[[0, 3]], onp.ones((2, 2)))
        onp.testing.assert_allclose(got[[1, 2, 4, 5]], onp.zeros((4, 2)))


class TestReviewRegressions:
    def test_array_reference_signature(self):
        src = mx.nd.array(_dense()).tostype("csr")
        out = sparse.array(src, mx.cpu())   # positional ctx must work
        assert out.stype == "csr"
        with pytest.raises(MXNetError, match="mx.nd.array"):
            sparse.array(onp.ones((2, 2)))
        out2 = sparse.array(onp.ones((2, 2)), stype="row_sparse")
        assert out2.stype == "row_sparse"


def test_sparse_add_and_random_gnb():
    import numpy as onp
    a = mx.nd.array([[1.0, 0.0], [0.0, 2.0]]).tostype("csr")
    b = mx.nd.ones((2, 2))
    got = sparse.add(a, b)
    assert got.stype == "default"  # csr + dense -> dense
    onp.testing.assert_allclose(got.asnumpy(), [[2, 1], [1, 3]])
    c = mx.nd.array([[0.0, 3.0], [0.0, 0.0]]).tostype("csr")
    same = sparse.add(a, c)
    assert same.stype == "csr"  # csr + csr keeps csr
    onp.testing.assert_allclose(sparse.elemwise_add(a, b).asnumpy(),
                                [[2, 1], [1, 3]])
    g = mx.random.generalized_negative_binomial(mu=3.0, alpha=0.2,
                                                shape=(2000,))
    assert abs(float(g.asnumpy().mean()) - 3.0) < 0.5
