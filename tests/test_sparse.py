"""Sparse surface tests (reference: tests/python/unittest/test_sparse_ndarray.py).

Dense-backed semantics per SURVEY.md §7.3.5: the API round-trips and the
views (indices/indptr/values) match scipy-style expectations."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError
from mxnet_tpu.ndarray import sparse


def _dense():
    d = onp.zeros((4, 5), "float32")
    d[0, 1] = 1.0
    d[0, 4] = 2.0
    d[2, 0] = 3.0
    return d


class TestCSR:
    def test_from_dense_and_views(self):
        a = mx.nd.array(_dense()).tostype("csr")
        assert a.stype == "csr" and isinstance(a, sparse.CSRNDArray)
        onp.testing.assert_array_equal(a.indices.asnumpy(), [1, 4, 0])
        onp.testing.assert_array_equal(a.indptr.asnumpy(), [0, 2, 2, 3, 3])
        onp.testing.assert_allclose(a.values.asnumpy(), [1.0, 2.0, 3.0])
        onp.testing.assert_allclose(a.asnumpy(), _dense())

    def test_from_aux_arrays(self):
        a = sparse.csr_matrix(([1.0, 2.0, 3.0], [1, 4, 0],
                               [0, 2, 2, 3, 3]), shape=(4, 5))
        onp.testing.assert_allclose(a.asnumpy(), _dense())

    def test_tostype_round_trip(self):
        a = mx.nd.array(_dense()).tostype("csr")
        back = a.tostype("default")
        assert back.stype == "default"
        onp.testing.assert_allclose(back.asnumpy(), _dense())

    def test_csr_requires_2d(self):
        with pytest.raises(MXNetError, match="2-D"):
            mx.nd.ones((2, 3, 4)).tostype("csr")

    def test_dot_with_dense(self):
        a = sparse.csr_matrix(_dense())
        b = mx.nd.array(onp.arange(10.0).reshape(5, 2).astype("float32"))
        out = sparse.dot(a, b)
        onp.testing.assert_allclose(out.asnumpy(), _dense() @ b.asnumpy())


class TestRowSparse:
    def test_views_and_retain(self):
        a = mx.nd.array(_dense()).tostype("row_sparse")
        assert a.stype == "row_sparse"
        onp.testing.assert_array_equal(a.indices.asnumpy(), [0, 2])
        onp.testing.assert_allclose(a.values.asnumpy(),
                                    _dense()[[0, 2]])
        kept = a.retain(mx.nd.array([0.0]))
        want = _dense().copy()
        want[2] = 0
        onp.testing.assert_allclose(kept.asnumpy(), want)

    def test_from_values_indices(self):
        vals = onp.ones((2, 3), "float32")
        a = sparse.row_sparse_array((vals, [1, 3]), shape=(5, 3))
        want = onp.zeros((5, 3), "float32")
        want[[1, 3]] = 1.0
        onp.testing.assert_allclose(a.asnumpy(), want)

    def test_zeros_and_bad_stype(self):
        z = sparse.zeros("row_sparse", (3, 2))
        assert z.stype == "row_sparse" and float(z.asnumpy().sum()) == 0
        with pytest.raises(MXNetError, match="storage type"):
            mx.nd.ones((2, 2)).tostype("bogus")


class TestKVStoreRowSparsePull:
    def test_row_sparse_pull_writes_requested_rows(self):
        # round 3: row_sparse_pull gathers ONLY the requested rows
        # (round 2 pulled the whole table — the dense-backed facade)
        from mxnet_tpu import kvstore as kv

        store = kv.create("local")
        store.init("emb", mx.nd.ones((6, 2)))
        out = mx.nd.zeros((6, 2))
        store.row_sparse_pull("emb", out, row_ids=mx.nd.array([0.0, 3.0]))
        got = out.asnumpy()
        onp.testing.assert_allclose(got[[0, 3]], onp.ones((2, 2)))
        onp.testing.assert_allclose(got[[1, 2, 4, 5]], onp.zeros((4, 2)))


class TestReviewRegressions:
    def test_array_reference_signature(self):
        src = mx.nd.array(_dense()).tostype("csr")
        out = sparse.array(src, mx.cpu())   # positional ctx must work
        assert out.stype == "csr"
        with pytest.raises(MXNetError, match="mx.nd.array"):
            sparse.array(onp.ones((2, 2)))
        out2 = sparse.array(onp.ones((2, 2)), stype="row_sparse")
        assert out2.stype == "row_sparse"
