"""Optimizer tests vs numpy reference implementations
(reference: tests/python/unittest/test_optimizer.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import optimizer as opt
from mxnet_tpu import telemetry as telemetry_mod


def _setup(shape=(4, 3), seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(*shape).astype("float32")
    g = rng.randn(*shape).astype("float32")
    return w, g, mx.nd.array(w), mx.nd.array(g)


def test_sgd_matches_numpy():
    w, g, wnd, gnd = _setup()
    o = opt.create("sgd", learning_rate=0.1, wd=0.01, rescale_grad=1.0)
    state = o.create_state(0, wnd)
    o.update(0, wnd, gnd, state)
    expect = w - 0.1 * (g + 0.01 * w)
    assert np.allclose(wnd.asnumpy(), expect, rtol=1e-5)


def test_sgd_momentum_matches_numpy():
    w, g, wnd, gnd = _setup()
    o = opt.create("sgd", learning_rate=0.1, momentum=0.9, wd=0.0)
    state = o.create_state(0, wnd)
    mom = np.zeros_like(w)
    for _ in range(3):
        o.update(0, wnd, gnd, state)
        mom = 0.9 * mom - 0.1 * g
        w = w + mom
    assert np.allclose(wnd.asnumpy(), w, rtol=1e-5)


def test_adam_matches_numpy():
    w, g, wnd, gnd = _setup()
    lr, b1, b2, eps = 0.01, 0.9, 0.999, 1e-8
    o = opt.create("adam", learning_rate=lr, beta1=b1, beta2=b2, epsilon=eps,
                   wd=0.0)
    state = o.create_state(0, wnd)
    m = np.zeros_like(w)
    v = np.zeros_like(w)
    for t in range(1, 4):
        o.update(0, wnd, gnd, state)
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        lr_t = lr * np.sqrt(1 - b2 ** t) / (1 - b1 ** t)
        w = w - lr_t * m / (np.sqrt(v) + eps)
    assert np.allclose(wnd.asnumpy(), w, rtol=1e-4, atol=1e-6)


def test_rmsprop_runs_and_descends():
    w, g, wnd, gnd = _setup()
    o = opt.create("rmsprop", learning_rate=0.01)
    state = o.create_state(0, wnd)
    before = np.abs(wnd.asnumpy()).sum()
    for _ in range(5):
        o.update(0, wnd, gnd, state)
    assert not np.allclose(wnd.asnumpy(), w)


@pytest.mark.parametrize("name", ["adagrad", "adadelta", "ftrl", "signum",
                                  "nag", "lamb", "adamw", "sgld", "dcasgd"])
def test_all_optimizers_update(name):
    w, g, wnd, gnd = _setup(seed=3)
    o = opt.create(name, **({"learning_rate": 0.05} if name != "adadelta" else {}))
    state = o.create_state_multi_precision(0, wnd)
    o.update_multi_precision(0, wnd, gnd, state)
    assert not np.allclose(wnd.asnumpy(), w), name
    assert np.all(np.isfinite(wnd.asnumpy())), name


def test_multi_precision_bf16():
    rng = np.random.RandomState(1)
    w = rng.randn(8, 8).astype("float32")
    wnd = mx.nd.array(w, dtype="bfloat16")
    gnd = mx.nd.array(rng.randn(8, 8), dtype="bfloat16")
    o = opt.create("sgd", learning_rate=0.1, momentum=0.9, multi_precision=True)
    state = o.create_state_multi_precision(0, wnd)
    # master weight is fp32
    assert str(state[0].dtype) == "float32"
    o.update_multi_precision(0, wnd, gnd, state)
    assert str(wnd.dtype) == "bfloat16"


def test_updater_state_roundtrip():
    w, g, wnd, gnd = _setup()
    o = opt.create("adam", learning_rate=0.01)
    upd = opt.get_updater(o)
    upd(0, gnd, wnd)
    states = upd.get_states()
    upd2 = opt.get_updater(opt.create("adam", learning_rate=0.01))
    upd2.set_states(states)
    assert 0 in upd2.states
    m1 = upd.states[0][0].asnumpy()
    m2 = upd2.states[0][0].asnumpy()
    assert np.allclose(m1, m2)


def test_lr_scheduler_factor():
    from mxnet_tpu.lr_scheduler import FactorScheduler, CosineScheduler

    s = FactorScheduler(step=10, factor=0.5, base_lr=1.0)
    assert s(1) == 1.0
    assert s(11) == 0.5
    assert s(21) == 0.25
    c = CosineScheduler(max_update=100, base_lr=1.0, final_lr=0.0)
    assert np.isclose(c(0), 1.0)
    assert np.isclose(c(50), 0.5, atol=1e-6)
    assert np.isclose(c(100), 0.0)


def test_lr_scheduler_warmup():
    from mxnet_tpu.lr_scheduler import PolyScheduler

    s = PolyScheduler(max_update=100, base_lr=1.0, warmup_steps=10,
                      warmup_begin_lr=0.0)
    assert s(5) == 0.5
    assert s(10) == 1.0


def test_optimizer_with_scheduler():
    from mxnet_tpu.lr_scheduler import FactorScheduler

    o = opt.create("sgd", learning_rate=1.0,
                   lr_scheduler=FactorScheduler(step=1, factor=0.5, base_lr=1.0))
    w, g, wnd, gnd = _setup()
    state = o.create_state(0, wnd)
    o.update(0, wnd, gnd, state)
    assert o.learning_rate < 1.0 or o.num_update == 1


def test_lr_mult_wd_mult():
    o = opt.create("sgd", learning_rate=1.0)
    o.set_lr_mult({0: 0.1})
    assert np.isclose(o._get_lr(0), 0.1)
    assert np.isclose(o._get_lr(1), 1.0)


# ---------------------------------------------------------------------------
# fused multi-tensor sweep engine (optimizer/multi_tensor.py)
# ---------------------------------------------------------------------------


def _train_eager(fused, optname, okw, monkeypatch, steps=10,
                 dtype="float32", mp=False, grad_req=None,
                 mixed_dtypes=False, double_backward=False):
    """One eager Trainer run; returns (loss bytes, weight bytes, state
    bytes) for bit-comparison between engine-on and engine-off."""
    import jax

    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.gluon.loss import L2Loss

    monkeypatch.setenv("MXNET_FUSED_OPTIMIZER", "1" if fused else "0")
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, in_units=32), nn.Dense(8, in_units=16))
    net.initialize()
    rs = np.random.RandomState(7)
    params = list(net.collect_params().values())
    for i, p in enumerate(params):
        cast = dtype
        if mixed_dtypes and i % 2 == 1:
            cast = "bfloat16" if dtype == "float32" else "float32"
        p.set_data(mx.nd.array(
            rs.randn(*p.shape).astype(np.float32)).astype(cast))
        if grad_req is not None and i == 1:
            p.grad_req = grad_req
    kw = dict(okw)
    if mp:
        kw["multi_precision"] = True
    tr = gluon.Trainer(net.collect_params(), optname, kw)
    loss_fn = L2Loss()
    rs2 = np.random.RandomState(11)
    x = mx.nd.array(rs2.randn(8, 32).astype(np.float32)).astype(dtype)
    y = mx.nd.array(rs2.randn(8, 8).astype(np.float32)).astype(dtype)
    losses = []
    for _ in range(steps):
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        if double_backward:
            # grad_req='add' accumulation: a second backward before the
            # step sums into the same grad buffers on both paths
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
        tr.step(8)
        losses.append(loss.asnumpy().tobytes())
    ws = [p.data().asnumpy().tobytes() for p in params]
    sts = []
    for upd in tr._updaters:
        for i in sorted(upd.states):
            for leaf in jax.tree_util.tree_leaves(
                    upd.states[i],
                    is_leaf=lambda z: z is None or hasattr(z, "asnumpy")):
                if leaf is not None:
                    sts.append(leaf.asnumpy().tobytes())
    return losses, ws, sts


class TestFusedSweepBitIdentity:
    """ISSUE 11 acceptance gate: the fused multi-tensor sweep is
    BIT-identical to the per-param reference (trained state over >= 10
    steps) for every fused family, multi-precision included."""

    @pytest.mark.parametrize("optname,okw,dtype,mp", [
        ("adam", {"learning_rate": 0.01}, "float32", False),
        ("adam", {"learning_rate": 0.01}, "bfloat16", True),
        ("sgd", {"learning_rate": 0.05, "momentum": 0.9}, "float32",
         False),
        ("sgd", {"learning_rate": 0.05, "momentum": 0.9, "wd": 1e-4},
         "bfloat16", True),
        ("adamw", {"learning_rate": 0.01, "wd": 0.01}, "float32", False),
        ("lamb", {"learning_rate": 0.01, "wd": 0.01}, "float32", False),
        ("lamb", {"learning_rate": 0.01, "wd": 0.01, "lower_bound": 0.1,
                  "upper_bound": 10.0}, "float32", False),
        ("lamb", {"learning_rate": 0.01}, "bfloat16", True),
    ])
    def test_trainer_ten_steps_bit_identical(self, optname, okw, dtype,
                                             mp, monkeypatch):
        a = _train_eager(True, optname, okw, monkeypatch, dtype=dtype,
                         mp=mp)
        b = _train_eager(False, optname, okw, monkeypatch, dtype=dtype,
                         mp=mp)
        assert a[0] == b[0], "losses diverged"
        assert a[1] == b[1], "weights diverged"
        assert a[2] == b[2], "optimizer state diverged"

    def test_mixed_trainable_set(self, monkeypatch):
        """fp32 + bf16 params in one Trainer (two dtype buckets) plus a
        grad_req='null' param excluded from the sweep."""
        a = _train_eager(True, "adam", {"learning_rate": 0.01},
                         monkeypatch, mixed_dtypes=True, mp=True,
                         grad_req="null")
        b = _train_eager(False, "adam", {"learning_rate": 0.01},
                         monkeypatch, mixed_dtypes=True, mp=True,
                         grad_req="null")
        assert a[1] == b[1] and a[2] == b[2]

    def test_grad_req_add_accumulation(self, monkeypatch):
        a = _train_eager(True, "adam", {"learning_rate": 0.01},
                         monkeypatch, grad_req="add", steps=5,
                         double_backward=True)
        b = _train_eager(False, "adam", {"learning_rate": 0.01},
                         monkeypatch, grad_req="add", steps=5,
                         double_backward=True)
        assert a[1] == b[1] and a[2] == b[2]

    def test_states_roundtrip_through_save_load(self, monkeypatch,
                                                tmp_path):
        """Fused-engine updater states stay in the Updater layout —
        save_states/load_states round-trips unchanged."""
        from mxnet_tpu import autograd, gluon
        from mxnet_tpu.gluon import nn
        from mxnet_tpu.gluon.loss import L2Loss

        monkeypatch.setenv("MXNET_FUSED_OPTIMIZER", "1")
        net = nn.Dense(8, in_units=16)
        net.initialize()
        tr = gluon.Trainer(net.collect_params(), "adam",
                           {"learning_rate": 0.01})
        x = mx.nd.array(np.random.RandomState(0).randn(4, 16)
                        .astype(np.float32))
        y = mx.nd.array(np.random.RandomState(1).randn(4, 8)
                        .astype(np.float32))
        loss_fn = L2Loss()
        for _ in range(3):
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            tr.step(4)
        f = str(tmp_path / "trainer.states")
        tr.save_states(f)
        net2 = nn.Dense(8, in_units=16)
        net2.initialize()
        tr2 = gluon.Trainer(net2.collect_params(), "adam",
                            {"learning_rate": 0.01})
        with autograd.record():
            loss = loss_fn(net2(x), y)
        loss.backward()
        tr2.step(4)     # materialize states
        tr2.load_states(f)
        m1 = tr._updaters[0].states[0][0].asnumpy()
        m2 = tr2._updaters[0].states[0][0].asnumpy()
        assert np.array_equal(m1, m2)
        assert tr2._optimizer.num_update == tr._optimizer.num_update


class TestFusedSweepDispatchCount:
    """ISSUE 11 acceptance gate: the eager optimizer phase collapses
    from O(params) dispatches to <= 2 per dtype bucket (LAMB: 3 — the
    reference's own phase1 / multi_sum_sq / phase2 kernel granularity,
    required for bit-identity; see _LambSweep)."""

    @staticmethod
    def _counts():
        snap = telemetry_mod.snapshot()
        fam = snap["metrics"].get("mxnet_optimizer_dispatch_total",
                                  {"samples": []})
        return {s["labels"]["path"]: s["value"] for s in fam["samples"]}

    def _one_step(self, optname, monkeypatch, fused, n_params=3,
                  mixed=False):
        from mxnet_tpu import autograd, gluon
        from mxnet_tpu.gluon import nn
        from mxnet_tpu.gluon.loss import L2Loss

        monkeypatch.setenv("MXNET_FUSED_OPTIMIZER",
                           "1" if fused else "0")
        net = nn.HybridSequential()
        units = 16
        for i in range(n_params):
            net.add(nn.Dense(units, in_units=units, use_bias=False))
        net.initialize()
        if mixed:
            net[0].cast("bfloat16")     # second dtype bucket (bf16-mp)
        tr = gluon.Trainer(net.collect_params(), optname,
                           {"learning_rate": 0.01,
                            "multi_precision": mixed})
        x = mx.nd.ones((4, units))
        loss_fn = L2Loss()
        with autograd.record():
            loss = loss_fn(net(x), mx.nd.zeros((4, units)))
        loss.backward()
        tr.step(4)      # states created + first sweep compiled
        telemetry_mod.enable()
        try:
            before = self._counts()
            with autograd.record():
                loss = loss_fn(net(x), mx.nd.zeros((4, units)))
            loss.backward()
            tr.step(4)
            after = self._counts()
            # counters are process-global: report this step's DELTA
            return {k: after.get(k, 0) - before.get(k, 0)
                    for k in set(after) | set(before)}
        finally:
            telemetry_mod.disable()

    def test_adam_one_dispatch_per_bucket(self, monkeypatch):
        counts = self._one_step("adam", monkeypatch, fused=True,
                                n_params=6)
        assert counts.get("fused_sweep", 0) == 1     # one fp32 bucket
        assert counts.get("per_param", 0) == 0

    def test_two_dtype_buckets_two_dispatches(self, monkeypatch):
        counts = self._one_step("adam", monkeypatch, fused=True,
                                n_params=4, mixed=True)
        assert counts.get("fused_sweep", 0) == 2     # bf16-mp + fp32
        assert counts.get("per_param", 0) == 0

    def test_lamb_three_dispatches_per_bucket(self, monkeypatch):
        counts = self._one_step("lamb", monkeypatch, fused=True,
                                n_params=5)
        assert counts.get("fused_sweep", 0) == 3
        assert counts.get("per_param", 0) == 0

    def test_per_param_path_counts_o_params(self, monkeypatch):
        counts = self._one_step("adam", monkeypatch, fused=False,
                                n_params=6)
        assert counts.get("fused_sweep", 0) == 0
        assert counts.get("per_param", 0) == 6

    def test_bucket_telemetry_recorded(self, monkeypatch):
        from mxnet_tpu import autograd, gluon
        from mxnet_tpu.gluon import nn
        from mxnet_tpu.gluon.loss import L2Loss

        monkeypatch.setenv("MXNET_FUSED_OPTIMIZER", "1")
        net = nn.Dense(8, in_units=8)
        net.initialize()
        tr = gluon.Trainer(net.collect_params(), "adam",
                           {"learning_rate": 0.01})
        x = mx.nd.ones((2, 8))
        loss_fn = L2Loss()

        def bucketed_params():
            snap = telemetry_mod.snapshot()
            fam = snap["metrics"].get(
                "mxnet_optimizer_bucketed_params_total", {"samples": []})
            return sum(s["value"] for s in fam["samples"])

        telemetry_mod.enable()
        try:
            before = bucketed_params()
            with autograd.record():
                loss = loss_fn(net(x), mx.nd.zeros((2, 8)))
            loss.backward()
            tr.step(2)
            assert bucketed_params() - before == 2   # weight + bias
            snap = telemetry_mod.snapshot()
            assert "mxnet_optimizer_bucket_bytes" in snap["metrics"]
        finally:
            telemetry_mod.disable()


class TestFusedSweepCompileOnce:
    """ISSUE 11 acceptance gate: the sweep compiles once per bucket
    signature (zero steady-state jit misses) and participates in
    warm_start() manifest replay."""

    @pytest.mark.retrace
    def test_steady_state_trainer_records_zero_sweep_misses(
            self, monkeypatch):
        from mxnet_tpu import autograd, gluon
        from mxnet_tpu.gluon import nn
        from mxnet_tpu.gluon.loss import L2Loss

        monkeypatch.setenv("MXNET_FUSED_OPTIMIZER", "1")
        net = nn.HybridSequential()
        net.add(nn.Dense(16, in_units=32), nn.Dense(8, in_units=16))
        net.initialize()
        tr = gluon.Trainer(net.collect_params(), "adam",
                           {"learning_rate": 0.01})
        x = mx.nd.ones((4, 32))
        y = mx.nd.zeros((4, 8))
        loss_fn = L2Loss()

        def step():
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            tr.step(4)

        def sweep_stats():
            snap = telemetry_mod.snapshot()
            fam = snap["metrics"].get("mxnet_jit_cache_total",
                                      {"samples": []})
            return {s["labels"]["result"]: s["value"]
                    for s in fam["samples"]
                    if s["labels"]["cache"] == "optimizer_sweep"}

        step()      # warm: compile the sweep once
        telemetry_mod.enable()
        try:
            before = sweep_stats()
            for _ in range(3):
                step()
            after = sweep_stats()
            misses = after.get("miss", 0) - before.get("miss", 0)
            hits = after.get("hit", 0) - before.get("hit", 0)
            assert misses == 0, (before, after)
            assert hits >= 3
        finally:
            telemetry_mod.disable()

    def test_warm_start_replays_sweep_signature(self, monkeypatch,
                                                tmp_path):
        from mxnet_tpu import autograd, compiler, gluon
        from mxnet_tpu.gluon import nn
        from mxnet_tpu.gluon.loss import L2Loss
        from mxnet_tpu.optimizer import multi_tensor as mt

        monkeypatch.setenv("MXNET_FUSED_OPTIMIZER", "1")
        m = compiler.enable_recording(str(tmp_path / "m.jsonl"))
        try:
            def steps(n=2):
                mx.random.seed(0)
                net = nn.Dense(16, in_units=32)
                net.initialize()
                tr = gluon.Trainer(net.collect_params(), "adam",
                                   {"learning_rate": 0.01})
                x = mx.nd.array(np.random.RandomState(1).randn(8, 32)
                                .astype(np.float32))
                y = mx.nd.array(np.random.RandomState(2).randn(8, 16)
                                .astype(np.float32))
                loss_fn = L2Loss()
                for _ in range(n):
                    with autograd.record():
                        loss = loss_fn(net(x), y)
                    loss.backward()
                    tr.step(8)
                return loss.asnumpy()

            ref = steps()
            # reload FROM DISK (not the live recorder): the loader's
            # KNOWN_SITES filter must accept optimizer_sweep entries —
            # the path a real fresh process takes
            reloaded = compiler.Manifest(str(tmp_path / "m.jsonl"))
            assert any(e["site"] == "optimizer_sweep"
                       for e in reloaded.entries())
            # fresh-process proxy: clear the sweep cache, replay the
            # on-disk manifest with NO provider, then train with zero
            # misses
            mt.sweep_cache().clear()
            report = compiler.warm_start(str(tmp_path / "m.jsonl"))
            assert report["failed"] == 0

            def sweep_misses():
                snap = telemetry_mod.snapshot()
                fam = snap["metrics"].get("mxnet_jit_cache_total",
                                          {"samples": []})
                return sum(s["value"] for s in fam["samples"]
                           if s["labels"]["cache"] == "optimizer_sweep"
                           and s["labels"]["result"] == "miss")

            telemetry_mod.enable()
            try:
                before = sweep_misses()
                out = steps()
                assert sweep_misses() - before == 0
            finally:
                telemetry_mod.disable()
            assert out.tobytes() == ref.tobytes()
        finally:
            compiler.disable_recording()


class TestFusedSweepTrainStep:
    """TrainStep integration: the traced update phase routes through the
    packed sweep only when the Pallas kernel engages (TPU +
    MXNET_PALLAS_FUSED); off-kernel the per-param loop is kept, so the
    knob cannot change CPU numerics."""

    def _run_step(self, monkeypatch, fused, steps=5, force_kernel=False,
                  optname="adam"):
        import jax

        from mxnet_tpu import parallel as par
        from mxnet_tpu.gluon import nn
        from mxnet_tpu.gluon.loss import L2Loss

        monkeypatch.setenv("MXNET_FUSED_OPTIMIZER",
                           "1" if fused else "0")
        if force_kernel:
            from mxnet_tpu.pallas_kernels import fused_optimizer as fopt

            orig = fopt.sweep_pallas
            monkeypatch.setattr(fopt, "fused_opt_supported",
                                lambda p: True)
            monkeypatch.setattr(
                fopt, "sweep_pallas",
                lambda fn, static, flats, vecs, scalars, outs,
                interpret=False: orig(fn, static, flats, vecs, scalars,
                                      outs, interpret=True))
        mx.random.seed(0)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, in_units=32), nn.Dense(8, in_units=16))
        net.initialize()
        rs = np.random.RandomState(7)
        for p in net.collect_params().values():
            p.set_data(mx.nd.array(rs.randn(*p.shape)
                                   .astype(np.float32)))
        mesh = par.make_mesh({"dp": 1}, devices=jax.devices()[:1])
        step = par.TrainStep(net, L2Loss(), optname, mesh=mesh,
                             optimizer_params={"learning_rate": 0.01})
        rs2 = np.random.RandomState(11)
        x = mx.nd.array(rs2.randn(8, 32).astype(np.float32))
        y = mx.nd.array(rs2.randn(8, 8).astype(np.float32))
        for _ in range(steps):
            loss, _ = step(x, y)
        return (loss.asnumpy(),
                [p.data().asnumpy()
                 for p in net.collect_params().values()])

    def test_cpu_knob_identity(self, monkeypatch):
        a = self._run_step(monkeypatch, fused=True)
        b = self._run_step(monkeypatch, fused=False)
        assert np.array_equal(a[0], b[0])
        assert all(np.array_equal(x, y) for x, y in zip(a[1], b[1]))

    def test_kernel_route_trains_close_to_reference(self, monkeypatch):
        """Forced kernel routing (interpret mode — the CPU oracle of the
        TPU path): the packed sweep runs inside the jitted step and the
        trained state stays within the kernels' documented
        FMA-contraction tolerance of the per-param reference."""
        a = self._run_step(monkeypatch, fused=True, force_kernel=True)
        b = self._run_step(monkeypatch, fused=False)
        assert np.isfinite(a[0]).all()
        for x, y in zip(a[1], b[1]):
            np.testing.assert_allclose(x, y, rtol=2e-4, atol=1e-6)

    def test_kernel_route_records_pallas_dispatch(self, monkeypatch):
        telemetry_mod.enable()
        try:
            self._run_step(monkeypatch, fused=True, steps=1,
                           force_kernel=True)
            snap = telemetry_mod.snapshot()
            fam = snap["metrics"].get("mxnet_pallas_dispatch_total",
                                      {"samples": []})
            kernels = {s["labels"]["kernel"]: s["value"]
                       for s in fam["samples"]}
            assert kernels.get("fused_opt_sweep", 0) >= 1
        finally:
            telemetry_mod.disable()

    def test_row_sparse_params_stay_on_lazy_path(self, monkeypatch):
        """Row-sparse embedding grads keep the lazy-row update even with
        the fused sweep routed: dense params sweep, the embedding's
        untouched rows stay bit-identical."""
        import jax

        from mxnet_tpu import parallel as par
        from mxnet_tpu.gluon import nn
        from mxnet_tpu.gluon.loss import L2Loss

        def build():
            mx.random.seed(0)
            net = nn.HybridSequential()
            net.add(nn.Embedding(50, 16, sparse_grad=True),
                    nn.Dense(8, in_units=16, flatten=False))
            net.initialize()
            rs = np.random.RandomState(3)
            for p in net.collect_params().values():
                p.set_data(mx.nd.array(rs.randn(*p.shape)
                                       .astype(np.float32)))
            return net

        def run(force_kernel):
            monkeypatch.setenv("MXNET_FUSED_OPTIMIZER", "1")
            if force_kernel:
                from mxnet_tpu.pallas_kernels import \
                    fused_optimizer as fopt

                orig = fopt.sweep_pallas
                monkeypatch.setattr(fopt, "fused_opt_supported",
                                    lambda p: True)
                monkeypatch.setattr(
                    fopt, "sweep_pallas",
                    lambda fn, static, flats, vecs, scalars, outs,
                    interpret=False: orig(fn, static, flats, vecs,
                                          scalars, outs,
                                          interpret=True))
            net = build()
            mesh = par.make_mesh({"dp": 1},
                                 devices=jax.devices()[:1])
            step = par.TrainStep(net, L2Loss(), "adam", mesh=mesh,
                                 optimizer_params={
                                     "learning_rate": 0.01})
            ids = mx.nd.array(np.array([[1, 2, 3, 1]], np.float32))
            y = mx.nd.array(np.zeros((1, 4, 8), np.float32))
            for _ in range(3):
                loss, _ = step(ids, y)
            emb = list(net.collect_params().values())[0]
            return emb.data().asnumpy(), loss.asnumpy()

        emb_k, loss_k = run(force_kernel=True)
        monkeypatch.setenv("MXNET_PALLAS_FUSED", "0")
        emb_r, loss_r = run(force_kernel=False)
        # untouched rows identical on both paths (no dense sweep over
        # the full table); touched rows updated
        init = np.zeros_like(emb_r)
        mx.random.seed(0)
        rs = np.random.RandomState(3)
        init = rs.randn(*emb_r.shape).astype(np.float32)
        untouched = [r for r in range(50) if r not in (1, 2, 3)]
        assert np.array_equal(emb_k[untouched], init[untouched])
        assert np.array_equal(emb_r[untouched], init[untouched])
        assert not np.allclose(emb_k[[1, 2, 3]], init[[1, 2, 3]])
        np.testing.assert_allclose(emb_k, emb_r, rtol=2e-4, atol=1e-6)


class TestOptimizerTailClasses:
    """Round-4: FTML / Adamax / Nadam / LBSGD classes (reference
    optimizer.py tail). Gate: each drives a quadratic to ~zero."""

    @pytest.mark.parametrize("name,kw", [
        ("ftml", {"learning_rate": 0.05}),
        ("adamax", {"learning_rate": 0.05}),
        ("nadam", {"learning_rate": 0.05}),
        ("lbsgd", {"learning_rate": 0.1, "eta": 1.0}),
    ])
    def test_quadratic_converges(self, name, kw):
        opt = mx.optimizer.create(name, **kw)
        w = mx.nd.array([1.0, -2.0])
        state = opt.create_state(0, w)
        for _ in range(150):
            opt.update(0, w, 2 * w, state)
        assert float((w.asnumpy() ** 2).sum()) < 0.5, name
