"""Optimizer tests vs numpy reference implementations
(reference: tests/python/unittest/test_optimizer.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import optimizer as opt


def _setup(shape=(4, 3), seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(*shape).astype("float32")
    g = rng.randn(*shape).astype("float32")
    return w, g, mx.nd.array(w), mx.nd.array(g)


def test_sgd_matches_numpy():
    w, g, wnd, gnd = _setup()
    o = opt.create("sgd", learning_rate=0.1, wd=0.01, rescale_grad=1.0)
    state = o.create_state(0, wnd)
    o.update(0, wnd, gnd, state)
    expect = w - 0.1 * (g + 0.01 * w)
    assert np.allclose(wnd.asnumpy(), expect, rtol=1e-5)


def test_sgd_momentum_matches_numpy():
    w, g, wnd, gnd = _setup()
    o = opt.create("sgd", learning_rate=0.1, momentum=0.9, wd=0.0)
    state = o.create_state(0, wnd)
    mom = np.zeros_like(w)
    for _ in range(3):
        o.update(0, wnd, gnd, state)
        mom = 0.9 * mom - 0.1 * g
        w = w + mom
    assert np.allclose(wnd.asnumpy(), w, rtol=1e-5)


def test_adam_matches_numpy():
    w, g, wnd, gnd = _setup()
    lr, b1, b2, eps = 0.01, 0.9, 0.999, 1e-8
    o = opt.create("adam", learning_rate=lr, beta1=b1, beta2=b2, epsilon=eps,
                   wd=0.0)
    state = o.create_state(0, wnd)
    m = np.zeros_like(w)
    v = np.zeros_like(w)
    for t in range(1, 4):
        o.update(0, wnd, gnd, state)
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        lr_t = lr * np.sqrt(1 - b2 ** t) / (1 - b1 ** t)
        w = w - lr_t * m / (np.sqrt(v) + eps)
    assert np.allclose(wnd.asnumpy(), w, rtol=1e-4, atol=1e-6)


def test_rmsprop_runs_and_descends():
    w, g, wnd, gnd = _setup()
    o = opt.create("rmsprop", learning_rate=0.01)
    state = o.create_state(0, wnd)
    before = np.abs(wnd.asnumpy()).sum()
    for _ in range(5):
        o.update(0, wnd, gnd, state)
    assert not np.allclose(wnd.asnumpy(), w)


@pytest.mark.parametrize("name", ["adagrad", "adadelta", "ftrl", "signum",
                                  "nag", "lamb", "adamw", "sgld", "dcasgd"])
def test_all_optimizers_update(name):
    w, g, wnd, gnd = _setup(seed=3)
    o = opt.create(name, **({"learning_rate": 0.05} if name != "adadelta" else {}))
    state = o.create_state_multi_precision(0, wnd)
    o.update_multi_precision(0, wnd, gnd, state)
    assert not np.allclose(wnd.asnumpy(), w), name
    assert np.all(np.isfinite(wnd.asnumpy())), name


def test_multi_precision_bf16():
    rng = np.random.RandomState(1)
    w = rng.randn(8, 8).astype("float32")
    wnd = mx.nd.array(w, dtype="bfloat16")
    gnd = mx.nd.array(rng.randn(8, 8), dtype="bfloat16")
    o = opt.create("sgd", learning_rate=0.1, momentum=0.9, multi_precision=True)
    state = o.create_state_multi_precision(0, wnd)
    # master weight is fp32
    assert str(state[0].dtype) == "float32"
    o.update_multi_precision(0, wnd, gnd, state)
    assert str(wnd.dtype) == "bfloat16"


def test_updater_state_roundtrip():
    w, g, wnd, gnd = _setup()
    o = opt.create("adam", learning_rate=0.01)
    upd = opt.get_updater(o)
    upd(0, gnd, wnd)
    states = upd.get_states()
    upd2 = opt.get_updater(opt.create("adam", learning_rate=0.01))
    upd2.set_states(states)
    assert 0 in upd2.states
    m1 = upd.states[0][0].asnumpy()
    m2 = upd2.states[0][0].asnumpy()
    assert np.allclose(m1, m2)


def test_lr_scheduler_factor():
    from mxnet_tpu.lr_scheduler import FactorScheduler, CosineScheduler

    s = FactorScheduler(step=10, factor=0.5, base_lr=1.0)
    assert s(1) == 1.0
    assert s(11) == 0.5
    assert s(21) == 0.25
    c = CosineScheduler(max_update=100, base_lr=1.0, final_lr=0.0)
    assert np.isclose(c(0), 1.0)
    assert np.isclose(c(50), 0.5, atol=1e-6)
    assert np.isclose(c(100), 0.0)


def test_lr_scheduler_warmup():
    from mxnet_tpu.lr_scheduler import PolyScheduler

    s = PolyScheduler(max_update=100, base_lr=1.0, warmup_steps=10,
                      warmup_begin_lr=0.0)
    assert s(5) == 0.5
    assert s(10) == 1.0


def test_optimizer_with_scheduler():
    from mxnet_tpu.lr_scheduler import FactorScheduler

    o = opt.create("sgd", learning_rate=1.0,
                   lr_scheduler=FactorScheduler(step=1, factor=0.5, base_lr=1.0))
    w, g, wnd, gnd = _setup()
    state = o.create_state(0, wnd)
    o.update(0, wnd, gnd, state)
    assert o.learning_rate < 1.0 or o.num_update == 1


def test_lr_mult_wd_mult():
    o = opt.create("sgd", learning_rate=1.0)
    o.set_lr_mult({0: 0.1})
    assert np.isclose(o._get_lr(0), 0.1)
    assert np.isclose(o._get_lr(1), 1.0)


class TestOptimizerTailClasses:
    """Round-4: FTML / Adamax / Nadam / LBSGD classes (reference
    optimizer.py tail). Gate: each drives a quadratic to ~zero."""

    @pytest.mark.parametrize("name,kw", [
        ("ftml", {"learning_rate": 0.05}),
        ("adamax", {"learning_rate": 0.05}),
        ("nadam", {"learning_rate": 0.05}),
        ("lbsgd", {"learning_rate": 0.1, "eta": 1.0}),
    ])
    def test_quadratic_converges(self, name, kw):
        opt = mx.optimizer.create(name, **kw)
        w = mx.nd.array([1.0, -2.0])
        state = opt.create_state(0, w)
        for _ in range(150):
            opt.update(0, w, 2 * w, state)
        assert float((w.asnumpy() ** 2).sum()) < 0.5, name
