"""Fault-tolerance subsystem tests (mxnet_tpu/fault.py, checkpoint.py,
kvstore retry, Trainer anomaly guard).

The acceptance contract this file proves:

* injection points are zero-cost when disabled (no behavior change with
  MXNET_FAULT_SPEC unset);
* a training run with injected fail-once collective faults completes
  with results identical to a fault-free run (retry absorbs the fault);
* exhausted retries raise MXNetError naming the site and attempt count;
* a kill during checkpoint write leaves the previous checkpoint the
  newest valid one, and resume from a bundle is bit-exact for params +
  optimizer state + RNG;
* a NaN step is skipped and counted, composing with the AMP loss
  scaler instead of fighting it.
"""
import os
import pickle

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, checkpoint, fault, gluon, telemetry
from mxnet_tpu.gluon import nn

pytestmark = pytest.mark.fault


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def make_net(seed=42):
    mx.random.seed(seed)
    net = nn.Dense(4, in_units=8)
    net.initialize(mx.init.Xavier())
    return net


def make_batch():
    x = mx.nd.array(np.random.RandomState(0).randn(8, 8).astype(np.float32))
    y = mx.nd.array(np.random.RandomState(1).randn(8, 4).astype(np.float32))
    return x, y


def train_step(net, trainer, x, y, batch_size=8):
    with autograd.record():
        loss = ((net(x) - y) ** 2).mean()
    loss.backward()
    trainer.step(batch_size)
    return float(loss.asnumpy())


def run_training(steps=4, seed=42, optimizer="adam", kvstore="tpu_sync"):
    net = make_net(seed)
    trainer = gluon.Trainer(net.collect_params(), optimizer,
                            {"learning_rate": 0.01}, kvstore=kvstore)
    x, y = make_batch()
    losses = [train_step(net, trainer, x, y) for _ in range(steps)]
    return net, trainer, losses


# ---------------------------------------------------------------------------
# spec grammar / framework
# ---------------------------------------------------------------------------

class TestSpecGrammar:
    def test_policies_parse(self):
        spec = fault.parse_spec(
            "engine.dispatch=latency:0.001;kvstore.push=once;"
            "kvstore.allreduce=every:3;checkpoint.write=nth:2;*=p:0.25")
        assert set(spec) == {"engine.dispatch", "kvstore.push",
                             "kvstore.allreduce", "checkpoint.write", "*"}
        assert spec["kvstore.push"].kind == "once"
        assert spec["kvstore.allreduce"].arg == 3
        assert spec["checkpoint.write"].arg == 2
        assert spec["*"].arg == 0.25

    def test_unknown_site_rejected(self):
        with pytest.raises(mx.MXNetError, match="unknown fault site"):
            fault.parse_spec("kvstore.push2=once")

    def test_bad_policy_rejected(self):
        with pytest.raises(mx.MXNetError, match="bad fault policy"):
            fault.parse_spec("kvstore.push=sometimes")
        with pytest.raises(mx.MXNetError, match="bad fault policy"):
            fault.parse_spec("kvstore.push=p:1.5")
        with pytest.raises(mx.MXNetError, match="bad fault policy"):
            fault.parse_spec("kvstore.push=every:0")

    def test_missing_equals_rejected(self):
        with pytest.raises(mx.MXNetError, match="site=policy"):
            fault.parse_spec("kvstore.push")

    def test_inject_scope_restores_state(self):
        assert not fault.active()
        with fault.inject("engine.dispatch=once"):
            assert fault.active()
        assert not fault.active()
        assert fault.stats() == {}

    def test_policy_semantics(self):
        # once: fires exactly on hit 1
        with fault.inject("engine.dispatch=once"):
            with pytest.raises(fault.FaultInjected):
                fault.check("engine.dispatch")
            for _ in range(5):
                fault.check("engine.dispatch")
        # nth:3 fires exactly on hit 3
        with fault.inject("engine.dispatch=nth:3"):
            fault.check("engine.dispatch")
            fault.check("engine.dispatch")
            with pytest.raises(fault.FaultInjected):
                fault.check("engine.dispatch")
            fault.check("engine.dispatch")
        # every:2 fires on hits 2, 4, ...
        with fault.inject("engine.dispatch=every:2") as stats:
            fired = 0
            for _ in range(6):
                try:
                    fault.check("engine.dispatch")
                except fault.FaultInjected:
                    fired += 1
            assert fired == 3
            assert stats()["engine.dispatch"]["injected"] == 3

    def test_probabilistic_is_seeded(self):
        def run(seed):
            fired = []
            with fault.inject("engine.dispatch=p:0.5", seed=seed):
                for i in range(64):
                    try:
                        fault.check("engine.dispatch")
                        fired.append(0)
                    except fault.FaultInjected:
                        fired.append(1)
            return fired
        a, b, c = run(7), run(7), run(8)
        assert a == b          # deterministic per seed
        assert a != c          # and the seed matters
        assert 0 < sum(a) < 64

    def test_wildcard_site(self):
        with fault.inject("*=once"):
            with pytest.raises(fault.FaultInjected):
                fault.check("kvstore.pull")

    def test_latency_injects_no_error(self):
        import time

        telemetry.reset()
        telemetry.enable()
        try:
            with fault.inject("engine.dispatch=latency:0.01") as stats:
                t0 = time.perf_counter()
                (mx.nd.ones((2,)) + 1).asnumpy()
                dt = time.perf_counter() - t0
                assert stats()["engine.dispatch"]["injected"] >= 1
                assert dt >= 0.01
            # latency injections count in the telemetry too, not only
            # in fault.stats()
            samples = telemetry.snapshot()["metrics"][
                "mxnet_fault_injected_total"]["samples"]
            assert samples[0]["labels"] == {"site": "engine.dispatch"}
            assert samples[0]["value"] >= 1
        finally:
            telemetry.disable()
            telemetry.reset()


class TestZeroCostWhenDisabled:
    def test_disabled_flag_is_single_branch_state(self):
        # the call-site contract: one attribute load on one stable object
        assert fault._state.enabled is False
        assert fault.active() is False

    def test_no_behavior_change_with_spec_unset(self):
        """MXNET_FAULT_SPEC unset: training twice (same seed) with the
        whole fault-tolerance stack in place is bit-identical — the
        instrumented hot paths change nothing when injection is off."""
        assert "MXNET_FAULT_SPEC" not in os.environ
        net1, _, losses1 = run_training(steps=3)
        net2, _, losses2 = run_training(steps=3)
        assert losses1 == losses2
        assert np.array_equal(net1.weight.data().asnumpy(),
                              net2.weight.data().asnumpy())

    def test_check_noop_when_disabled(self):
        fault.check("engine.dispatch")  # no spec, disabled: must no-op


# ---------------------------------------------------------------------------
# comms retry / backoff
# ---------------------------------------------------------------------------

class TestCommsRetry:
    def test_fail_once_allreduce_recovers(self):
        """A transient collective failure is absorbed by the retry: the
        reduced value is identical to the fault-free one."""
        import jax

        if len(jax.devices()) < 2:
            pytest.skip("needs >= 2 devices")
        grads_np = [np.full((4,), float(i + 1), np.float32)
                    for i in range(2)]

        def push_pull(spec):
            store = mx.kv.create("tpu_sync")
            store.init(0, mx.nd.zeros((4,)))
            grads = [mx.nd.array(g).as_in_context(mx.Context("cpu", i))
                     for i, g in enumerate(grads_np)]
            if spec:
                with fault.inject(spec) as stats:
                    store.push(0, grads)
                    st = stats()
            else:
                store.push(0, grads)
                st = None
            out = mx.nd.zeros((4,))
            store.pull(0, out)
            return out.asnumpy(), st

        clean, _ = push_pull(None)
        faulty, st = push_pull("kvstore.allreduce=once")
        assert st["kvstore.allreduce"]["injected"] == 1
        assert st["kvstore.allreduce"]["hits"] >= 2   # the retry
        np.testing.assert_array_equal(clean, faulty)

    def test_exhausted_retries_raise_with_attempt_count(self):
        store = mx.kv.create("tpu_sync")
        store.init(7, mx.nd.zeros((4,)))
        grads = [mx.nd.ones((4,)).as_in_context(mx.Context("cpu", i))
                 for i in range(2)]
        with fault.inject("kvstore.allreduce=every:1"):
            with pytest.raises(mx.MXNetError,
                               match=r"kvstore\.allreduce.*failed after "
                                     r"3 attempt"):
                store.push(7, grads)

    def test_retry_attempt_knobs(self, monkeypatch):
        monkeypatch.setenv("MXNET_COMM_RETRY_ATTEMPTS", "5")
        monkeypatch.setenv("MXNET_COMM_RETRY_DELAY", "0")
        calls = []

        def flaky():
            calls.append(1)
            raise fault.FaultInjected("kvstore.push", len(calls))

        with pytest.raises(mx.MXNetError, match="after 5 attempt"):
            fault.retry_call("kvstore.push", flaky, detail="key 0")
        assert len(calls) == 5

    def test_retry_recovers_and_reports_detail(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise fault.FaultInjected("kvstore.pull", len(calls))
            return "ok"

        assert fault.retry_call("kvstore.pull", flaky, attempts=3,
                                base_delay=0) == "ok"

    def test_nontransient_error_not_retried(self):
        calls = []

        def broken():
            calls.append(1)
            raise ValueError("deterministic bug")

        with pytest.raises(ValueError):
            fault.retry_call("kvstore.push", broken, base_delay=0)
        assert len(calls) == 1   # no retry: would only mask the bug

    def test_push_fault_during_training_is_transparent(self):
        """Tentpole acceptance: training with an injected fail-once comms
        fault finishes IDENTICAL to the fault-free run."""
        clean_net, _, clean_losses = run_training(steps=3)
        with fault.inject("kvstore.push=once") as stats:
            faulty_net, _, faulty_losses = run_training(steps=3)
            assert stats()["kvstore.push"]["injected"] == 1
        assert clean_losses == faulty_losses
        assert np.array_equal(clean_net.weight.data().asnumpy(),
                              faulty_net.weight.data().asnumpy())

    def test_retry_telemetry(self):
        telemetry.reset()
        telemetry.enable()
        try:
            with fault.inject("kvstore.allreduce=once"):
                store = mx.kv.create("tpu_sync")
                store.init(0, mx.nd.zeros((4,)))
                store.push(0, [mx.nd.ones((4,)).as_in_context(
                    mx.Context("cpu", i)) for i in range(2)])
            snap = telemetry.snapshot()["metrics"]
            retries = {tuple(s["labels"].items()): s["value"]
                       for s in snap["mxnet_retry_total"]["samples"]}
            assert retries[(("site", "kvstore.allreduce"),
                            ("outcome", "retry"))] == 1
            assert retries[(("site", "kvstore.allreduce"),
                            ("outcome", "recovered"))] == 1
            faults = snap["mxnet_fault_injected_total"]["samples"]
            assert faults[0]["labels"] == {"site": "kvstore.allreduce"}
            assert faults[0]["value"] == 1
        finally:
            telemetry.disable()
            telemetry.reset()


# ---------------------------------------------------------------------------
# engine dispatch site
# ---------------------------------------------------------------------------

class TestEngineDispatchSite:
    def test_dispatch_fault_propagates_deterministically(self):
        a = mx.nd.ones((4,))
        with fault.inject("engine.dispatch=nth:2"):
            b = a + 1                          # hit 1: passes
            with pytest.raises(fault.FaultInjected, match="engine.dispatch"):
                _ = a * 2                      # hit 2: fires
            c = a - 1                          # hit 3: passes again
        np.testing.assert_array_equal(b.asnumpy(), np.full((4,), 2.0))
        np.testing.assert_array_equal(c.asnumpy(), np.zeros((4,)))


# ---------------------------------------------------------------------------
# crash-safe checkpointing
# ---------------------------------------------------------------------------

class TestCheckpointManager:
    def test_atomic_write_never_tears(self, tmp_path):
        p = tmp_path / "f.bin"
        checkpoint.atomic_write(str(p), b"old-content")
        with fault.inject("checkpoint.write=once"):
            with pytest.raises(fault.FaultInjected):
                checkpoint.atomic_write(str(p), b"new-content")
        assert p.read_bytes() == b"old-content"
        assert [f for f in os.listdir(tmp_path) if ".tmp" in f] == []

    def test_save_load_roundtrip_bit_exact(self, tmp_path):
        net, trainer, _ = run_training(steps=3)
        mgr = checkpoint.CheckpointManager(str(tmp_path), keep_last=3)
        path = mgr.save(3, params=net, trainer=trainer, epoch=1,
                        extra={"lr": 0.01})
        assert mgr.latest_step() == 3 and mgr.is_valid(3)

        # reference: continue the ORIGINAL run
        x, y = make_batch()
        ref_losses = [train_step(net, trainer, x, y) for _ in range(3)]
        ref_w = net.weight.data().asnumpy().copy()
        ref_draw = mx.nd.random.uniform(shape=(4,)).asnumpy()

        # crash-sim: fresh process state, restore, replay
        mx.random.seed(999)   # pollute the RNG: restore must undo this
        net2 = make_net(seed=7)   # different init: restore must undo this
        tr2 = gluon.Trainer(net2.collect_params(), "adam",
                            {"learning_rate": 0.01}, kvstore="tpu_sync")
        meta = mgr.restore(block=net2, trainer=tr2)
        assert meta["step"] == 3 and meta["epoch"] == 1
        assert meta["extra"] == {"lr": 0.01}
        res_losses = [train_step(net2, tr2, x, y) for _ in range(3)]
        res_draw = mx.nd.random.uniform(shape=(4,)).asnumpy()

        assert ref_losses == res_losses        # bit-exact, not allclose
        assert np.array_equal(ref_w, net2.weight.data().asnumpy())
        assert np.array_equal(ref_draw, res_draw)
        assert os.path.isdir(path)

    def test_kill_during_write_keeps_previous_checkpoint(self, tmp_path):
        """Acceptance: a crash at ANY file of the in-flight bundle leaves
        the previous checkpoint manifest-valid, loadable, and newest."""
        net, trainer, _ = run_training(steps=2)
        mgr = checkpoint.CheckpointManager(str(tmp_path), keep_last=3)
        mgr.save(2, params=net, trainer=trainer)
        before = mgr.load(2)["params"]["weight"].asnumpy()

        # the bundle writes params, states, rng, meta, manifest in order;
        # kill at each of the first 5 commits in turn
        for nth in range(1, 6):
            with fault.inject(f"checkpoint.write=nth:{nth}"):
                with pytest.raises(fault.FaultInjected):
                    mgr.save(5, params=net, trainer=trainer)
            assert mgr.latest_step() == 2, f"kill at write #{nth}"
            assert mgr.is_valid(2)
        # staging debris never pollutes discovery, and is swept by the
        # next successful save
        mgr.save(6, params=net, trainer=trainer)
        assert mgr.latest_step() == 6
        assert [e for e in os.listdir(tmp_path) if ".staging-" in e] == []
        np.testing.assert_array_equal(
            before, mgr.load(6)["params"]["weight"].asnumpy())

    def test_corrupt_newest_falls_back_to_older_valid(self, tmp_path):
        net, trainer, _ = run_training(steps=2)
        mgr = checkpoint.CheckpointManager(str(tmp_path), keep_last=3)
        mgr.save(1, params=net, trainer=trainer)
        mgr.save(2, params=net, trainer=trainer)
        # flip bytes in the newest bundle's params payload
        with open(os.path.join(mgr.path(2), "params.params"),
                  "r+b") as f:
            f.seek(40)
            f.write(b"\xde\xad\xbe\xef")
        assert not mgr.is_valid(2)
        assert mgr.latest_step() == 1          # discovery skips corrupt
        with pytest.raises(mx.MXNetError, match="checksum"):
            mgr.load(2)

    def test_no_checkpoint_raises_clear_error(self, tmp_path):
        mgr = checkpoint.CheckpointManager(str(tmp_path))
        assert mgr.latest_step() is None
        with pytest.raises(mx.MXNetError, match="no checksum-valid"):
            mgr.load()

    def test_staging_sweep_is_age_gated(self, tmp_path):
        """A fresh staging dir may be another live writer's in-flight
        bundle — only crash leftovers (old mtime) are swept."""
        import time

        net, trainer, _ = run_training(steps=1)
        mgr = checkpoint.CheckpointManager(str(tmp_path))
        fresh = tmp_path / ".ckpt-00000009.staging-live"
        fresh.mkdir()
        old = tmp_path / ".ckpt-00000008.staging-dead"
        old.mkdir()
        past = time.time() - 2 * mgr._STAGING_SWEEP_AGE_S
        os.utime(old, (past, past))
        mgr.save(1, params=net, trainer=trainer)
        assert fresh.is_dir()          # live writer left alone
        assert not old.exists()        # crash leftover swept
        assert mgr.latest_step() == 1  # staging never pollutes discovery

    def test_retention_keeps_last_k(self, tmp_path):
        net, trainer, _ = run_training(steps=1)
        mgr = checkpoint.CheckpointManager(str(tmp_path), keep_last=2)
        for s in (1, 2, 3, 4):
            mgr.save(s, params=net, trainer=trainer)
        assert mgr.steps() == [4, 3]
        assert sorted(os.listdir(tmp_path)) == ["ckpt-00000003",
                                                "ckpt-00000004"]

    def test_checkpoint_write_telemetry(self, tmp_path):
        net, trainer, _ = run_training(steps=1)
        telemetry.reset()
        telemetry.enable()
        try:
            mgr = checkpoint.CheckpointManager(str(tmp_path))
            mgr.save(1, params=net, trainer=trainer)
            snap = telemetry.snapshot()["metrics"]
            assert snap["mxnet_checkpoint_write_seconds"][
                "samples"][0]["count"] == 1
        finally:
            telemetry.disable()
            telemetry.reset()


# ---------------------------------------------------------------------------
# step anomaly guard
# ---------------------------------------------------------------------------

class TestStepAnomalyGuard:
    def _poisoned_trainer(self, check_nonfinite=True):
        net = make_net()
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1},
                                check_nonfinite=check_nonfinite)
        x, y = make_batch()
        with autograd.record():
            loss = ((net(x) - y) ** 2).mean()
        loss.backward()
        # poison one gradient
        g = net.weight.grad()
        g_np = g.asnumpy().copy()
        g_np[0, 0] = np.nan
        g._set_data(mx.nd.array(g_np).data)
        return net, trainer

    def test_nan_step_skipped_and_counted(self):
        net, trainer = self._poisoned_trainer()
        w_before = net.weight.data().asnumpy().copy()
        telemetry.reset()
        telemetry.enable()
        try:
            trainer.step(8)
            snap = telemetry.snapshot()["metrics"]
            skipped = snap["mxnet_steps_skipped_total"]["samples"]
            assert skipped[0]["labels"] == {"reason": "nonfinite_grad"}
            assert skipped[0]["value"] == 1
        finally:
            telemetry.disable()
            telemetry.reset()
        assert trainer.steps_skipped == 1
        # the poisoned update was NOT applied
        assert np.array_equal(w_before, net.weight.data().asnumpy())

    def test_guard_off_by_default(self):
        net, trainer = self._poisoned_trainer(check_nonfinite=False)
        w_before = net.weight.data().asnumpy().copy()
        trainer.step(8)   # reference behavior: NaN propagates
        assert trainer.steps_skipped == 0
        assert np.isnan(net.weight.data().asnumpy()).any()
        assert not np.array_equal(w_before, net.weight.data().asnumpy())

    def test_guard_env_knob(self, monkeypatch):
        monkeypatch.setenv("MXNET_CHECK_NONFINITE", "1")
        net = make_net()
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1})
        assert trainer._check_nonfinite

    def test_composes_with_amp_loss_scaler(self):
        """With a DynamicLossScaler attached the scaler owns overflow:
        step skipped ONCE, scale backed off, shared skip counter bumped —
        the guard defers instead of double-handling."""
        from mxnet_tpu import amp

        net, trainer = self._poisoned_trainer(check_nonfinite=True)
        scaler = amp.DynamicLossScaler(init_scale=64.0, scale_factor=2.0)
        trainer._amp_loss_scaler = scaler
        amp._patch_trainer_step(trainer)
        w_before = net.weight.data().asnumpy().copy()
        trainer.step(8)
        assert np.array_equal(w_before, net.weight.data().asnumpy())
        assert scaler.loss_scale == 32.0       # backoff happened
        assert trainer.steps_skipped == 1      # counted exactly once


# ---------------------------------------------------------------------------
# state-file error paths (satellites)
# ---------------------------------------------------------------------------

class TestStateFileErrors:
    def test_trainer_load_states_missing_file(self, tmp_path):
        _, trainer, _ = run_training(steps=1)
        missing = str(tmp_path / "nope.states")
        with pytest.raises(mx.MXNetError, match="nope.states"):
            trainer.load_states(missing)

    def test_trainer_load_states_corrupt_file(self, tmp_path):
        _, trainer, _ = run_training(steps=1)
        bad = tmp_path / "bad.states"
        bad.write_bytes(b"this is not a pickle")
        with pytest.raises(mx.MXNetError,
                           match=r"bad.states.*corrupt or wrong format"):
            trainer.load_states(str(bad))

    def test_kvstore_load_optimizer_states_errors(self, tmp_path):
        store = mx.kv.create("local")
        store.set_optimizer(mx.optimizer.create("sgd", learning_rate=0.1))
        with pytest.raises(mx.MXNetError, match="gone.states"):
            store.load_optimizer_states(str(tmp_path / "gone.states"))
        bad = tmp_path / "junk.states"
        bad.write_bytes(b"\x00\x01junk")
        with pytest.raises(mx.MXNetError, match="junk.states"):
            store.load_optimizer_states(str(bad))

    def test_kvstore_states_roundtrip_atomic(self, tmp_path):
        store = mx.kv.create("local")
        store.set_optimizer(mx.optimizer.create("adam",
                                                learning_rate=0.1))
        store.init(0, mx.nd.zeros((4,)))
        store.push(0, mx.nd.ones((4,)))
        f = str(tmp_path / "kv.states")
        store.save_optimizer_states(f)
        store2 = mx.kv.create("local")
        store2.set_optimizer(mx.optimizer.create("adam",
                                                 learning_rate=0.1))
        store2.load_optimizer_states(f)
        assert 0 in store2._updater.states

    def test_updater_states_carry_optimizer_counters(self):
        """v2 state pickle restores num_update / per-index counts — the
        Adam bias-correction clock a bit-exact resume depends on."""
        from mxnet_tpu import optimizer as opt

        o = opt.create("adam", learning_rate=0.01)
        upd = opt.get_updater(o)
        w, g = mx.nd.ones((4,)), mx.nd.ones((4,))
        for _ in range(5):
            upd(0, g, w)
        assert o.num_update == 5
        blob = upd.get_states()
        o2 = opt.create("adam", learning_rate=0.01)
        upd2 = opt.get_updater(o2)
        upd2.set_states(blob)
        assert o2.num_update == 5
        assert o2._index_update_count == {0: 5}

    def test_load_states_dump_optimizer_keeps_counters(self, tmp_path):
        """A dump_optimizer=True payload embeds its own Optimizer; the
        Trainer must carry the restored update counters onto its LIVE
        optimizer when re-pointing the updaters at it."""
        _, trainer, _ = run_training(steps=3)
        f = str(tmp_path / "dump.states")
        checkpoint.atomic_write(
            f, trainer._updaters[0].get_states(dump_optimizer=True))
        _, tr2, _ = run_training(steps=1, seed=5)
        assert tr2._optimizer.num_update == 1
        tr2.load_states(f)
        assert tr2._optimizer.num_update == 3
        for upd in tr2._updaters:
            assert upd.optimizer is tr2._optimizer

    def test_updater_legacy_payload_still_loads(self):
        from mxnet_tpu import optimizer as opt

        legacy = pickle.dumps({0: np.ones((4,), np.float32)})
        upd = opt.get_updater(opt.create("sgd", learning_rate=0.1))
        upd.set_states(legacy)
        assert 0 in upd.states

    def test_nd_load_errors_name_the_file(self, tmp_path):
        """Missing / truncated / garbage .params files raise MXNetError
        with the filename — never a raw OSError or struct.error."""
        missing = str(tmp_path / "gone.params")
        with pytest.raises(mx.MXNetError, match="gone.params"):
            mx.nd.load(missing)
        junk = tmp_path / "junk.params"
        junk.write_bytes(b"garbage")
        with pytest.raises(mx.MXNetError, match="junk.params"):
            mx.nd.load(str(junk))
        # truncate a real file mid-payload
        net = make_net()
        good = str(tmp_path / "net.params")
        net.save_parameters(good)
        data = open(good, "rb").read()
        trunc = tmp_path / "trunc.params"
        trunc.write_bytes(data[:len(data) // 2])
        with pytest.raises(mx.MXNetError, match="trunc.params"):
            mx.nd.load(str(trunc))

    def test_load_parameters_error_names_available_keys(self, tmp_path):
        net = make_net()
        f = str(tmp_path / "net.params")
        net.save_parameters(f)
        # a Sequential wrapper prefixes its child's params ('0.weight'),
        # so loading the bare Dense checkpoint is the classic mismatch
        seq = nn.HybridSequential()
        seq.add(nn.Dense(4, in_units=8))
        seq.initialize(mx.init.Xavier())
        with pytest.raises(mx.MXNetError) as ei:
            seq.load_parameters(f)
        msg = str(ei.value)
        assert "missing in" in msg
        assert "weight" in msg and "bias" in msg   # the available keys
        assert "contains 2 parameter" in msg


# ---------------------------------------------------------------------------
# leak guard self-check
# ---------------------------------------------------------------------------

class TestLeakGuard:
    def test_inject_cleans_up_for_next_test(self):
        with fault.inject("engine.dispatch=once"):
            pass
        assert not fault.active()
