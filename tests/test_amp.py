"""AMP tests (reference: tests/python/gpu/test_amp.py, loss scaler tests)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import amp, autograd, gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.loss import L2Loss


@pytest.fixture
def amp_initialized():
    amp.init("bfloat16")
    yield
    amp._deinit_for_tests()


class TestOpCasting:
    def test_target_ops_autocast_to_bf16(self, amp_initialized):
        x = mx.nd.ones((2, 4))          # float32 input
        w = mx.nd.ones((3, 4))
        b = mx.nd.zeros((3,))
        out = mx.nd.FullyConnected(x, w, b, num_hidden=3)
        assert str(out.dtype) == "bfloat16"

    def test_fp32_ops_stay_f32(self, amp_initialized):
        x = mx.nd.ones((2, 4)).astype("bfloat16")
        out = mx.nd.softmax(x)
        assert str(out.dtype) == "float32"

    def test_uninitialized_is_untouched(self):
        out = mx.nd.FullyConnected(mx.nd.ones((2, 4)), mx.nd.ones((3, 4)),
                                   mx.nd.zeros((3,)), num_hidden=3)
        assert str(out.dtype) == "float32"

    def test_bad_dtype_rejected(self):
        with pytest.raises(mx.MXNetError, match="bfloat16"):
            amp.init("int8")


class TestLossScaler:
    def test_grow_and_backoff(self):
        s = amp.DynamicLossScaler(init_scale=64.0, scale_factor=2.0,
                                  scale_window=2)
        s.update_scale(False)
        assert s.loss_scale == 64.0
        s.update_scale(False)           # window hit -> grow
        assert s.loss_scale == 128.0
        s.update_scale(True)            # overflow -> backoff
        assert s.loss_scale == 64.0
        s.update_scale(False)
        s.update_scale(True)            # overflow resets the window
        assert s.loss_scale == 32.0

    def test_overflow_skips_step_and_halves(self, amp_initialized):
        net = nn.Dense(2, in_units=3)
        net.initialize()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1})
        amp.init_trainer(tr)
        tr._amp_loss_scaler = amp.DynamicLossScaler(init_scale=8.0,
                                                    scale_window=100)
        w0 = net.weight.data().asnumpy().copy()
        x = mx.nd.ones((2, 3))
        with autograd.record():
            loss = L2Loss()(net(x), mx.nd.ones((2, 2)))
        loss.backward()
        # poison the gradient with inf -> step must be skipped
        g = net.weight.grad()
        g._set_data((g * float("inf")).data)
        tr.step(2)
        onp.testing.assert_allclose(net.weight.data().asnumpy(), w0)
        assert tr._amp_loss_scaler.loss_scale == 4.0

    def test_scale_loss_trains_equivalently(self, amp_initialized):
        def train(with_amp):
            rs = onp.random.RandomState(3)
            net = nn.Dense(1, in_units=2)
            net.initialize()
            net.weight.set_data(mx.nd.array([[0.5, -0.5]]))
            net.bias.set_data(mx.nd.zeros((1,)))
            tr = gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.1})
            if with_amp:
                amp.init_trainer(tr)
                tr._amp_loss_scaler = amp.DynamicLossScaler(
                    init_scale=128.0, scale_window=10 ** 9)
            x = mx.nd.array(rs.randn(8, 2).astype("float32"))
            y = mx.nd.array(rs.randn(8, 1).astype("float32"))
            for _ in range(5):
                with autograd.record():
                    loss = L2Loss()(net(x), y)
                    if with_amp:
                        with amp.scale_loss(loss, tr) as scaled:
                            scaled.backward()
                    else:
                        loss.backward()
                tr.step(8)
            return net.weight.data().asnumpy()

        onp.testing.assert_allclose(train(True), train(False),
                                    rtol=2e-2, atol=1e-3)

    def test_bf16_trainer_scale_is_one(self, amp_initialized):
        net = nn.Dense(1, in_units=2)
        net.initialize()
        tr = gluon.Trainer(net.collect_params(), "sgd", {})
        amp.init_trainer(tr)
        assert tr._amp_loss_scaler.loss_scale == 1.0


class TestConvert:
    def test_convert_hybrid_block(self):
        net = nn.Dense(2, in_units=3)
        net.initialize()
        amp.convert_hybrid_block(net)
        assert str(net.weight.data().dtype) == "bfloat16"

    def test_convert_model_keeps_fp32_list(self):
        from mxnet_tpu import symbol as sym

        data = sym.var("data")
        net = sym.FullyConnected(data, name="fc", num_hidden=2)
        args = {"fc_weight": mx.nd.ones((2, 3)), "fc_bias": mx.nd.ones((2,))}
        _, cargs, _ = amp.convert_model(net, args, {},
                                        fp32_params=["fc_bias"])
        assert str(cargs["fc_weight"].dtype) == "bfloat16"
        assert str(cargs["fc_bias"].dtype) == "float32"

    def test_unscale_for_clipping(self, amp_initialized):
        net = nn.Dense(1, in_units=2)
        net.initialize()
        net.weight.set_data(mx.nd.array([[1.0, 1.0]]))
        net.bias.set_data(mx.nd.zeros((1,)))
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.0})
        amp.init_trainer(tr)
        tr._amp_loss_scaler = amp.DynamicLossScaler(init_scale=16.0,
                                                    scale_window=10 ** 9)
        x = mx.nd.ones((1, 2))
        with autograd.record():
            loss = L2Loss()(net(x), mx.nd.zeros((1, 1)))
            with amp.scale_loss(loss, tr) as scaled:
                scaled.backward()
        g_scaled = net.weight.grad().asnumpy().copy()
        amp.unscale(tr)
        g = net.weight.grad().asnumpy()
        onp.testing.assert_allclose(g * 16.0, g_scaled, rtol=1e-5)
        tr.step(1)  # lr 0: just exercises the no-double-divide path
        assert tr._amp_loss_scaler.loss_scale == 16.0
