"""Fused projection+CE head (ops/fused_loss.py — the SoftmaxOutput
lineage): loss and ALL gradients must match the materialized-logits
reference; the BERT fused-pretrain block must train."""
import numpy as onp
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import parallel as par
from mxnet_tpu.ops.fused_loss import softmax_ce_head


def test_matches_logits_reference_fwd_bwd():
    rs = onp.random.RandomState(0)
    N, D, V = 48, 24, 700   # V not a chunk multiple: exercises padding
    h = jnp.asarray(rs.randn(N, D) * 0.5, jnp.float32)
    w = jnp.asarray(rs.randn(V, D) * 0.1, jnp.float32)
    b = jnp.asarray(rs.randn(V) * 0.1, jnp.float32)
    lab = jnp.asarray(rs.randint(0, V, (N,)), jnp.int32)

    def ref(h, w, b):
        logits = h @ w.T + b
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(logits, lab[:, None], axis=-1)[:, 0]
        return (lse - picked).mean()

    def fused(h, w, b):
        return softmax_ce_head(h, w, b, lab, chunk=256).mean()

    lr, gr = jax.value_and_grad(ref, argnums=(0, 1, 2))(h, w, b)
    lf, gf = jax.value_and_grad(fused, argnums=(0, 1, 2))(h, w, b)
    assert float(lf) == pytest.approx(float(lr), abs=1e-4)
    for a, bb, nm in zip(gr, gf, "hwb"):
        onp.testing.assert_allclose(onp.asarray(bb), onp.asarray(a),
                                    rtol=1e-4, atol=1e-4, err_msg=nm)


def test_bf16_path_close_to_f32():
    rs = onp.random.RandomState(1)
    N, D, V = 32, 16, 512
    h = jnp.asarray(rs.randn(N, D) * 0.5, jnp.float32)
    w = jnp.asarray(rs.randn(V, D) * 0.1, jnp.float32)
    b = jnp.zeros((V,), jnp.float32)
    lab = jnp.asarray(rs.randint(0, V, (N,)), jnp.int32)
    f32 = softmax_ce_head(h, w, b, lab, chunk=128)
    bf = softmax_ce_head(h.astype(jnp.bfloat16), w.astype(jnp.bfloat16),
                         b, lab, chunk=128)
    onp.testing.assert_allclose(onp.asarray(bf), onp.asarray(f32),
                                rtol=0.05, atol=0.05)


def test_bert_fused_block_trains_and_ties():
    from mxnet_tpu.gluon.model_zoo.nlp.bert import BERTForPretrainFused

    net = BERTForPretrainFused(vocab_size=128, max_length=32, num_layers=1,
                               units=32, hidden_size=64, num_heads=2,
                               dropout=0.0, chunk=64)
    net.initialize()
    mesh = par.make_mesh({"dp": 1}, devices=jax.devices()[:1])
    step = par.TrainStep(
        net, lambda outs, *a: outs, "adam", mesh=mesh, loss_only=True,
        optimizer_params={"learning_rate": 5e-3})
    rs = onp.random.RandomState(0)
    tok = mx.nd.array(rs.randint(0, 128, (4, 32)).astype(onp.int32))
    lab = mx.nd.array(rs.randint(0, 128, (4, 32)).astype(onp.int32))
    emb = net.bert.word_embed.weight
    w0 = emb.data().asnumpy().copy()
    losses = []
    for _ in range(10):
        loss, _ = step((tok, lab), ())
        losses.append(float(loss.asnumpy()))
    assert losses[-1] < losses[0], losses
    # PROJECTION-side gradients really flow to the tied table: vocab rows
    # never looked up by any token still move, which only the CE head's
    # dW (softmax over the whole vocab) can cause
    w1 = emb.data().asnumpy()
    used = set(tok.asnumpy().astype(int).ravel().tolist())
    unused = [r for r in range(128) if r not in used][:20]
    assert unused and not onp.allclose(w1[unused], w0[unused]), \
        "tied projection gradient did not reach unused vocab rows"


def test_nobias_variant_matches_zero_bias():
    """bias=None (Llama lm_head): the bias-free custom-VJP variant must
    match the biased path with a zero bias, fwd and grads — without
    computing a vocab-sized bias cotangent."""
    rs = onp.random.RandomState(2)
    N, D, V = 32, 16, 512   # V % chunk == 0 -> true nobias path
    h = jnp.asarray(rs.randn(N, D) * 0.5, jnp.float32)
    w = jnp.asarray(rs.randn(V, D) * 0.1, jnp.float32)
    lab = jnp.asarray(rs.randint(0, V, (N,)), jnp.int32)
    zb = jnp.zeros((V,), jnp.float32)

    def with_zero_bias(h, w):
        return softmax_ce_head(h, w, zb, lab, chunk=128).mean()

    def no_bias(h, w):
        return softmax_ce_head(h, w, None, lab, chunk=128).mean()

    lr, gr = jax.value_and_grad(with_zero_bias, argnums=(0, 1))(h, w)
    lf, gf = jax.value_and_grad(no_bias, argnums=(0, 1))(h, w)
    assert float(lf) == pytest.approx(float(lr), abs=1e-5)
    for a, b, nm in zip(gr, gf, "hw"):
        onp.testing.assert_allclose(onp.asarray(b), onp.asarray(a),
                                    rtol=1e-5, atol=1e-5, err_msg=nm)
