"""Shared fixtures. The CPU-forcing re-exec lives in the repo-root
conftest.py; here we only provide seeding and helpers (reference:
tests/python/unittest/common.py :: with_seed)."""
import os
import random as pyrandom
import zlib

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def seeded(request):
    """Seed np/mx/python RNGs per test; log the seed for repro
    (reference: common.py::with_seed, env MXNET_TEST_SEED).

    crc32, not hash(): python string hashing is randomized per process,
    which made the 'per-test seed' different on every run (the round-1
    flaky-test root cause)."""
    seed = int(os.environ.get("MXNET_TEST_SEED", "0")) or \
        zlib.crc32(request.node.nodeid.encode()) % (2**31)
    np.random.seed(seed)
    pyrandom.seed(seed)
    import mxnet_tpu as mx

    mx.random.seed(seed)
    yield seed


@pytest.fixture(autouse=True)
def telemetry_leak_guard():
    """State-leak guard (mirrors the engine-type restore discipline): a
    test that enables mx.telemetry globally and forgets to disable it
    would silently tax every later test's dispatch path — fail loudly
    instead. Tests that WANT telemetry enable it and disable in teardown
    (or monkeypatch mxnet_tpu.telemetry._state.enabled)."""
    from mxnet_tpu import telemetry

    was_enabled = telemetry.enabled()
    yield
    leaked = telemetry.enabled() and not was_enabled
    if leaked:
        telemetry.disable()
        pytest.fail(
            "test left mx.telemetry globally enabled; call "
            "telemetry.disable() in teardown")


@pytest.fixture(autouse=True)
def tracing_leak_guard():
    """Mirror of the telemetry guard for request tracing: a test that
    enables mx.tracing globally and forgets to disable it would make
    every later serving test mint spans (and grow the flight-recorder
    ring) on its hot path — fail loudly instead. Tests that want
    tracing call tracing.reset() (disable + clear ring) in teardown."""
    from mxnet_tpu import tracing

    was_enabled = tracing.enabled()
    yield
    leaked = tracing.enabled() and not was_enabled
    if leaked:
        tracing.reset()
        pytest.fail(
            "test left mx.tracing globally enabled; call "
            "tracing.reset() (or disable()) in teardown")


@pytest.fixture(autouse=True)
def serving_leak_guard():
    """Guard for the serving stack: a test that leaves a Server's
    scheduler (or reload-watcher) thread running would keep dispatching
    — and keep model state alive — under every later test. Fail the
    leaking test loudly; tests stop servers in teardown (or use the
    Server context manager)."""
    yield
    import sys

    # All sweeps run BEFORE failing: a test that leaks a
    # FleetController AND a Router AND an unrelated standalone Server
    # must have all three stopped, or the surviving thread taxes every
    # later test — controllers first (a live one could re-scale the
    # router mid-teardown), then ingresses (the edge holds a router),
    # then routers (stopping one stops its replicas too), then
    # servers, then standalone worker PROCESSES (a leaked subprocess
    # would pin its port, its model, and a whole interpreter)
    problems = []
    cmod = sys.modules.get("mxnet_tpu.serving.controller")
    if cmod is not None:
        leaked_controllers = cmod.live_controllers()
        if leaked_controllers:
            problems.append(
                f"test left FleetController(s) running: "
                f"{[c.name for c in leaked_controllers]}; call stop() "
                "in teardown or use the context manager")
            for c in leaked_controllers:
                try:
                    c.stop(timeout=5)
                except Exception:
                    pass
    imod = sys.modules.get("mxnet_tpu.serving.ingress")
    if imod is not None:
        leaked_ingresses = imod.live_ingresses()
        if leaked_ingresses:
            problems.append(
                f"test left serving Ingress(es) bound and accepting: "
                f"{[i.name for i in leaked_ingresses]}; call stop() in "
                "teardown or use the context manager")
            for i in leaked_ingresses:
                try:
                    i.stop(timeout=5)
                except Exception:
                    pass
    rmod = sys.modules.get("mxnet_tpu.serving.router")
    if rmod is not None:
        leaked_routers = rmod.live_routers()
        if leaked_routers:
            problems.append(
                f"test left serving Router(s) running: "
                f"{[r.name for r in leaked_routers]}; call stop() in "
                "teardown or use the Router context manager")
            for r in leaked_routers:
                try:
                    r.stop(drain=False, timeout=5)
                except Exception:
                    pass
    mod = sys.modules.get("mxnet_tpu.serving.server")
    if mod is not None:
        leaked = mod.live_servers()
        if leaked:
            # name the leaked server's TENANT REGISTRY too: a
            # multi-tenant server pins every registered block (and its
            # decode engine/arenas), not just the constructor model —
            # "which tenants' state survived" is the first question
            # when a later test's memory or executables look haunted
            def _tenants(s):
                try:
                    return ",".join(sorted(s.models()))
                except Exception:  # noqa: BLE001 - diagnostics only
                    return "?"
            problems.append(
                f"test left serving Server(s) running: "
                f"{[f'{s.name}[{_tenants(s)}]' for s in leaked]}; "
                "call stop() in teardown or use the Server context "
                "manager")
            for s in leaked:
                s.stop(drain=False)
    wmod = sys.modules.get("mxnet_tpu.serving.remote")
    if wmod is not None:
        leaked_workers = wmod.live_workers()
        if leaked_workers:
            problems.append(
                f"test left worker subprocess(es) alive: "
                f"{[(w.name, w.proc.pid if w.proc else None) for w in leaked_workers]}; "
                "call RemoteReplica.stop() in teardown or use the "
                "context manager")
            for w in leaked_workers:
                try:
                    w.stop(drain=False, timeout=5)
                except Exception:
                    pass
                p = w.proc
                if p is not None and p.poll() is None:
                    p.kill()        # the guard REAPS: a zombie python
                    p.wait()        # must not outlive the test run
    if problems:
        pytest.fail("; ".join(problems))


@pytest.fixture(autouse=True)
def elastic_leak_guard():
    """Guard for the elastic runtime: a test that leaves an
    ElasticRunner's heartbeat thread running would keep touching
    heartbeat files (and pin the runner's net/trainer state) under
    every later test. Fail the leaking test loudly; tests call stop()
    in teardown or use the runner as a context manager."""
    yield
    import sys
    import threading

    mod = sys.modules.get("mxnet_tpu.parallel.elastic")
    if mod is None:        # elastic never imported: nothing to leak
        return
    leaked = mod.live_runners()
    strays = [t.name for t in threading.enumerate()
              if t.name.startswith("mxnet-elastic-")]
    if leaked or strays:
        for r in leaked:
            r.stop()
        pytest.fail(
            f"test left elastic heartbeat thread(s) running: "
            f"{[r.launch_rank for r in leaked] or strays}; call "
            "ElasticRunner.stop() in teardown or use it as a context "
            "manager")


@pytest.fixture(autouse=True)
def fault_leak_guard():
    """Mirror of the telemetry guard for the fault injector: a test that
    leaves fault injection globally enabled would make every later test
    randomly fail at instrumented sites — fail the leaking test loudly.
    Tests use ``fault.inject(...)`` (scoped) or clear() in teardown."""
    from mxnet_tpu import fault

    was_active = fault.active()
    yield
    leaked = fault.active() and not was_active
    if leaked:
        fault.clear()
        pytest.fail(
            "test left mx.fault injection globally enabled; use "
            "fault.inject() as a context manager or call fault.clear() "
            "in teardown")
