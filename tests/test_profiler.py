"""Profiler / callback / monitor tests.

Reference strategy: tests/python/unittest/test_profiler.py (set_config +
start/stop + dumps round-trip, scoped objects) and callback Speedometer
behaviour.
"""
import logging

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import profiler, callback, monitor


class TestProfiler:
    def test_config_and_state(self, tmp_path):
        profiler.set_config(filename=str(tmp_path / "prof.json"),
                            profile_all=True)
        with pytest.raises(ValueError):
            profiler.set_config(bogus_key=1)
        assert profiler.state() == "stop"

    def test_scopes_aggregate(self):
        with profiler.Task("unit-task"):
            x = mx.nd.ones((4, 4))
            (x + x).asnumpy()
        ev = profiler.Event("unit-event").start()
        ev.stop()
        table = profiler.dumps(reset=True)
        assert "Task::unit-task" in table
        assert "Event::unit-event" in table

    def test_counter_marker(self):
        c = profiler.Counter("unit-counter", 5)
        c += 3
        c -= 1
        table = profiler.dumps(reset=True)
        assert "unit-counter" in table
        profiler.Marker("unit-marker").mark()

    def test_profile_memory_reports_pool_stats(self):
        """set_config(profile_memory=True) wires dumps() to
        storage.pool_stats(): one Memory:: line per local device with
        the allocator counters (zeros on CPU, which exposes no stats —
        the line must still appear so the flag visibly works)."""
        profiler.set_config(profile_memory=True)
        try:
            x = mx.nd.ones((8, 8))
            (x * 2).asnumpy()
            table = profiler.dumps()
            mem = [ln for ln in table.splitlines()
                   if ln.startswith("Memory::")]
            import jax

            assert len(mem) == len(jax.local_devices())
            for ln in mem:
                assert "bytes_in_use=" in ln
                assert "peak_bytes_in_use=" in ln
                assert "bytes_limit=" in ln
        finally:
            profiler.set_config(profile_memory=False)
        assert not [ln for ln in profiler.dumps(reset=True).splitlines()
                    if ln.startswith("Memory::")]

    def test_start_stop_trace(self, tmp_path):
        # device trace round-trip: start -> run a jitted op -> stop
        profiler.set_config(filename=str(tmp_path / "p.json"))
        profiler.start()
        try:
            (mx.nd.ones((8, 8)) * 2).asnumpy()
        finally:
            profiler.stop()
        assert profiler.state() == "stop"
        out = profiler.dump()
        assert (tmp_path / "p.json").exists(), out


class TestCallback:
    def _param(self, epoch, nbatch, metric=None):
        class P:
            pass

        p = P()
        p.epoch, p.nbatch, p.eval_metric = epoch, nbatch, metric
        return p

    def test_speedometer_logs(self, caplog):
        from mxnet_tpu import metric as metric_mod

        m = metric_mod.create("acc")
        m.update([mx.nd.array([0, 1])],
                 [mx.nd.array([[0.9, 0.1], [0.1, 0.9]])])
        sp = callback.Speedometer(batch_size=4, frequent=2)
        with caplog.at_level(logging.INFO):
            for nb in range(5):
                sp(self._param(0, nb, m))
        assert any("Speed" in r.message for r in caplog.records)

    def test_speedometer_mfu_math(self, caplog, monkeypatch):
        # Drive the actual __call__ MFU branch with a pinned clock and a
        # fake 2-device peak; check the logged percentage is
        # speed * flops_per_sample / (per_chip_peak * num_devices).
        import time as time_mod

        monkeypatch.setattr(callback, "device_peak_flops", lambda d=None: 1e12)
        ticks = [100.0, 101.0]  # init tic, then measure; repeat last after
        monkeypatch.setattr(time_mod, "time",
                            lambda: ticks.pop(0) if len(ticks) > 1 else ticks[0])
        sp = callback.Speedometer(batch_size=8, frequent=1,
                                  flops_per_sample=1e10, num_devices=2)
        with caplog.at_level(logging.INFO):
            sp(self._param(0, 0))  # init
            sp(self._param(0, 1))  # speed = 1*8/1s = 8 samples/s
        msgs = [r.getMessage() for r in caplog.records if "MFU" in r.getMessage()]
        assert msgs, caplog.records
        # MFU = 100 * 8 * 1e10 / (1e12 * 2) = 4.0%
        assert "MFU=4.0%" in msgs[-1]

    def test_device_peak_flops_known_kinds(self):
        peak = callback.device_peak_flops()
        # CPU has no known peak; TPU returns positive float
        assert peak is None or peak > 0

    def test_do_checkpoint(self, tmp_path):
        from mxnet_tpu import symbol as sym

        data = sym.var("data")
        net = sym.FullyConnected(data, name="fc", num_hidden=2)
        cb = callback.do_checkpoint(str(tmp_path / "ck"), period=1)
        arg = {"fc_weight": mx.nd.zeros((2, 3)), "fc_bias": mx.nd.zeros((2,))}
        cb(0, net, arg, {})
        assert (tmp_path / "ck-0001.params").exists()
        assert (tmp_path / "ck-symbol.json").exists()


class TestMonitor:
    def test_monitor_collects_norms(self, caplog):
        from mxnet_tpu import symbol as sym

        data = sym.var("data")
        net = sym.FullyConnected(data, name="fc", num_hidden=4)
        exe = net.simple_bind(ctx=mx.cpu(), data=(2, 3))
        mon = monitor.Monitor(interval=1, pattern=".*fc.*|output.*")
        mon.install(exe)
        mon.tic()
        exe.forward(data=mx.nd.ones((2, 3)))
        res = mon.toc()
        names = [n for (_, n, _) in res]
        assert any("fc_weight" in n for n in names)
        assert any(n.startswith("output") for n in names)
        # stats are finite floats
        for _, _, v in res:
            assert np.isfinite(v)
