"""gluon.utils + mx.viz tests (reference:
tests/python/unittest/test_gluon_utils.py, test_viz.py)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import utils


class TestGluonUtils:
    def test_split_data_even_and_uneven(self):
        x = mx.nd.array(onp.arange(12.0).reshape(6, 2))
        parts = utils.split_data(x, 3)
        assert [p.shape for p in parts] == [(2, 2)] * 3
        onp.testing.assert_allclose(parts[1].asnumpy(),
                                    [[4, 5], [6, 7]])
        with pytest.raises(MXNetError, match="evenly"):
            utils.split_data(x, 4)
        parts = utils.split_data(x, 4, even_split=False)
        assert len(parts) == 4                     # ALWAYS num_slice
        assert sum(p.shape[0] for p in parts) == 6
        assert parts[-1].shape[0] == 3             # remainder in the last

    def test_split_and_load(self):
        x = onp.arange(8.0).reshape(4, 2)
        out = utils.split_and_load(x, [mx.cpu(0), mx.cpu(0)])
        assert len(out) == 2 and out[0].shape == (2, 2)
        one = utils.split_and_load(mx.nd.array(x), [mx.cpu(0)])
        assert one[0].shape == (4, 2)

    def test_clip_global_norm(self):
        a = mx.nd.array(onp.array([3.0, 0.0], "float32"))
        b = mx.nd.array(onp.array([0.0, 4.0], "float32"))
        norm = utils.clip_global_norm([a, b], 1.0)
        assert norm == pytest.approx(5.0, rel=1e-6)
        total = onp.concatenate([a.asnumpy(), b.asnumpy()])
        assert onp.linalg.norm(total) == pytest.approx(1.0, rel=1e-4)
        # under the limit: untouched
        c = mx.nd.array(onp.array([0.3], "float32"))
        utils.clip_global_norm([c], 10.0)
        onp.testing.assert_allclose(c.asnumpy(), [0.3])

    def test_check_sha1_and_download(self, tmp_path):
        import hashlib

        f = tmp_path / "x.bin"
        f.write_bytes(b"hello")
        good = hashlib.sha1(b"hello").hexdigest()
        assert utils.check_sha1(str(f), good)
        assert not utils.check_sha1(str(f), "0" * 40)
        with pytest.raises(MXNetError, match="no network"):
            utils.download("http://example.com/x")


class TestViz:
    def test_print_summary_counts_params(self, tmp_path):
        from mxnet_tpu.gluon import nn

        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
        net.initialize()
        x = mx.nd.ones((2, 8))
        net.hybridize()
        net(x)
        prefix = str(tmp_path / "m")
        net.export(prefix)
        sym = mx.sym.load(prefix + "-symbol.json")
        text = mx.viz.print_summary(sym, shape={"data": (2, 8)})
        assert "FullyConnected" in text
        # 8*16+16 + 16*4+4 = 212
        assert "Total params: 212" in text
        assert "(2, 16)" in text                  # per-layer output shape

    def test_plot_network_gated(self, tmp_path):
        from mxnet_tpu.gluon import nn

        net = nn.HybridSequential()
        net.add(nn.Dense(2))
        net.initialize()
        net.hybridize()
        net(mx.nd.ones((1, 3)))
        prefix = str(tmp_path / "p")
        net.export(prefix)
        sym = mx.sym.load(prefix + "-symbol.json")
        try:
            import graphviz  # noqa: F401
            dot = mx.viz.plot_network(sym)
            assert "fullyconnected" in dot.source.lower()
        except ImportError:
            with pytest.raises(MXNetError, match="graphviz"):
                mx.viz.plot_network(sym)
