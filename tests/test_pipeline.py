"""Pipeline parallelism tests (SURVEY §2.4 PP row — new TPU capability).

Oracle = the sequential fallback: the GPipe schedule over the ``pp`` mesh
axis must compute the SAME function as applying the stacked layers in
order on one device — fwd and bwd — and must compose with the fused
sharded TrainStep (dp x pp, and dp x pp x tp).
"""
import numpy as onp
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import autograd, parallel as par
from mxnet_tpu.gluon import loss as gloss, nn
from mxnet_tpu.gluon.model_zoo import nlp
from mxnet_tpu.parallel.pipeline import pipeline_apply


def _stacked_mlp(n_stages, l_per, d, seed=0):
    """Stage params for a toy residual-MLP layer: h + tanh(h @ W + b)."""
    rs = onp.random.RandomState(seed)
    w = jnp.asarray(rs.randn(n_stages, l_per, d, d) * 0.3, jnp.float32)
    b = jnp.asarray(rs.randn(n_stages, l_per, d) * 0.1, jnp.float32)
    return (w, b)


def _stage_fn(leaves, h, key):
    w, b = leaves
    return h + jnp.tanh(h @ w + b)


class TestPipelineApply:
    @pytest.mark.parametrize("n_stages,l_per,n_micro",
                             [(4, 1, 4), (4, 2, 8), (2, 3, 2), (8, 1, 4)])
    def test_matches_sequential(self, n_stages, l_per, n_micro):
        d, B = 16, 8
        stacked = _stacked_mlp(n_stages, l_per, d)
        rs = onp.random.RandomState(1)
        x = jnp.asarray(rs.randn(B, 6, d), jnp.float32)
        key = jax.random.PRNGKey(0)
        mesh = par.make_mesh({"pp": n_stages},
                             devices=jax.devices()[:n_stages])
        want = pipeline_apply(_stage_fn, stacked, x, key, mesh=None)
        got = pipeline_apply(_stage_fn, stacked, x, key, mesh=mesh,
                             n_microbatches=n_micro)
        onp.testing.assert_allclose(onp.asarray(got), onp.asarray(want),
                                    rtol=2e-5, atol=2e-5)

    def test_grads_match_sequential(self):
        n_stages, l_per, d, B = 4, 2, 12, 8
        stacked = _stacked_mlp(n_stages, l_per, d, seed=2)
        rs = onp.random.RandomState(3)
        x = jnp.asarray(rs.randn(B, 4, d), jnp.float32)
        key = jax.random.PRNGKey(0)
        mesh = par.make_mesh({"pp": n_stages},
                             devices=jax.devices()[:n_stages])

        def loss(params, xx, m):
            y = pipeline_apply(_stage_fn, params, xx, key, mesh=m,
                               n_microbatches=4)
            return (y ** 2).sum()

        gw = jax.grad(loss)(stacked, x, None)
        gp = jax.grad(loss)(stacked, x, mesh)
        for a, b, nm in zip(gp, gw, "wb"):
            onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                        rtol=2e-4, atol=2e-4,
                                        err_msg=f"d{nm}")

    def test_remat_matches(self):
        n_stages, d = 4, 8
        stacked = _stacked_mlp(n_stages, 1, d, seed=4)
        x = jnp.asarray(onp.random.RandomState(5).randn(4, 3, d),
                        jnp.float32)
        key = jax.random.PRNGKey(0)
        mesh = par.make_mesh({"pp": n_stages},
                             devices=jax.devices()[:n_stages])

        def loss(params, remat):
            y = pipeline_apply(_stage_fn, params, x, key, mesh=mesh,
                               remat=remat)
            return (y ** 2).sum()

        g0 = jax.grad(loss)(stacked, False)
        g1 = jax.grad(loss)(stacked, True)
        for a, b in zip(g1, g0):
            onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                        rtol=2e-5, atol=2e-5)

    def test_bad_shapes_raise(self):
        stacked = _stacked_mlp(4, 1, 8)
        x = jnp.zeros((6, 8), jnp.float32)  # 6 not divisible by 4
        mesh = par.make_mesh({"pp": 4}, devices=jax.devices()[:4])
        with pytest.raises(ValueError, match="divisible"):
            pipeline_apply(_stage_fn, stacked, x, jax.random.PRNGKey(0),
                           mesh=mesh, n_microbatches=4)
        mesh2 = par.make_mesh({"pp": 2}, devices=jax.devices()[:2])
        with pytest.raises(ValueError, match="stages"):
            pipeline_apply(_stage_fn, stacked, x, jax.random.PRNGKey(0),
                           mesh=mesh2)


class TestPipelinedBlock:
    def test_offmesh_forward_and_param_surface(self):
        net = nlp.llama_tiny_pp(n_stages=2, layers_per_stage=2)
        net.initialize()
        tokens = mx.nd.array(onp.random.RandomState(0).randint(
            0, 256, (4, 8)), dtype="int32")
        out = net(tokens)
        assert out.shape == (4, 8, 256)
        names = list(net.collect_params())
        stacked = [n for n in names if "pp_" in n]
        # 2 norms + 3 attn denses + 2 mlp denses per stage template
        assert len(stacked) == 7
        for n in stacked:
            p = net.collect_params()[n]
            assert tuple(p.shape[:2]) == (2, 2), n
        # template's own (donor) params are NOT in the trainable surface
        assert not any("stage_" in n and "pp_" not in n for n in names)

    def test_trainstep_pp_matches_offmesh_loss(self):
        """Same init → first-step loss identical on-mesh and off-mesh."""
        onp.random.seed(7)
        mx.random.seed(7)
        rs = onp.random.RandomState(11)
        tokens = rs.randint(0, 256, (8, 8)).astype("int32")
        labels = rs.randint(0, 256, (8, 8)).astype("int32")

        def build():
            mx.random.seed(42)  # initializer reproducibility contract (r5)
            net = nlp.llama_tiny_pp(n_stages=4, n_microbatches=4)
            net.initialize()
            return net

        class LMLoss(gloss.Loss):
            def __init__(self):
                super().__init__(weight=None, batch_axis=0)
                self._ce = gloss.SoftmaxCrossEntropyLoss()

            def hybrid_forward(self, F, pred, label):
                return self._ce(pred.reshape((-1, pred.shape[-1])),
                                label.reshape((-1,)))

        losses = []
        for mesh_axes in (None, {"dp": 2, "pp": 4}):
            net = build()
            mesh = par.make_mesh(mesh_axes) if mesh_axes else \
                par.make_mesh({"dp": 1}, devices=jax.devices()[:1])
            rules = nlp.llama_pp_sharding_rules() if mesh_axes else None
            step = par.TrainStep(net, LMLoss(), "sgd", mesh=mesh,
                                 rules=rules, loss_only=True,
                                 optimizer_params={"learning_rate": 0.1})
            loss, _ = step(mx.nd.array(tokens, dtype="int32"),
                           mx.nd.array(labels, dtype="int32"))
            losses.append(float(loss.asnumpy()))
        assert abs(losses[0] - losses[1]) < 2e-4, losses

    def test_trainstep_pp_tp_dp_converges(self):
        onp.random.seed(13)
        mx.random.seed(13)
        net = nlp.llama_tiny_pp(n_stages=2, layers_per_stage=2,
                                n_microbatches=4)
        net.initialize()
        mesh = par.make_mesh({"dp": 2, "pp": 2, "tp": 2})

        class LMLoss(gloss.Loss):
            def __init__(self):
                super().__init__(weight=None, batch_axis=0)
                self._ce = gloss.SoftmaxCrossEntropyLoss()

            def hybrid_forward(self, F, pred, label):
                return self._ce(pred.reshape((-1, pred.shape[-1])),
                                label.reshape((-1,)))

        step = par.TrainStep(net, LMLoss(), "adam", mesh=mesh,
                             rules=nlp.llama_pp_sharding_rules(),
                             loss_only=True,
                             optimizer_params={"learning_rate": 3e-3})
        rs = onp.random.RandomState(17)
        tokens = mx.nd.array(rs.randint(0, 256, (8, 8)), dtype="int32")
        # memorize a fixed batch: loss must drop hard
        first = last = None
        for i in range(30):
            loss, _ = step(tokens, tokens)
            v = float(loss.asnumpy())
            if first is None:
                first = v
            last = v
        assert last < first * 0.6, (first, last)


def test_concrete_shape_template():
    """Regression: a stage template with fully concrete shapes (no
    deferred init) must still forward — the template donor params are
    initialized lazily from the stacked shapes."""
    from mxnet_tpu.gluon import nn

    class Res(nn.HybridSequential):
        pass

    def factory():
        blk = Res()
        blk.add(nn.Dense(8, in_units=8, flatten=False))
        return blk

    net = par.Pipelined(factory, n_stages=2)
    net.initialize()
    x = mx.nd.array(onp.random.RandomState(0).randn(4, 8).astype("float32"))
    y = net(x)
    assert y.shape == (4, 8)
    assert onp.isfinite(y.asnumpy()).all()


class Test1F1B:
    """pipeline_train_1f1b: the memory-bounded schedule (VERDICT #10).
    Gradients and loss must match the sequential reference exactly."""

    def _setup(self):
        rs = onp.random.RandomState(0)
        S, D, B = 4, 6, 8
        w = jnp.asarray(rs.randn(S, D, D) * 0.3, jnp.float32)
        b = jnp.asarray(rs.randn(S, D) * 0.1, jnp.float32)
        x = jnp.asarray(rs.randn(B, D), jnp.float32)
        y = jnp.asarray(rs.randn(B, D), jnp.float32)

        def stage_fn(leaves, h, key):
            wl, bl = leaves
            return jnp.tanh(h @ wl + bl)

        def loss_fn(h, lbl):
            return ((h - lbl) ** 2).mean()

        return stage_fn, loss_fn, (w, b), x, y

    def test_grads_match_sequential(self):
        import jax as _jax

        stage_fn, loss_fn, leaves, x, y = self._setup()
        key = _jax.random.PRNGKey(0)
        mesh = par.make_mesh({"pp": 4}, devices=jax.devices()[:4])
        loss_p, grads_p, dx_p = par.pipeline_train_1f1b(
            stage_fn, loss_fn, leaves, x, y, key, mesh=mesh,
            n_microbatches=4)
        # sequential reference (the same function's off-mesh path)
        loss_s, grads_s, dx_s = par.pipeline_train_1f1b(
            stage_fn, loss_fn, leaves, x, y, key, mesh=None)
        # per-micro mean losses average to the full-batch mean only when
        # microbatches are equal-sized (they are)
        assert float(loss_p) == pytest.approx(float(loss_s), rel=1e-5)
        for gp, gs in zip(grads_p, grads_s):
            onp.testing.assert_allclose(onp.asarray(gp), onp.asarray(gs),
                                        rtol=1e-4, atol=1e-5)
        onp.testing.assert_allclose(onp.asarray(dx_p), onp.asarray(dx_s),
                                    rtol=1e-4, atol=1e-5)

    def test_more_microbatches_than_stages(self):
        import jax as _jax

        stage_fn, loss_fn, leaves, x, y = self._setup()
        key = _jax.random.PRNGKey(1)
        mesh = par.make_mesh({"pp": 4}, devices=jax.devices()[:4])
        loss_p, grads_p, _ = par.pipeline_train_1f1b(
            stage_fn, loss_fn, leaves, x, y, key, mesh=mesh,
            n_microbatches=8)
        loss_s, grads_s, _ = par.pipeline_train_1f1b(
            stage_fn, loss_fn, leaves, x, y, key, mesh=None)
        assert float(loss_p) == pytest.approx(float(loss_s), rel=1e-5)
        for gp, gs in zip(grads_p, grads_s):
            onp.testing.assert_allclose(onp.asarray(gp), onp.asarray(gs),
                                        rtol=1e-4, atol=1e-5)

    def test_pipelined_block_flag(self):
        with pytest.raises(ValueError, match="schedule"):
            par.Pipelined(lambda: None, n_stages=4, schedule="zigzag")


class _ResLayer(mx.gluon.HybridBlock):
    """Shape-preserving residual stage for pipeline tests."""

    def __init__(self, d, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.fc = nn.Dense(d, flatten=False)

    def hybrid_forward(self, F, x):
        return x + F.tanh(self.fc(x))


class TestTrainStep1F1B:
    """VERDICT r3 #9: the SAME user code runs GPipe or 1F1B by flag —
    ``TrainStep(Pipelined(..., schedule=...), loss, opt)``. Gate: the two
    schedules produce matching losses and updated parameters."""

    D, B, T, S = 12, 8, 4, 4

    def _build_net(self, schedule):
        net = par.Pipelined(lambda: _ResLayer(self.D), n_stages=self.S,
                            layers_per_stage=1, n_microbatches=4,
                            schedule=schedule)
        net.initialize()
        return net

    def _batch(self):
        rs = onp.random.RandomState(11)
        x = mx.nd.array(rs.randn(self.B, self.T, self.D).astype("float32"))
        y = mx.nd.array(rs.randn(self.B, self.T, self.D).astype("float32"))
        return x, y

    def _run_one_step(self, schedule, x, y, donor=None):
        net = self._build_net(schedule)
        net(x)  # settle stacked shapes
        if donor is not None:
            for p_dst, p_src in zip(net.collect_params().values(),
                                    donor.collect_params().values()):
                p_dst.set_data(p_src.data())
        mesh = par.make_mesh({"pp": self.S},
                             devices=jax.devices()[:self.S])
        step = par.TrainStep(net, gloss.L2Loss(), "sgd", mesh=mesh,
                             rules=par.pipeline_sharding_rules(),
                             loss_only=True,
                             optimizer_params={"learning_rate": 0.2})
        loss, _ = step(x, y)
        return net, float(loss.asnumpy())

    def test_same_start_same_result(self):
        x, y = self._batch()
        donor = self._build_net("gpipe")
        donor(x)  # settle; donor is never stepped
        net_g, loss_g = self._run_one_step("gpipe", x, y, donor=donor)
        net_f, loss_f = self._run_one_step("1f1b", x, y, donor=donor)
        assert loss_f == pytest.approx(loss_g, rel=1e-4)
        for (k1, p1), (k2, p2) in zip(
                sorted(net_g._collect_params_with_prefix().items()),
                sorted(net_f._collect_params_with_prefix().items())):
            onp.testing.assert_allclose(
                p1.data().asnumpy(), p2.data().asnumpy(),
                rtol=2e-4, atol=2e-5, err_msg=f"{k1} vs {k2}")


class TestTrainStepRemat:
    """TrainStep(remat=...) — the policy knob threaded through
    parallel/step.py (ISSUE 7): any compiled step can trade recompute
    for memory, with a bit-identical loss trajectory."""

    def _run(self, remat, steps=3, donate=False):
        mx.random.seed(0)
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(32, in_units=16, flatten=False,
                             activation="gelu"))
            net.add(nn.Dense(8, flatten=False))
        net.initialize()
        net(mx.nd.zeros((1, 16)))
        rs = onp.random.RandomState(5)
        # definition order, NOT sorted-by-name: auto-prefix counters
        # advance across tests, and "dense10_" sorts before "dense9_"
        for p in net.collect_params().values():
            p.set_data(mx.nd.array(
                rs.randn(*p.shape).astype(onp.float32) * 0.1))
        step = par.TrainStep(net, gloss.L2Loss(), "sgd",
                             optimizer_params={"learning_rate": 0.05},
                             remat=remat, donate_inputs=donate)
        rs2 = onp.random.RandomState(1)
        losses = []
        for _ in range(steps):
            x = mx.nd.array(rs2.randn(4, 16).astype(onp.float32))
            y = mx.nd.array(rs2.randn(4, 8).astype(onp.float32))
            losses.append(float(step(x, y)[0].asnumpy()))
        return losses

    def test_policies_match_no_remat(self):
        base = self._run(None)
        assert self._run("full") == base
        assert self._run("dots") == base

    def test_invalid_policy_raises_at_construction(self):
        net = nn.Dense(4, in_units=4)
        net.initialize()
        with pytest.raises(ValueError, match="remat policy"):
            par.TrainStep(net, gloss.L2Loss(), "sgd", remat="bogus")

    def test_remat_composes_with_donation(self):
        # fresh buffers per step: remat + donate_inputs train together
        base = self._run(None)
        assert self._run("full", donate=True) == base


class TestDonateInputsShapeChange:
    """Regression (ISSUE 7 satellite): a donating TrainStep reused after
    a shape change must invalidate its cached lowering and refuse a
    donated-dead buffer with a clear error — never dispatch against it."""

    def _make(self):
        net = nn.Dense(8, in_units=16, flatten=False)
        net.initialize()
        return par.TrainStep(net, gloss.L2Loss(), "sgd",
                             optimizer_params={"learning_rate": 0.1},
                             donate_inputs=True)

    @staticmethod
    def _batch(rs, b):
        return (mx.nd.array(rs.randn(b, 16).astype(onp.float32)),
                mx.nd.array(rs.randn(b, 8).astype(onp.float32)))

    def test_fresh_buffers_across_shape_changes(self):
        step = self._make()
        rs = onp.random.RandomState(0)
        for b in (4, 6, 4, 6):
            x, y = self._batch(rs, b)
            loss, _ = step(x, y)
            assert onp.isfinite(loss.asnumpy()).all()

    def test_donated_reuse_raises_mxnet_error(self):
        from mxnet_tpu.base import MXNetError

        step = self._make()
        rs = onp.random.RandomState(0)
        xa, ya = self._batch(rs, 4)
        step(xa, ya)[0].asnumpy()          # donates xa/ya buffers
        xb, yb = self._batch(rs, 6)
        step(xb, yb)[0].asnumpy()          # shape change
        with pytest.raises(MXNetError, match="donated"):
            step(xa, ya)                   # dead buffers, clear error

    def test_shape_change_invalidates_cached_lowering(self):
        step = self._make()
        rs = onp.random.RandomState(0)
        step(*self._batch(rs, 4))[0].asnumpy()
        assert len(step._cache) == 1
        step(*self._batch(rs, 6))[0].asnumpy()
        # the shape-A lowering (donated-dead inputs) must be gone
        assert len(step._cache) == 1
