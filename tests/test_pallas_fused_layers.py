"""Fused Pallas layer-kernel tests (ISSUE 7 tentpole).

The kernels run in interpret mode on the CPU oracle (pattern:
test_pallas_kernels.py); on real TPU the same tests validate the
compiled kernels. Bit-/tolerance-identity contract: the fused
``fused_layer_norm`` / ``fused_rms_norm`` / ``fused_bias_gelu`` forward
AND grads must match the eager ops/nn.py path across the shape gates,
and the op-level routing (``MXNET_PALLAS_FUSED=1``) must be a pure
dispatch decision — identical math either way.
"""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu.pallas_kernels import fused_layers as fl

pytestmark = pytest.mark.pallas


def _rows(shape=(16, 256), seed=0, dtype="float32"):
    rs = np.random.RandomState(seed)
    return jnp.asarray(rs.randn(*shape).astype(dtype))


def _vec(d=256, seed=1):
    rs = np.random.RandomState(seed)
    return jnp.asarray(rs.randn(d).astype("float32"))


class TestFusedLayerNorm:
    def test_plain_matches_eager_layer_norm(self):
        """No residual/dropout: the kernel must match the eager
        ops/nn.py::layer_norm math (f32 stats, centered variance)."""
        from mxnet_tpu.ops.nn import layer_norm

        x, g, b = _rows(), _vec(seed=1), _vec(seed=2)
        out = fl.fused_layer_norm(x, g, b, interpret=True)
        ref = layer_norm(x, g, b)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("shape", [(16, 128), (8, 16, 256),
                                       (24, 768), (8, 1024)])
    def test_shapes_across_gates(self, shape):
        x = _rows(shape)
        g, b = _vec(shape[-1], 1), _vec(shape[-1], 2)
        res = _rows(shape, seed=5)
        out = fl.fused_layer_norm(x, g, b, res, interpret=True)
        ref = fl.fused_layer_norm_reference(x, g, b, res)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_residual_dropout_matches_reference(self):
        """The kernel's stateless hash mask must be BITWISE the
        reference's — same elements dropped, values then equal to
        tolerance."""
        x, res = _rows(), _rows(seed=3)
        g, b = _vec(seed=1), _vec(seed=2)
        seed = jnp.asarray(11, jnp.uint32)
        out = fl.fused_layer_norm(x, g, b, res, dropout=0.25, seed=seed,
                                  interpret=True)
        ref = fl.fused_layer_norm_reference(x, g, b, res, dropout=0.25,
                                            seed=seed)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_gradients_match_reference(self):
        """Backward recomputes xhat from saved (mean, rstd) — dx/dres/
        dgamma/dbeta must match autodiff through the eager composition,
        with the dropout mask regenerated bit-identically."""
        x, res = _rows(), _rows(seed=3)
        g, b = _vec(seed=1), _vec(seed=2)
        seed = jnp.asarray(5, jnp.uint32)

        def lf(x, res, g, b):
            return jnp.sum(fl.fused_layer_norm(
                x, g, b, res, dropout=0.25, seed=seed,
                interpret=True) ** 2)

        def lr(x, res, g, b):
            return jnp.sum(fl.fused_layer_norm_reference(
                x, g, b, res, dropout=0.25, seed=seed) ** 2)

        gf = jax.grad(lf, argnums=(0, 1, 2, 3))(x, res, g, b)
        gr = jax.grad(lr, argnums=(0, 1, 2, 3))(x, res, g, b)
        for a, r, name in zip(gf, gr, ("dx", "dres", "dgamma", "dbeta")):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                       rtol=2e-4, atol=2e-4,
                                       err_msg=name)

    def test_gradients_no_dropout_no_residual(self):
        x, g, b = _rows(), _vec(seed=1), _vec(seed=2)

        def lf(x, g, b):
            return jnp.sum(fl.fused_layer_norm(x, g, b,
                                               interpret=True) ** 2)

        def lr(x, g, b):
            return jnp.sum(fl.fused_layer_norm_reference(x, g, b) ** 2)

        gf = jax.grad(lf, argnums=(0, 1, 2))(x, g, b)
        gr = jax.grad(lr, argnums=(0, 1, 2))(x, g, b)
        for a, r, name in zip(gf, gr, ("dx", "dgamma", "dbeta")):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                       rtol=2e-4, atol=2e-4,
                                       err_msg=name)

    def test_bf16_tolerance(self):
        x = _rows().astype(jnp.bfloat16)
        res = _rows(seed=3).astype(jnp.bfloat16)
        g, b = _vec(seed=1), _vec(seed=2)
        out = fl.fused_layer_norm(x, g, b, res, interpret=True)
        ref = fl.fused_layer_norm_reference(x, g, b, res)
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(ref, np.float32),
                                   rtol=0.05, atol=0.05)

    def test_dropout_requires_seed(self):
        x, g, b = _rows(), _vec(seed=1), _vec(seed=2)
        with pytest.raises(ValueError, match="seed"):
            fl.fused_layer_norm(x, g, b, dropout=0.1, interpret=True)

    def test_shape_gate(self):
        """fused_ln_shape_supported: lane-aligned feature dim, 8-multiple
        rows, VMEM-resident D; fused_ln_supported additionally requires
        TPU execution (False on the CPU test platform)."""
        ok = jnp.zeros((16, 256))
        assert fl.fused_ln_shape_supported(ok)
        assert not fl.fused_ln_shape_supported(jnp.zeros((16, 100)))
        assert not fl.fused_ln_shape_supported(jnp.zeros((15, 256)))
        assert not fl.fused_ln_shape_supported(jnp.zeros((16, 16384)))
        assert not fl.fused_ln_shape_supported(jnp.zeros((256,)))
        # platform gate: no TPU in the CPU test process
        assert not fl.fused_ln_supported(ok)


class TestFusedRMSNorm:
    def test_matches_eager_rms_norm(self):
        from mxnet_tpu.ops.attention import rms_norm

        x, w = _rows(), _vec(seed=4)
        out = fl.fused_rms_norm(x, w, interpret=True)
        ref = rms_norm(x, w)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_gradients_match(self):
        x, w = _rows(), _vec(seed=4)
        gf = jax.grad(lambda x, w: jnp.sum(
            fl.fused_rms_norm(x, w, interpret=True) ** 2),
            argnums=(0, 1))(x, w)
        gr = jax.grad(lambda x, w: jnp.sum(
            fl.fused_rms_norm_reference(x, w) ** 2), argnums=(0, 1))(x, w)
        for a, r, name in zip(gf, gr, ("dx", "dw")):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                       rtol=2e-4, atol=2e-4,
                                       err_msg=name)

    def test_mixed_dtype_promotes_like_eager(self):
        """bf16 activations with f32 norm weights: the eager path rounds
        xhat to bf16 then promotes by the weight multiply — the kernel
        must produce the same dtype AND the same rounding."""
        x = _rows((8, 256)).astype(jnp.bfloat16)
        w = _vec(256, 4)  # f32
        out = fl.fused_rms_norm(x, w, interpret=True)
        ref = fl.fused_rms_norm_reference(x, w)
        assert out.dtype == ref.dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-6, atol=1e-6)

    def test_bf16_llama_shape(self):
        x = _rows((4, 8, 512)).astype(jnp.bfloat16)
        w = _vec(512, 4)
        out = fl.fused_rms_norm(x, w, interpret=True)
        ref = fl.fused_rms_norm_reference(x, w)
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(ref, np.float32),
                                   rtol=0.05, atol=0.05)


class TestFusedBiasGelu:
    def test_matches_eager_dense_epilogue(self):
        """gelu(x + bias) must equal the unfused pair (bias add in the
        matmul dtype, then exact-erf Activation gelu)."""
        x, b = _rows(), _vec(seed=6)
        out = fl.fused_bias_gelu(x, b, interpret=True)
        ref = jax.nn.gelu(x + b.astype(x.dtype), approximate=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_gradients_match(self):
        x, b = _rows(), _vec(seed=6)
        gf = jax.grad(lambda x, b: jnp.sum(
            fl.fused_bias_gelu(x, b, interpret=True) ** 2),
            argnums=(0, 1))(x, b)
        gr = jax.grad(lambda x, b: jnp.sum(
            fl.fused_bias_gelu_reference(x, b) ** 2), argnums=(0, 1))(x, b)
        for a, r, name in zip(gf, gr, ("dx", "dbias")):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                       rtol=2e-4, atol=2e-4,
                                       err_msg=name)

    def test_bf16(self):
        x = _rows((8, 16, 128)).astype(jnp.bfloat16)
        b = _vec(128, 6)
        out = fl.fused_bias_gelu(x, b, interpret=True)
        ref = fl.fused_bias_gelu_reference(x, b)
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(ref, np.float32),
                                   rtol=0.05, atol=0.05)


class TestOpRouting:
    """The ops/nn.py + model-zoo seams: MXNET_PALLAS_FUSED toggles a pure
    dispatch decision. On the CPU platform the fused ops take the
    reference composition, so env on/off must be value-identical for
    dropout-free graphs."""

    def test_fused_ops_env_off_is_eager(self, monkeypatch):
        import mxnet_tpu as mx

        monkeypatch.delenv("MXNET_PALLAS_FUSED", raising=False)
        x = mx.nd.array(np.random.RandomState(0)
                        .randn(4, 256).astype(np.float32))
        g = mx.nd.array(np.ones(256, np.float32))
        b = mx.nd.array(np.zeros(256, np.float32))
        fused = mx.nd.fused_layer_norm(x, g, b)
        plain = mx.nd.LayerNorm(x, g, b)
        np.testing.assert_allclose(fused.asnumpy(), plain.asnumpy(),
                                   rtol=1e-6, atol=1e-6)

    def test_fused_layer_norm_op_residual(self, monkeypatch):
        import mxnet_tpu as mx

        monkeypatch.setenv("MXNET_PALLAS_FUSED", "1")
        rs = np.random.RandomState(1)
        x = mx.nd.array(rs.randn(4, 256).astype(np.float32))
        res = mx.nd.array(rs.randn(4, 256).astype(np.float32))
        g = mx.nd.array(rs.randn(256).astype(np.float32))
        b = mx.nd.array(rs.randn(256).astype(np.float32))
        out = mx.nd.fused_layer_norm(x, g, b, res)
        ref = mx.nd.LayerNorm(x + res, g, b)
        np.testing.assert_allclose(out.asnumpy(), ref.asnumpy(),
                                   rtol=1e-5, atol=1e-5)

    def test_fused_bias_gelu_op_matches_dense_pair(self, monkeypatch):
        import mxnet_tpu as mx

        monkeypatch.setenv("MXNET_PALLAS_FUSED", "1")
        rs = np.random.RandomState(2)
        x = mx.nd.array(rs.randn(4, 128).astype(np.float32))
        b = mx.nd.array(rs.randn(128).astype(np.float32))
        out = mx.nd.fused_bias_gelu(x, b)
        ref = mx.nd.Activation(x + b, act_type="gelu")
        np.testing.assert_allclose(out.asnumpy(), ref.asnumpy(),
                                   rtol=1e-6, atol=1e-6)

    def test_encoder_cell_fused_path_matches(self, monkeypatch):
        """TransformerEncoderCell (the BERT building block) with the
        fused add+norm + bias+gelu path vs the eager path — identical
        at dropout=0 (one forward+backward)."""
        import mxnet_tpu as mx
        from mxnet_tpu import autograd
        from mxnet_tpu.gluon.model_zoo.nlp.transformer import (
            TransformerEncoderCell)

        def run(env):
            if env:
                monkeypatch.setenv("MXNET_PALLAS_FUSED", "1")
            else:
                monkeypatch.delenv("MXNET_PALLAS_FUSED", raising=False)
            mx.random.seed(0)
            cell = TransformerEncoderCell(64, 128, 4, dropout=0.0,
                                          activation="gelu")
            cell.initialize()
            x = mx.nd.array(np.random.RandomState(1)
                            .randn(2, 16, 64).astype(np.float32))
            cell(x)  # settle deferred shapes
            rs = np.random.RandomState(3)
            for name, p in sorted(cell.collect_params().items()):
                p.set_data(mx.nd.array(
                    rs.randn(*p.shape).astype(np.float32) * 0.05))
            x.attach_grad()
            with autograd.record():
                y = cell(x)
            y.backward()
            return y.asnumpy(), x.grad.asnumpy()

        y0, g0 = run(False)
        y1, g1 = run(True)
        np.testing.assert_allclose(y1, y0, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(g1, g0, rtol=1e-5, atol=1e-5)

    def test_encoder_cell_fused_dropout_trains(self, monkeypatch):
        """Dropout > 0 through the fused op (hash mask, gated rng draw):
        forward+backward runs and produces finite grads."""
        import mxnet_tpu as mx
        from mxnet_tpu import autograd
        from mxnet_tpu.gluon.model_zoo.nlp.transformer import (
            TransformerEncoderCell)

        monkeypatch.setenv("MXNET_PALLAS_FUSED", "1")
        mx.random.seed(0)
        cell = TransformerEncoderCell(64, 128, 4, dropout=0.1,
                                      activation="gelu")
        cell.initialize()
        x = mx.nd.array(np.random.RandomState(1)
                        .randn(2, 16, 64).astype(np.float32))
        x.attach_grad()
        with autograd.record():
            y = cell(x)
        y.backward()
        assert np.isfinite(y.asnumpy()).all()
        assert np.isfinite(x.grad.asnumpy()).all()

    def test_knob_toggle_invalidates_eager_op_cache(self, monkeypatch):
        """MXNET_PALLAS_FUSED keys the per-op executable cache (like
        `platform`): toggling it mid-process must re-trace, not replay
        the previously-routed body."""
        import mxnet_tpu as mx
        from mxnet_tpu import telemetry

        monkeypatch.delenv("MXNET_PALLAS_FUSED", raising=False)
        x = mx.nd.array(np.zeros((8, 256), np.float32))
        g = mx.nd.array(np.ones(256, np.float32))
        b = mx.nd.array(np.zeros(256, np.float32))
        # a unique attr value gives this test its own cache entries —
        # the per-op cache key is shape-independent, so sibling tests
        # would otherwise have pre-warmed both knob states
        eps = 1.2345e-5
        telemetry.enable()
        try:
            def counts():
                fam = telemetry.snapshot()["metrics"].get(
                    "mxnet_jit_cache_total")
                out = {(s["labels"]["cache"], s["labels"]["result"]):
                       s["value"] for s in (fam["samples"] if fam
                                            else ())}
                return (out.get(("eager_op", "hit"), 0),
                        out.get(("eager_op", "miss"), 0))

            mx.nd.LayerNorm(x, g, b, eps=eps)      # knob-off: miss
            _, m1 = counts()
            mx.nd.LayerNorm(x, g, b, eps=eps)      # warm replay: hit
            h2, m2 = counts()
            assert m2 == m1 and h2 >= 1
            monkeypatch.setenv("MXNET_PALLAS_FUSED", "1")
            mx.nd.LayerNorm(x, g, b, eps=eps)      # knob flip: re-trace
            _, m3 = counts()
            assert m3 == m2 + 1
        finally:
            telemetry.disable()
            telemetry.reset()

    def test_pallas_dispatch_telemetry(self, monkeypatch):
        """mxnet_pallas_dispatch_total{kernel} counts kernel routings —
        zero here (CPU platform keeps the eager path), present as a
        family once a routing records."""
        import mxnet_tpu as mx
        from mxnet_tpu import telemetry

        monkeypatch.setenv("MXNET_PALLAS_FUSED", "1")
        telemetry.enable()
        try:
            x = mx.nd.array(np.zeros((8, 256), np.float32))
            g = mx.nd.array(np.ones(256, np.float32))
            b = mx.nd.array(np.zeros(256, np.float32))
            mx.nd.fused_layer_norm(x, g, b)  # CPU -> eager, no dispatch
            fam = telemetry.snapshot()["metrics"].get(
                "mxnet_pallas_dispatch_total")
            counts = {s["labels"]["kernel"]: s["value"]
                      for s in (fam["samples"] if fam else ())}
            assert counts.get("fused_layer_norm", 0) == 0
            # record directly (the TPU-routing path's call)
            telemetry.record_pallas_dispatch("fused_layer_norm")
            fam = telemetry.snapshot()["metrics"][
                "mxnet_pallas_dispatch_total"]
            counts = {s["labels"]["kernel"]: s["value"]
                      for s in fam["samples"]}
            assert counts["fused_layer_norm"] == 1
        finally:
            telemetry.disable()
            telemetry.reset()
