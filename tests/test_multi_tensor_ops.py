"""Multi-tensor fused optimizer ops vs the single-tensor oracle.

Reference strategy: upstream tests multi_sgd_* against looped sgd_update
(tests/python/unittest/test_optimizer.py::test_multi_sgd).
"""
import numpy as np
import pytest

import mxnet_tpu as mx


def _params(n=3, seed=0, dtype=np.float32):
    rs = np.random.RandomState(seed)
    shapes = [(4, 5), (7,), (2, 3, 2)][:n]
    ws = [mx.nd.array(rs.randn(*s).astype(dtype)) for s in shapes]
    gs = [mx.nd.array(rs.randn(*s).astype(dtype)) for s in shapes]
    return ws, gs


LRS = (0.1, 0.01, 0.2)
WDS = (0.0, 1e-4, 1e-3)


def test_multi_sgd_update_matches_loop():
    ws, gs = _params()
    inputs = [t for pair in zip(ws, gs) for t in pair]
    outs = mx.nd.multi_sgd_update(*inputs, lrs=LRS, wds=WDS,
                                  rescale_grad=0.5, num_weights=3)
    for i, (w, g) in enumerate(zip(ws, gs)):
        want = mx.nd.sgd_update(w, g, lr=LRS[i], wd=WDS[i], rescale_grad=0.5)
        np.testing.assert_allclose(outs[i].asnumpy(), want.asnumpy(),
                                   rtol=1e-6)


def test_multi_sgd_mom_update_matches_loop():
    ws, gs = _params()
    ms = [mx.nd.zeros(w.shape) + 0.1 for w in ws]
    inputs = [t for trip in zip(ws, gs, ms) for t in trip]
    outs = mx.nd.multi_sgd_mom_update(*inputs, lrs=LRS, wds=WDS,
                                      momentum=0.9, num_weights=3)
    for i, (w, g, m) in enumerate(zip(ws, gs, ms)):
        w2, m2 = mx.nd.sgd_mom_update(w, g, m, lr=LRS[i], wd=WDS[i],
                                      momentum=0.9)
        np.testing.assert_allclose(outs[2 * i].asnumpy(), w2.asnumpy(),
                                   rtol=1e-6)
        np.testing.assert_allclose(outs[2 * i + 1].asnumpy(), m2.asnumpy(),
                                   rtol=1e-6)


def test_multi_mp_sgd_mom_update_matches_loop():
    ws, gs = _params(dtype=np.float16)
    ms = [mx.nd.zeros(w.shape, dtype="float32") for w in ws]
    w32s = [w.astype("float32") for w in ws]
    inputs = [t for quad in zip(ws, gs, ms, w32s) for t in quad]
    outs = mx.nd.multi_mp_sgd_mom_update(*inputs, lrs=LRS, wds=WDS,
                                         momentum=0.9, num_weights=3)
    for i, (w, g, m, w32) in enumerate(zip(ws, gs, ms, w32s)):
        w2, m2, w322 = mx.nd.mp_sgd_mom_update(w, g, m, w32, lr=LRS[i],
                                               wd=WDS[i], momentum=0.9)
        np.testing.assert_allclose(outs[3 * i].asnumpy(), w2.asnumpy(),
                                   rtol=1e-3)
        np.testing.assert_allclose(outs[3 * i + 2].asnumpy(), w322.asnumpy(),
                                   rtol=1e-6)
    assert outs[0].dtype == np.float16  # low-precision weight kept
    assert outs[2].dtype == np.float32  # master copy fp32


def test_preloaded_multi_sgd_update_tensor_lrs():
    ws, gs = _params()
    inputs = [t for pair in zip(ws, gs) for t in pair]
    lrs_t = mx.nd.array(np.array(LRS, np.float32))
    wds_t = mx.nd.array(np.array(WDS, np.float32))
    outs = mx.nd.preloaded_multi_sgd_update(*inputs, lrs_t, wds_t,
                                            num_weights=3)
    for i, (w, g) in enumerate(zip(ws, gs)):
        want = mx.nd.sgd_update(w, g, lr=LRS[i], wd=WDS[i])
        np.testing.assert_allclose(outs[i].asnumpy(), want.asnumpy(),
                                   rtol=1e-6)


def test_multi_sum_sq():
    ws, _ = _params()
    out = mx.nd.multi_sum_sq(*ws, num_arrays=3)
    want = np.array([float((w.asnumpy() ** 2).sum()) for w in ws], np.float32)
    np.testing.assert_allclose(out.asnumpy(), want, rtol=1e-5)


def test_multi_mp_sgd_update_matches_loop():
    ws, gs = _params(dtype=np.float16)
    w32s = [w.astype("float32") for w in ws]
    inputs = [t for trip in zip(ws, gs, w32s) for t in trip]
    outs = mx.nd.multi_mp_sgd_update(*inputs, lrs=LRS, wds=WDS, num_weights=3)
    for i, (w, g, w32) in enumerate(zip(ws, gs, w32s)):
        w2, w322 = mx.nd.mp_sgd_update(w, g, w32, lr=LRS[i], wd=WDS[i])
        np.testing.assert_allclose(outs[2 * i].asnumpy(), w2.asnumpy(),
                                   rtol=1e-3)
        np.testing.assert_allclose(outs[2 * i + 1].asnumpy(),
                                   w322.asnumpy(), rtol=1e-6)


# ---------------------------------------------------------------------------
# packed-layout re-expression (the fused-sweep engine behind the ops)
# ---------------------------------------------------------------------------


def test_multi_sgd_mixed_dtype_buckets():
    """A call mixing fp32 and fp16 weights splits into per-dtype packed
    buckets and still matches the looped oracle member-wise."""
    ws32, gs32 = _params(n=2, seed=1)
    ws16, gs16 = _params(n=2, seed=2, dtype=np.float16)
    ws = [ws32[0], ws16[0], ws32[1], ws16[1]]
    gs = [gs32[0], gs16[0], gs32[1], gs16[1]]
    lrs = (0.1, 0.2, 0.05, 0.15)
    wds = (0.0, 1e-3, 1e-4, 0.0)
    inputs = [t for pair in zip(ws, gs) for t in pair]
    outs = mx.nd.multi_sgd_update(*inputs, lrs=lrs, wds=wds,
                                  num_weights=4)
    for i, (w, g) in enumerate(zip(ws, gs)):
        want = mx.nd.sgd_update(w, g, lr=lrs[i], wd=wds[i])
        assert outs[i].dtype == w.dtype
        np.testing.assert_allclose(outs[i].asnumpy().astype(np.float32),
                                   want.asnumpy().astype(np.float32),
                                   rtol=2e-3)


def test_multi_sgd_mom_zero_momentum_still_rewrites_mom():
    """momentum=0 through the packed path keeps the op contract: the
    momentum buffer is rewritten to -lr*g, not passed through."""
    ws, gs = _params(n=2)
    ms = [mx.nd.zeros(w.shape) + 0.5 for w in ws]
    inputs = [t for trip in zip(ws, gs, ms) for t in trip]
    outs = mx.nd.multi_sgd_mom_update(*inputs, lrs=LRS[:2], wds=WDS[:2],
                                      momentum=0.0, num_weights=2)
    for i, (w, g, m) in enumerate(zip(ws, gs, ms)):
        w2, m2 = mx.nd.sgd_mom_update(w, g, m, lr=LRS[i], wd=WDS[i],
                                      momentum=0.0)
        np.testing.assert_allclose(outs[2 * i + 1].asnumpy(),
                                   m2.asnumpy(), rtol=1e-6)
        assert not np.allclose(outs[2 * i + 1].asnumpy(), 0.5)


def _lamb_loop_oracle(w, g, m, v, lr, wd, t, **kw):
    """Looped single-tensor composition: phase1 -> norms -> phase2."""
    upd, m2, v2 = mx.nd.lamb_update_phase1(
        w, g, m, v, t=t, wd=wd, **kw)
    r1 = w.norm()
    r2 = upd.norm()
    w2 = mx.nd.lamb_update_phase2(w, upd, r1, r2, lr=lr)
    return w2, m2, v2


def test_multi_lamb_update_matches_loop():
    ws, gs = _params()
    ms = [mx.nd.zeros(w.shape) + 0.01 for w in ws]
    vs = [mx.nd.zeros(w.shape) + 0.001 for w in ws]
    inputs = [t for quad in zip(ws, gs, ms, vs) for t in quad]
    outs = mx.nd.multi_lamb_update(*inputs, lrs=LRS, wds=WDS, t=3,
                                   rescale_grad=0.5, num_weights=3)
    for i in range(3):
        w2, m2, v2 = _lamb_loop_oracle(
            ws[i], gs[i], ms[i], vs[i], LRS[i], WDS[i], 3,
            rescale_grad=0.5)
        np.testing.assert_allclose(outs[3 * i].asnumpy(), w2.asnumpy(),
                                   rtol=1e-5, atol=1e-7)
        np.testing.assert_allclose(outs[3 * i + 1].asnumpy(),
                                   m2.asnumpy(), rtol=1e-6)
        np.testing.assert_allclose(outs[3 * i + 2].asnumpy(),
                                   v2.asnumpy(), rtol=1e-6)


def test_multi_mp_lamb_update_matches_loop():
    ws, gs = _params(dtype=np.float16)
    w32s = [w.astype("float32") for w in ws]
    ms = [mx.nd.zeros(w.shape, dtype="float32") for w in ws]
    vs = [mx.nd.zeros(w.shape, dtype="float32") + 1e-4 for w in ws]
    inputs = [t for q in zip(ws, gs, ms, vs, w32s) for t in q]
    outs = mx.nd.multi_mp_lamb_update(*inputs, lrs=LRS, wds=WDS, t=2,
                                      num_weights=3)
    for i in range(3):
        g32 = gs[i].astype("float32")
        upd, m2, v2 = mx.nd.mp_lamb_update_phase1(
            ws[i], g32, ms[i], vs[i], w32s[i], t=2, wd=WDS[i])
        r1 = w32s[i].norm()
        r2 = upd.norm()
        w2, w322 = mx.nd.mp_lamb_update_phase2(
            ws[i], upd, r1, r2, w32s[i], lr=LRS[i])
        assert outs[4 * i].dtype == np.float16       # low weight kept
        assert outs[4 * i + 3].dtype == np.float32   # master fp32
        np.testing.assert_allclose(
            outs[4 * i].asnumpy().astype(np.float32),
            w2.asnumpy().astype(np.float32), rtol=2e-3)
        np.testing.assert_allclose(outs[4 * i + 3].asnumpy(),
                                   w322.asnumpy(), rtol=1e-5, atol=1e-7)
        np.testing.assert_allclose(outs[4 * i + 1].asnumpy(),
                                   m2.asnumpy(), rtol=1e-6)


def test_packed_sweep_pallas_interpret_matches_lax():
    """The Pallas sweep kernel (interpret mode = CPU oracle) agrees with
    the identical-formula lax fallback to FMA-contraction tolerance."""
    from mxnet_tpu.optimizer import multi_tensor as mt

    rs = np.random.RandomState(0)
    shapes = [(4, 5), (7,), (2, 3, 2)]
    ws = [rs.randn(*s).astype(np.float32) for s in shapes]
    gs = [rs.randn(*s).astype(np.float32) for s in shapes]
    ms = [np.zeros(s, np.float32) + 0.1 for s in shapes]
    vs = [np.zeros(s, np.float32) + 0.2 for s in shapes]
    static = {"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8,
              "clip_gradient": None}
    ins = {"w": ws, "g": gs, "mean": ms, "var": vs}
    vecs = {"lr": list(LRS), "wd": list(WDS)}
    lax_out = mt.packed_apply("adam", static, shapes, ins, vecs, 0.5,
                              platform="cpu")
    ker_out = mt.packed_apply("adam", static, shapes, ins, vecs, 0.5,
                              platform="cpu", interpret=True)
    for role in ("w", "mean", "var"):
        for a, b in zip(lax_out[role], ker_out[role]):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-7)


def test_segment_sumsq_matches_per_member_norms():
    import jax.numpy as jnp

    from mxnet_tpu.optimizer import multi_tensor as mt

    rs = np.random.RandomState(3)
    shapes = [(16, 32), (7,), (3, 5, 7)]
    arrs = [rs.randn(*s).astype(np.float32) for s in shapes]
    sizes = [int(np.prod(s)) for s in shapes]
    offsets = np.concatenate([[0], np.cumsum(sizes)]).tolist()
    flat = jnp.concatenate([jnp.asarray(a).reshape(-1) for a in arrs])
    out = np.asarray(mt.segment_sumsq(flat, shapes, offsets))
    for i, a in enumerate(arrs):
        want = float(jnp.sum(jnp.square(jnp.asarray(a))))
        assert out[i] == np.float32(want)
