"""Multi-tensor fused optimizer ops vs the single-tensor oracle.

Reference strategy: upstream tests multi_sgd_* against looped sgd_update
(tests/python/unittest/test_optimizer.py::test_multi_sgd).
"""
import numpy as np
import pytest

import mxnet_tpu as mx


def _params(n=3, seed=0, dtype=np.float32):
    rs = np.random.RandomState(seed)
    shapes = [(4, 5), (7,), (2, 3, 2)][:n]
    ws = [mx.nd.array(rs.randn(*s).astype(dtype)) for s in shapes]
    gs = [mx.nd.array(rs.randn(*s).astype(dtype)) for s in shapes]
    return ws, gs


LRS = (0.1, 0.01, 0.2)
WDS = (0.0, 1e-4, 1e-3)


def test_multi_sgd_update_matches_loop():
    ws, gs = _params()
    inputs = [t for pair in zip(ws, gs) for t in pair]
    outs = mx.nd.multi_sgd_update(*inputs, lrs=LRS, wds=WDS,
                                  rescale_grad=0.5, num_weights=3)
    for i, (w, g) in enumerate(zip(ws, gs)):
        want = mx.nd.sgd_update(w, g, lr=LRS[i], wd=WDS[i], rescale_grad=0.5)
        np.testing.assert_allclose(outs[i].asnumpy(), want.asnumpy(),
                                   rtol=1e-6)


def test_multi_sgd_mom_update_matches_loop():
    ws, gs = _params()
    ms = [mx.nd.zeros(w.shape) + 0.1 for w in ws]
    inputs = [t for trip in zip(ws, gs, ms) for t in trip]
    outs = mx.nd.multi_sgd_mom_update(*inputs, lrs=LRS, wds=WDS,
                                      momentum=0.9, num_weights=3)
    for i, (w, g, m) in enumerate(zip(ws, gs, ms)):
        w2, m2 = mx.nd.sgd_mom_update(w, g, m, lr=LRS[i], wd=WDS[i],
                                      momentum=0.9)
        np.testing.assert_allclose(outs[2 * i].asnumpy(), w2.asnumpy(),
                                   rtol=1e-6)
        np.testing.assert_allclose(outs[2 * i + 1].asnumpy(), m2.asnumpy(),
                                   rtol=1e-6)


def test_multi_mp_sgd_mom_update_matches_loop():
    ws, gs = _params(dtype=np.float16)
    ms = [mx.nd.zeros(w.shape, dtype="float32") for w in ws]
    w32s = [w.astype("float32") for w in ws]
    inputs = [t for quad in zip(ws, gs, ms, w32s) for t in quad]
    outs = mx.nd.multi_mp_sgd_mom_update(*inputs, lrs=LRS, wds=WDS,
                                         momentum=0.9, num_weights=3)
    for i, (w, g, m, w32) in enumerate(zip(ws, gs, ms, w32s)):
        w2, m2, w322 = mx.nd.mp_sgd_mom_update(w, g, m, w32, lr=LRS[i],
                                               wd=WDS[i], momentum=0.9)
        np.testing.assert_allclose(outs[3 * i].asnumpy(), w2.asnumpy(),
                                   rtol=1e-3)
        np.testing.assert_allclose(outs[3 * i + 2].asnumpy(), w322.asnumpy(),
                                   rtol=1e-6)
    assert outs[0].dtype == np.float16  # low-precision weight kept
    assert outs[2].dtype == np.float32  # master copy fp32


def test_preloaded_multi_sgd_update_tensor_lrs():
    ws, gs = _params()
    inputs = [t for pair in zip(ws, gs) for t in pair]
    lrs_t = mx.nd.array(np.array(LRS, np.float32))
    wds_t = mx.nd.array(np.array(WDS, np.float32))
    outs = mx.nd.preloaded_multi_sgd_update(*inputs, lrs_t, wds_t,
                                            num_weights=3)
    for i, (w, g) in enumerate(zip(ws, gs)):
        want = mx.nd.sgd_update(w, g, lr=LRS[i], wd=WDS[i])
        np.testing.assert_allclose(outs[i].asnumpy(), want.asnumpy(),
                                   rtol=1e-6)


def test_multi_sum_sq():
    ws, _ = _params()
    out = mx.nd.multi_sum_sq(*ws, num_arrays=3)
    want = np.array([float((w.asnumpy() ** 2).sum()) for w in ws], np.float32)
    np.testing.assert_allclose(out.asnumpy(), want, rtol=1e-5)


def test_multi_mp_sgd_update_matches_loop():
    ws, gs = _params(dtype=np.float16)
    w32s = [w.astype("float32") for w in ws]
    inputs = [t for trip in zip(ws, gs, w32s) for t in trip]
    outs = mx.nd.multi_mp_sgd_update(*inputs, lrs=LRS, wds=WDS, num_weights=3)
    for i, (w, g, w32) in enumerate(zip(ws, gs, w32s)):
        w2, w322 = mx.nd.mp_sgd_update(w, g, w32, lr=LRS[i], wd=WDS[i])
        np.testing.assert_allclose(outs[2 * i].asnumpy(), w2.asnumpy(),
                                   rtol=1e-3)
        np.testing.assert_allclose(outs[2 * i + 1].asnumpy(),
                                   w322.asnumpy(), rtol=1e-6)
