"""Continuous-batching autoregressive decode (mxnet_tpu/serving/
{kvcache,buckets,server}.py + the LLaMA paged decode engine): paged
KV-cache accounting (all-or-nothing admission, typed ``CacheFull``,
defrag), decode bit-identity against the full-recompute oracle,
requests joining and leaving the decode batch mid-stream, hot reload
deferred to completion boundaries, token streaming across the worker
wire protocol (crash mid-generate = typed failure, never a wedge), and
the zero-steady-state-retrace contract on the ``serving_decode``
compile-cache site.

The Pallas paged-attention kernel is checked in interpret mode against
the eager gather oracle (the same CPU-reference pattern as
test_pallas_kernels.py).
"""
import os
import sys
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import serving, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.serving import wire
from mxnet_tpu.serving.buckets import BucketGrid
from mxnet_tpu.serving.kvcache import (CacheFull, PagePool, apply_defrag,
                                       make_kv_arena)

pytestmark = pytest.mark.serving

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")
if FIXTURES not in sys.path:
    sys.path.insert(0, FIXTURES)

import worker_factory  # noqa: E402  (the fixtures dir is the point)

_NETS = {}


def get_net(seed=7):
    """One tiny LLaMA per seed, shared across tests: the decode engine's
    compile cache is keyed by architecture, so every server built from
    the same config re-hits the warm executables."""
    if seed not in _NETS:
        _NETS[seed] = worker_factory.tiny_llama(seed=seed)
    return _NETS[seed]


def oracle(net, prompt, n_new):
    """Full-recompute argmax decode — the bit-identity reference."""
    toks = list(prompt)
    for _ in range(n_new):
        logits = net(mx.nd.array(np.asarray(toks, np.int32)[None, :],
                                 dtype="int32")).asnumpy()
        toks.append(int(np.argmax(logits[0, -1])))
    return np.asarray(toks[len(prompt):], dtype=np.int32)


def make_server(net=None, **kw):
    kw.setdefault("batch_buckets", (1, 2))
    kw.setdefault("shape_buckets", [(8,)])
    kw.setdefault("slo_ms", 500.0)
    kw.setdefault("dtype", "int32")
    kw.setdefault("warmup", False)
    kw.setdefault("decode_pages", 96)
    kw.setdefault("page_size", 4)
    kw.setdefault("len_buckets", (8, 16))
    return serving.Server(net if net is not None else get_net(), **kw)


PROMPT_A = np.array([3, 1, 4, 1, 5], dtype=np.int32)
PROMPT_B = np.array([2, 7, 1, 8, 2, 8, 1], dtype=np.int32)


def wait_until(pred, timeout=30.0, interval=0.01, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


# ---------------------------------------------------------------------------
# PagePool accounting
# ---------------------------------------------------------------------------

class TestPagePool:
    def test_alloc_free_roundtrip(self):
        pool = PagePool(8, page_size=4)
        assert pool.capacity_tokens == 28          # scratch excluded
        pages = pool.alloc("a", 10)                # 3 pages
        assert len(pages) == 3 and 0 not in pages  # page 0 reserved
        assert pool.stats()["used"] == 3
        assert pool.free("a") == 3
        assert pool.stats() == {"free": 7, "used": 0, "reserved": 1,
                                "owners": 0, "page_size": 4, "n_pages": 8}
        assert pool.free("a") == 0                 # idempotent

    def test_exhaustion_is_all_or_nothing(self):
        pool = PagePool(4, page_size=4)            # 3 usable pages
        pool.alloc("a", 8)                         # 2 pages
        free_before = pool.stats()["free"]
        with pytest.raises(CacheFull):
            pool.alloc("b", 8)                     # needs 2, 1 free
        assert pool.stats()["free"] == free_before  # nothing leaked
        with pytest.raises(MXNetError):
            pool.alloc("a", 4)                     # double-alloc typed

    def test_extend_grows_or_fails_cleanly(self):
        pool = PagePool(5, page_size=4)
        pool.alloc("a", 4)
        assert len(pool.extend("a", 9)) == 3
        held = list(pool.page_table("a"))
        with pytest.raises(CacheFull):
            pool.extend("a", 100)
        assert list(pool.page_table("a")) == held  # unchanged on failure

    def test_page_table_pads_with_scratch(self):
        pool = PagePool(8, page_size=4)
        pool.alloc("a", 6)
        pt = pool.page_table("a", width=5)
        assert pt.dtype == np.int32 and pt.shape == (5,)
        assert list(pt[2:]) == [0, 0, 0]           # scratch-padded tail
        with pytest.raises(MXNetError):
            pool.page_table("a", width=1)

    def test_defrag_packs_and_moves_arena_rows(self):
        pool = PagePool(10, page_size=2)
        pool.alloc("a", 4)
        pool.alloc("b", 4)
        pool.alloc("c", 2)
        pool.free("a")                             # holes at the front
        arena, _ = make_kv_arena(1, pool, 1, 4)
        rs = np.random.RandomState(0)
        arena = jnp.asarray(rs.randn(*arena.shape).astype(np.float32))
        # remember where each live owner's tokens live pre-defrag
        def slots_of(owner):
            return [int(p) * 2 + i for p in pool.page_table(owner)
                    for i in range(2)]
        before = {o: np.asarray(arena[0, slots_of(o)]) for o in "bc"}
        moves = pool.defrag()
        assert moves                               # something moved
        live = sorted(p for o in "bc" for p in pool.page_table(o))
        assert live == list(range(1, len(live) + 1))   # packed low
        arena = apply_defrag(arena, moves, page_size=2)
        for o in "bc":                             # bytes followed pages
            np.testing.assert_array_equal(
                np.asarray(arena[0, slots_of(o)]), before[o])


# ---------------------------------------------------------------------------
# BucketGrid length buckets
# ---------------------------------------------------------------------------

class TestLenBuckets:
    def test_prefill_bucket_rounds_up_and_rejects(self):
        grid = BucketGrid((1, 2), [(8,)], len_buckets=(8, 16))
        assert grid.prefill_bucket(1) == 8
        assert grid.prefill_bucket(8) == 8
        assert grid.prefill_bucket(9) == 16
        with pytest.raises(MXNetError):
            grid.prefill_bucket(17)

    def test_generate_signatures_include_decode_column(self):
        grid = BucketGrid((1, 2), [(8,)], len_buckets=(8, 16))
        sigs = set(grid.generate_signatures())
        assert (1, 1) in sigs and (2, 1) in sigs   # the decode column
        assert (2, 8) in sigs and (2, 16) in sigs  # prefill grid


# ---------------------------------------------------------------------------
# decode correctness on the serving path
# ---------------------------------------------------------------------------

class TestDecodeBitIdentity:
    def test_tokens_match_full_recompute_oracle(self):
        net = get_net()
        want_a = oracle(net, PROMPT_A, 6)
        want_b = oracle(net, PROMPT_B, 5)
        srv = make_server().start()
        try:
            got = []
            ha = srv.submit_generate(
                PROMPT_A, 6, on_token=lambda i, t: got.append((i, t)))
            hb = srv.submit_generate(PROMPT_B, 5)
            np.testing.assert_array_equal(ha.result(timeout=120), want_a)
            np.testing.assert_array_equal(hb.result(timeout=120), want_b)
            # streaming saw every token, in order, exactly once
            assert got == list(enumerate(want_a))
            assert ha.tokens() == list(want_a)
            assert ha.next_token(2, timeout=5) == int(want_a[2])
            assert ha.next_token(99, timeout=5) is None  # ended first
            st = srv.stats()
            assert st["tokens"] == 11
            assert st["kvcache"]["used"] == 0      # all pages returned
        finally:
            srv.stop()

    def test_join_and_leave_mid_stream(self):
        net = get_net()
        want_a = oracle(net, PROMPT_A, 24)
        want_b = oracle(net, PROMPT_B, 4)
        done = {}
        srv = make_server().start()
        try:
            # pace A so B provably joins while A is mid-decode
            ha = srv.submit_generate(
                PROMPT_A, 24,
                on_token=lambda i, t: time.sleep(0.01))
            assert ha.next_token(0, timeout=120) == int(want_a[0])
            hb = srv.submit_generate(PROMPT_B, 4)
            hb.future.add_done_callback(
                lambda f: done.setdefault("b", time.monotonic()))
            ha.future.add_done_callback(
                lambda f: done.setdefault("a", time.monotonic()))
            np.testing.assert_array_equal(hb.result(timeout=120), want_b)
            np.testing.assert_array_equal(ha.result(timeout=120), want_a)
            assert done["b"] < done["a"]           # B left the batch first
            assert srv.stats()["kvcache"]["used"] == 0
        finally:
            srv.stop()

    def test_cache_admission(self):
        srv = make_server(decode_pages=8, page_size=4).start()
        # 8 pages -> 28-token budget; a request past it sheds typed NOW
        try:
            with pytest.raises(CacheFull):
                srv.submit_generate(PROMPT_A, 300)
            # two requests that cannot coexist (4 pages each, 7 free)
            # serialize through the pool instead of failing: the second
            # waits for the first's pages to come home
            net = get_net()
            want = oracle(net, PROMPT_A, 8)
            h1 = srv.submit_generate(PROMPT_A, 8)
            h2 = srv.submit_generate(PROMPT_A, 8)
            np.testing.assert_array_equal(h1.result(timeout=120), want)
            np.testing.assert_array_equal(h2.result(timeout=120), want)
            assert srv.stats()["kvcache"]["used"] == 0
        finally:
            srv.stop()

    def test_prompt_validation_is_synchronous(self):
        srv = make_server().start()
        try:
            with pytest.raises(MXNetError):
                srv.submit_generate(np.zeros((0,), np.int32), 4)
            with pytest.raises(MXNetError):
                srv.submit_generate(PROMPT_A, 0)
            with pytest.raises(MXNetError):       # no len bucket fits
                srv.submit_generate(np.zeros(17, np.int32), 4)
        finally:
            srv.stop()


class TestHotReload:
    def test_swap_never_lands_mid_request(self):
        net_a, net_b = get_net(7), get_net(8)
        want_a = oracle(net_a, PROMPT_A, 12)
        want_after = oracle(net_b, PROMPT_A, 4)
        srv = make_server(net_a).start()
        try:
            h = srv.submit_generate(
                PROMPT_A, 12, on_token=lambda i, t: time.sleep(0.01))
            assert h.next_token(0, timeout=120) is not None
            srv.swap_model(net_b)                  # mid-generate
            # the in-flight completion ran ENTIRELY on the old weights
            np.testing.assert_array_equal(h.result(timeout=120), want_a)
            # the next completion sees the new ones
            h2 = srv.submit_generate(PROMPT_A, 4)
            np.testing.assert_array_equal(h2.result(timeout=120),
                                          want_after)
        finally:
            srv.stop()


class TestRetracesAndTelemetry:
    def test_zero_steady_state_retraces(self):
        net = get_net()
        srv = make_server(net).start()
        was = telemetry.enabled()
        telemetry.reset()
        try:
            srv.submit_generate(PROMPT_A, 4).result(timeout=120)  # warm
            telemetry.enable()
            srv.submit_generate(PROMPT_B, 6).result(timeout=120)
            snap = telemetry.snapshot()["metrics"]["mxnet_jit_cache_total"]
            lookups = {tuple(s["labels"].values()): s["value"]
                       for s in snap["samples"]}
            assert lookups.get(("serving_decode", "hit"), 0) > 0
            assert ("serving_decode", "miss") not in lookups
        finally:
            srv.stop()
            telemetry.reset()
            if not was:
                telemetry.disable()

    def test_decode_metrics_published(self):
        was = telemetry.enabled()
        telemetry.reset()
        telemetry.enable()
        try:
            srv = make_server().start()
            try:
                srv.submit_generate(PROMPT_A, 3).result(timeout=120)
            finally:
                srv.stop()
            text = telemetry.prom_text()
            assert "mxnet_serving_decode_steps_total" in text
            assert "mxnet_serving_tokens_total 3" in text
            assert 'mxnet_serving_kvcache_pages{state="free"}' in text
            assert "mxnet_serving_token_seconds_bucket" in text
            assert "mxnet_serving_decode_batch_width_bucket" in text
        finally:
            telemetry.reset()
            if not was:
                telemetry.disable()


# ---------------------------------------------------------------------------
# token streaming across the worker wire protocol (fake-worker seam:
# same pattern as test_serving_worker.py — every failure mode, no exec)
# ---------------------------------------------------------------------------

class GenFakeProc:
    _next_pid = [60000]

    def __init__(self):
        self._rc = None
        self._done = threading.Event()
        GenFakeProc._next_pid[0] += 1
        self.pid = GenFakeProc._next_pid[0]
        self.on_terminate = None

    def poll(self):
        return self._rc

    def wait(self, timeout=None):
        if not self._done.wait(timeout):
            import subprocess
            raise subprocess.TimeoutExpired("fake-gen-worker", timeout)
        return self._rc

    def exit(self, rc):
        if self._rc is None:
            self._rc = rc
            self._done.set()

    def terminate(self):
        if self.on_terminate is not None:
            self.on_terminate()
        self.exit(-15)

    kill = terminate


class GenFakeWorker:
    """Wire-protocol generate server. ``mode``:

    * ``"reconcile"`` — streams token frames for the FIRST TWO tokens
      only, then a gen_done carrying the full payload: the client must
      reconcile the missing tail (token frames are best-effort; the
      finale is authoritative).
    * ``"crash_mid_generate"`` — one token frame, then the connection
      dies: every streaming handle must resolve typed.
    """

    TOKENS = [11, 12, 13, 14]

    def __init__(self, rep, mode="reconcile"):
        self.rep = rep
        self.mode = mode
        self.proc = GenFakeProc()
        self.stop_health = threading.Event()

    def spawn(self, port):
        threading.Thread(target=self._run, args=(port,),
                         daemon=True).start()
        return self.proc

    def _run(self, port):
        sock = wire.connect("127.0.0.1", port, timeout=10)
        self.proc.on_terminate = sock.close
        send_lock = threading.Lock()
        grid = self.rep.grid

        def send(frame):
            with send_lock:
                wire.send_frame(sock, frame)

        send({"kind": "hello", "name": self.rep.name,
              "pid": self.proc.pid,
              "batch_buckets": list(grid.batch_buckets),
              "shape_buckets": [list(s) for s in grid.shape_buckets]
              if grid.shape_buckets else None,
              "len_buckets": list(grid.len_buckets),
              "slo_ms": self.rep.slo_s * 1e3, "metrics_port": None})

        def health_loop():
            while not self.stop_health.wait(0.02):
                try:
                    send({"kind": "health", "age": 0.0, "queue_depth": 0,
                          "requests": 0, "batches": 0, "errors": 0})
                except OSError:
                    return

        threading.Thread(target=health_loop, daemon=True).start()
        try:
            while True:
                frame = wire.recv_frame(sock)
                if frame["kind"] == "generate":
                    rid = frame["id"]
                    if self.mode == "crash_mid_generate":
                        send({"kind": "token", "id": rid, "i": 0,
                              "token": self.TOKENS[0]})
                        sock.close()
                        self.proc.exit(-9)
                        return
                    for i, t in enumerate(self.TOKENS[:2]):
                        send({"kind": "token", "id": rid, "i": i,
                              "token": t})
                    send({"kind": "gen_done", "id": rid, "ok": True,
                          "payload": np.asarray(self.TOKENS, np.int32)})
                elif frame["kind"] == "stop":
                    send({"kind": "bye"})
                    sock.close()
                    self.proc.exit(0)
                    return
        except (wire.FrameError, OSError):
            self.proc.exit(self.proc._rc if self.proc._rc is not None
                           else -9)
        finally:
            self.stop_health.set()


def gen_fake_remote(mode="reconcile", name="g0"):
    rep = serving.RemoteReplica(
        "worker_factory:tiny_llama", name=name,
        batch_buckets=(1, 2), shape_buckets=[(8,)], slo_ms=500,
        python_paths=[FIXTURES], respawn=False,
        decode_pages=16, page_size=4, len_buckets=(8, 16))
    workers = []

    def spawn(port):
        w = GenFakeWorker(rep, mode=mode)
        workers.append(w)
        return w.spawn(port)

    rep._spawn = spawn
    return rep, workers


class TestRemoteStreaming:
    def test_token_frames_stream_and_finale_reconciles(self):
        rep, _ = gen_fake_remote(mode="reconcile")
        rep.start()
        try:
            seen = []
            h = rep.submit_generate(
                PROMPT_A, 4, on_token=lambda i, t: seen.append((i, t)))
            out = h.result(timeout=30)
            np.testing.assert_array_equal(
                out, np.asarray(GenFakeWorker.TOKENS, np.int32))
            # 2 streamed + 2 reconciled from the finale, still in order
            assert seen == list(enumerate(GenFakeWorker.TOKENS))
            assert h.tokens() == GenFakeWorker.TOKENS
        finally:
            rep.stop()

    def test_crash_mid_generate_resolves_typed(self):
        rep, _ = gen_fake_remote(mode="crash_mid_generate")
        rep.start()
        try:
            h = rep.submit_generate(PROMPT_A, 4)
            with pytest.raises(serving.WorkerCrashed):
                h.result(timeout=30)               # typed, never a hang
            # pre-crash token frames are best-effort (waitpid may beat
            # the reader to the buffered frame): whatever arrived is a
            # prefix, and the stream is sealed either way
            got = h.tokens()
            assert got == GenFakeWorker.TOKENS[:len(got)]
            assert h.next_token(len(got), timeout=5) is None
            wait_until(lambda: not rep.is_running, 10,
                       msg="crash marks worker down")
            assert rep.crash_count == 1
        finally:
            rep.stop()

    def test_generate_without_decode_config_is_synchronous_typed(self):
        rep = serving.RemoteReplica(
            "worker_factory:tiny_net", name="nogen",
            batch_buckets=(2,), shape_buckets=[(8,)], slo_ms=50,
            python_paths=[FIXTURES])
        with pytest.raises(MXNetError):
            rep.submit_generate(PROMPT_A, 4)


# ---------------------------------------------------------------------------
# Pallas paged-attention kernel (interpret mode vs the eager oracle)
# ---------------------------------------------------------------------------

class TestPagedKernel:
    def _case(self, b=2, h=4, kv=2, d=128, n_pages=8, ps=8, seed=0):
        from mxnet_tpu.ops.attention import _paged_reference

        rs = np.random.RandomState(seed)
        k_arena = jnp.asarray(
            rs.randn(n_pages * ps, kv, d).astype(np.float32))
        v_arena = jnp.asarray(
            rs.randn(n_pages * ps, kv, d).astype(np.float32))
        q = jnp.asarray(rs.randn(b, h, 1, d).astype(np.float32))
        # row 0: 13 tokens over 2 pages + scratch-padded tail page;
        # row 1: 24 tokens over all 3 table slots
        page_table = jnp.asarray(
            np.array([[1, 2, 0], [3, 4, 5]], np.int32))
        lengths = jnp.asarray(np.array([13, 24], np.int32))
        scale = 1.0 / np.sqrt(d)
        ref = _paged_reference(q, k_arena, v_arena, page_table, lengths,
                               (lengths - 1)[:, None], ps, scale)
        return q, k_arena, v_arena, page_table, lengths, scale, ref

    def test_interpret_matches_eager_oracle(self):
        from mxnet_tpu.pallas_kernels import paged_attention_kernel

        q, ka, va, pt, ln, scale, ref = self._case()
        out = paged_attention_kernel(q, ka, va, pt, ln, page_size=8,
                                     scale=scale, interpret=True)
        assert not np.isnan(np.asarray(out)).any()
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_shape_gates(self):
        from mxnet_tpu.pallas_kernels import paged_shape_supported

        q, ka, _, _, _, _, _ = self._case()
        assert paged_shape_supported(q, ka, 8)
        assert not paged_shape_supported(q, ka, 4)      # page tiling
        assert not paged_shape_supported(q[:, :, :, :64], ka[:, :, :64],
                                         8)             # lane width
        q2 = jnp.concatenate([q, q], axis=2)            # two query rows
        assert not paged_shape_supported(q2, ka, 8)
