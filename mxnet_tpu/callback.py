"""Training callbacks (reference: ``python/mxnet/callback.py``).

``Speedometer`` (throughput every N batches — here with optional MFU
reporting against the device's bf16 peak, the north-star metric),
``do_checkpoint``, ``log_train_metric``, ``ProgressBar``. All follow the
reference's ``BatchEndParam``/``(epoch, symbol, arg, aux)`` callback
contracts so ``Module.fit`` / user loops drive them unchanged.
"""
from __future__ import annotations

import logging
import time

__all__ = ["Speedometer", "do_checkpoint", "log_train_metric", "ProgressBar",
           "device_peak_flops"]

# bf16 peak TFLOP/s per chip by TPU generation (public spec sheets);
# used only for the optional MFU line — throughput is always reported.
_TPU_PEAK_TFLOPS = {
    "v4": 275.0, "v5e": 197.0, "v5 lite": 197.0, "v5p": 459.0,
    "v6e": 918.0,
}


def device_peak_flops(device=None):
    """Best-effort bf16 peak FLOP/s of ``device`` (default: first device).

    Returns None when unknown (e.g. CPU) — callers should skip MFU then.
    """
    import jax

    device = device or jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for key, tf in _TPU_PEAK_TFLOPS.items():
        if key in kind:
            return tf * 1e12
    return None


class Speedometer:
    """Log training speed (and optionally MFU) every ``frequent`` batches.

    Reference: ``callback.py::Speedometer``. Extra TPU-native parameter
    ``flops_per_sample``: when given and the device peak is known, an MFU
    percentage is appended — BASELINE.md's north-star metric.
    """

    def __init__(self, batch_size, frequent=50, auto_reset=True,
                 flops_per_sample=None, num_devices=None):
        self.batch_size = batch_size
        self.frequent = frequent
        self.auto_reset = auto_reset
        self.flops_per_sample = flops_per_sample
        # batch_size counts samples across ALL chips (global batch), so the
        # MFU denominator must be the aggregate peak of the chips doing the
        # work; default = every default-backend device
        self.num_devices = num_devices
        self.init = False
        self.tic = 0.0
        self.last_count = 0
        self._peak = None

    def __call__(self, param):
        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count

        if not self.init:
            self.init = True
            self.tic = time.time()
            return
        if count % self.frequent != 0:
            return
        speed = self.frequent * self.batch_size / (time.time() - self.tic)
        mfu = ""
        if self.flops_per_sample:
            if self._peak is None:
                per_chip = device_peak_flops() or 0.0
                if per_chip:
                    import jax

                    n = self.num_devices or jax.device_count()
                    self._peak = per_chip * n
                else:
                    self._peak = 0.0
            if self._peak:
                mfu = "\tMFU=%.1f%%" % (
                    100.0 * speed * self.flops_per_sample / self._peak)
        if param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            if self.auto_reset:
                param.eval_metric.reset()
            msg = "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec%s"
            msg += "\t%s=%f" * len(name_value)
            logging.info(msg, param.epoch, count, speed, mfu,
                         *sum(name_value, ()))
        else:
            logging.info("Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec%s",
                         param.epoch, count, speed, mfu)
        self.tic = time.time()


def do_checkpoint(prefix, period=1):
    """Epoch-end callback: save checkpoint every ``period`` epochs.

    Reference: ``callback.py::do_checkpoint`` → ``model.save_checkpoint``.
    """
    from .module.module import save_checkpoint

    period = int(max(1, period))

    def _callback(epoch, sym, arg, aux):
        if (epoch + 1) % period == 0:
            save_checkpoint(prefix, epoch + 1, sym, arg, aux)

    return _callback


def log_train_metric(period, auto_reset=False):
    """Batch-end callback: log the evaluation metric every ``period``."""

    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            for name, value in name_value:
                logging.info("Iter[%d] Batch[%d] Train-%s=%f",
                             param.epoch, param.nbatch, name, value)
            if auto_reset:
                param.eval_metric.reset()

    return _callback


class ProgressBar:
    """Text progress bar over total batch count (reference: ProgressBar)."""

    def __init__(self, total, length=80):
        self.bar_len = length
        self.total = total

    def __call__(self, param):
        count = param.nbatch
        filled_len = int(round(self.bar_len * count / float(self.total)))
        percents = int(round(100.0 * count / float(self.total)))
        prog_bar = "=" * filled_len + "-" * (self.bar_len - filled_len)
        logging.info("[%s] %s%s\r", prog_bar, percents, "%")
