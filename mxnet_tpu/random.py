"""``mx.random`` namespace.

Reference: ``python/mxnet/random.py`` — seed + module-level sampling
functions delegating to the random ops.
"""
from __future__ import annotations

from . import ndarray as nd
from .random_state import seed  # re-export

__all__ = ["seed", "uniform", "normal", "randn", "randint", "exponential",
           "gamma", "poisson", "negative_binomial",
           "generalized_negative_binomial", "multinomial", "shuffle"]

uniform = nd.random.uniform
normal = nd.random.normal
randint = nd.random.randint
exponential = nd.random.exponential
gamma = nd.random.gamma
poisson = nd.random.poisson
negative_binomial = nd.random.negative_binomial
generalized_negative_binomial = nd.random.generalized_negative_binomial
multinomial = nd.random.sample_multinomial
nd.random.multinomial = nd.random.sample_multinomial


def randn(*shape, ctx=None, dtype="float32", loc=0.0, scale=1.0):
    return nd.random.normal(loc=loc, scale=scale, shape=shape, ctx=ctx, dtype=dtype)


def shuffle(data, **kwargs):
    from .ndarray import imperative_invoke
    from .ops.registry import get_op

    return imperative_invoke(get_op("_shuffle"), [data], {})


# patch the placeholder in mx.nd.random
nd.random.seed = seed
nd.random.randn = randn
nd.random.shuffle = shuffle
