"""The fused, sharded training step — SURVEY.md §3.5's end state.

Reference call stack being replaced: ``Trainer.step`` → kvstore push/pull
(NCCL allreduce / ps-lite ZPush-ZPull) → per-context ``Optimizer.update``
(src/kvstore/*, python/mxnet/gluon/trainer.py). On TPU that whole stack is
ONE compiled executable: forward, loss, backward, gradient psum over the
``dp`` mesh axis (inserted by GSPMD from the batch sharding), and the
optimizer sweep — all fused, parameters donated so the update is in-place
in HBM.

    step = TrainStep(net, loss, optimizer='adam', mesh=make_mesh({'dp': 8}))
    loss, outs = step(data, label)     # one device-side step, no host sync

Semantics preserved from the reference:
* optimizer state dtypes/bias corrections identical to the eager Updater
  (the same ``Optimizer`` object runs inside the trace — in dynamic mode,
  so step count and scheduled LR stay traced scalars and one executable
  serves every step);
* BatchNorm moving stats (aux states) are returned as extra outputs and
  written back, like CachedOp's aux-state contract;
* gradient clipping/rescale via the optimizer's own attributes.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from .. import optimizer as opt_mod
from .. import mutation, random_state
from ..base import MXNetError
from ..context import current_context
from ..ndarray import NDArray
from ..gluon.block import (make_pure_fn, nested_flatten_nd,
                           nested_unflatten_nd, resolve_remat_policy)
from .mesh import current_mesh, make_mesh
from .sharding import ShardingRules, named_sharding, spec_for_param

__all__ = ["TrainStep"]


def _as_tuple(x):
    if x is None:
        return ()
    if isinstance(x, (list, tuple)):
        return tuple(x)
    return (x,)


class TrainStep:
    """Compile ``net`` + ``loss`` + ``optimizer`` into one sharded step.

    Parameters
    ----------
    net : HybridBlock with initialized parameters.
    loss : callable ``loss(outputs, *labels) -> NDArray`` (a gluon Loss
        block works); reduced by mean inside the graph.
    optimizer : Optimizer instance or name ('sgd', 'adam', ...).
    mesh : jax Mesh; default = the active ``use_mesh`` mesh, else all
        visible devices on one ``dp`` axis.
    rules : ShardingRules for parameter layout (tensor parallelism);
        unmatched params are replicated.
    batch_axis : mesh axes the leading batch dimension is sharded over
        (default ``('dp',)``; pass e.g. ``('dp','fsdp')`` for combined axes).
    seq_axis : optional mesh axis for sequence sharding of rank>=2 inputs
        (dimension 1) — context parallelism for long sequences.
    donate_inputs : donate the batch buffers to the executable (XLA may
        reuse their HBM for activations). Only for single-use batches —
        an async input pipeline (``io.DeviceFeedIter``) stages a fresh
        buffer per step; a benchmark replaying one staged batch must NOT
        set this (the donated buffer is dead after the call).
    remat : gradient-rematerialization policy for the whole net inside
        the compiled step — ``None`` (save activations, the default),
        ``"full"`` (save nothing: recompute the forward in the backward,
        max memory headroom for ~one extra forward of FLOPs) or
        ``"dots"`` (matmul outputs saved, elementwise/norm recompute —
        no MXU work re-runs). The same policy names as
        ``gluon.block.remat_call`` / the Llama zoo's ``remat=`` kwarg,
        resolved by the one shared validator — but threaded here ANY
        compiled step can trade recompute for the batch-size headroom
        the MFU targets need, not just nets that opted in at
        construction. Composes with model-level remat_call (inner
        checkpoints nest).
    """

    def __init__(self, net, loss, optimizer, mesh=None,
                 rules: Optional[ShardingRules] = None,
                 batch_axis: Sequence[str] = ("dp",), seq_axis=None,
                 optimizer_params=None, loss_only=False,
                 donate_inputs=False, remat=None):
        self.net = net
        self.loss = loss
        # loss_only: don't return model outputs from the step — for nets
        # with huge heads (e.g. an MLM decoder's (B, L, vocab) logits) the
        # returned buffer otherwise must be materialized in HBM and shipped
        # out of the executable every step
        self.loss_only = bool(loss_only)
        if not isinstance(optimizer, opt_mod.Optimizer):
            optimizer = opt_mod.create(optimizer, **(optimizer_params or {}))
        self.optimizer = optimizer
        if mesh is None:
            mesh = current_mesh() or make_mesh()
        self.mesh = mesh
        self.rules = rules
        self.batch_axis = tuple(a for a in _as_tuple(batch_axis)
                                if a in mesh.axis_names)
        self.seq_axis = seq_axis if (seq_axis in mesh.axis_names) else None
        self.donate_inputs = bool(donate_inputs)
        # validate eagerly — a typo must raise at construction, not from
        # inside the first traced step
        resolve_remat_policy(remat)
        self.remat = remat
        from ..compiler import service as _csvc

        self._cache = _csvc.SiteCache("train_step")
        self._params = None          # List[Parameter]
        self._param_specs = None     # per-param PartitionSpec
        self._trainable = None       # indices into _params
        self._state_leaf_nds = None  # flat list of state NDArrays (persist)
        self._state_meta = None      # per-trainable (treedef, n_leaves, shapes)

    # -- setup ----------------------------------------------------------
    def _abstract_settle(self, shape_vals, fallback=None):
        """Resolve deferred parameter shapes with an eval_shape probe.

        Shape inference is host-side — nothing is computed (parameter
        initializers still run concretely when a deferred init resolves,
        unless the param was built under ``abstract_init``). The probe
        must not advance the global PRNG stream with traced keys
        (rng-consuming ops like Dropout run under the trace), so the
        stream state is snapshotted and restored. ``fallback`` (the eager
        forward — the reference move, HybridBlock.__call__ on
        DeferredInitializationError) covers blocks whose forward needs
        concrete values.
        """
        import jax

        net = self.net

        def _shape_probe(*vals):
            ctx = current_context()
            nds = [NDArray(data=v, ctx=ctx) for v in vals]
            net(*nds)
            return 0

        try:
            with random_state.preserved_stream():
                jax.eval_shape(_shape_probe, *shape_vals)
        except Exception:
            if fallback is None:
                raise
            # fallback runs AFTER the stream restore: an aborted probe
            # leaves traced keys in the stateful stream, and an eager
            # fallback splitting one of those is an escaped-tracer error
            # (found live, round 5)
            fallback()

    def _bind_params(self):
        """Record the settled parameter list, trainable ordinals,
        optimizer param_dict and per-param shardings — shared by the live
        path and aot_compile so the two can't diverge.

        Per-param lr_mult/wd_mult flow through the optimizer's
        param_dict, keyed by the SAME trainable ordinals update() is
        called with (mirrors Trainer._init_optimizer wiring).
        """
        params = list(self.net.collect_params().values())
        self._params = params
        self._trainable = [i for i, p in enumerate(params)
                           if p.grad_req != "null"]
        self.optimizer.param_dict = {
            k: params[i] for k, i in enumerate(self._trainable)}
        self._param_specs = [
            spec_for_param(p.name, p.shape, self.rules, self.mesh)
            for p in params]
        self._check_sparse_sharing()
        return params

    def _check_sparse_sharing(self):
        """A row-sparse-grad embedding weight must not be shared with
        another block (weight-tied softmax head): the dense cotangent
        from the other use would be silently dropped by the lazy row
        update. Detects PARAMETER-OBJECT sharing across blocks; passing
        the same array through other ops manually remains the user's
        responsibility (same contract as the reference's stype checks).
        """
        owners = {}

        def walk(block):
            for p in getattr(block, "_reg_params", {}).values():
                if getattr(p, "grad_stype", "default") == "row_sparse":
                    owners.setdefault(id(p), [p, 0])
                    owners[id(p)][1] += 1
            for child in getattr(block, "_children", {}).values():
                walk(child)

        walk(self.net)
        for p, count in owners.values():
            if count > 1:
                raise MXNetError(
                    f"Parameter {p.name} has grad_stype='row_sparse' but "
                    f"is shared by {count} blocks (weight tying); the "
                    "lazy row update would drop the dense gradient from "
                    "the other use — build the Embedding with "
                    "sparse_grad=False for tied weights")

    def _settle_params(self, data_tuple):
        params = list(self.net.collect_params().values())
        if any(p._data is None for p in params):
            net = self.net
            self._abstract_settle([v.data for v in data_tuple],
                                  fallback=lambda: net(*data_tuple))
            if any(p._data is None
                   for p in net.collect_params().values()):
                net(*data_tuple)
        params = self._bind_params()
        # lay params out on the mesh once (single-process view: one NDArray
        # per param; its payload becomes a sharded global jax.Array)
        import jax

        for p, spec in zip(params, self._param_specs):
            arr = p.data()
            arr._set_data(
                jax.device_put(arr.data, named_sharding(self.mesh, spec)))

    def _make_state_builder(self):
        """The batched optimizer-state constructor + its treedef slots.

        ONE traced function builds every state leaf: building states
        eagerly costs hundreds of tiny device round-trips (~minutes of
        first-step latency through a remote TPU relay; PERF.md round 3).
        Shared by _init_states (jit, concrete) and aot_compile
        (eval_shape, abstract) so the state layout can't diverge between
        live training and AOT memory analysis.
        """
        import jax

        is_leaf = lambda x: x is None or isinstance(x, NDArray)
        optimizer = self.optimizer
        trainable = list(self._trainable)
        ctx = self._params[0].data().context if self._params \
            else current_context()
        treedefs = [None] * len(trainable)

        def _all_states(param_vals):
            flat = []
            for k, i in enumerate(trainable):
                w = NDArray(data=param_vals[k], ctx=ctx)
                state = optimizer.create_state_multi_precision(k, w)
                leaves, treedefs[k] = jax.tree_util.tree_flatten(
                    state, is_leaf=is_leaf)
                flat.append(tuple(None if leaf is None else leaf.data
                                  for leaf in leaves))
            return tuple(flat)

        return _all_states, treedefs, ctx

    def _state_layout(self, k, i, leaves, treedef, on_leaf):
        """Per-param state-leaf layout: ``(treedef, present, specs)`` meta
        entry, calling ``on_leaf(leaf, leaf_spec)`` for each present leaf.
        The rule: a leaf shaped like its param shards like the param;
        everything else (scalars, row stats) replicates."""
        from jax.sharding import PartitionSpec as P

        p = self._params[i]
        spec = self._param_specs[i]
        present = [leaf is not None for leaf in leaves]
        specs = []
        for leaf in leaves:
            if leaf is None:
                continue
            leaf_spec = spec if tuple(leaf.shape) == tuple(p.shape) else P()
            specs.append(leaf_spec)
            on_leaf(leaf, leaf_spec)
        return (treedef, present, specs)

    def _init_states(self):
        import jax

        _all_states, treedefs, ctx = self._make_state_builder()
        trainable = list(self._trainable)
        param_data = tuple(self._params[i].data().data for i in trainable)
        # transfer-guard exemption: the builder may implicitly move host
        # scalars/param copies across platforms (remote-relay context)
        with jax.transfer_guard("allow"):
            all_leaves = jax.jit(_all_states)(param_data)

        leaf_nds: List[NDArray] = []
        meta = []
        for k, i in enumerate(trainable):
            meta.append(self._state_layout(
                k, i, all_leaves[k], treedefs[k],
                lambda leaf, spec: leaf_nds.append(NDArray(
                    data=jax.device_put(
                        leaf, named_sharding(self.mesh, spec)), ctx=ctx))))
        self._state_leaf_nds = leaf_nds
        self._state_meta = meta

    def _batch_spec(self, val):
        from jax.sharding import PartitionSpec as P

        entries = [None] * val.ndim
        if val.ndim >= 1 and self.batch_axis:
            size = 1
            for ax in self.batch_axis:
                size *= self.mesh.shape[ax]
            if size > 1 and val.shape[0] % size == 0:
                entries[0] = self.batch_axis if len(self.batch_axis) > 1 \
                    else self.batch_axis[0]
        if self.seq_axis and val.ndim >= 2:
            s = self.mesh.shape[self.seq_axis]
            if s > 1 and val.shape[1] % s == 0:
                entries[1] = self.seq_axis
        return P(*entries)

    # -- build ----------------------------------------------------------
    def _pipelined_1f1b(self):
        """The net itself as a 1F1B-scheduled Pipelined block, or None.

        The 1F1B schedule folds the loss into the last pipeline stage, so
        the step cannot be built as grad(loss(net(x))) — TrainStep routes
        it through :func:`pipeline_train_1f1b` instead. Supported shape:
        ``net`` IS the Pipelined trunk (embedding/head belong in the loss
        callable, which runs on the last stage)."""
        from .pipeline import Pipelined

        net = self.net
        if isinstance(net, Pipelined) and net._schedule == "1f1b":
            return net
        return None

    # -- build ----------------------------------------------------------
    def _build(self, data_tuple, label_tuple, training):
        import jax
        from jax.sharding import PartitionSpec as P

        ctx = self._params[0].data().context if self._params else current_context()
        pipe = self._pipelined_1f1b()
        if pipe is not None:
            from .pipeline import pipeline_train_1f1b

            stage_all = pipe._stage_fn_1f1b(ctx, training)
            pipe_axis, pipe_micro = pipe._axis, pipe._n_micro
            pure, cell = None, {"aux_arrays": [], "treedef": None,
                                "n_out": 0}
            if len(data_tuple) != 1 or len(label_tuple) != 1:
                raise MXNetError(
                    "TrainStep over a 1F1B Pipelined takes exactly one "
                    "data and one label array")
        else:
            param_arrays = [p.data() for p in self._params]
            pure, cell = make_pure_fn(self.net, param_arrays, ctx, training)
            if self.remat is not None:
                # net forward under jax.checkpoint: activations inside the
                # span are recomputed during the backward per the policy.
                # Parameters/batch enter as checkpoint arguments (always
                # saved); the loss head stays outside the span.
                pure = jax.checkpoint(
                    pure, policy=resolve_remat_policy(self.remat))
        if pipe is not None and self.remat is not None:
            raise MXNetError(
                "TrainStep(remat=...) does not apply to a 1F1B Pipelined "
                "net — the pipelined trunk owns its own remat "
                "(Pipelined(remat=True))")
        loss_only = self.loss_only or pipe is not None
        trainable = list(self._trainable)
        if pipe is not None:
            id2k = {id(self._params[i]): k for k, i in enumerate(trainable)}
            try:
                stacked_ks = [id2k[id(sp)] for sp in pipe._stacked]
            except KeyError:
                raise MXNetError(
                    "1F1B TrainStep requires every stacked pipeline "
                    "parameter to be trainable (grad_req != 'null')")
            if len(stacked_ks) != len(trainable):
                raise MXNetError(
                    "TrainStep(schedule='1f1b') supports a net whose "
                    "trainable params are exactly the Pipelined trunk's "
                    "stacked parameters; put embedding/head inside the "
                    "loss callable")
        n_data = len(data_tuple)
        optimizer = self.optimizer
        loss_fn = self.loss
        state_meta = self._state_meta
        params_by_i = [p.name for p in self._params]
        mesh = self.mesh

        def step_fn(param_vals, state_vals, t, lr, rng, *batch_vals):
            import jax.numpy as jnp

            data_vals = batch_vals[:n_data]
            label_vals = batch_vals[n_data:]

            def loss_of(train_vals):
                pvals = list(param_vals)
                for k, i in enumerate(trainable):
                    pvals[i] = train_vals[k]
                outs, aux = pure(tuple(pvals), rng, *data_vals)
                out_nd = [NDArray(data=v, ctx=ctx) for v in outs]
                out_tree = nested_unflatten_nd(cell["treedef"], out_nd)
                label_nds = [NDArray(data=v, ctx=ctx) for v in label_vals]
                loss_out = loss_fn(out_tree, *label_nds)
                flat_loss, _ = nested_flatten_nd(loss_out)
                loss_val = jnp.mean(flat_loss[0].data.astype(jnp.float32))
                return loss_val, (outs, aux)

            from .sparse_grad import lazy_row_update, sparse_grad_scope

            train_vals = tuple(param_vals[i] for i in trainable)
            if pipe is not None:
                # 1F1B: loss folded into the last stage; grads come from
                # the schedule, not from AD over the block forward
                def head_loss(h, y):
                    l_out = loss_fn(NDArray(data=h, ctx=ctx),
                                    NDArray(data=y, ctx=ctx))
                    flat_l, _ = nested_flatten_nd(l_out)
                    return jnp.mean(flat_l[0].data.astype(jnp.float32))

                leaves = tuple(train_vals[k] for k in stacked_ks)
                loss_val, g_stacked, _dx = pipeline_train_1f1b(
                    stage_all, head_loss, leaves, data_vals[0],
                    label_vals[0], rng, mesh=mesh, axis=pipe_axis,
                    n_microbatches=pipe_micro)
                grads = [None] * len(trainable)
                for k, g in zip(stacked_ks, g_stacked):
                    grads[k] = g
                outs, aux = (), ()
                sparse_by_k = {}
            else:
                with sparse_grad_scope() as sp_log:
                    (loss_val, (outs, aux)), grads = jax.value_and_grad(
                        loss_of, has_aux=True)(train_vals)
                # scope entries are keyed by parameter NAME (the embedding
                # op's _sparse_uid); map to trainable ordinals
                sparse_by_k = {}
                for uid, entries in sp_log.entries.items():
                    for k, i in enumerate(trainable):
                        if params_by_i[i] == uid:
                            sparse_by_k[k] = entries
                            break

            from ..optimizer import multi_tensor as mt

            # the horizontally-fused sweep replaces the per-ordinal
            # update loop for the fused families when the Pallas sweep
            # kernel is routed (TPU + MXNET_PALLAS_FUSED — the traced
            # body is keyed by both routing knobs): the whole bucket
            # updates in ONE VMEM kernel instead of N per-param op
            # chains. Off-kernel the per-param loop stays — inside one
            # jitted step XLA already fuses it, and keeping the exact
            # per-param expressions keeps the traced numerics
            # bit-identical whatever the knob. Row-sparse lazy-update
            # params ALWAYS stay on the per-param path, as do
            # optimizers outside the family set
            step_platform = mesh.devices.flat[0].platform
            fuse_family = mt.family_of(optimizer) \
                if (mt.fused_sweep_enabled()
                    and mt.traced_sweep_routed(step_platform)) else None
            new_params = list(param_vals)
            new_state_vals = list(state_vals)
            with optimizer.dynamic(t, lr):
                with mutation.mutation_scope():
                    fused_items = []      # (k, w, g, leaves)
                    fused_slots = {}      # k -> (i, [state_val idx])
                    pos = 0
                    for k, i in enumerate(trainable):
                        treedef, present, _ = state_meta[k]
                        cursor = pos
                        n_live = sum(1 for p_ in present if p_)
                        if k not in sparse_by_k and fuse_family and \
                                mt.traceable_state(
                                    optimizer, fuse_family,
                                    self._params[i], n_live):
                            idxs = list(range(cursor, cursor + n_live))
                            fused_items.append(
                                (k, param_vals[i], grads[k],
                                 [state_vals[c] for c in idxs]))
                            fused_slots[k] = (i, idxs)
                            pos = cursor + n_live
                            continue
                        w_nd = NDArray(data=param_vals[i], ctx=ctx)
                        leaf_nds = []
                        live = []
                        for is_present in present:
                            if is_present:
                                nd_leaf = NDArray(data=state_vals[cursor], ctx=ctx)
                                leaf_nds.append(nd_leaf)
                                live.append((cursor, nd_leaf))
                                cursor += 1
                            else:
                                leaf_nds.append(None)
                        state = jax.tree_util.tree_unflatten(treedef, leaf_nds)
                        if k in sparse_by_k:
                            # row-sparse embedding grad: lazy row update;
                            # the dense zero cotangent in grads[k] stays
                            # unconsumed and DCEs out of the executable
                            lazy_row_update(optimizer, k, w_nd,
                                            sparse_by_k[k], state, ctx)
                        else:
                            g_nd = NDArray(data=grads[k], ctx=ctx)
                            optimizer.update_multi_precision(
                                k, w_nd, g_nd, state)
                        new_params[i] = w_nd.data
                        for idx, nd_leaf in live:
                            new_state_vals[idx] = nd_leaf.data
                        pos = cursor
                    if fused_items:
                        swept = mt.traced_fused_update(
                            optimizer, fuse_family, fused_items,
                            platform=step_platform)
                        for k, (new_w, new_leaves) in swept.items():
                            i, idxs = fused_slots[k]
                            new_params[i] = new_w
                            for idx, leaf in zip(idxs, new_leaves):
                                new_state_vals[idx] = leaf
            if loss_only:
                outs = ()
            return (tuple(new_params), tuple(new_state_vals), loss_val,
                    tuple(outs), tuple(aux))

        mesh = self.mesh
        ns = lambda spec: named_sharding(mesh, spec)
        rep = ns(P())
        param_sh = tuple(ns(s) for s in self._param_specs)
        state_sh = tuple(ns(spec) for (_, _, specs) in state_meta
                         for spec in specs)
        batch_sh = tuple(ns(self._batch_spec(v))
                         for v in list(data_tuple) + list(label_tuple))
        in_sh = (param_sh, state_sh, rep, rep, rep) + batch_sh
        import os

        if os.environ.get("MXNET_TPU_DONATE", "1") == "0":
            # donation off (MXNET_TPU_DONATE=0): an HBM optimization
            # with no value on host memory, and XLA:CPU's persistent-
            # cache deserializer is unreliable for executables carrying
            # input-output aliasing metadata (heap corruption on load,
            # reproduced with plain jax.jit on this container's jax) —
            # CPU processes that opt into the disk tier set this
            donate: tuple = ()
        else:
            donate = (0, 1)
            if self.donate_inputs:
                # batch args start after (params, states, t, lr, rng)
                donate = donate + tuple(range(5, 5 + len(batch_sh)))
        # outputs: params/states keep their layout (no per-step reshard);
        # loss replicated; model outputs/aux left to XLA (None = inferred)
        jitted = jax.jit(
            step_fn,
            in_shardings=in_sh,
            out_shardings=(param_sh, state_sh, rep, None, None),
            donate_argnums=donate,
        )

        def cell_probe():
            # settle `cell` (output treedef + aux arrays) without a
            # compile when an exported-blob hit skipped the trace
            if pipe is not None or cell["treedef"] is not None:
                return
            pvals = tuple(
                jax.ShapeDtypeStruct(tuple(p.shape),
                                     jax.numpy.dtype(str(p.dtype)))
                for p in self._params)
            with random_state.preserved_stream():
                rng_t = random_state.get_state_key()
            jax.eval_shape(
                pure, pvals,
                jax.ShapeDtypeStruct(tuple(rng_t.shape), rng_t.dtype),
                *(jax.ShapeDtypeStruct(tuple(v.shape),
                                       jax.numpy.dtype(str(v.dtype)))
                  for v in data_tuple))

        return {"jitted": jitted, "cell": cell, "batch_sh": batch_sh,
                "loss_only": loss_only, "cell_probe": cell_probe}

    def aot_compile(self, data, label=()):
        """AOT-compile the sharded train step on ABSTRACT parameters.

        For validating recipes whose weights don't fit the host (e.g. the
        Llama-3-8B stretch config on a dev box): the net must have been
        built and "initialized" under ``gluon.parameter.abstract_init()``.
        Settle, state layout, step build, lowering and XLA compilation all
        run the normal TrainStep code path — only buffers never
        materialize. Returns the ``jax.stages.Compiled`` executable
        (``.memory_analysis()`` gives per-device HBM numbers).

        ``data``/``label``: host-shaped template NDArrays or
        ``jax.ShapeDtypeStruct``s describing one global batch.
        """
        import jax

        data_tuple = _as_tuple(data)
        label_tuple = _as_tuple(label)

        def _struct(v):
            if isinstance(v, jax.ShapeDtypeStruct):
                return v
            return jax.ShapeDtypeStruct(tuple(v.shape),
                                        jax.numpy.dtype(str(v.dtype)))

        batch_structs = [_struct(v) for v in data_tuple + label_tuple]

        # settle (abstract): eval_shape probe resolves deferred shapes with
        # zero-cost placeholder data (no eager fallback — AOT nets must
        # settle abstractly by definition)
        net = self.net
        params = list(net.collect_params().values())
        if any(p._data is None for p in params):
            self._abstract_settle(batch_structs[:len(data_tuple)])
        params = self._bind_params()
        # this instance now holds abstract params and no live state
        # buffers — it can compile but never execute
        self._aot_only = True

        # optimizer states: shape-only evaluation of the SAME batched
        # state builder _init_states compiles
        _all_states, treedefs, ctx = self._make_state_builder()
        trainable = list(self._trainable)
        param_structs = tuple(
            jax.ShapeDtypeStruct(tuple(p.shape),
                                 jax.numpy.dtype(str(p.dtype)))
            for p in params)
        train_structs = tuple(param_structs[i] for i in trainable)
        state_shapes = jax.eval_shape(_all_states, train_structs)

        state_structs = []
        meta = []
        for k, i in enumerate(trainable):
            meta.append(self._state_layout(
                k, i, state_shapes[k], treedefs[k],
                lambda leaf, spec: state_structs.append(
                    jax.ShapeDtypeStruct(
                        tuple(leaf.shape), leaf.dtype,
                        sharding=named_sharding(self.mesh, spec)))))
        self._state_meta = meta
        self._state_leaf_nds = []  # aot: no live state buffers

        entry = self._build(
            tuple(NDArray(data=s, ctx=ctx) for s in
                  batch_structs[:len(data_tuple)]),
            tuple(NDArray(data=s, ctx=ctx) for s in
                  batch_structs[len(data_tuple):]),
            True)

        import numpy as np

        param_sharded = tuple(
            jax.ShapeDtypeStruct(s.shape, s.dtype,
                                 sharding=named_sharding(self.mesh, spec))
            for s, spec in zip(param_structs, self._param_specs))
        t = jax.ShapeDtypeStruct((), np.int32)
        lr = jax.ShapeDtypeStruct((), np.float32)
        # key shape/dtype only — the stream snapshot keeps the compile
        # from advancing the program's random sequence (reproducibility)
        with random_state.preserved_stream():
            key = random_state.get_state_key()
        rng = jax.ShapeDtypeStruct(tuple(key.shape), key.dtype)
        batch_in = tuple(
            jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh)
            for s, sh in zip(batch_structs, entry["batch_sh"]))

        from ..base import execution_platform
        from .mesh import use_mesh

        with execution_platform(self.mesh.devices.flat[0].platform), \
                use_mesh(self.mesh):
            lowered = entry["jitted"].lower(
                param_sharded, tuple(state_structs), t, lr, rng, *batch_in)
            return lowered.compile()

    def save_sharded(self, directory):
        """Per-process sharded checkpoint (SURVEY §5.4 stretch; see
        parallel/checkpoint.py)."""
        from .checkpoint import save_sharded

        save_sharded(self, directory)

    def restore_sharded(self, directory, example_data=None):
        """Restore a sharded checkpoint in place (params + optimizer
        state + counters); see parallel/checkpoint.py."""
        from .checkpoint import restore_sharded

        restore_sharded(self, directory, example_data=example_data)

    def input_shardings(self, data, label=()):
        """The NamedShardings this step will place its batch inputs with,
        one per array in ``(data..., label...)`` order.

        The async input pipeline's contract (``io.DeviceFeedIter`` passes
        itself as the consumer): a batch ``device_put`` with exactly
        these shardings enters ``__call__`` as a true no-op. Works before
        the first step — only the mesh and batch/seq axes are consulted,
        arrays just need ``shape``/``ndim`` (NDArray, numpy, jax, or
        ShapeDtypeStruct)."""
        return tuple(named_sharding(self.mesh, self._batch_spec(v))
                     for v in _as_tuple(data) + _as_tuple(label))

    def stage_batch(self, data, label=()):
        """Place host batches on the mesh with this step's input sharding.

        In-place on the NDArrays; a later ``__call__`` with the same arrays
        makes the per-step ``device_put`` a no-op. Benchmarks and
        synthetic-data loops use this to keep data device-resident.
        """
        import jax

        for v in _as_tuple(data) + _as_tuple(label):
            v._set_data(jax.device_put(
                v.data, named_sharding(self.mesh, self._batch_spec(v))))

    # -- cache spine (compilation service) -------------------------------
    def _key_for(self, data_tuple, label_tuple):
        import os

        from ..compiler import signature

        # routing knobs key the cache like shapes do: the traced body
        # dispatches on them (Pallas fused kernels, hash dropout), so a
        # knob toggled between steps must re-trace, not replay. The
        # donation knob is a BUILD-time knob of this site specifically —
        # toggling MXNET_TPU_DONATE between steps must not replay an
        # executable with the other aliasing contract
        return signature(
            "train_step", id(self),
            avals=tuple((tuple(v.shape), str(v.dtype))
                        for v in data_tuple + label_tuple),
            extra=(len(data_tuple), True,
                   os.environ.get("MXNET_TPU_DONATE", "1") != "0"))

    def _entry_for(self, data_tuple, label_tuple):
        """The compiled entry for this batch signature: cache hit, or
        build + AOT-compile through the service's executable table and
        journal the signature to the manifest."""
        key = self._key_for(data_tuple, label_tuple)
        entry = self._cache.lookup(key)
        if entry is not self._cache.MISS:
            return entry
        if self.donate_inputs and len(self._cache):
            # shape change with input donation: invalidate the stale
            # lowerings. Their input buffers were donated — a later
            # cache hit replaying a batch staged for the OLD shape
            # would dispatch against donated-dead buffers (an opaque
            # XLA RuntimeError at best, garbage reads at worst);
            # re-lowering on return to a shape forces fresh staging.
            # Deliberate trade: a donating step fed ALTERNATING
            # shapes re-lowers on every switch. Donation is for
            # single-use streamed batches (one bucket shape per
            # step instance); alternating-bucket replay wants
            # donate_inputs=False, which keeps every lowering.
            self._cache.clear()
        entry = self._build(data_tuple, label_tuple, True)
        self._aot_seal(entry, data_tuple, label_tuple)
        self._cache.insert(key, entry)
        from .. import compiler

        compiler.record_signature("train_step", {
            "ident": self.warm_ident(),
            "data": tuple((tuple(v.shape), str(v.dtype))
                          for v in data_tuple),
            "label": tuple((tuple(v.shape), str(v.dtype))
                           for v in label_tuple),
            "routing": compiler.routing_knobs()})
        return entry

    def _aot_seal(self, entry, data_tuple, label_tuple):
        """AOT-compile the entry's step executable ahead of dispatch
        through the service's persistence stack: in-process executable
        table (a duplicate step recipe shares one XLA compile), the
        exported-StableHLO blob store (a warm process skips the trace),
        and jax's persistent compile cache (it skips the compile). Falls
        back to the plain trace-at-first-call jit on any surprise."""
        import os as _os

        import jax
        import numpy as np

        try:
            from ..compiler import keys as _ckeys
            from ..compiler import service as _csvc

            jitted = entry["jitted"]
            param_sds = tuple(
                jax.ShapeDtypeStruct(tuple(p.shape),
                                     jax.numpy.dtype(str(p.dtype)))
                for p in self._params)
            state_sds = tuple(
                jax.ShapeDtypeStruct(tuple(s.shape),
                                     jax.numpy.dtype(str(s.dtype)))
                for s in self._state_leaf_nds)
            with random_state.preserved_stream():
                rng = random_state.get_state_key()
            batch_sds = tuple(
                jax.ShapeDtypeStruct(tuple(v.shape),
                                     jax.numpy.dtype(str(v.dtype)))
                for v in tuple(data_tuple) + tuple(label_tuple))
            args = (param_sds, state_sds,
                    jax.ShapeDtypeStruct((), np.int32),
                    jax.ShapeDtypeStruct((), np.float32),
                    jax.ShapeDtypeStruct(tuple(rng.shape), rng.dtype)
                    ) + batch_sds
            from ..base import execution_platform
            from .mesh import use_mesh

            platform = self.mesh.devices.flat[0].platform
            donate = _os.environ.get("MXNET_TPU_DONATE", "1") != "0"
            with execution_platform(platform), use_mesh(self.mesh):
                if donate:
                    # donation-carrying programs stay on the direct
                    # lower path (export round-trips drop aliasing);
                    # still table-deduped + disk-compile-cached
                    lowered = jitted.lower(*args)
                    fp = _csvc.fingerprint_lowered(lowered)
                    compiled = _csvc.exec_table.get_or_build(
                        fp, lowered.compile)
                    entry["jitted"] = _csvc.GuardedExec(
                        compiled, lambda: jitted)
                else:
                    loss = self.loss
                    loss_id = _ckeys.graph_ident(loss) \
                        if hasattr(loss, "collect_params") \
                        else _ckeys.callable_ident(loss)
                    sig_fp = _ckeys.fingerprint(_ckeys.encode((
                        "train_step", self.warm_ident(), loss_id,
                        tuple((tuple(s.shape), str(s.dtype))
                              for s in param_sds + state_sds + args[5:]),
                        (tuple(rng.shape), str(rng.dtype)),
                        _ckeys.routing_knobs(), platform,
                        jax.__version__)))
                    sealed = _csvc.seal_executable(
                        sig_fp, jitted, args, fallback=lambda: jitted)
                    if entry["cell"]["aux_arrays"] is None:
                        try:
                            entry["cell_probe"]()
                        except Exception:
                            # cell can't settle abstractly: keep the
                            # trace-at-first-call jit (it settles cell
                            # concretely)
                            sealed = jitted
                    entry["jitted"] = sealed
        except Exception:
            pass    # trace-at-first-call path stays

    def warm_ident(self) -> str:
        """Routing ident for ``train_step`` manifest entries: net
        architecture + optimizer class + mesh layout + step config. Loose
        by design — the replay re-lowers against THIS live step, so a
        loose match costs a compile, never a wrong executable."""
        from ..compiler import fingerprint, graph_ident

        return fingerprint((
            graph_ident(self.net), type(self.optimizer).__name__,
            tuple(self.mesh.axis_names),
            tuple(int(self.mesh.shape[a]) for a in self.mesh.axis_names),
            tuple(self.batch_axis), self.seq_axis,
            str(self.remat), bool(self.loss_only)))

    def warm(self, data, label=()) -> str:
        """AOT-compile this step for one batch signature before training
        dispatches it (the manifest replay target; callable directly with
        template NDArrays or ``(shape, dtype)`` specs). Settles
        parameters and optimizer state if needed, then builds + compiles
        the executable into the step cache — the first real ``__call__``
        with this signature is a pure cache hit, zero retraces."""
        from ..ndarray import zeros as nd_zeros

        def to_nd(v):
            if isinstance(v, NDArray):
                return nd_zeros(tuple(v.shape), dtype=str(v.dtype))
            if isinstance(v, (list, tuple)) and v \
                    and isinstance(v[0], (int,)):
                return nd_zeros(tuple(v), dtype="float32")
            shape, dtype = v
            return nd_zeros(tuple(shape), dtype=dtype)

        data_tuple = tuple(to_nd(v) for v in _as_tuple(data))
        label_tuple = tuple(to_nd(v) for v in _as_tuple(label))
        if getattr(self, "_aot_only", False):
            raise MXNetError("this TrainStep was used for aot_compile; "
                             "warm() needs a live step")
        if self._params is None:
            self._settle_params(data_tuple)
            self._init_states()
        hit = self._key_for(data_tuple, label_tuple) in self._cache
        # route through the live entry path: it owns the donation-
        # invalidation rule (a donating step must never hold two batch
        # shapes at once — a warm() that seeded several would hand real
        # traffic donated-dead buffers on the alternate shape)
        self._entry_for(data_tuple, label_tuple)
        return "deduped" if hit else "replayed"

    def warm_from_spec(self, spec) -> str:
        """``compiler.warm_start``'s train_step replay hook."""
        return self.warm(tuple(spec.get("data") or ()),
                         tuple(spec.get("label") or ()))

    # -- call ------------------------------------------------------------
    def __call__(self, data, label):
        import jax

        if getattr(self, "_aot_only", False):
            raise MXNetError(
                "this TrainStep was used for aot_compile (abstract "
                "parameters, no optimizer state buffers); build a fresh "
                "TrainStep on a concretely initialized net to train")
        data_tuple = _as_tuple(data)
        label_tuple = _as_tuple(label)
        if self._params is None:
            self._settle_params(data_tuple)
            self._init_states()
        entry = self._entry_for(data_tuple, label_tuple)
        jitted, cell = entry["jitted"], entry["cell"]

        optimizer = self.optimizer
        # advance step counts eagerly (the dynamic-mode counterpart of
        # Optimizer._update_count inside the reference's Updater)
        for k in range(len(self._trainable)):
            optimizer._update_count(k)
        import numpy as np

        # fixed-width host scalars: under jax_enable_x64 a bare Python
        # int/float would trace as i64/f64 and drip f64 math into the step
        t = np.int32(optimizer.num_update)
        lr = np.float32(optimizer.learning_rate)
        rng = random_state.get_state_key()

        param_vals = tuple(p.data().data for p in self._params)
        state_vals = tuple(s.data for s in self._state_leaf_nds)
        # explicit device_put: host batches become sharded global arrays
        # (each host feeds its slice on pods — SURVEY.md §7.1 "Data").
        # A batch already carrying the exact target sharding (staged by
        # io.DeviceFeedIter / stage_batch) skips the put entirely — the
        # async-pipeline contract that makes entry a true no-op.
        batch_vals = []
        for v, sh in zip(data_tuple + label_tuple, entry["batch_sh"]):
            d = v.data
            if self.donate_inputs and getattr(d, "is_deleted", None) \
                    and d.is_deleted():
                raise MXNetError(
                    "TrainStep(donate_inputs=True): a batch buffer passed "
                    "to this step was already donated to a previous "
                    "dispatch (its device memory was reused for "
                    "activations). Donation is for single-use batches — "
                    "stage a FRESH buffer per step (io.DeviceFeedIter "
                    "does), or build the step with donate_inputs=False "
                    "to replay one staged batch")
            if getattr(d, "sharding", None) == sh:
                batch_vals.append(d)
            else:
                batch_vals.append(jax.device_put(d, sh))
        from ..base import execution_platform
        from .mesh import use_mesh

        # mesh context active during trace: in-graph mesh consumers (ring
        # attention's shard_map) resolve the step's mesh
        with execution_platform(self.mesh.devices.flat[0].platform), \
                use_mesh(self.mesh):
            new_params, new_states, loss_val, outs, aux = jitted(
                param_vals, state_vals, t, lr, rng, *batch_vals)
        if not getattr(self, "_first_step_marked", False):
            self._first_step_marked = True
            from .. import compiler

            compiler.mark_event("first_train_step")

        for p, v in zip(self._params, new_params):
            p.data()._set_data(v)
        for s, v in zip(self._state_leaf_nds, new_states):
            s._set_data(v)
        for arr, v in zip(cell["aux_arrays"], aux):
            arr._set_data(v)
        ctx = self._params[0].data().context if self._params else current_context()
        # read the flag the executable was traced with, not the live
        # attribute — toggling self.loss_only between steps must not desync
        # the host return path from the compiled output arity
        if entry["loss_only"]:
            return NDArray(data=loss_val, ctx=ctx), None
        out_nd = [NDArray(data=v, ctx=ctx) for v in outs]
        out_tree = nested_unflatten_nd(cell["treedef"], out_nd)
        return NDArray(data=loss_val, ctx=ctx), out_tree
