"""Pipeline parallelism — GPipe over a ``pp`` mesh axis.

The reference has no pipeline parallelism (SURVEY.md §2.4 — PP row: "NO";
listed as the stretch capability for the Llama-scale config). TPU-native
design: the repeated trunk of a deep network becomes ONE block whose
parameters carry a leading ``(n_stages, layers_per_stage)`` stage axis
sharded over the mesh's ``pp`` axis. The forward runs a GPipe schedule
inside ``shard_map``: every device applies its own stage's layers to a
circulating activation while microbatches stream in, and activations hop
stage→stage over ICI via ``lax.ppermute``. The bubble is the usual
``(n_stages - 1) / (n_microbatches + n_stages - 1)``.

Because the schedule is pure jax (``lax.scan`` + ``ppermute``) it nests
inside the fused :class:`~mxnet_tpu.parallel.step.TrainStep` executable
exactly like ring attention does: GSPMD keeps handling the ``dp``/``tp``
axes while only ``pp`` is manual, and gradients flow by differentiating
through the scan (``ppermute``'s transpose is the reverse rotation).

Usage::

    trunk = Pipelined(lambda: LlamaBlock(...), n_stages=4,
                      layers_per_stage=2, n_microbatches=8)
    # trunk is an ordinary HybridBlock: compose, initialize, train with
    # TrainStep(net, ..., rules=pipeline_sharding_rules() + model rules)
"""
from __future__ import annotations

import math
import re
from typing import Optional

import numpy as _np

from .. import initializer as _initializer
from .. import random_state
from ..autograd import is_training
from ..gluon.block import HybridBlock, make_pure_fn
from ..ndarray import NDArray, array as nd_array
from .mesh import current_mesh

__all__ = ["pipeline_apply", "Pipelined", "pipeline_sharding_rules",
           "pipeline_active"]


def pipeline_active(axis="pp", mesh=None):
    """True when a mesh is live and ``axis`` spans more than one device."""
    mesh = mesh or current_mesh()
    return (mesh is not None and axis in mesh.axis_names
            and mesh.shape[axis] > 1)


def pipeline_apply(stage_fn, stacked_leaves, x, rng, *, mesh=None,
                   axis="pp", n_microbatches=None, remat=False):
    """Run ``x`` through ``n_stages * layers_per_stage`` layers, pipelined.

    Parameters
    ----------
    stage_fn : ``stage_fn(leaves, h, key) -> h`` — one layer applied to
        activation ``h``; ``leaves`` is a tuple of per-layer parameter
        arrays, ``key`` a PRNG key. Must preserve ``h``'s shape/dtype.
    stacked_leaves : tuple of arrays, each ``(n_stages, layers_per_stage)
        + param_shape`` — the stage-stacked parameters.
    x : ``(B, ...)`` activations (batch-leading).
    rng : PRNG key (folded per stage/layer/tick for e.g. dropout).
    mesh : jax Mesh holding the ``axis``; ``None`` → sequential fallback
        (identical math — the schedule only changes WHERE layers run).
    n_microbatches : microbatch count (must divide B); default n_stages.
    remat : rematerialize each layer in the backward (``jax.checkpoint``)
        — the standard GPipe memory/flops trade.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    n_stages = int(stacked_leaves[0].shape[0])
    l_per = int(stacked_leaves[0].shape[1])
    if remat:
        stage_fn = jax.checkpoint(stage_fn)

    def run_layers(leaves, h, key):
        def one(hc, sl):
            lp, i = sl
            return stage_fn(lp, hc, jax.random.fold_in(key, i)), None

        h, _ = lax.scan(one, h, (leaves, jnp.arange(l_per)))
        return h

    if not pipeline_active(axis, mesh):
        # one device (or no mesh): the same layers, applied in order
        flat = tuple(a.reshape((n_stages * l_per,) + a.shape[2:])
                     for a in stacked_leaves)

        def one(hc, sl):
            lp, i = sl
            return stage_fn(lp, hc, jax.random.fold_in(rng, i)), None

        y, _ = lax.scan(one, x, (flat, jnp.arange(n_stages * l_per)))
        return y

    if n_stages != mesh.shape[axis]:
        raise ValueError(
            f"pipeline has {n_stages} stages but mesh axis '{axis}' spans "
            f"{mesh.shape[axis]} devices; they must match")
    n_micro = int(n_microbatches or n_stages)
    b = x.shape[0]
    if b % n_micro:
        raise ValueError(
            f"batch {b} not divisible by n_microbatches={n_micro}")
    stream = x.reshape((n_micro, b // n_micro) + x.shape[1:])

    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    last = n_stages - 1
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def body(local_stacked, stream, key):
        # local_stacked leaves: (1, l_per, ...) — this device's stage
        local = tuple(a[0] for a in local_stacked)
        stage = lax.axis_index(axis)
        key = jax.random.fold_in(key, stage)
        state = jnp.zeros_like(stream[0])
        out = jnp.zeros_like(stream)
        mark = getattr(lax, "pcast", None)
        if mark is not None:
            # invariant zeros become pp-varying carries (see ring_attention)
            state = mark(state, (axis,), to="varying")
            out = mark(out, (axis,), to="varying")
            stream = mark(stream, (axis,), to="varying")

        def tick(carry, t):
            state, out = carry
            inj = stream[jnp.minimum(t, n_micro - 1)]
            h = jnp.where(stage == 0, inj, state)
            y = run_layers(local, h, jax.random.fold_in(key, t))
            widx = jnp.clip(t - last, 0, n_micro - 1)
            take = jnp.logical_and(stage == last, t >= last)
            out = jnp.where(take, out.at[widx].set(y), out)
            state = lax.ppermute(y, axis, perm)
            return (state, out), None

        (_, out), _ = lax.scan(tick, (state, out),
                               jnp.arange(n_micro + last))
        # results live on the last stage; broadcast so the (replicated-
        # over-pp) head/loss sees them everywhere
        out = lax.psum(jnp.where(stage == last, out, jnp.zeros_like(out)),
                       axis)
        return out

    fn = shard_map(body, mesh=mesh,
                   in_specs=(P(axis), P(), P()), out_specs=P(),
                   axis_names=frozenset({axis}))
    out = fn(stacked_leaves, stream, rng)
    return out.reshape(x.shape)


class _StackedInit(_initializer.Initializer):
    """Initialize a stage-stacked parameter by applying the template
    layer's own initializer independently per (stage, layer) copy — so a
    pipelined trunk starts from the same distribution as ``n`` separately
    constructed layers."""

    def __init__(self, base, lead):
        super().__init__()
        self._base = base
        self._lead = tuple(lead)

    def __call__(self, desc, arr):
        copies = 1
        for d in self._lead:
            copies *= int(d)
        sub = tuple(arr.shape[len(self._lead):])
        outs = []
        for _ in range(copies):
            host = nd_array(_np.zeros(sub, dtype="float32"))
            self._base(_initializer.InitDesc(str(desc),
                                             global_init=self._base), host)
            outs.append(host.asnumpy())
        arr[:] = _np.stack(outs).reshape(arr.shape)


class Pipelined(HybridBlock):
    """A stack of identical layers executed as a GPipe pipeline.

    ``stage_factory() -> HybridBlock`` builds ONE layer (activation-in,
    activation-out, shape-preserving). The trunk owns
    ``n_stages * layers_per_stage`` independent copies of that layer's
    parameters, stacked with a leading ``(n_stages, layers_per_stage)``
    axis; :func:`pipeline_sharding_rules` lays the stage axis over the
    mesh's ``pp`` dimension and :class:`TrainStep` compiles the schedule
    into the fused training step.

    Off-mesh (no ``pp`` axis, or a single device) the block computes the
    identical function sequentially, so models build/run/test anywhere.

    Notes: the template layer must not mutate aux state (BatchNorm-style
    moving stats) — stages run inside ``lax.scan`` where write-back has
    no defined order; use normalization without running stats (LayerNorm/
    RMSNorm), as transformer trunks do.
    """

    def __init__(self, stage_factory, n_stages, layers_per_stage=1,
                 axis="pp", n_microbatches=None, remat=False,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._n_stages = int(n_stages)
        self._l_per = int(layers_per_stage)
        self._axis = axis
        self._n_micro = n_microbatches
        self._remat = bool(remat)
        with self.name_scope():
            tmpl = stage_factory()
        if not isinstance(tmpl, HybridBlock):
            raise TypeError("stage_factory must build a HybridBlock")
        # NOT registered as a child: the template's own parameters are a
        # shape/init donor, never trained — the stacked copies are.
        self._template_holder = [tmpl]
        self._stacked = None       # list[Parameter], 1:1 with _tmpl_params
        self._tmpl_params = None   # list[Parameter] of the template

    # -- parameter lifecycle -------------------------------------------
    def _ensure_stub_params(self):
        """Create the stacked Parameters (deferred shapes allowed) so
        ``collect_params``/``initialize`` see them before first forward."""
        if self._stacked is not None:
            return
        tmpl = self._template_holder[0]
        tparams = list(tmpl.collect_params().values())
        lead = (self._n_stages, self._l_per)
        stacked = []
        for p in tparams:
            base = _initializer.create(p.init)
            shape = None
            if p.shape is not None and all(s > 0 for s in p.shape):
                shape = lead + tuple(p.shape)
            name = p.name
            if name.startswith(self.prefix):
                name = name[len(self.prefix):]
            name = "pp_" + name.replace(".", "_")
            sp = self.params.get(name, shape=shape,
                                 init=_StackedInit(base, lead),
                                 allow_deferred_init=True)
            stacked.append(sp)
        self._tmpl_params = tparams
        self._stacked = stacked

    def collect_params(self, select=None):
        self._ensure_stub_params()
        return super().collect_params(select)

    def _settle(self, x):
        """Resolve deferred shapes: settle the template on a sample
        microbatch, then size + initialize the stacked parameters."""
        ctx = x.context
        tmpl = self._template_holder[0]
        tparams = self._tmpl_params
        if any(p._data is None for p in tparams):
            tmpl.initialize(ctx=ctx)
            sample = x[0:1]
            tmpl(sample)
        lead = (self._n_stages, self._l_per)
        for p, sp in zip(tparams, self._stacked):
            if sp._data is not None:
                continue
            sp.shape = lead + tuple(p.shape)
            if sp._deferred_init is not None:
                sp._finish_deferred_init()
            else:
                sp.initialize(ctx=ctx)

    def _ensure_template_ready(self, ctx):
        """The template's arrays are pure-fn swap vehicles: their VALUES
        are never read, but they must exist. When the stacked params are
        already sized (concrete-shape template, or restored checkpoint),
        derive template shapes from them — no sample forward needed."""
        for p, sp in zip(self._tmpl_params, self._stacked):
            if p._data is not None:
                continue
            if p.shape is None or any(s <= 0 for s in p.shape):
                p.shape = tuple(sp.shape[2:])
            p._deferred_init = None
            p.initialize(ctx=ctx)

    # -- forward --------------------------------------------------------
    def _eager_forward(self, x):
        import jax

        self._ensure_stub_params()
        if any(sp._data is None for sp in self._stacked):
            if isinstance(x.data, jax.core.Tracer):
                raise RuntimeError(
                    "Pipelined parameters have deferred shapes; run one "
                    "eager forward (or TrainStep, which does) before "
                    "tracing")
            self._settle(x)
        ctx = x.context
        tmpl = self._template_holder[0]
        self._ensure_template_ready(ctx)
        tmpl_arrays = [p.data(ctx) for p in self._tmpl_params]
        pure, _cell = make_pure_fn(tmpl, tmpl_arrays, ctx, is_training())

        def stage_fn(leaves, h, key):
            out_vals, aux_vals = pure(tuple(leaves), key, h)
            if aux_vals:
                raise RuntimeError(
                    "Pipelined stage mutates aux state (e.g. BatchNorm "
                    "running stats) — unsupported inside the pipeline "
                    "scan; use LayerNorm/RMSNorm in the stage")
            return out_vals[0]

        stacked_vals = tuple(sp.data(ctx).data for sp in self._stacked)
        mesh = current_mesh()
        y = pipeline_apply(stage_fn, stacked_vals, x.data,
                           random_state.get_state_key(), mesh=mesh,
                           axis=self._axis, n_microbatches=self._n_micro,
                           remat=self._remat)
        return NDArray(data=y, ctx=ctx)

    def hybrid_forward(self, F, x, **params):  # pragma: no cover
        raise NotImplementedError(
            "Pipelined lowers through _eager_forward (jit/TrainStep); the "
            "legacy symbolic composition path is not supported")


def pipeline_sharding_rules(axis="pp", extra=None):
    """Rules laying every ``pp_*`` stacked parameter's stage axis over the
    mesh's pipeline axis. ``extra`` maps inner-dimension rules for
    composing with tensor parallelism, e.g.::

        pipeline_sharding_rules(extra=[
            (r"pp_.*(q|kv|gateup)_weight$", ("tp",)),      # column-parallel
            (r"pp_.*(out|down)_weight$",    (None, "tp")),  # row-parallel
        ])

    where the tuple gives entries for the dims AFTER the (stage, layer)
    lead — ("tp",) shards a stacked (S, L, out, in) weight's out dim.
    """
    from jax.sharding import PartitionSpec as P

    from .sharding import ShardingRules

    rules = []
    for pat, inner in (extra or []):
        rules.append((pat, P(axis, None, *inner)))
    # plain substring (not \b-anchored): '_' is a word character, so a
    # boundary would never match inside prefixed names like 'trunk_pp_...'
    rules.append((r"pp_", P(axis)))
    return ShardingRules(rules)
