"""Pipeline parallelism — GPipe over a ``pp`` mesh axis.

The reference has no pipeline parallelism (SURVEY.md §2.4 — PP row: "NO";
listed as the stretch capability for the Llama-scale config). TPU-native
design: the repeated trunk of a deep network becomes ONE block whose
parameters carry a leading ``(n_stages, layers_per_stage)`` stage axis
sharded over the mesh's ``pp`` axis. The forward runs a GPipe schedule
inside ``shard_map``: every device applies its own stage's layers to a
circulating activation while microbatches stream in, and activations hop
stage→stage over ICI via ``lax.ppermute``. The bubble is the usual
``(n_stages - 1) / (n_microbatches + n_stages - 1)``.

Because the schedule is pure jax (``lax.scan`` + ``ppermute``) it nests
inside the fused :class:`~mxnet_tpu.parallel.step.TrainStep` executable
exactly like ring attention does: GSPMD keeps handling the ``dp``/``tp``
axes while only ``pp`` is manual, and gradients flow by differentiating
through the scan (``ppermute``'s transpose is the reverse rotation).

Usage::

    trunk = Pipelined(lambda: LlamaBlock(...), n_stages=4,
                      layers_per_stage=2, n_microbatches=8)
    # trunk is an ordinary HybridBlock: compose, initialize, train with
    # TrainStep(net, ..., rules=pipeline_sharding_rules() + model rules)
"""
from __future__ import annotations

import math
import re
from typing import Optional

import numpy as _np

from .. import initializer as _initializer
from .. import random_state
from ..autograd import is_training
from ..gluon.block import HybridBlock, make_pure_fn
from ..ndarray import NDArray, array as nd_array
from .mesh import current_mesh

__all__ = ["pipeline_apply", "Pipelined", "pipeline_sharding_rules",
           "pipeline_active", "pipeline_train_1f1b"]


def pipeline_active(axis="pp", mesh=None):
    """True when a mesh is live and ``axis`` spans more than one device."""
    mesh = mesh or current_mesh()
    return (mesh is not None and axis in mesh.axis_names
            and mesh.shape[axis] > 1)


def pipeline_apply(stage_fn, stacked_leaves, x, rng, *, mesh=None,
                   axis="pp", n_microbatches=None, remat=False):
    """Run ``x`` through ``n_stages * layers_per_stage`` layers, pipelined.

    Parameters
    ----------
    stage_fn : ``stage_fn(leaves, h, key) -> h`` — one layer applied to
        activation ``h``; ``leaves`` is a tuple of per-layer parameter
        arrays, ``key`` a PRNG key. Must preserve ``h``'s shape/dtype.
    stacked_leaves : tuple of arrays, each ``(n_stages, layers_per_stage)
        + param_shape`` — the stage-stacked parameters.
    x : ``(B, ...)`` activations (batch-leading).
    rng : PRNG key (folded per stage/layer/tick for e.g. dropout).
    mesh : jax Mesh holding the ``axis``; ``None`` → sequential fallback
        (identical math — the schedule only changes WHERE layers run).
    n_microbatches : microbatch count (must divide B); default n_stages.
    remat : rematerialize each layer in the backward (``jax.checkpoint``)
        — the standard GPipe memory/flops trade.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    n_stages = int(stacked_leaves[0].shape[0])
    l_per = int(stacked_leaves[0].shape[1])
    if remat:
        stage_fn = jax.checkpoint(stage_fn)

    def run_layers(leaves, h, key):
        def one(hc, sl):
            lp, i = sl
            return stage_fn(lp, hc, jax.random.fold_in(key, i)), None

        h, _ = lax.scan(one, h, (leaves, jnp.arange(l_per)))
        return h

    if not pipeline_active(axis, mesh):
        # one device (or no mesh): the same layers, applied in order
        flat = tuple(a.reshape((n_stages * l_per,) + a.shape[2:])
                     for a in stacked_leaves)

        def one(hc, sl):
            lp, i = sl
            return stage_fn(lp, hc, jax.random.fold_in(rng, i)), None

        y, _ = lax.scan(one, x, (flat, jnp.arange(n_stages * l_per)))
        return y

    if n_stages != mesh.shape[axis]:
        raise ValueError(
            f"pipeline has {n_stages} stages but mesh axis '{axis}' spans "
            f"{mesh.shape[axis]} devices; they must match")
    n_micro = int(n_microbatches or n_stages)
    b = x.shape[0]
    if b % n_micro:
        raise ValueError(
            f"batch {b} not divisible by n_microbatches={n_micro}")
    stream = x.reshape((n_micro, b // n_micro) + x.shape[1:])

    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    last = n_stages - 1
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def body(local_stacked, stream, key):
        # local_stacked leaves: (1, l_per, ...) — this device's stage
        local = tuple(a[0] for a in local_stacked)
        stage = lax.axis_index(axis)
        key = jax.random.fold_in(key, stage)
        state = jnp.zeros_like(stream[0])
        out = jnp.zeros_like(stream)
        mark = getattr(lax, "pcast", None)
        if mark is not None:
            # invariant zeros become pp-varying carries (see ring_attention)
            state = mark(state, (axis,), to="varying")
            out = mark(out, (axis,), to="varying")
            stream = mark(stream, (axis,), to="varying")

        def tick(carry, t):
            state, out = carry
            inj = stream[jnp.minimum(t, n_micro - 1)]
            h = jnp.where(stage == 0, inj, state)
            y = run_layers(local, h, jax.random.fold_in(key, t))
            widx = jnp.clip(t - last, 0, n_micro - 1)
            take = jnp.logical_and(stage == last, t >= last)
            out = jnp.where(take, out.at[widx].set(y), out)
            state = lax.ppermute(y, axis, perm)
            return (state, out), None

        (_, out), _ = lax.scan(tick, (state, out),
                               jnp.arange(n_micro + last))
        # results live on the last stage; broadcast so the (replicated-
        # over-pp) head/loss sees them everywhere
        out = lax.psum(jnp.where(stage == last, out, jnp.zeros_like(out)),
                       axis)
        return out

    fn = shard_map(body, mesh=mesh,
                   in_specs=(P(axis), P(), P()), out_specs=P(),
                   axis_names=frozenset({axis}))
    out = fn(stacked_leaves, stream, rng)
    return out.reshape(x.shape)


class _StackedInit(_initializer.Initializer):
    """Initialize a stage-stacked parameter by applying the template
    layer's own initializer independently per (stage, layer) copy — so a
    pipelined trunk starts from the same distribution as ``n`` separately
    constructed layers."""

    def __init__(self, base, lead):
        super().__init__()
        self._base = base
        self._lead = tuple(lead)

    def __call__(self, desc, arr):
        copies = 1
        for d in self._lead:
            copies *= int(d)
        sub = tuple(arr.shape[len(self._lead):])
        outs = []
        for _ in range(copies):
            host = nd_array(_np.zeros(sub, dtype="float32"))
            self._base(_initializer.InitDesc(str(desc),
                                             global_init=self._base), host)
            outs.append(host.asnumpy())
        arr[:] = _np.stack(outs).reshape(arr.shape)


class Pipelined(HybridBlock):
    """A stack of identical layers executed as a GPipe pipeline.

    ``stage_factory() -> HybridBlock`` builds ONE layer (activation-in,
    activation-out, shape-preserving). The trunk owns
    ``n_stages * layers_per_stage`` independent copies of that layer's
    parameters, stacked with a leading ``(n_stages, layers_per_stage)``
    axis; :func:`pipeline_sharding_rules` lays the stage axis over the
    mesh's ``pp`` dimension and :class:`TrainStep` compiles the schedule
    into the fused training step.

    Off-mesh (no ``pp`` axis, or a single device) the block computes the
    identical function sequentially, so models build/run/test anywhere.

    Notes: the template layer must not mutate aux state (BatchNorm-style
    moving stats) — stages run inside ``lax.scan`` where write-back has
    no defined order; use normalization without running stats (LayerNorm/
    RMSNorm), as transformer trunks do.
    """

    def __init__(self, stage_factory, n_stages, layers_per_stage=1,
                 axis="pp", n_microbatches=None, remat=False,
                 schedule="gpipe", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if schedule not in ("gpipe", "1f1b"):
            raise ValueError(f"unknown pipeline schedule {schedule!r}")
        # 1F1B bounds activation memory by starting each microbatch's
        # backward as soon as it drains — which requires the LOSS inside
        # the schedule, so it cannot hide behind this AD-transparent
        # block's forward. TrainStep detects schedule='1f1b' and routes
        # training through pipeline_train_1f1b (loss folded into the last
        # stage); plain forward (inference/eval) uses the GPipe schedule,
        # which computes the identical function.
        self._schedule = schedule
        self._n_stages = int(n_stages)
        self._l_per = int(layers_per_stage)
        self._axis = axis
        self._n_micro = n_microbatches
        self._remat = bool(remat)
        with self.name_scope():
            tmpl = stage_factory()
        if not isinstance(tmpl, HybridBlock):
            raise TypeError("stage_factory must build a HybridBlock")
        # NOT registered as a child: the template's own parameters are a
        # shape/init donor, never trained — the stacked copies are.
        self._template_holder = [tmpl]
        self._stacked = None       # list[Parameter], 1:1 with _tmpl_params
        self._tmpl_params = None   # list[Parameter] of the template

    # -- parameter lifecycle -------------------------------------------
    def _ensure_stub_params(self):
        """Create the stacked Parameters (deferred shapes allowed) so
        ``collect_params``/``initialize`` see them before first forward."""
        if self._stacked is not None:
            return
        tmpl = self._template_holder[0]
        tparams = list(tmpl.collect_params().values())
        lead = (self._n_stages, self._l_per)
        stacked = []
        for p in tparams:
            base = _initializer.create(p.init)
            shape = None
            if p.shape is not None and all(s > 0 for s in p.shape):
                shape = lead + tuple(p.shape)
            name = p.name
            if name.startswith(self.prefix):
                name = name[len(self.prefix):]
            name = "pp_" + name.replace(".", "_")
            sp = self.params.get(name, shape=shape,
                                 init=_StackedInit(base, lead),
                                 allow_deferred_init=True)
            stacked.append(sp)
        self._tmpl_params = tparams
        self._stacked = stacked

    def collect_params(self, select=None):
        self._ensure_stub_params()
        return super().collect_params(select)

    def _settle(self, x):
        """Resolve deferred shapes: settle the template on a sample
        microbatch, then size + initialize the stacked parameters."""
        ctx = x.context
        tmpl = self._template_holder[0]
        tparams = self._tmpl_params
        if any(p._data is None for p in tparams):
            tmpl.initialize(ctx=ctx)
            sample = x[0:1]
            tmpl(sample)
        lead = (self._n_stages, self._l_per)
        for p, sp in zip(tparams, self._stacked):
            if sp._data is not None:
                continue
            sp.shape = lead + tuple(p.shape)
            if sp._deferred_init is not None:
                sp._finish_deferred_init()
            else:
                sp.initialize(ctx=ctx)

    def _ensure_template_ready(self, ctx):
        """The template's arrays are pure-fn swap vehicles: their VALUES
        are never read, but they must exist. When the stacked params are
        already sized (concrete-shape template, or restored checkpoint),
        derive template shapes from them — no sample forward needed."""
        for p, sp in zip(self._tmpl_params, self._stacked):
            if p._data is not None:
                continue
            if p.shape is None or any(s <= 0 for s in p.shape):
                p.shape = tuple(sp.shape[2:])
            p._deferred_init = None
            p.initialize(ctx=ctx)

    # -- forward --------------------------------------------------------
    def _eager_forward(self, x):
        import jax

        self._ensure_stub_params()
        if any(sp._data is None for sp in self._stacked):
            if isinstance(x.data, jax.core.Tracer):
                raise RuntimeError(
                    "Pipelined parameters have deferred shapes; run one "
                    "eager forward (or TrainStep, which does) before "
                    "tracing")
            self._settle(x)
        ctx = x.context
        tmpl = self._template_holder[0]
        self._ensure_template_ready(ctx)
        tmpl_arrays = [p.data(ctx) for p in self._tmpl_params]
        pure, _cell = make_pure_fn(tmpl, tmpl_arrays, ctx, is_training())

        def stage_fn(leaves, h, key):
            out_vals, aux_vals = pure(tuple(leaves), key, h)
            if aux_vals:
                raise RuntimeError(
                    "Pipelined stage mutates aux state (e.g. BatchNorm "
                    "running stats) — unsupported inside the pipeline "
                    "scan; use LayerNorm/RMSNorm in the stage")
            return out_vals[0]

        stacked_vals = tuple(sp.data(ctx).data for sp in self._stacked)
        mesh = current_mesh()
        y = pipeline_apply(stage_fn, stacked_vals, x.data,
                           random_state.get_state_key(), mesh=mesh,
                           axis=self._axis, n_microbatches=self._n_micro,
                           remat=self._remat)
        return NDArray(data=y, ctx=ctx)

    def hybrid_forward(self, F, x, **params):  # pragma: no cover
        raise NotImplementedError(
            "Pipelined lowers through _eager_forward (jit/TrainStep); the "
            "legacy symbolic composition path is not supported")

    # -- 1F1B integration (TrainStep) -----------------------------------
    def _stage_fn_1f1b(self, ctx, training):
        """Build ``stage_fn(leaves, h, key) -> h`` running this stage's
        ``layers_per_stage`` layers — the :func:`pipeline_train_1f1b`
        contract, where ``leaves`` are one stage's parameter slices
        (``(layers_per_stage,) + param_shape``)."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        tmpl = self._template_holder[0]
        self._ensure_template_ready(ctx)
        tmpl_arrays = [p.data(ctx) for p in self._tmpl_params]
        pure, _cell = make_pure_fn(tmpl, tmpl_arrays, ctx, training)
        l_per = self._l_per

        def layer(lp, hc, key):
            out_vals, aux_vals = pure(tuple(lp), key, hc)
            if aux_vals:
                raise RuntimeError(
                    "Pipelined stage mutates aux state (e.g. BatchNorm "
                    "running stats) — unsupported inside the pipeline "
                    "scan; use LayerNorm/RMSNorm in the stage")
            return out_vals[0]

        def stage_all(leaves, h, key):
            def one(hc, sl):
                lp, i = sl
                return layer(lp, hc, jax.random.fold_in(key, i)), None

            h, _ = lax.scan(one, h, (leaves, jnp.arange(l_per)))
            return h

        return stage_all


def pipeline_sharding_rules(axis="pp", extra=None):
    """Rules laying every ``pp_*`` stacked parameter's stage axis over the
    mesh's pipeline axis. ``extra`` maps inner-dimension rules for
    composing with tensor parallelism, e.g.::

        pipeline_sharding_rules(extra=[
            (r"pp_.*(q|kv|gateup)_weight$", ("tp",)),      # column-parallel
            (r"pp_.*(out|down)_weight$",    (None, "tp")),  # row-parallel
        ])

    where the tuple gives entries for the dims AFTER the (stage, layer)
    lead — ("tp",) shards a stacked (S, L, out, in) weight's out dim.
    """
    from jax.sharding import PartitionSpec as P

    from .sharding import ShardingRules

    rules = []
    for pat, inner in (extra or []):
        rules.append((pat, P(axis, None, *inner)))
    # plain substring (not \b-anchored): '_' is a word character, so a
    # boundary would never match inside prefixed names like 'trunk_pp_...'
    rules.append((r"pp_", P(axis)))
    return ShardingRules(rules)


# ---------------------------------------------------------------------------
# 1F1B (one-forward-one-backward) schedule
# ---------------------------------------------------------------------------


def pipeline_train_1f1b(stage_fn, loss_fn, stacked_leaves, x, labels, rng,
                        *, mesh=None, axis="pp", n_microbatches=None):
    """Fused forward+backward pipeline with the 1F1B schedule.

    GPipe (``pipeline_apply`` + AD) runs ALL microbatch forwards before
    any backward because the loss sits outside the schedule — every stage
    holds ``n_micro`` boundary activations. 1F1B folds the loss into the
    last stage so microbatch ``m``'s backward starts the tick its forward
    drains, bounding live activations per stage to the in-flight count
    (``<= n_stages``) instead of ``n_micro``. The bubble fraction stays
    ``(S-1)/(M+S-1)`` per direction (the schedule overlaps the two
    directions tick-for-tick: fwd of micro ``t-s`` and bwd of micro
    ``t-(2(S-1)-s)`` share each tick); the win is MEMORY — which is why
    this entry point takes the loss and cannot be AD-transparent.

    Per-stage backward recomputes the stage from its saved INPUT (the
    remat trade). The input stash is a RING of ``2*(n_stages-1)+1``
    slots (a micro's input lives from its fwd tick ``m+s`` to its bwd
    tick ``m+2(S-1)-s``, so at most ``2(S-1)+1`` are in flight), making
    per-stage activation memory independent of ``n_microbatches``.

    Parameters
    ----------
    stage_fn : ``stage_fn(leaves, h, key) -> h`` — one stage (all its
        layers); shape/dtype-preserving.
    loss_fn : ``loss_fn(h, labels_micro) -> scalar`` — head + loss on the
        LAST stage's output (mean over the microbatch).
    stacked_leaves : tuple of ``(n_stages,) + param_shape`` arrays.
    x, labels : (B, ...) arrays, microbatched alongside each other.
    rng : PRNG key.

    Returns ``(mean_loss, grads_stacked, dx)``.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    n_stages = int(stacked_leaves[0].shape[0])
    n_micro_seq = int(n_microbatches or n_stages)
    if not pipeline_active(axis, mesh):
        # sequential reference: same math, one device — microbatched with
        # the SAME per-(stage, micro) key folds as the pipelined
        # schedule, so key-using stages (dropout) stay bit-identical
        def full(leaves, x):
            xs = x.reshape((n_micro_seq, x.shape[0] // n_micro_seq)
                           + x.shape[1:])
            ys = labels.reshape((n_micro_seq,) + xs.shape[1:2]
                                + labels.shape[1:])
            total = 0.0
            for m in range(n_micro_seq):
                h = xs[m]
                for s in range(n_stages):
                    key_s = jax.random.fold_in(rng, s)
                    h = stage_fn(tuple(a[s] for a in leaves), h,
                                 jax.random.fold_in(key_s, m))
                total = total + loss_fn(h, ys[m])
            return total / n_micro_seq

        loss, (gl, gx) = jax.value_and_grad(full, argnums=(0, 1))(
            stacked_leaves, x)
        return loss, gl, gx

    mesh = mesh or current_mesh()
    if n_stages != mesh.shape[axis]:
        raise ValueError(
            f"pipeline has {n_stages} stages but mesh axis '{axis}' spans "
            f"{mesh.shape[axis]} devices")
    n_micro = int(n_microbatches or n_stages)
    b = x.shape[0]
    if b % n_micro:
        raise ValueError(f"batch {b} not divisible by {n_micro}")
    xs = x.reshape((n_micro, b // n_micro) + x.shape[1:])
    ys = labels.reshape((n_micro, b // n_micro) + labels.shape[1:])

    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    last = n_stages - 1
    fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    bwd_perm = [((i + 1) % n_stages, i) for i in range(n_stages)]
    # stage s: fwd of micro (t - s), bwd of micro (t - (2*last - s));
    # the last backward is stage 0's micro M-1 at t = M - 1 + 2*last
    total = n_micro + 2 * last

    def body(local_stacked, xs, ys, key):
        local = tuple(a[0] for a in local_stacked)
        stage = lax.axis_index(axis)
        key = jax.random.fold_in(key, stage)

        def run_stage(leaves, h, m):
            return stage_fn(leaves, h, jax.random.fold_in(key, m))

        micro_shape = xs.shape[1:]
        # in-flight input ring: micro m's input is saved at fwd tick
        # m+s and read at bwd tick m+2*last-s; the gap is <= 2*last, so
        # ring_n slots never collide and memory is O(n_stages), not
        # O(n_micro)
        ring_n = min(n_micro, 2 * last + 1)
        saved = jnp.zeros((ring_n,) + micro_shape, xs.dtype)
        fwd_state = jnp.zeros(micro_shape, xs.dtype)
        bwd_state = jnp.zeros(micro_shape, jnp.float32)
        gacc = tuple(jnp.zeros(a.shape[1:], jnp.float32)
                     for a in local_stacked)
        dx = jnp.zeros(xs.shape, jnp.float32)
        loss_acc = jnp.zeros((), jnp.float32)
        mark = getattr(lax, "pcast", None)
        if mark is not None:
            saved, fwd_state, bwd_state, dx, loss_acc = (
                mark(v, (axis,), to="varying")
                for v in (saved, fwd_state, bwd_state, dx, loss_acc))
            gacc = tuple(mark(g, (axis,), to="varying") for g in gacc)

        def tick(carry, t):
            saved, fwd_state, bwd_state, gacc, dx, loss_acc = carry
            mf = t - stage
            mb = t - (2 * last - stage)
            fwd_on = jnp.logical_and(mf >= 0, mf < n_micro)
            bwd_on = jnp.logical_and(mb >= 0, mb < n_micro)
            mf_c = jnp.clip(mf, 0, n_micro - 1)
            mb_c = jnp.clip(mb, 0, n_micro - 1)

            # ---- forward unit ----
            h_in = jnp.where(stage == 0, xs[mf_c], fwd_state)
            saved = jnp.where(fwd_on,
                              saved.at[mf_c % ring_n].set(h_in), saved)
            h_out = run_stage(local, h_in, mf_c)
            # loss + seed cotangent: only the last stage pays for the
            # head — shard_map manual mode gives each device its own
            # control flow, so lax.cond here is a real branch
            lval, dh_seed = lax.cond(
                stage == last,
                lambda: jax.value_and_grad(
                    lambda hh: loss_fn(hh, ys[mf_c]))(h_out),
                lambda: (jnp.zeros((), jnp.float32),
                         jnp.zeros_like(h_out)))
            loss_acc = loss_acc + jnp.where(fwd_on, lval, 0.0)

            # ---- backward unit (recompute from the saved stage input);
            # on the last stage fwd and bwd of a micro share the tick, so
            # the seed is consumed immediately rather than hopped ----
            g_in = jnp.where(stage == last,
                             dh_seed.astype(jnp.float32), bwd_state)
            h_saved = saved[mb_c % ring_n]
            _, vjp = jax.vjp(
                lambda lv, hh: run_stage(lv, hh, mb_c), local, h_saved)
            gl, gh = vjp(g_in.astype(h_saved.dtype))
            gacc = tuple(
                jnp.where(bwd_on, a + gi.astype(jnp.float32), a)
                for a, gi in zip(gacc, gl))
            dx = jnp.where(
                jnp.logical_and(stage == 0, bwd_on),
                dx.at[mb_c].set(gh.astype(jnp.float32)), dx)

            # ---- hops ----
            fwd_state = lax.ppermute(h_out, axis, fwd_perm)
            bwd_state = lax.ppermute(gh.astype(jnp.float32), axis,
                                     bwd_perm)
            return (saved, fwd_state, bwd_state, gacc, dx, loss_acc), None

        (saved, fwd_state, bwd_state, gacc, dx, loss_acc), _ = lax.scan(
            tick, (saved, fwd_state, bwd_state, gacc, dx, loss_acc),
            jnp.arange(total))
        # the reported loss is the mean of per-micro means; grads from
        # per-micro losses therefore rescale by 1/n_micro to match the
        # full-batch-mean convention of the sequential reference
        loss_acc = lax.psum(loss_acc, axis) / n_micro
        inv = jnp.float32(1.0 / n_micro)
        dx = lax.psum(dx, axis) * inv
        return (loss_acc, tuple((g * inv)[None] for g in gacc), dx)

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P(axis), P(), P(), P()),
        out_specs=(P(), P(axis), P()),
        axis_names=frozenset({axis}), check_vma=False)
    loss, grads, dx = fn(stacked_leaves, xs, ys, rng)
    return (loss, tuple(g.astype(a.dtype) for g, a in
                        zip(grads, stacked_leaves)),
            dx.reshape(x.shape).astype(x.dtype))
