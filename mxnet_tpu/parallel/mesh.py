"""Device-mesh management — the TPU-native replacement for MXNet's
multi-device Context lists.

Reference mapping (SURVEY.md §2.4): MXNet expresses data parallelism as a
python list of contexts (``ctx=[mx.gpu(0), mx.gpu(1)]``) fed to
``DataParallelExecutorGroup`` / Gluon ``Trainer``, and model parallelism as
``group2ctx`` manual placement. The TPU-native design replaces both with ONE
``jax.sharding.Mesh`` whose named axes carry the parallelism meaning:

* ``dp`` — data parallel (batch sharding; gradient psum over this axis)
* ``tp`` — tensor parallel (GSPMD param sharding — NEW vs reference)
* ``pp`` — pipeline parallel (stage axis; collective-permute microbatching)
* ``sp`` — sequence/context parallel (ring attention over this axis)
* ``ep`` — expert parallel (MoE experts)

XLA inserts the collectives (psum/all-gather/reduce-scatter/ppermute) over
ICI; multi-host layouts ride DCN via the same mesh (jax.distributed
bootstrap — see mxnet_tpu.kvstore and tools/launch.py).
"""
from __future__ import annotations

import contextlib
import threading
from typing import Dict, List, Optional, Sequence

import numpy as _np

from ..base import MXNetError

__all__ = ["AXES", "make_mesh", "current_mesh", "use_mesh", "local_devices",
           "mesh_axis_size"]

# canonical axis order: outermost (slowest, crosses DCN first) to innermost
AXES = ("pp", "dp", "ep", "sp", "tp")

_state = threading.local()


def local_devices(device_type: Optional[str] = None):
    """All JAX devices visible to this process, accelerator first."""
    import jax

    if device_type:
        return jax.devices(device_type)
    return jax.devices()


def make_mesh(axes: Optional[Dict[str, int]] = None, devices=None):
    """Create a named device mesh.

    ``axes`` maps axis name -> size; at most one size may be -1 (inferred
    from the device count). Default: all devices on the ``dp`` axis — the
    reference's data-parallel ctx-list (``kvstore='device'``) equivalent.

        mesh = make_mesh({'dp': 4, 'tp': 2})
        with use_mesh(mesh):
            ...
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = local_devices()
    n = len(devices)
    if axes is None:
        axes = {"dp": n}
    names: List[str] = []
    sizes: List[int] = []
    infer_idx = None
    for name, size in axes.items():
        names.append(name)
        if size == -1:
            if infer_idx is not None:
                raise MXNetError("only one mesh axis may have size -1")
            infer_idx = len(sizes)
            sizes.append(1)
        else:
            sizes.append(int(size))
    known = int(_np.prod(sizes))
    if infer_idx is not None:
        if n % known:
            raise MXNetError(
                f"cannot infer axis {names[infer_idx]!r}: {n} devices not "
                f"divisible by {known}")
        sizes[infer_idx] = n // known
        known = n
    if known != n:
        raise MXNetError(
            f"mesh axes {dict(zip(names, sizes))} need {known} devices but "
            f"{n} are visible")
    dev_array = _np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, axis_names=tuple(names))


def current_mesh():
    """The mesh installed by :func:`use_mesh` (or None)."""
    return getattr(_state, "mesh", None)


@contextlib.contextmanager
def use_mesh(mesh):
    prev = getattr(_state, "mesh", None)
    _state.mesh = mesh
    try:
        yield mesh
    finally:
        _state.mesh = prev


def mesh_axis_size(mesh, axis: str) -> int:
    if mesh is None or axis not in mesh.axis_names:
        return 1
    return mesh.shape[axis]
