"""Sharded (per-process) checkpointing for TrainStep.

Reference capability: ``Module.save_checkpoint`` / Gluon
``save_parameters`` + ``Trainer.save_states`` cover the single-host case
by gathering everything to host 0 — fine for ResNet, impossible for a
model that only exists sharded over a pod (SURVEY.md §5.4 "stretch:
sharded save behind the same call"; VERDICT r4 #6: an 8B model living on
a 32-device mesh via ``abstract_init`` had no tested save/resume path).

Design (ocp-style, but on the ``.params`` container so the format stays
the framework's own):

* ``save_sharded(step, directory)`` — every process writes ONE
  ``shard-{pid:05d}-of-{n:05d}.params`` file holding, for each parameter
  and optimizer-state leaf, the process's ADDRESSABLE shards only
  (deduplicated: a replicated value stores one copy per process, a
  tp-sharded weight stores each distinct slice once). Keys are
  ``{name}@{slice}`` where ``{slice}`` is the shard's global index
  (e.g. ``0:128,64:128``) — self-describing, mesh-topology-free.
  Process 0 additionally writes ``meta.json`` (names, global shapes,
  dtypes, optimizer counters, process count); every process writes
  ``index-{pid}.json`` listing its keys so restore can locate any slice
  without opening every file.
* ``restore_sharded(step, directory)`` — each process materializes ONLY
  the slices its local devices need (per the step's own shardings),
  device_puts them shard-by-shard, and assembles global arrays with
  ``jax.make_array_from_single_device_arrays``. No host ever holds a
  full copy of any tensor, so the path works for models larger than any
  single host/device memory. Optimizer counters are restored so LR
  schedules and bias-correction terms continue bit-identically.

Restore requires slice-compatible shardings (the natural case: same mesh
shape and rules). A mismatched slice raises with the missing key named.

Stale-file hygiene: a save into a directory that already holds a
checkpoint writes ALL new data under ``.saving`` temp names first (the
old checkpoint survives a crash anywhere in the data-write phase), then
— behind a cross-process barrier — process 0 removes the old save
wholesale (``meta.json`` first, so the directory is loudly invalid
rather than a silent mix of two saves), every process renames its files
into place, and process 0 commits by writing ``meta.json`` last.
Restore validates the on-disk index set against ``meta.json``'s process
count and refuses both truncated and stale-extra checkpoints.
"""
from __future__ import annotations

import json
import os
import re
from typing import Dict

import numpy as _np

from ..base import MXNetError

__all__ = ["save_sharded", "restore_sharded"]

# the exact artifact names this format writes — stale-file hygiene and
# restore validation both key off these
_SHARD_RE = re.compile(r"shard-(\d{5})-of-(\d{5})\.params")
_INDEX_RE = re.compile(r"index-(\d{5})\.json")
# in-progress saves write under this suffix so a crash mid-save can
# never destroy or masquerade as the committed checkpoint
_TMP_SUFFIX = ".saving"


def _checkpoint_files(directory):
    """(shard files, index files, has_meta) already present."""
    shards, indexes, has_meta = [], [], False
    for f in os.listdir(directory):
        if _SHARD_RE.fullmatch(f):
            shards.append(f)
        elif _INDEX_RE.fullmatch(f):
            indexes.append(f)
        elif f == "meta.json":
            has_meta = True
    return shards, indexes, has_meta


def _slice_key(index, shape) -> str:
    parts = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        parts.append(f"{start}:{stop}")
    return ",".join(parts) if parts else "scalar"


def _param_names(step):
    """Structure-relative parameter names, aligned with step._params.

    Uses the block-attribute path (``_collect_params_with_prefix`` — the
    same names Block.save_parameters writes), NOT Parameter.name: Gluon's
    per-class name counters are process-global, so two instances of the
    same architecture disagree on raw names (dense0_ vs dense2_) while
    their attribute paths are identical."""
    by_id = {}
    collect = getattr(step.net, "_collect_params_with_prefix", None)
    if collect is not None:
        for k, p in collect().items():
            by_id[id(p)] = k
    prefix = getattr(step.net, "prefix", "") or ""
    names = []
    for p in step._params:
        n = by_id.get(id(p))
        if n is None:  # fallback: prefix-relative raw name
            n = p.name[len(prefix):] \
                if prefix and p.name.startswith(prefix) else p.name
        names.append(n)
    return names


def _named_arrays(step):
    """(name, jax.Array holder) pairs for every persistent tensor of the
    step: parameters by structure-relative name, state leaves
    positionally."""
    pairs = []
    for n, p in zip(_param_names(step), step._params):
        pairs.append((n, p.data()))
    for j, leaf in enumerate(step._state_leaf_nds):
        pairs.append((f"__state{j}", leaf))
    return pairs


def save_sharded(step, directory: str) -> None:
    """Write this process's shard file (+ index, + meta on process 0)."""
    import jax

    from ..ndarray import serialization

    if step._params is None or step._state_leaf_nds is None:
        raise MXNetError(
            "save_sharded: TrainStep has no settled parameters/state — "
            "run at least one step (or restore into it) first")
    os.makedirs(directory, exist_ok=True)
    pid, nproc = jax.process_index(), jax.process_count()
    fname = f"shard-{pid:05d}-of-{nproc:05d}.params"
    iname = f"index-{pid:05d}.json"

    entries: Dict[str, _np.ndarray] = {}
    meta_arrays = {}
    for name, nd in _named_arrays(step):
        arr = nd.data
        meta_arrays[name] = {"shape": list(arr.shape),
                             "dtype": str(arr.dtype)}
        seen = set()
        for sh in arr.addressable_shards:
            ikey = _slice_key(sh.index, arr.shape)
            if ikey in seen:
                continue
            seen.add(ikey)
            entries[f"{name}@{ikey}"] = _np.asarray(sh.data)

    # New data lands under temp names FIRST: a crash anywhere in the
    # (long) data-write phase leaves the previous checkpoint in this
    # directory fully intact. Only after every process has its shard on
    # disk does process 0 sweep the OLD checkpoint (meta.json first —
    # from that instant the directory is loudly "no valid checkpoint",
    # never a silent mix of two saves), then everyone renames into
    # place and process 0 commits with meta.json LAST.
    tmp = _TMP_SUFFIX
    index = serialization.save_indexed(
        os.path.join(directory, fname + tmp), entries)
    with open(os.path.join(directory, iname + tmp), "w") as f:
        json.dump({"file": fname, "entries": index}, f)
    if nproc > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("mxnet_tpu_sharded_ckpt_data")
    if pid == 0:
        # stale-file hygiene: files of a previous checkpoint (same or
        # DIFFERENT process count) must never be resolvable by the new
        # checkpoint's restore — remove the old save wholesale, plus
        # any temp litter from a crashed earlier attempt
        shards, indexes, has_meta = _checkpoint_files(directory)
        if has_meta:
            os.unlink(os.path.join(directory, "meta.json"))
        # this save's OWN temp files (every rank's, not just p0's) are
        # the new checkpoint — only temp names outside the current
        # topology's name set are litter from a crashed attempt
        current = {f"shard-{p:05d}-of-{nproc:05d}.params{tmp}"
                   for p in range(nproc)}
        current |= {f"index-{p:05d}.json{tmp}" for p in range(nproc)}
        litter = [f for f in os.listdir(directory)
                  if f.endswith(tmp) and f not in current
                  and (_SHARD_RE.fullmatch(f[:-len(tmp)])
                       or _INDEX_RE.fullmatch(f[:-len(tmp)]))]
        for f in indexes + shards + litter:
            os.unlink(os.path.join(directory, f))
    if nproc > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("mxnet_tpu_sharded_ckpt_clean")
    os.replace(os.path.join(directory, fname + tmp),
               os.path.join(directory, fname))
    os.replace(os.path.join(directory, iname + tmp),
               os.path.join(directory, iname))
    # cross-process barrier BEFORE the commit marker: meta.json is written
    # LAST by process 0, so a checkpoint with meta.json present has every
    # shard fully on disk — a crash mid-save can never masquerade as a
    # complete checkpoint
    if nproc > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("mxnet_tpu_sharded_ckpt_save")
    if pid == 0:
        opt = step.optimizer
        meta = {
            "nproc": nproc,
            "arrays": meta_arrays,
            "param_names": _param_names(step),
            "n_state_leaves": len(step._state_leaf_nds),
            "optimizer": {
                "num_update": int(opt.num_update),
                "index_update_count": {
                    str(k): int(v)
                    for k, v in opt._index_update_count.items()},
            },
        }
        with open(os.path.join(directory, "meta.json"), "w") as f:
            json.dump(meta, f)


class _ShardReader:
    """Per-key lazy shard lookup: key -> host numpy array.

    Reads use the byte index (seek + read of exactly one slice), never a
    whole-file parse; keys present in several processes' files resolve to
    THIS process's own file first, so a same-topology restore touches
    only local data."""

    def __init__(self, directory, nproc: int):
        import jax

        self._dir = directory
        own = f"index-{jax.process_index():05d}.json"
        self._key_to_loc: Dict[str, tuple] = {}
        idx_files = sorted(
            f for f in os.listdir(directory) if _INDEX_RE.fullmatch(f))
        # validate the index set against meta.json's process count: a
        # missing index means a truncated checkpoint, an EXTRA one is a
        # stale file from an older save (different topology) whose
        # slices must never resolve
        pids = {int(_INDEX_RE.fullmatch(f).group(1)) for f in idx_files}
        expected = set(range(int(nproc)))
        if pids != expected:
            missing = sorted(expected - pids)
            stale = sorted(pids - expected)
            raise MXNetError(
                f"restore_sharded: index files in {directory!r} do not "
                f"match meta.json (nproc={nproc})"
                + (f"; missing index files for processes {missing}"
                   if missing else "")
                + (f"; stale index files from processes {stale} of an "
                   "older checkpoint — clean the directory" if stale
                   else ""))
        # own index LAST so its entries override other processes'
        for idx in [f for f in idx_files if f != own] + \
                ([own] if own in idx_files else []):
            with open(os.path.join(directory, idx)) as f:
                rec = json.load(f)
            for k, entry in rec["entries"].items():
                self._key_to_loc[k] = (rec["file"], entry)

    def get(self, key: str) -> _np.ndarray:
        loc = self._key_to_loc.get(key)
        if loc is None:
            raise MXNetError(
                f"restore_sharded: slice {key!r} not found in checkpoint "
                "— the saving and restoring shardings must be "
                "slice-compatible (same mesh shape and rules)")
        from ..ndarray import serialization

        fname, entry = loc
        return serialization.read_indexed(
            os.path.join(self._dir, fname), entry)


def _materialize(name, shape, dtype, sharding, reader):
    """Assemble one global array from per-device slices — local devices
    only, no full-array host copy."""
    import jax

    index_map = sharding.addressable_devices_indices_map(tuple(shape))
    shards = []
    devs = []
    for dev, index in index_map.items():
        ikey = _slice_key(index, shape)
        host = reader.get(f"{name}@{ikey}").astype(dtype, copy=False)
        shards.append(jax.device_put(host, dev))
        devs.append(dev)
    return jax.make_array_from_single_device_arrays(
        tuple(shape), sharding, shards)


def restore_sharded(step, directory: str, example_data=None) -> None:
    """Restore parameters, optimizer state, and counters in place.

    Works on a live step (buffers overwritten) and on a freshly built
    step (pass ``example_data`` — the training batch, or same-shaped
    arrays — so deferred shapes settle before the restore); each process
    reads only the slices its devices own.
    """
    import jax

    with open(os.path.join(directory, "meta.json")) as f:
        meta = json.load(f)
    if step._params is None:
        if example_data is None:
            raise MXNetError(
                "restore_sharded: settle the step's parameters first "
                "(run one step, or pass example_data=) — restore "
                "replaces buffer contents, not the model structure")
        from .step import _as_tuple

        step._settle_params(_as_tuple(example_data))
    if step._state_leaf_nds is None or (
            not step._state_leaf_nds
            and meta["n_state_leaves"]):
        step._init_states()
    names = _param_names(step)
    if names != meta["param_names"]:
        raise MXNetError(
            "restore_sharded: parameter set mismatch — checkpoint has "
            f"{len(meta['param_names'])} params, step has {len(names)} "
            "(or ordering/naming differs)")
    if len(step._state_leaf_nds) != meta["n_state_leaves"]:
        raise MXNetError(
            f"restore_sharded: optimizer state layout mismatch "
            f"({len(step._state_leaf_nds)} leaves vs checkpoint "
            f"{meta['n_state_leaves']}) — same optimizer required")

    reader = _ShardReader(directory, meta["nproc"])
    for name, nd in _named_arrays(step):
        rec = meta["arrays"].get(name)
        arr = nd.data
        if rec is None:
            raise MXNetError(
                f"restore_sharded: {name!r} absent from checkpoint meta")
        if tuple(rec["shape"]) != tuple(arr.shape) \
                or rec["dtype"] != str(arr.dtype):
            raise MXNetError(
                f"restore_sharded: {name!r} is {rec['dtype']}"
                f"{tuple(rec['shape'])} in the checkpoint but "
                f"{arr.dtype}{tuple(arr.shape)} in the step — same "
                "architecture/dtype config required")
        new = _materialize(name, rec["shape"], rec["dtype"],
                           arr.sharding, reader)
        nd._set_data(new)

    opt = step.optimizer
    opt.num_update = meta["optimizer"]["num_update"]
    opt._restore_update_counts({
        int(k): v
        for k, v in meta["optimizer"]["index_update_count"].items()})
