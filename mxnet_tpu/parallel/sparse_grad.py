"""Row-sparse embedding gradients — the TPU-native lazy-update path.

Reference: MXNet's ``Embedding(sparse_grad=True)`` produces a
``RowSparseNDArray`` gradient that kvstore + optimizer consume without
densifying (``indexing_op.cc`` TakeNonzeroAxis0 backward +
``optimizer.py`` lazy_update). XLA has no sparse gradient type, so the
equivalent here is FACTORED, not typed:

* the embedding lookup runs through a ``jax.custom_vjp`` whose backward
  logs ``(rows, dY)`` into a trace-scoped side channel and returns a
  symbolic-zero dense cotangent (dead code unless someone consumes it);
* the train step replaces that parameter's optimizer call with a LAZY
  ROW update: duplicate rows are combined with a static-shape dedupe
  (sort + segment-sum, duplicate slots parked on an out-of-range
  sentinel row that scatter ``mode='drop'`` discards), the weight and
  its param-shaped optimizer-state rows are gathered, the REAL
  ``Optimizer.update_multi_precision`` runs on the (N, D) row batch —
  identical math, bias corrections and multi-precision dtype rules —
  and the results scatter back.

The HLO of such a step contains no (vocab, dim) gradient buffer: the
only full-table tensors are the parameter and its states. Constraint
(same as the reference): a sparse-grad embedding weight must not also
receive dense gradients (e.g. tied softmax weights) — the dense
cotangent from other uses would be silently dropped. TrainStep raises
when the Parameter OBJECT is shared across blocks
(``_check_sparse_sharing``); routing the same array through other ops
manually is the user's responsibility, as with the reference's
storage-type checks.
"""
from __future__ import annotations

import contextlib

__all__ = ["sparse_grad_scope", "sparse_grad_active", "log_sparse_grad",
           "dedupe_rows", "lazy_row_update"]

_SCOPE = [None]


class _Log:
    def __init__(self):
        self.entries = {}  # uid -> list[(rows, vals)]

    def add(self, uid, rows, vals):
        self.entries.setdefault(uid, []).append((rows, vals))


@contextlib.contextmanager
def sparse_grad_scope():
    """Activate the (rows, dY) side channel for embedding backwards."""
    prev = _SCOPE[0]
    log = _Log()
    _SCOPE[0] = log
    try:
        yield log
    finally:
        _SCOPE[0] = prev


def sparse_grad_active():
    return _SCOPE[0] is not None


def log_sparse_grad(uid, rows, vals):
    if _SCOPE[0] is not None:
        _SCOPE[0].add(uid, rows, vals)


def dedupe_rows(rows, vals, n_total):
    """Combine duplicate row ids with static shapes.

    rows: (N,) int32; vals: (N, D). Returns (uniq_rows, summed) both of
    length N: segment k holds the k-th distinct row's id and the SUM of
    its values; surplus slots hold ``n_total`` (out of range — callers
    scatter with ``mode='drop'``).
    """
    import jax
    import jax.numpy as jnp

    n = rows.shape[0]
    order = jnp.argsort(rows)
    r = rows[order]
    v = vals[order]
    first = jnp.concatenate([jnp.ones((1,), bool), r[1:] != r[:-1]])
    seg = jnp.cumsum(first) - 1                     # segment id per entry
    summed = jax.ops.segment_sum(v, seg, num_segments=n)
    uniq = jnp.full((n,), n_total, dtype=rows.dtype).at[seg].set(r)
    return uniq, summed


def lazy_row_update(optimizer, k, param_nd, entries, state, ctx):
    """Run the optimizer on only the touched rows of ``param_nd``.

    entries: list[(rows, vals)] from the scope log (concatenated).
    state: the param's optimizer-state pytree (leaves are NDArrays shaped
    like the param, or None). Mutates the NDArray payloads in place like
    ``Optimizer.update_multi_precision`` does on the dense path.
    """
    import jax
    import jax.numpy as jnp

    from ..ndarray import NDArray

    V = param_nd.shape[0]
    rows = jnp.concatenate(
        [r.reshape(-1).astype(jnp.int32) for r, _ in entries])
    vals = jnp.concatenate(
        [v.reshape(-1, v.shape[-1]) for _, v in entries])
    uniq, summed = dedupe_rows(rows, vals, V)

    def gather(nd):
        return nd.data[uniq]                        # OOB rows clamp-read

    def scatter(nd, new_rows):
        nd._set_data(nd.data.at[uniq].set(new_rows, mode="drop"))

    w_rows = NDArray(data=gather(param_nd), ctx=ctx)
    g_rows = NDArray(data=summed.astype(param_nd.dtype), ctx=ctx)

    leaves, treedef = jax.tree_util.tree_flatten(
        state, is_leaf=lambda x: x is None or isinstance(x, NDArray))
    row_leaves = []
    for leaf in leaves:
        if leaf is None:
            row_leaves.append(None)
            continue
        if tuple(leaf.shape) != tuple(param_nd.shape):
            raise NotImplementedError(
                "lazy_row_update: optimizer state leaf shaped "
                f"{leaf.shape} != param {param_nd.shape}; this optimizer "
                "has non-rowwise state — use a dense-grad embedding")
        row_leaves.append(NDArray(data=gather(leaf), ctx=ctx))
    row_state = jax.tree_util.tree_unflatten(treedef, row_leaves)

    optimizer.update_multi_precision(k, w_rows, g_rows, row_state)

    scatter(param_nd, w_rows.data)
    for leaf, row_leaf in zip(leaves, row_leaves):
        if leaf is not None:
            scatter(leaf, row_leaf.data)
