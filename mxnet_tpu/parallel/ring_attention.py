"""Ring attention — sequence/context parallelism over a mesh axis
(reference capability: long-context training; design follows the Ring
Attention construction — arXiv:2310.01889 — expressed TPU-natively as
``shard_map`` + ``lax.ppermute`` over ICI).

Each device holds a sequence shard of Q/K/V. K/V blocks rotate around the
ring while every device folds them into an online-softmax accumulator for
its local Q shard, so

* memory per device is O(L_local) — no device ever materializes the full
  (L, L) score matrix or the full K/V;
* communication is nearest-neighbor ``ppermute`` riding ICI, overlapping
  with the per-block attention math;
* the math is EXACTLY softmax(QK^T)V (the same online-softmax algebra as
  the Pallas flash kernel, accumulated across ring steps).

Gradients flow by differentiating through the scan (``ppermute``'s
transpose is the reverse rotation, inserted by AD). Residual note: the
scan saves the rotating K/V carries, so training memory is O(L) per
device like gather-based attention — a custom recompute VJP is the
planned upgrade; inference/scoring is O(L_local).
"""
from __future__ import annotations

import math

__all__ = ["ring_attention", "ring_attention_sharded"]

_NEG = -1e30


def ring_attention_sharded(q, k, v, axis_name, causal=False, scale=None):
    """Per-shard body: call inside ``shard_map`` over ``axis_name``.

    q/k/v: (B, H, L_local, D) — this device's sequence shard.
    """
    import jax.numpy as jnp
    from jax import lax

    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, h, lq, d = q.shape
    qf = q.astype(jnp.float32) * jnp.float32(scale)
    perm = [(i, (i + 1) % n) for i in range(n)]

    q_pos = idx * lq + jnp.arange(lq)                     # global positions

    lk = k.shape[2]

    def step(carry, s):
        acc, m, l, kb, vb = carry
        k_idx = (idx - s) % n

        def attend(args):
            acc, m, l = args
            kf = kb.astype(jnp.float32)
            scores = jnp.einsum("bhqd,bhkd->bhqk", qf, kf)
            if causal:
                k_pos = k_idx * lk + jnp.arange(lk)
                mask = k_pos[None, :] <= q_pos[:, None]
                scores = jnp.where(mask[None, None], scores, _NEG)
            m_new = jnp.maximum(m, scores.max(axis=-1, keepdims=True))
            p = jnp.exp(scores - m_new)
            if causal:
                p = jnp.where(mask[None, None], p, 0.0)
            alpha = jnp.exp(m - m_new)
            l_new = l * alpha + p.sum(axis=-1, keepdims=True)
            acc_new = acc * alpha + jnp.einsum(
                "bhqk,bhkd->bhqd", p, vb.astype(jnp.float32))
            return acc_new, m_new, l_new

        if causal:
            # skip blocks entirely above the diagonal (the ~half of ring
            # steps whose keys are all in this shard's future)
            any_visible = k_idx * lk <= idx * lq + (lq - 1)
            acc, m, l = lax.cond(any_visible, attend,
                                 lambda args: args, (acc, m, l))
        else:
            acc, m, l = attend((acc, m, l))
        # rotate K/V to the next device; the last step's rotation closes
        # the ring (XLA elides unused outputs if it can)
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return (acc, m, l, kb, vb), None

    acc0 = jnp.zeros((b, h, lq, d), jnp.float32)
    m0 = jnp.full((b, h, lq, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((b, h, lq, 1), jnp.float32)
    # constants start device-invariant; the scan carries become varying
    # per shard, so mark the initial values varying over the ring axis
    mark = getattr(lax, "pcast", None)
    if mark is not None:
        acc0 = mark(acc0, (axis_name,), to="varying")
        m0 = mark(m0, (axis_name,), to="varying")
        l0 = mark(l0, (axis_name,), to="varying")
    (acc, m, l, _, _), _ = lax.scan(step, (acc0, m0, l0, k, v),
                                    jnp.arange(n))
    out = acc / jnp.where(l == 0.0, 1.0, l)
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh=None, axis="sp", causal=False, scale=None):
    """Sequence-parallel exact attention over ``mesh[axis]``.

    q/k/v: GLOBAL (B, H, L, D) arrays (sharded or replicated — the
    shard_map in_spec lays them on the axis). Returns (B, H, L, D) with
    the same sequence sharding. Falls back to dense attention when the
    mesh axis has a single device.
    """
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from .mesh import current_mesh

    mesh = mesh or current_mesh()
    if not ring_active(axis, mesh):
        from ..ops.attention import _sdpa_reference

        if scale is None:
            scale = 1.0 / math.sqrt(q.shape[-1])
        return _sdpa_reference(q, k, v, None, scale, causal)
    # ONLY the ring axis is manual (axis_names): batch (dp) and head (tp)
    # shardings stay with GSPMD — making every axis manual would
    # all-gather q/k/v over the other mesh axes and replicate the
    # attention compute per dp/tp shard
    spec = P(None, None, axis, None)
    fn = shard_map(
        lambda a, b_, c: ring_attention_sharded(a, b_, c, axis,
                                                causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        axis_names=frozenset({axis}))
    return fn(q, k, v)


def ring_active(axis, mesh=None):
    """True when ring attention would actually run over ``axis`` (a mesh
    is active and the axis spans more than one device)."""
    from .mesh import current_mesh

    mesh = mesh or current_mesh()
    return (mesh is not None and axis in mesh.axis_names
            and mesh.shape[axis] > 1)
