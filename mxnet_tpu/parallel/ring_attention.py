"""Ring attention — sequence/context parallelism over a mesh axis
(reference capability: long-context training; design follows the Ring
Attention construction — arXiv:2310.01889 — expressed TPU-natively as
``shard_map`` + ``lax.ppermute`` over ICI).

Each device holds a sequence shard of Q/K/V. K/V blocks rotate around the
ring while every device folds them into a running softmax merge for its
local Q shard, so

* memory per device is O(L_local) — no device ever materializes the full
  (L, L) score matrix or the full K/V, in the FORWARD **and** the
  BACKWARD: a ``jax.custom_vjp`` saves only (q, k, v, out, lse) shards
  and re-walks the ring in the backward pass, rotating a
  (q, dO, lse, delta, dQ) bundle while each device accumulates dK/dV for
  its resident shard — probabilities are recomputed per pair from the
  global logsumexp, the FlashAttention recompute trade stretched over
  the ring (round-2 weakness #3: the old scan saved every rotating K/V
  carry, making training memory O(L));
* communication is nearest-neighbor ``ppermute`` riding ICI, overlapping
  with the per-block attention math;
* the math is EXACTLY softmax(QK^T)V — per-pair partials merge through
  their base-2 logsumexp (the same domain the Pallas kernels emit);
* on TPU, each per-pair block attention runs the Pallas flash kernels in
  both directions when the shard shapes qualify (``flash_supported``);
  anywhere else an einsum path computes the identical algebra.
"""
from __future__ import annotations

import functools
import math

import jax

__all__ = ["ring_attention", "ring_attention_sharded", "ring_active"]

_NEG = -1e30
_LOG2E = 1.4426950408889634


def _pair_fwd(q, k, v, scale, pair_causal, use_kernel, interpret=False):
    """One (q-shard, k-shard) block attention -> (out f32, lse2 f32).

    ``out`` is normalized within the pair; ``lse2`` is the pair's base-2
    logsumexp of the SCALED scores, shaped (B, H, Lq). Fully-masked rows
    emit out = 0, lse2 = -inf, which merge as zero weight.
    """
    import jax.numpy as jnp

    if use_kernel:
        from ..pallas_kernels.flash_attention import _flash_fwd_pallas

        out, lse = _flash_fwd_pallas(q, k, v, scale, pair_causal,
                                     interpret=interpret)
        b, h, lq, d = q.shape
        nq = lse.shape[1]
        lse2 = lse[:, :, 0, :].reshape(b, h, lq)
        return out.astype(jnp.float32), lse2

    qf = q.astype(jnp.float32) * jnp.float32(scale * _LOG2E)
    s = jnp.einsum("bhqd,bhkd->bhqk", qf, k.astype(jnp.float32))
    if pair_causal:
        lq, lk = q.shape[2], k.shape[2]
        mask = jnp.tril(jnp.ones((lq, lk), bool), k=lk - lq)
        s = jnp.where(mask[None, None], s, _NEG)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp2(s - m)
    if pair_causal:
        p = jnp.where(mask[None, None], p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    out = out / jnp.where(l == 0.0, 1.0, l)
    lse2 = jnp.where(l == 0.0, _NEG, m + jnp.log2(jnp.where(
        l == 0.0, 1.0, l)))[..., 0]
    return out, lse2


def _merge(out_a, lse_a, out_b, lse_b):
    """Merge two normalized partial attentions via base-2 logsumexp."""
    import jax.numpy as jnp

    m = jnp.maximum(lse_a, lse_b)
    # fully-masked partials carry lse = -inf -> weight 0 (guard m=-inf)
    m_safe = jnp.where(m <= _NEG, 0.0, m)
    wa = jnp.exp2(lse_a - m_safe)
    wb = jnp.exp2(lse_b - m_safe)
    tot = wa + wb
    tot_safe = jnp.where(tot == 0.0, 1.0, tot)
    out = (out_a * wa[..., None] + out_b * wb[..., None]) / tot_safe[..., None]
    lse = jnp.where(tot == 0.0, _NEG, m_safe + jnp.log2(tot_safe))
    return out, lse


def _pair_bwd(q, k, v, do, lse2, delta, scale, pair_causal, use_kernel,
              interpret=False):
    """Gradients of one block pair given the GLOBAL lse2/delta.

    Returns (dq, dk, dv) contributions in f32. p recomputed as
    exp2(s2 - lse2) — rows of q fully masked within this pair produce
    zero contributions (s2 = -inf).
    """
    import jax.numpy as jnp

    b, h, lq, d = q.shape
    if use_kernel:
        from ..pallas_kernels.flash_attention import (_block_sizes,
                                                      _flash_bwd_pallas)

        bh = b * h
        bq = _block_sizes(lq, k.shape[2])[0]
        nq = lq // bq
        lse_k = jnp.broadcast_to(
            lse2.reshape(bh, nq, 1, bq), (bh, nq, 8, bq))
        dq, dk, dv = _flash_bwd_pallas(
            q, k, v, None, lse_k, do, scale, pair_causal,
            interpret=interpret, delta=delta.reshape(bh, lq))
        return (dq.astype(jnp.float32), dk.astype(jnp.float32),
                dv.astype(jnp.float32))

    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    s2 = jnp.einsum("bhqd,bhkd->bhqk", qf * jnp.float32(scale * _LOG2E), kf)
    if pair_causal:
        lk = k.shape[2]
        mask = jnp.tril(jnp.ones((lq, lk), bool), k=lk - lq)
        s2 = jnp.where(mask[None, None], s2, _NEG)
    p = jnp.exp2(s2 - lse2[..., None])                    # (B,H,Lq,Lk)
    dv_c = jnp.einsum("bhqk,bhqd->bhkd", p, dof)
    dp = jnp.einsum("bhqd,bhkd->bhqk", dof, vf)
    ds = p * (dp - delta[..., None]) * jnp.float32(scale)
    dk_c = jnp.einsum("bhqk,bhqd->bhkd", ds, qf)
    dq_c = jnp.einsum("bhqk,bhkd->bhqd", ds, kf)
    return dq_c, dk_c, dv_c


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring(q, k, v, axis_name, causal, scale):
    return _ring_fwd(q, k, v, axis_name, causal, scale)[0]


def _use_kernel(q, k, v, causal):
    from ..pallas_kernels.flash_attention import flash_supported

    return flash_supported(q, k, v, causal=causal)


def _ring_fwd(q, k, v, axis_name, causal, scale):
    import jax.numpy as jnp
    from jax import lax

    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, h, lq, d = q.shape
    lk = k.shape[2]
    perm = [(i, (i + 1) % n) for i in range(n)]
    kernel_ok = _use_kernel(q, k, v, causal)

    def step(carry, s):
        out, lse, kb, vb = carry
        k_idx = (idx - s) % n

        def attend(args):
            out, lse = args
            # diagonal pair: lq == lk blocks, standard causal; strictly
            # past pair: full attention
            if causal:
                o_i, l_i = lax.cond(
                    k_idx == idx,
                    lambda: _pair_fwd(q, kb, vb, scale, True, kernel_ok),
                    lambda: _pair_fwd(q, kb, vb, scale, False, kernel_ok))
            else:
                o_i, l_i = _pair_fwd(q, kb, vb, scale, False, kernel_ok)
            return _merge(out, lse, o_i, l_i)

        if causal:
            # skip blocks entirely in this shard's future
            visible = k_idx <= idx
            out, lse = lax.cond(visible, attend, lambda a: a, (out, lse))
        else:
            out, lse = attend((out, lse))
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return (out, lse, kb, vb), None

    out0 = jnp.zeros((b, h, lq, d), jnp.float32)
    lse0 = jnp.full((b, h, lq), _NEG, jnp.float32)
    mark = getattr(lax, "pcast", None)
    if mark is not None:
        out0 = mark(out0, (axis_name,), to="varying")
        lse0 = mark(lse0, (axis_name,), to="varying")
    (out, lse, _, _), _ = lax.scan(step, (out0, lse0, k, v),
                                   jnp.arange(n))
    return out.astype(q.dtype), (q, k, v, out.astype(q.dtype), lse)


def _ring_bwd(axis_name, causal, scale, res, g):
    """One reverse ring pass: the (q, dO, lse, delta, dQ) bundle rotates;
    each device folds the visiting shard into its resident dK/dV."""
    import jax.numpy as jnp
    from jax import lax

    q, k, v, out, lse = res
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    kernel_ok = _use_kernel(q, k, v, causal)

    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)                               # (B,H,Lq)

    def step(carry, s):
        qb, dob, lseb, deltab, dqb, dk_acc, dv_acc = carry
        # the visiting bundle originated on device (idx - s) % n; its q
        # block index is that origin — local k block index is idx
        q_idx = (idx - s) % n

        def attend(args):
            dqb, dk_acc, dv_acc = args
            if causal:
                dq_c, dk_c, dv_c = lax.cond(
                    q_idx == idx,
                    lambda: _pair_bwd(qb, k, v, dob, lseb, deltab, scale,
                                      True, kernel_ok),
                    lambda: _pair_bwd(qb, k, v, dob, lseb, deltab, scale,
                                      False, kernel_ok))
            else:
                dq_c, dk_c, dv_c = _pair_bwd(qb, k, v, dob, lseb, deltab,
                                             scale, False, kernel_ok)
            return dqb + dq_c, dk_acc + dk_c, dv_acc + dv_c

        if causal:
            visible = idx <= q_idx  # local keys not in visiting q's future
            dqb, dk_acc, dv_acc = lax.cond(
                visible, attend, lambda a: a, (dqb, dk_acc, dv_acc))
        else:
            dqb, dk_acc, dv_acc = attend((dqb, dk_acc, dv_acc))
        qb = lax.ppermute(qb, axis_name, perm)
        dob = lax.ppermute(dob, axis_name, perm)
        lseb = lax.ppermute(lseb, axis_name, perm)
        deltab = lax.ppermute(deltab, axis_name, perm)
        dqb = lax.ppermute(dqb, axis_name, perm)
        return (qb, dob, lseb, deltab, dqb, dk_acc, dv_acc), None

    b, h, lq, d = q.shape
    dq0 = jnp.zeros((b, h, lq, d), jnp.float32)
    dk0 = jnp.zeros_like(dq0)
    dv0 = jnp.zeros_like(dq0)
    mark = getattr(lax, "pcast", None)
    if mark is not None:
        # constants start device-invariant; the scan carries become
        # varying per shard
        dq0 = mark(dq0, (axis_name,), to="varying")
        dk0 = mark(dk0, (axis_name,), to="varying")
        dv0 = mark(dv0, (axis_name,), to="varying")
    (_, _, _, _, dq, dk, dv), _ = lax.scan(
        step, (q, g, lse, delta, dq0, dk0, dv0), jnp.arange(n))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring.defvjp(_ring_fwd, _ring_bwd)


def ring_attention_sharded(q, k, v, axis_name, causal=False, scale=None):
    """Per-shard body: call inside ``shard_map`` over ``axis_name``.

    q/k/v: (B, H, L_local, D) — this device's sequence shard.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if causal and q.shape[2] != k.shape[2]:
        # the per-pair diagonal masks and shard-index visibility tests
        # assume equal q/k shard lengths; unequal-length causal ring
        # (chunked scoring against a longer cache) needs global-position
        # masks — fail loudly rather than attend to the future
        raise ValueError(
            f"causal ring attention requires equal q/k shard lengths, "
            f"got lq={q.shape[2]}, lk={k.shape[2]}")
    return _ring(q, k, v, axis_name, causal, float(scale))


def ring_attention(q, k, v, mesh=None, axis="sp", causal=False, scale=None):
    """Sequence-parallel exact attention over ``mesh[axis]``.

    q/k/v: GLOBAL (B, H, L, D) arrays (sharded or replicated — the
    shard_map in_spec lays them on the axis). Returns (B, H, L, D) with
    the same sequence sharding. Falls back to dense attention when the
    mesh axis has a single device.
    """
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from .mesh import current_mesh

    mesh = mesh or current_mesh()
    if not ring_active(axis, mesh):
        from ..ops.attention import _sdpa_reference

        if scale is None:
            scale = 1.0 / math.sqrt(q.shape[-1])
        return _sdpa_reference(q, k, v, None, scale, causal)
    # ONLY the ring axis is manual (axis_names): batch (dp) and head (tp)
    # shardings stay with GSPMD — making every axis manual would
    # all-gather q/k/v over the other mesh axes and replicate the
    # attention compute per dp/tp shard
    spec = P(None, None, axis, None)
    # check_vma=False: the Pallas per-pair kernels' out_shapes carry no
    # varying-mesh-axes annotation (jax would demand `vma` on every
    # ShapeDtypeStruct inside the manual region otherwise)
    fn = shard_map(
        lambda a, b_, c: ring_attention_sharded(a, b_, c, axis,
                                                causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        axis_names=frozenset({axis}), check_vma=False)
    return fn(q, k, v)


def ring_active(axis, mesh=None):
    """True when ring attention would actually run over ``axis`` (a mesh
    is active and the axis spans more than one device)."""
    from .mesh import current_mesh

    mesh = mesh or current_mesh()
    return (mesh is not None and axis in mesh.axis_names
            and mesh.shape[axis] > 1)
