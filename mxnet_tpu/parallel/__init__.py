"""mxnet_tpu.parallel — mesh, sharding, and fused distributed training.

The TPU-native replacement for the reference's multi-device/multi-node
machinery (SURVEY.md §2.4, §3.5, §5.8): context lists, KVStore comm trees,
NCCL, and ps-lite collapse into ONE ``jax.sharding.Mesh`` with declarative
layouts; XLA inserts the collectives over ICI/DCN.

    from mxnet_tpu import parallel as par
    mesh = par.make_mesh({'dp': 8})
    step = par.TrainStep(net, loss, 'sgd', mesh=mesh)
"""
from .mesh import AXES, make_mesh, current_mesh, use_mesh, local_devices, \
    mesh_axis_size
from .sharding import (PartitionSpec, ShardingRules, named_sharding,
                       replicated, shard_array, shard_parameters,
                       spec_for_param)
from .step import TrainStep
from .checkpoint import save_sharded, restore_sharded
from .elastic import ElasticRunner, HeartbeatBoard, Membership
from .ring_attention import ring_attention, ring_attention_sharded
from .pipeline import (Pipelined, pipeline_apply, pipeline_active,
                       pipeline_sharding_rules, pipeline_train_1f1b)

__all__ = ["ElasticRunner", "HeartbeatBoard", "Membership",
           "save_sharded", "restore_sharded",
           "ring_attention", "ring_attention_sharded",
           "Pipelined", "pipeline_apply", "pipeline_active",
           "pipeline_sharding_rules", "pipeline_train_1f1b",
           "AXES", "make_mesh", "current_mesh", "use_mesh", "local_devices",
           "mesh_axis_size", "PartitionSpec", "ShardingRules",
           "named_sharding", "replicated", "shard_array", "shard_parameters",
           "spec_for_param", "TrainStep"]
