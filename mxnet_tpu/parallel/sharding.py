"""Parameter/activation sharding rules (GSPMD layout plane).

The reference has no tensor parallelism (SURVEY.md §2.4 — TP row: "NO");
its model-parallel story is manual ``group2ctx`` placement. Here layout is
declarative: a list of ``(name_regex, PartitionSpec)`` rules maps parameter
names to mesh axes and GSPMD inserts the collectives. Model zoos ship their
own rule sets (e.g. Megatron-style column/row splits for transformer blocks
— ``mxnet_tpu.gluon.model_zoo.nlp``); anything unmatched is replicated.
"""
from __future__ import annotations

import re
from typing import List, Optional, Sequence, Tuple

from .mesh import mesh_axis_size

__all__ = ["PartitionSpec", "ShardingRules", "named_sharding",
           "spec_for_param", "shard_array", "shard_parameters",
           "replicated"]


def PartitionSpec(*specs):  # noqa: N802 — re-export with lazy import
    from jax.sharding import PartitionSpec as P

    return P(*specs)


def named_sharding(mesh, spec):
    from jax.sharding import NamedSharding

    return NamedSharding(mesh, spec)


def replicated(mesh):
    from jax.sharding import PartitionSpec as P

    return named_sharding(mesh, P())


class ShardingRules:
    """Ordered ``(regex, PartitionSpec)`` rules; first match wins.

        rules = ShardingRules([
            (r".*_attention_qkv_weight$", P("tp", None)),
            (r".*_ffn1_weight$",          P("tp", None)),
            (r".*_ffn2_weight$",          P(None, "tp")),
        ])
    """

    def __init__(self, rules: Optional[Sequence[Tuple[str, object]]] = None):
        self._rules: List[Tuple[re.Pattern, object]] = []
        for pattern, spec in rules or []:
            self.add(pattern, spec)

    def add(self, pattern: str, spec) -> "ShardingRules":
        self._rules.append((re.compile(pattern), spec))
        return self

    def extend(self, other: "ShardingRules") -> "ShardingRules":
        self._rules.extend(other._rules)
        return self

    def match(self, name: str):
        for pat, spec in self._rules:
            if pat.search(name):
                return spec
        return None

    def __len__(self):
        return len(self._rules)


def _axes_of(entry):
    if entry is None:
        return ()
    if isinstance(entry, (tuple, list)):
        return tuple(entry)
    return (entry,)


def spec_for_param(name: str, shape, rules: Optional[ShardingRules], mesh):
    """Resolve a param's PartitionSpec, falling back to replication when no
    rule matches or the dimension doesn't divide the mesh axis (a warning-
    free fallback keeps odd-shaped heads/vocab tails working)."""
    from jax.sharding import PartitionSpec as P

    spec = rules.match(name) if rules is not None else None
    if spec is None:
        return P()
    entries = list(spec) + [None] * (len(shape) - len(spec))
    axis_names = set(getattr(mesh, "axis_names", ()) or ())
    for dim, entry in zip(shape, entries):
        size = 1
        for ax in _axes_of(entry):
            if ax not in axis_names:
                # rule names an axis this mesh doesn't have (e.g. TP rules
                # on a dp-only mesh): fall back to replication
                return P()
            size *= mesh_axis_size(mesh, ax)
        if size > 1 and dim % size:
            return P()
    return P(*entries[: len(shape)])


def shard_array(value, mesh, spec):
    """device_put a jax array with a NamedSharding."""
    import jax

    return jax.device_put(value, named_sharding(mesh, spec))


def shard_parameters(params, mesh, rules: Optional[ShardingRules] = None):
    """Lay out initialized Gluon parameters over ``mesh`` in place.

    ``params`` is a ParameterDict (or dict of Parameter). Returns
    ``{name: PartitionSpec}`` for every parameter — the layout map the
    fused train step reuses for its in/out shardings.
    """
    specs = {}
    values = params.values() if hasattr(params, "values") else params
    for p in values:
        spec = spec_for_param(p.name, p.shape, rules, mesh)
        specs[p.name] = spec
        if p._data is not None:
            for arr in p.list_data():
                arr._set_data(shard_array(arr.data, mesh, spec))
    return specs
