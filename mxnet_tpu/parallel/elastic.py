"""``mx.parallel.elastic`` — elastic multi-host training runtime.

The reference survived worker crashes because ps-lite's tracker restarted
dead nodes and the parameter server kept the authoritative weights
(PAPER.md §2.2). A TPU-native multi-controller job has neither: every
process holds a full replica and a single dead worker hangs every sibling
at its next collective. This module replaces the tracker with something
strictly stronger — supervised, *epoch-versioned* membership with
bit-exact state hand-off:

* **Heartbeat liveness.** Every worker registers under a shared
  coordinator directory (``coord_dir/hb/rank-NNNNN.json``, written once
  with host/pid/incarnation) and a daemon thread touches the file every
  ``MXNET_ELASTIC_HEARTBEAT_INTERVAL`` (0.5 s). A rank whose file goes
  stale past ``MXNET_ELASTIC_HEARTBEAT_TIMEOUT`` (5 s) is dead to its
  siblings — no RPC, no extra service, works for any shared filesystem
  (one host's /tmp for local jobs, NFS/GCS-fuse across hosts). Touches
  run under ``fault.retry_call`` at site ``elastic.heartbeat``.

* **Membership epochs.** On any join/leave, every survivor (1)
  checkpoints through :class:`~mxnet_tpu.checkpoint.CheckpointManager`
  (bundle tagged with the elastic epoch + member set), (2) tears down
  ``jax.distributed`` when the job is truly multi-process, (3)
  re-bootstraps at the new world size (dense ranks over the sorted
  survivor set, coordinator = new rank 0, port advanced by epoch so a
  stale coordinator socket can never be re-joined), (4) restores the
  bundle **bit-exactly** — params, optimizer counters, RNG stream and
  compression residuals all ride the PR-3 bundle format — and continues.
  The transition is committed through the shared ``EPOCH`` record
  (epoch, member set, the survivors' last completed step): a survivor
  that reads a record already committed for the same member set ADOPTS
  its epoch (concurrent survivors can never split across epoch-derived
  ports), and each transition re-bases the kvstore barrier-sequence
  namespace so post-restart barriers still rendezvous. The epoch id is
  threaded into telemetry (``mxnet_elastic_membership_epoch``) and the
  bundle's ``extra`` tag.

* **Graceful degradation.** A rank that stays dead just shrinks the
  membership: survivors train on at the reduced world size, and
  :class:`Membership` gives the deterministic shard re-assignment of the
  data stream (``owns(index)`` / ``shard_indices(n)`` over dense ranks),
  so every sample keeps exactly one owner at every epoch.

* **Preemption as the common case.** Spot/preemptible capacity makes
  leave/join routine, not exceptional: ``install_preemption_handler``
  turns the provider's SIGTERM notice into a *graceful* leave — the
  loop finishes the current step, checkpoints at that boundary,
  unlinks its heartbeat file (siblings see the departure immediately,
  no staleness wait) and raises :class:`Preempted`, whose
  ``exit_code`` (75, ``PREEMPTED_EXIT_CODE``) tells
  ``tools/launch.py`` to respawn it OUTSIDE the ``--max-restarts``
  failure budget with a flat backoff. ``tools/chaos_check.py``'s
  preemption gate drives a scripted preemption schedule through this
  path and asserts the trajectory stays bit-identical to an
  uninterrupted run at sustained throughput.

A restarted worker (``tools/launch.py --max-restarts N`` respawns it
with the same ``DMLC_WORKER_ID``) finds the newest valid bundle for its
rank at :meth:`ElasticRunner.start` and resumes from it — kill a worker
mid-step, rejoin, and the final loss is bit-identical to an
uninterrupted run (``tools/chaos_check.py`` elastic gate). A rejoiner
in real distributed mode additionally reconciles to the survivors'
committed step from the join record (``adopted_step``): the survivors
trained on during the outage (or committed a step behind the victim's
last save), and resuming from its own newest bundle would give it a
different remaining step count — the mismatched steps wedge at a
collective — and stale weights in every allreduce. It restores the
bundle AT the committed step instead: its own when one exists, else a
survivor's (survivors checkpoint at exactly that step before
publishing the commit, and ``dist_sync`` data-parallel state is
replicated across ranks).

::

    runner = elastic.ElasticRunner(coord_dir, params=net, trainer=trainer,
                                   save_every=50)
    losses = runner.run(lambda step, m: train_one_step(step, m), 10_000)

``step_fn(step, membership)`` is the user's training step; shard the
data stream with ``membership.owns(sample_index)`` and the re-assignment
on membership change is automatic.
"""
from __future__ import annotations

import json
import logging
import os
import signal as _signal
import socket
import threading
import time
import warnings
import weakref
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from .. import fault, telemetry
from ..base import MXNetError
from ..checkpoint import CheckpointManager, atomic_write
from ..fault import _state as _fault_state

__all__ = ["ElasticRunner", "HeartbeatBoard", "Membership",
           "Preempted", "PREEMPTED_EXIT_CODE", "live_runners"]

# EX_TEMPFAIL: "capacity reclaimed, respawn me" — tools/launch.py treats
# workers exiting with this code as preempted (restarted outside the
# --max-restarts failure budget, flat backoff)
PREEMPTED_EXIT_CODE = 75


class Preempted(MXNetError):
    """Raised by :meth:`ElasticRunner.run` after a graceful preemption
    leave: the state is checkpointed at ``step`` (the last completed
    step), the heartbeat is retired, and the process should exit with
    :attr:`exit_code` (``PREEMPTED_EXIT_CODE``) so the supervisor
    respawns it as a preemption, not a failure."""

    def __init__(self, msg: str, step: int):
        super().__init__(msg)
        self.step = int(step)
        self.exit_code = PREEMPTED_EXIT_CODE

_HB_DIR = "hb"
_EPOCH_FILE = "EPOCH"
_THREAD_PREFIX = "mxnet-elastic-heartbeat"

# Runners whose heartbeat thread is (or may be) running — the test-suite
# leak guard sweeps this (same pattern as serving.live_servers()).
_RUNNERS: "weakref.WeakSet[ElasticRunner]" = weakref.WeakSet()


def live_runners() -> List["ElasticRunner"]:
    """Runners with a running heartbeat thread (leak-guard hook)."""
    return [r for r in list(_RUNNERS) if r.heartbeat_running()]


def _sync_barrier_epoch(epoch: int) -> None:
    """Re-base kvstore cross-process barrier sequence numbering to this
    membership epoch (every survivor does this at the transition, a
    restarted rank at start), so barriers after a restart rendezvous
    under the same epoch-tagged keys instead of survivors waiting at
    seq k+1 against the rejoiner's seq 1 forever."""
    try:
        from ..kvstore.kvstore import reset_barrier_epoch
    except ImportError:   # kvstore unavailable: nothing to re-base
        return
    reset_barrier_epoch(epoch)


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw else default
    except ValueError as e:
        raise MXNetError(f"{name}={raw!r} is not a number") from e


@dataclass(frozen=True)
class Membership:
    """One epoch of cluster membership.

    ``members`` are *launch* ranks (the ``DMLC_WORKER_ID`` a worker was
    started with — stable across restarts); ``rank``/``world_size`` are
    the dense re-assignment over the sorted survivor set, which is what
    collectives and data sharding use. Dense ranks are a pure function
    of the member set, so every survivor computes the same assignment
    without any extra coordination round.
    """

    epoch: int
    rank: int                 # dense rank within this membership
    world_size: int
    members: Tuple[int, ...]  # sorted launch ranks alive this epoch
    launch_rank: int          # this worker's launch rank

    def owns(self, index: int) -> bool:
        """Deterministic shard assignment: does this worker own sample
        ``index`` of the (infinite) data stream at this epoch?"""
        return int(index) % self.world_size == self.rank

    def shard_indices(self, n: int) -> range:
        """This worker's slice of ``range(n)`` (round-robin by dense
        rank — the re-assignment every survivor agrees on)."""
        return range(self.rank, int(n), self.world_size)


class HeartbeatBoard:
    """The per-rank heartbeat files under ``coord_dir/hb/``.

    Registration writes ``rank-NNNNN.json`` once (atomic:
    host/pid/incarnation/started); liveness afterwards is ONE ``utime``
    touch per interval and ONE ``listdir`` + ``stat`` sweep per check —
    no payload re-reads on the hot path. Staleness is wall-clock mtime
    age, so it works across processes and (with a shared mount and sane
    clock skew vs. the multi-second timeout) across hosts.
    """

    def __init__(self, coord_dir: str):
        self.coord_dir = os.fspath(coord_dir)
        self.hb_dir = os.path.join(self.coord_dir, _HB_DIR)
        os.makedirs(self.hb_dir, exist_ok=True)

    def path(self, rank: int) -> str:
        return os.path.join(self.hb_dir, f"rank-{int(rank):05d}.json")

    def register(self, rank: int, extra: Optional[Dict] = None) -> str:
        info = {"rank": int(rank), "host": socket.gethostname(),
                "pid": os.getpid(), "started_unix": time.time(),
                "incarnation": f"{os.getpid()}-{time.time_ns()}"}
        if extra:
            info.update(extra)
        p = self.path(rank)
        atomic_write(p, json.dumps(info).encode("utf-8"))
        return p

    def touch(self, rank: int) -> None:
        os.utime(self.path(rank), None)

    def read(self, rank: int) -> Dict:
        try:
            with open(self.path(rank), "rb") as f:
                info = json.loads(f.read().decode("utf-8"))
            return info if isinstance(info, dict) else {}
        except (OSError, ValueError, UnicodeDecodeError):
            return {}

    def mtimes(self) -> Dict[int, float]:
        """rank -> heartbeat mtime for every registered rank."""
        out: Dict[int, float] = {}
        try:
            entries = os.listdir(self.hb_dir)
        except OSError:
            return out
        for e in entries:
            if not (e.startswith("rank-") and e.endswith(".json")):
                continue
            try:
                out[int(e[len("rank-"):-len(".json")])] = \
                    os.path.getmtime(os.path.join(self.hb_dir, e))
            except (ValueError, OSError):
                continue
        return out

    def alive(self, timeout: float, now: Optional[float] = None) -> List[int]:
        """Ranks whose heartbeat is fresher than ``timeout`` seconds."""
        now = time.time() if now is None else now
        return sorted(r for r, m in self.mtimes().items()
                      if now - m <= timeout)

    def remove(self, rank: int) -> None:
        """Retire a rank's heartbeat file — the FAST leave signal: a
        gracefully-leaving rank (preemption) unlinks its file so the
        siblings see the departure on their next membership check
        instead of waiting out the staleness timeout."""
        try:
            os.unlink(self.path(rank))
        except OSError:
            pass


class ElasticRunner:
    """Supervised elastic training loop (see module docstring).

    ``params``/``trainer`` are the Block and Gluon Trainer whose state
    the epoch protocol checkpoints and restores (either may be None for
    a state-free loop). One :class:`CheckpointManager` per launch rank
    (prefix ``r{rank}``) lives under ``coord_dir/ckpts`` by default, so
    all ranks of a local job share one directory without colliding.

    Injection hooks ``bootstrap_fn(membership)`` / ``shutdown_fn()``
    replace the real ``jax.distributed`` teardown/re-init in tests
    (single process, faked sibling ranks).

    ``distributed`` contract: ``None`` (auto) participates in epoch
    teardown/re-bootstrap only when ``jax.distributed`` is ALREADY
    initialized in this process — right for first-incarnation workers
    that bootstrapped via ``create('dist_sync')``, and for
    single-process / collective-free jobs (the chaos gate). A
    **restarted** rank of a real multi-process job must pass
    ``distributed=True`` and let the runner own the bootstrap: the
    original coordinator port is dead, so it must NOT call
    ``create('dist_sync')`` first — the runner instead waits for the
    survivors' join commit and rendezvouses at the epoch-derived port
    (see ``_await_join_commit``).
    """

    def __init__(self, coord_dir: str, *, params=None, trainer=None,
                 rank: Optional[int] = None,
                 world_size: Optional[int] = None,
                 ckpt_dir: Optional[str] = None,
                 ckpt_mgr: Optional[CheckpointManager] = None,
                 keep_last: int = 3, save_every: int = 0,
                 heartbeat_interval: Optional[float] = None,
                 heartbeat_timeout: Optional[float] = None,
                 join_timeout: Optional[float] = None,
                 on_epoch: Optional[Callable] = None,
                 distributed: Optional[bool] = None,
                 bootstrap_fn: Optional[Callable] = None,
                 shutdown_fn: Optional[Callable] = None,
                 warm_start: Optional[Callable] = None):
        self.coord_dir = os.fspath(coord_dir)
        self.board = HeartbeatBoard(self.coord_dir)
        self.launch_rank = int(os.environ.get("DMLC_WORKER_ID", "0")) \
            if rank is None else int(rank)
        self.launch_world = int(os.environ.get("DMLC_NUM_WORKER", "1")) \
            if world_size is None else int(world_size)
        if self.launch_world < 1:
            raise MXNetError(
                f"elastic world_size must be >= 1, got {self.launch_world}")
        if not 0 <= self.launch_rank < self.launch_world:
            raise MXNetError(
                f"elastic rank {self.launch_rank} outside world of "
                f"{self.launch_world}")
        self.params = params
        self.trainer = trainer
        if ckpt_mgr is not None:
            self.ckpt = ckpt_mgr
        else:
            self.ckpt = CheckpointManager(
                ckpt_dir or os.path.join(self.coord_dir, "ckpts"),
                prefix=f"r{self.launch_rank}", keep_last=keep_last)
        self.save_every = int(save_every)
        self.heartbeat_interval = _env_float(
            "MXNET_ELASTIC_HEARTBEAT_INTERVAL", 0.5) \
            if heartbeat_interval is None else float(heartbeat_interval)
        self.heartbeat_timeout = _env_float(
            "MXNET_ELASTIC_HEARTBEAT_TIMEOUT", 5.0) \
            if heartbeat_timeout is None else float(heartbeat_timeout)
        if self.heartbeat_timeout <= 0 or self.heartbeat_interval <= 0:
            raise MXNetError(
                "elastic heartbeat interval/timeout must be > 0")
        self.join_timeout = _env_float("MXNET_ELASTIC_JOIN_TIMEOUT", 60.0) \
            if join_timeout is None else float(join_timeout)
        self.on_epoch = on_epoch
        self._distributed = distributed
        self._bootstrap_fn = bootstrap_fn
        self._shutdown_fn = shutdown_fn
        # compilation-service hook: called with the new Membership after
        # every (re-)bootstrap — start() AND each epoch transition — so a
        # rejoiner/survivor replays its signature manifest
        # (``compiler.warm_start(manifest, train_steps=[step])``) and
        # re-enters training hot instead of paying a full retrace at
        # every membership epoch
        self._warm_start_fn = warm_start
        self.membership: Optional[Membership] = None
        self.transitions: List[Dict] = []
        self.start_step = 0
        self.resumed_from: Optional[int] = None
        # set when a distributed rejoin skipped ahead to the survivors'
        # committed step (the survivors trained on during our outage)
        self.adopted_step: Optional[int] = None
        self._started = False
        self._last_completed = -1
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        self._preempt = threading.Event()
        self._preempt_reason = ""
        self._old_handlers: Dict[int, object] = {}
        self._preempt_signal_spec: tuple = ()   # re-armed by start()
        _RUNNERS.add(self)

    # -- heartbeats ----------------------------------------------------
    def heartbeat_running(self) -> bool:
        t = self._hb_thread
        return t is not None and t.is_alive()

    def _touch(self) -> None:
        if _fault_state.enabled:
            fault.check("elastic.heartbeat",
                        f"rank {self.launch_rank}")
        self.board.touch(self.launch_rank)

    def heartbeat(self) -> None:
        """One liveness touch (bounded retry at ``elastic.heartbeat`` —
        a transient shared-FS hiccup must not make this rank look dead).
        The daemon thread calls this on every interval; call it manually
        from inside very long steps if the step time can exceed the
        sibling timeout."""
        fault.retry_call("elastic.heartbeat", self._touch,
                         detail=f"rank {self.launch_rank}")

    def _hb_loop(self) -> None:
        while not self._hb_stop.wait(self.heartbeat_interval):
            try:
                self.heartbeat()
            except Exception:
                # a persistently failing touch makes US look dead;
                # the siblings' epoch protocol is the recovery path —
                # killing the training thread from here would be worse
                continue

    # -- membership ----------------------------------------------------
    def _alive_now(self) -> List[int]:
        alive = set(self.board.alive(self.heartbeat_timeout))
        alive.add(self.launch_rank)     # we are running this very line
        return sorted(alive)

    def _epoch_file(self) -> str:
        return os.path.join(self.coord_dir, _EPOCH_FILE)

    def _read_epoch_record(
            self) -> Tuple[int, Optional[Tuple[int, ...]], Optional[int]]:
        """The shared ``(epoch, members, step)`` commit record (members
        and step None for a legacy bare-int or pre-step file). ``step``
        is the committing survivors' last completed step — the rejoin
        reconciliation point."""
        try:
            with open(self._epoch_file(), "rb") as f:
                raw = f.read().decode("utf-8").strip()
        except OSError:
            return 0, None, None
        try:
            rec = json.loads(raw or "0")
        except ValueError:
            return 0, None, None
        if isinstance(rec, dict):
            try:
                members = rec.get("members")
                step = rec.get("step")
                return (int(rec.get("epoch", 0)),
                        tuple(int(r) for r in members)
                        if members is not None else None,
                        int(step) if step is not None else None)
            except (TypeError, ValueError):
                return 0, None, None
        try:
            return int(rec), None, None
        except (TypeError, ValueError):
            return 0, None, None

    def _read_epoch(self) -> int:
        return self._read_epoch_record()[0]

    def _publish_epoch(self, epoch: int,
                       members: Optional[Tuple[int, ...]] = None,
                       step: Optional[int] = None) -> None:
        # best-effort monotonic max across ranks: the record is advisory
        # for epoch numbering (late joiners adopt it) — but it is ALSO
        # the rejoin-handshake signal (a joiner waits for a committed
        # membership that includes it), so it carries the member set and
        # the survivors' committed step (the rejoiner's skip-ahead point)
        if epoch > self._read_epoch():
            rec = {"epoch": int(epoch), "members": list(members or ())}
            if step is not None and step >= 0:   # -1: nothing completed
                rec["step"] = int(step)
            atomic_write(self._epoch_file(),
                         json.dumps(rec).encode("utf-8"))

    def _make_membership(self, epoch: int, members: List[int]) -> Membership:
        members = sorted(members)
        if self.launch_rank not in members:
            members = sorted(members + [self.launch_rank])
        return Membership(epoch=epoch,
                          rank=members.index(self.launch_rank),
                          world_size=len(members),
                          members=tuple(members),
                          launch_rank=self.launch_rank)

    # -- lifecycle -----------------------------------------------------
    def start(self) -> Membership:
        """Register, start the heartbeat thread, wait for the initial
        world (bounded by ``join_timeout`` — whoever registered by then
        forms epoch 0's membership), and resume from this rank's newest
        valid bundle when one exists (the rejoin path)."""
        if self._started:
            return self.membership
        if self._preempt_signal_spec and not self._old_handlers:
            # a previous run()'s stop() restored the OS handlers; the
            # user's one-time install_preemption_handler() stays in
            # force across this runner's phases
            try:
                self.install_preemption_handler(
                    self._preempt_signal_spec)
            except ValueError:
                pass    # not the main thread: run unprotected
        self.board.register(self.launch_rank)
        self.board.touch(self.launch_rank)
        self._hb_stop = threading.Event()
        self._hb_thread = threading.Thread(
            target=self._hb_loop,
            name=f"{_THREAD_PREFIX}-r{self.launch_rank}", daemon=True)
        self._hb_thread.start()
        deadline = time.monotonic() + self.join_timeout
        alive = self._alive_now()
        while (len(alive) < self.launch_world
               and time.monotonic() < deadline):
            time.sleep(min(0.05, self.heartbeat_interval))
            alive = self._alive_now()
        epoch = self._read_epoch()
        self.start_step = 0
        step = self.ckpt.latest_step()
        if step is not None:
            meta = self._restore()
            # a sharded restore may have picked a DIFFERENT step than
            # our own newest bundle (the newest step whose full shard
            # set is still on disk — possibly a surviving peer's newer
            # one); the schedule must follow what was actually restored
            step = int(meta.get("step", step))
            self.start_step = step + 1
            self.resumed_from = step
            tag = (meta.get("extra") or {}).get("elastic") or {}
            bundle_epoch = int(tag.get("epoch", 0))
            epoch = max(epoch, bundle_epoch)
            telemetry.record_elastic_restart()
            if self._is_distributed():
                # rejoin handshake: the survivors commit our join as a
                # transition (publishing the epoch record BEFORE their
                # blocking re-bootstrap — see _transition), and we must
                # enter the SAME rendezvous: wait for a committed
                # membership that names us, then bootstrap at exactly
                # the COMMITTED epoch AND member set — our own alive
                # snapshot is stale by now (another rank may have died
                # while we restarted), and a world-size disagreement
                # would wedge the rendezvous on both sides
                epoch, committed, committed_step = \
                    self._await_join_commit(bundle_epoch, epoch)
                if committed is not None:
                    alive = list(committed)
                    if committed_step is not None \
                            and committed_step != self.start_step - 1:
                        self._reconcile_to(committed_step, committed)
        self.membership = self._make_membership(epoch, alive)
        self._adopt_partition(self.membership)
        self._last_completed = self.start_step - 1
        self._publish_epoch(epoch, self.membership.members,
                            self._last_completed)
        telemetry.set_elastic_epoch(epoch)
        _sync_barrier_epoch(epoch)
        if (step is not None and self._is_distributed()
                and self.membership.world_size > 1):
            (self._bootstrap_fn or self._default_bootstrap)(self.membership)
        self._run_warm_start(self.membership)
        self._started = True
        return self.membership

    def _run_warm_start(self, membership: Membership) -> None:
        """Replay compile signatures after a (re-)bootstrap so the next
        step is a cache hit. Best-effort: a warm failure costs a retrace
        on the first step, never the membership transition."""
        if self._warm_start_fn is None:
            return
        t0 = time.perf_counter()
        try:
            self._warm_start_fn(membership)
        except Exception:
            logging.getLogger(__name__).exception(
                "elastic warm_start hook failed; first step will retrace")
            return
        from .. import compiler

        compiler.mark_event("elastic_warm_done")
        telemetry.record_elastic_warm(time.perf_counter() - t0)

    def _await_join_commit(
            self, bundle_epoch: int, epoch: int
    ) -> Tuple[int, Optional[Tuple[int, ...]], Optional[int]]:
        """Wait (bounded by ``join_timeout``) for the survivors to
        commit a membership that INCLUDES this rank at an epoch past
        the bundle we resumed from — their signal that they are in (or
        about to enter) the re-bootstrap rendezvous for our join. A
        plain epoch advance is not enough: the leave transition that
        recorded our death also advanced it. Returns the committed
        ``(epoch, members, step)`` — the rejoiner must adopt ALL of
        them, not its own alive snapshot / bundle step (the survivors
        trained on during the outage). Times out to
        ``(best known epoch, None, None)`` (all survivors gone:
        continue solo, degraded)."""
        deadline = time.monotonic() + self.join_timeout
        while time.monotonic() < deadline:
            cur, members, step = self._read_epoch_record()
            if cur > bundle_epoch and members is not None \
                    and self.launch_rank in members:
                return max(cur, epoch), members, step
            time.sleep(min(0.05, self.heartbeat_interval))
        return epoch, None, None

    def _reconcile_to(self, step: int,
                      members: Tuple[int, ...]) -> None:
        """Align this rejoiner to the survivors' committed ``step`` —
        resuming at our own bundle's step would give us a DIFFERENT
        remaining step count than our peers (our extra or missing steps
        wedge at a collective once the schedules drift apart), and
        adopting the step count alone would pair our stale weights with
        their step-``step`` weights in every allreduce. The survivors
        checkpoint at exactly this step BEFORE publishing the join
        commit (see ``_transition``), so under the shared checkpoint
        layout a bundle at ``step`` exists by the time we read the
        record: prefer our OWN (pure bit-exact replay — the
        survivors-behind-us case), else restore a survivor's
        (``dist_sync`` data-parallel state — params, optimizer
        counters, and a seed-replicated RNG stream — is replicated
        across ranks, so its bundle is our state at that step; per-rank
        compression residuals ride along as the closest available
        approximation, and are stale at a membership change either
        way). When neither is reachable (custom ``ckpt_mgr`` layout),
        the step count is still adopted so the schedules align."""
        restored_from = None
        if self.ckpt.is_valid(step):
            self._restore(step=step)
            restored_from = self.launch_rank
        else:
            for r in members:
                if r == self.launch_rank:
                    continue
                mgr = CheckpointManager(self.ckpt.directory,
                                        prefix=f"r{int(r)}",
                                        keep_last=self.ckpt.keep_last)
                if mgr.is_valid(step):
                    self._restore(mgr, step=step)
                    restored_from = int(r)
                    break
        if restored_from is not None:
            self.resumed_from = step
        else:
            warnings.warn(
                f"elastic rejoin: no bundle at the survivors' committed "
                f"step {step} reachable under {self.ckpt.directory!r} "
                f"(members {tuple(members)}); adopting the step count "
                f"with state from step {self.resumed_from} — expect "
                "numeric divergence until the next full checkpoint",
                RuntimeWarning, stacklevel=3)
        self.adopted_step = step
        self.start_step = step + 1

    # -- preemption (graceful leave: spot/preemptible capacity) --------
    def request_preemption(self, reason: str = "requested") -> None:
        """Flag this worker for a graceful leave: the supervised loop
        finishes the CURRENT step, checkpoints at that boundary,
        retires its heartbeat (fast leave — the file is unlinked, not
        left to go stale), and raises :class:`Preempted`. Safe from any
        thread and from signal handlers (a bare ``Event.set``)."""
        self._preempt_reason = reason
        self._preempt.set()

    @property
    def preemption_requested(self) -> bool:
        return self._preempt.is_set()

    def install_preemption_handler(self, signals=(_signal.SIGTERM,)
                                   ) -> "ElasticRunner":
        """Route OS preemption notice (cloud spot reclaim is a SIGTERM
        with a grace window) into :meth:`request_preemption`. Previous
        handlers are restored by :meth:`stop` — and because ``run()``
        stops the runner on the way out, the installation is
        remembered and **re-armed by the next** :meth:`start`/``run()``
        of this runner, so multi-phase training stays covered between
        phases it drives itself. Main thread only (a CPython
        signal-module constraint)."""
        self._preempt_signal_spec = tuple(signals)
        for sig in signals:
            old = _signal.signal(
                sig, lambda signum, frame:
                self.request_preemption(
                    f"signal {_signal.Signals(signum).name}"))
            self._old_handlers.setdefault(int(sig), old)
        return self

    def _restore_signal_handlers(self) -> None:
        handlers, self._old_handlers = self._old_handlers, {}
        for sig, old in handlers.items():
            try:
                _signal.signal(sig, old)
            except (ValueError, TypeError, OSError):
                pass

    def _graceful_leave(self) -> None:
        """The preemption protocol: checkpoint at the completed-step
        boundary (this bundle is what the respawned incarnation — or a
        surviving peer adopting our shard — resumes from), stop the
        heartbeat thread, and unlink the heartbeat file so the
        siblings' membership check sees the leave NOW instead of after
        the staleness timeout."""
        if self._last_completed >= 0:
            self._save(self._last_completed)
        telemetry.record_elastic_preemption()
        self.stop()
        self.board.remove(self.launch_rank)
        logging.getLogger(__name__).info(
            "rank %d preempted (%s): checkpointed step %d, left",
            self.launch_rank, self._preempt_reason,
            self._last_completed)

    def stop(self) -> None:
        """Stop the heartbeat thread (idempotent) and restore any
        preemption signal handlers. The heartbeat file is left to go
        stale — that IS the leave signal to the siblings (a graceful
        preemption leave additionally unlinks it — see
        :meth:`_graceful_leave`)."""
        self._hb_stop.set()
        t = self._hb_thread
        if t is not None and t.is_alive():
            t.join(timeout=max(1.0, 4 * self.heartbeat_interval))
        self._hb_thread = None
        self._started = False
        self._restore_signal_handlers()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- checkpoint round-trip -----------------------------------------
    def _save(self, step: int, membership: Optional[Membership] = None):
        m = membership or self.membership
        tag = {"epoch": m.epoch if m else 0,
               "members": list(m.members) if m else [self.launch_rank],
               "launch_rank": self.launch_rank}
        return self.ckpt.save(step, params=self.params,
                              trainer=self.trainer,
                              extra={"elastic": tag})

    def _restore(self, mgr: Optional[CheckpointManager] = None,
                 step: Optional[int] = None) -> Dict:
        """Bit-exact restore from the newest valid bundle (or ``step``,
        or another rank's manager ``mgr`` — the join reconciliation),
        bounded retry at ``elastic.rejoin`` (restore is an idempotent
        overwrite).

        Under a ZeRO-partitioned trainer each rank's bundle carries only
        its OWN optimizer-state shard, so params + RNG come from ``mgr``
        but the sharded state is gathered from EVERY rank bundle at the
        same step and re-sharded into the current partition identity
        (``Trainer.load_states_resharded``) — this is what makes rejoin
        at a *different* world size restore bit-exact."""
        mgr = self.ckpt if mgr is None else mgr
        tr = self.trainer
        sharded = self._is_sharded()

        def _do():
            if _fault_state.enabled:
                fault.check("elastic.rejoin",
                            f"rank {self.launch_rank}")
            if not sharded:
                return mgr.restore(block=self.params,
                                   trainer=self.trainer, step=step)
            pick, pick_mgr = step, mgr
            if step is None:
                # resume-newest under a sharded layout: "newest" is the
                # newest step whose FULL source-world shard set is still
                # on disk — our own newest bundle's peer shards may be
                # gone (a peer died before saving that step, or a
                # surviving peer's keep_last GC advanced past it while
                # we restarted). Params and the RNG stream are
                # replicated under dist_sync, so ANY bundle of the
                # complete group can anchor the restore; skipping ahead
                # to a surviving peer's newer complete step is the same
                # adopt-the-survivors'-schedule semantics as
                # _reconcile_to, not divergence.
                for s in self._sharded_steps():
                    files, anchor, complete = self._sharded_coverage(s)
                    if complete:
                        pick = s
                        if anchor != f"r{self.launch_rank}":
                            pick_mgr = CheckpointManager(
                                self.ckpt.directory, prefix=anchor,
                                keep_last=self.ckpt.keep_last)
                        break
            meta = pick_mgr.restore(block=self.params, trainer=None,
                                    step=pick)
            if tr is not None:
                files = self._sharded_state_files(meta["step"])
                if not files:
                    # a pre-partition bundle (or foreign layout): fall
                    # back to the strict single-file path so the typed
                    # mismatch error names the problem
                    tr.load_states(pick_mgr.states_path(meta["step"]))
                else:
                    tr.load_states_resharded(files)
            return meta

        return fault.retry_call("elastic.rejoin", _do,
                                detail=f"rank {self.launch_rank}")

    def _sharded_steps(self) -> List[int]:
        """Union of bundle steps across every rank prefix under the
        shared checkpoint directory, newest first — the candidate resume
        points of a sharded restore (a peer's bundle can be newer than
        any of ours)."""
        import re as _re

        pat = _re.compile(r"^r\d+-(\d{8})$")
        try:
            entries = os.listdir(self.ckpt.directory)
        except OSError:
            entries = []
        steps = {int(m.group(1)) for e in entries
                 for m in (pat.match(e),) if m}
        return sorted(steps, reverse=True)

    def _sharded_coverage(
            self, step: int) -> Tuple[List[str], Optional[str], bool]:
        """The rank bundles' ``trainer.states`` shards at ``step`` plus
        whether they form a COMPLETE set: a group whose ``zero.json``
        manifests agree on one source world W and together cover ranks
        0..W-1. A step can mix plans — a transition re-carves the
        boundary bundle under the NEW world while dead peers' old-plan
        bundles sit beside it — so completeness is judged per plan, not
        per directory listing. Returns ``(files, anchor, complete)``:
        when complete, ``files`` is exactly the covering group (rank
        order) and ``anchor`` a member prefix (our own when present) fit
        to anchor the params/RNG restore; otherwise every valid bundle's
        path and ``None``."""
        import re as _re

        pat = _re.compile(r"^(r\d+)-%08d$" % int(step))
        try:
            entries = os.listdir(self.ckpt.directory)
        except OSError:
            entries = []
        by_world: Dict[int, Dict[int, Tuple[str, str]]] = {}
        loose: List[Tuple[str, str]] = []
        for e in sorted(entries):
            m = pat.match(e)
            if not m:
                continue
            mgr = CheckpointManager(self.ckpt.directory,
                                    prefix=m.group(1),
                                    keep_last=self.ckpt.keep_last)
            if not mgr.is_valid(step):
                continue
            man = mgr.partition_manifest(step)
            item = (m.group(1), mgr.states_path(step))
            try:
                w, r = int(man["world"]), int(man["rank"])
            except (TypeError, KeyError, ValueError):
                loose.append(item)
                continue
            by_world.setdefault(w, {})[r] = item
        complete = [w for w, shards in by_world.items()
                    if set(shards) >= set(range(w))]
        if complete:
            # two complete groups at one step is contrived (requires
            # disjoint prefix sets each covering a full world); prefer
            # the smaller world — the plan a shrink transition just
            # carved, whose full set survives the death by construction
            w = min(complete)
            group = [by_world[w][r] for r in range(w)]
            prefixes = {p for p, _ in group}
            own = f"r{self.launch_rank}"
            anchor = own if own in prefixes else group[0][0]
            return [path for _, path in group], anchor, True
        files = [path for _, path in loose]
        for shards in by_world.values():
            files.extend(path for _, path in shards.values())
        return sorted(files), None, False

    def _sharded_state_files(self, step: int) -> List[str]:
        """Every rank bundle's ``trainer.states`` shard at ``step``
        under the shared checkpoint directory (the ``r<launch_rank>``
        prefix layout every worker of the job uses) — the complete
        covering group when one exists."""
        return self._sharded_coverage(step)[0]

    def _is_sharded(self) -> bool:
        """True when the trainer carves per-rank ZeRO state shards into
        its checkpoints (``partition=`` mode)."""
        tr = self.trainer
        return tr is not None \
            and getattr(tr, "_partition", None) is not None

    def _adopt_partition(self, m: Membership) -> None:
        """Bind a ZeRO-partitioned trainer to this membership's (rank,
        world) so its next checkpoint carves shards under the NEW plan.
        No-op for replicated trainers."""
        tr = self.trainer
        if tr is None or getattr(tr, "_partition", None) is None:
            return
        if not tr._kv_initialized:
            tr._init_kvstore()
        if tr._zero is not None:
            tr._zero.reconfigure(m.rank, m.world_size)

    # -- the epoch protocol --------------------------------------------
    def check_membership(self) -> Membership:
        """Compare the heartbeat board against the current membership;
        on any join/leave run one epoch transition (checkpoint →
        teardown → re-bootstrap → bit-exact restore). Called by
        :meth:`run` between steps; call it yourself in a hand-rolled
        loop."""
        if not self._started:
            raise MXNetError("ElasticRunner.start() before "
                             "check_membership()")
        alive = self._alive_now()
        current = set(self.membership.members)
        if set(alive) == current:
            return self.membership
        left = sorted(current - set(alive))
        joined = sorted(set(alive) - current)
        for r in left:
            telemetry.record_elastic_heartbeat_miss(r)
        return self._transition(alive, left, joined)

    def _is_distributed(self) -> bool:
        if self._distributed is not None:
            return bool(self._distributed)
        try:
            from ..kvstore.kvstore import dist_initialized

            return dist_initialized()
        except Exception:
            return False

    def _default_shutdown(self) -> None:
        import jax

        jax.distributed.shutdown()

    def _default_bootstrap(self, m: Membership) -> None:
        # coordinator = the new rank 0's host; the port advances with
        # the epoch so a survivor can never rendezvous with a stale
        # coordinator socket from a previous epoch. The timeout is the
        # SAME mapping as the first bootstrap (_maybe_init_distributed):
        # <= 0 is the documented unbounded opt-out, not a 1 s fuse
        host = self.board.read(m.members[0]).get("host") or "127.0.0.1"
        base = int(os.environ.get("DMLC_PS_ROOT_PORT", "9091"))
        from ..kvstore.kvstore import _bootstrap_timeout_s
        import jax

        jax.distributed.initialize(
            coordinator_address=f"{host}:{base + 1 + m.epoch}",
            num_processes=m.world_size, process_id=m.rank,
            initialization_timeout=_bootstrap_timeout_s())

    def _transition(self, alive: List[int], left: List[int],
                    joined: List[int]) -> Membership:
        old = self.membership
        new_members = tuple(sorted(set(alive)))  # _alive_now includes us
        rec_epoch, rec_members, _rec_step = self._read_epoch_record()
        if rec_epoch > old.epoch and rec_members == new_members:
            # another survivor already committed THIS transition (same
            # member set, newer epoch): adopt its epoch. Incrementing
            # here would split the survivors across epochs — the first
            # to transition at E+1, everyone who read its record at
            # E+2 — and epoch-derived coordinator ports would wedge
            # both rendezvous. The record is the transition's commit,
            # not just advisory numbering.
            epoch = rec_epoch
        else:
            epoch = max(old.epoch, rec_epoch) + 1
        new = self._make_membership(epoch, list(new_members))
        # 1) adopt the new partition identity BEFORE the boundary
        # checkpoint: the bundle must be carved under the NEW plan so
        # the survivors' shard set is complete by construction — under
        # the OLD plan a freshly-dead rank's shard of this step exists
        # NOWHERE on disk (it died before saving it), and any later
        # restore gathering at this step would fail. Safe to do early: a
        # virtual partition holds the full state locally, so the carve
        # is a serialization identity, not a data movement. No-op for
        # replicated trainers.
        self._adopt_partition(new)
        # 2) survivors checkpoint BEFORE touching the collective runtime
        # (a crash inside the re-bootstrap must lose at most this step)
        if self._last_completed >= 0:
            self._save(self._last_completed, new)
        # 3) publish the commit record BEFORE the blocking re-bootstrap:
        # a rejoining rank waits on it (_await_join_commit) to enter the
        # same rendezvous — publishing after would deadlock the join;
        # it carries our committed step so the rejoiner can skip ahead
        # to the survivors' schedule
        self._publish_epoch(epoch, new.members, self._last_completed)
        # 4) tear down the old world's collective runtime
        distributed = self._is_distributed()
        if distributed:
            (self._shutdown_fn or self._default_shutdown)()
        # 5) re-bootstrap at the new world size
        if distributed:
            (self._bootstrap_fn or self._default_bootstrap)(new)
        # 6) restore bit-exact. Replicated trainers keep the idempotent
        # overwrite (every survivor provably resumes from the committed
        # bytes). A ZeRO-partitioned trainer SKIPS it: its full state is
        # authoritative in memory and was just carved to disk under the
        # new plan in step 2 — and a gather here would race peer
        # survivors that have not finished their own boundary save yet
        if self._last_completed >= 0 and not self._is_sharded():
            self._restore()
        # 7) warm the compile caches for the new world BEFORE the next
        # step dispatches — PR 8's teardown + re-bootstrap made every
        # membership epoch pay a cold retrace; the manifest replay turns
        # that into executable-table / disk-cache hits
        self._run_warm_start(new)
        self.membership = new
        telemetry.set_elastic_epoch(epoch)
        _sync_barrier_epoch(epoch)
        telemetry.record_elastic_restart(len(joined))
        rec = {"epoch": epoch, "left": left, "joined": joined,
               "world_size": new.world_size,
               "step": self._last_completed}
        self.transitions.append(rec)
        if self.on_epoch is not None:
            self.on_epoch(new, rec)
        return new

    # -- the supervised loop -------------------------------------------
    def run(self, step_fn: Callable, num_steps: int) -> List:
        """Run ``step_fn(step, membership)`` for steps
        ``[start_step, num_steps)`` under supervision: heartbeat thread
        alive throughout, membership checked between steps (join/leave
        triggers the epoch protocol), a bundle saved every
        ``save_every`` completed steps (0 = only at epoch transitions).
        Returns the list of ``step_fn`` results for the steps THIS
        incarnation ran (a resumed worker returns the tail)."""
        self.start()
        results = []
        try:
            for step in range(self.start_step, int(num_steps)):
                if self._preempt.is_set():
                    # graceful leave at the step BOUNDARY: the current
                    # step's work is committed, the next one never
                    # starts half-done
                    self._graceful_leave()
                    raise Preempted(
                        f"rank {self.launch_rank} preempted "
                        f"({self._preempt_reason}) after step "
                        f"{self._last_completed}; state checkpointed — "
                        f"exit with code {PREEMPTED_EXIT_CODE} for a "
                        "preemption respawn", self._last_completed)
                m = self.check_membership()
                results.append(step_fn(step, m))
                self._last_completed = step
                if self.save_every > 0 and \
                        (step + 1) % self.save_every == 0:
                    self._save(step)
        finally:
            self.stop()
        return results
