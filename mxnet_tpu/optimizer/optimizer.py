"""Optimizer registry and implementations.

Reference: ``python/mxnet/optimizer/optimizer.py`` — the `Optimizer` base
(lr/wd multipliers, num_update tracking, lr_scheduler hook, multi-precision
master weights) and the zoo: SGD, NAG, Adam, RMSProp, AdaGrad, AdaDelta,
Ftrl, Signum, SGLD, DCASGD, LAMB; ``src/operator/contrib/adamw.cc`` for
AdamW. State math executes through the optimizer update ops
(``mxnet_tpu/ops/optimizer_op.py``) with `out=` writeback, so a Trainer
step can also fuse them into a jitted graph.
"""
from __future__ import annotations

import contextlib
import math
import pickle
from typing import Dict, Optional

import numpy as _np

from .. import ndarray as nd
from ..base import MXNetError
from ..ndarray import NDArray

__all__ = ["Optimizer", "SGD", "NAG", "Adam", "AdamW", "RMSProp", "AdaGrad",
           "AdaDelta", "Ftrl", "Signum", "SGLD", "DCASGD", "LAMB",
           "FTML", "Adamax", "Nadam", "LBSGD",
           "Updater", "create", "register", "get_updater"]

_REGISTRY: Dict[str, type] = {}


def register(klass):
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(name, **kwargs):
    if isinstance(name, Optimizer):
        return name
    key = str(name).lower()
    if key not in _REGISTRY:
        raise MXNetError(f"unknown optimizer {name!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[key](**kwargs)


class Optimizer:
    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 multi_precision=False, param_dict=None, begin_num_update=0):
        self.rescale_grad = rescale_grad
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None and getattr(lr_scheduler, "base_lr", None):
            self.lr = lr_scheduler.base_lr
        self.multi_precision = multi_precision
        self.num_update = begin_num_update
        self.begin_num_update = begin_num_update
        self._index_update_count: Dict[int, int] = {}
        # reference: Optimizer._all_index_update_counts — one count
        # stream PER DEVICE, switched by _set_current_context. A param
        # replicated over N devices must advance t once per step on
        # each replica, not N times on a shared clock: Adam's bias
        # correction reads t, and a shared clock hands every replica a
        # DIFFERENT t (ctx0 sees 1,N+1,..., ctx1 sees 2,N+2,...), so
        # the supposedly identical device copies drift apart.
        self._all_index_update_counts: Dict[int, Dict[int, int]] = \
            {0: self._index_update_count}
        # seed for streams created after a restore: a rejoined device
        # must resume the saved clock, not restart t at 1
        self._count_baseline: Dict[int, int] = {}
        self.idx2name = dict(param_idx2name or {})
        self.param_dict = dict(param_dict or {})
        self.lr_mult: Dict[str, float] = {}
        self.wd_mult: Dict[str, float] = {}
        # dynamic-trace mode (see .dynamic()): (t, base_lr) as traced scalars
        self._dyn = None

    # -- state ----------------------------------------------------------
    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and str(weight.dtype) in ("float16", "bfloat16"):
            w32 = weight.astype("float32")
            return (w32, self.create_state(index, w32))
        return self.create_state(index, weight)

    # -- lr/wd ----------------------------------------------------------
    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise MXNetError("cannot set lr directly when an LRScheduler is active")
        self.lr = lr

    @property
    def learning_rate(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = dict(args_wd_mult)

    @contextlib.contextmanager
    def dynamic(self, t, base_lr):
        """Trace mode for the fused (jitted) train step.

        ``t`` (step count) and ``base_lr`` (scheduled learning rate) enter
        the compiled graph as traced scalars, so ONE executable serves every
        step — bias corrections and LR schedules stay dynamic instead of
        being baked in at trace time. The eager path (Updater/Trainer) never
        uses this; it keeps MXNet's per-index python counters.
        """
        prev = self._dyn
        self._dyn = (t, base_lr)
        try:
            yield
        finally:
            self._dyn = prev

    def _set_current_context(self, device_id):
        """Switch the per-index update-count stream to ``device_id``
        (reference: Optimizer._set_current_context). New streams seed
        from the restored-counter baseline — empty on a fresh run."""
        if device_id not in self._all_index_update_counts:
            self._all_index_update_counts[device_id] = \
                dict(self._count_baseline)
        self._index_update_count = self._all_index_update_counts[device_id]

    def _restore_update_counts(self, counts):
        """Install restored per-index counts as the clock of EVERY
        device stream, current and future — a resumed multi-device run
        must see the same t on every replica."""
        self._count_baseline = dict(counts)
        self._index_update_count = dict(counts)
        self._all_index_update_counts = {0: self._index_update_count}

    def _update_count(self, index):
        if self._dyn is not None:
            return  # counts advance eagerly in the fused-step driver
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self.num_update, self._index_update_count[index])

    def _t(self, index):
        """Per-index update count; traced scalar in dynamic mode."""
        if self._dyn is not None:
            return self._dyn[0]
        return self._index_update_count[index]

    def _get_lr(self, index):
        if self._dyn is not None:
            lr = self._dyn[1]
        else:
            lr = self.lr_scheduler(self.num_update) if self.lr_scheduler else self.lr
        if index in self.param_dict:
            lr *= self.param_dict[index].lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.param_dict:
            wd *= self.param_dict[index].wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    # -- updates --------------------------------------------------------
    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and str(weight.dtype) in ("float16", "bfloat16"):
            w32, base_state = state
            g32 = grad.astype("float32")
            self.update(index, w32, g32, base_state)
            weight._set_data(w32.data.astype(weight.data.dtype))
        else:
            self.update(index, weight, grad, state)

    def _common_kwargs(self, index):
        kw = {"lr": self._get_lr(index), "wd": self._get_wd(index),
              "rescale_grad": self.rescale_grad}
        if self.clip_gradient is not None:
            kw["clip_gradient"] = self.clip_gradient
        return kw


@register
class SGD(Optimizer):
    """reference: optimizer.py::SGD (momentum + multi-precision)."""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd.zeros(weight.shape, ctx=weight.context, dtype=str(weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        if state is None:
            nd.sgd_update(weight, grad, out=weight, **kw)
        else:
            nd.sgd_mom_update(weight, grad, state, momentum=self.momentum,
                              out=[weight, state], **kw)


@register
class NAG(Optimizer):
    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd.zeros(weight.shape, ctx=weight.context, dtype=str(weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        if state is None:
            nd.sgd_update(weight, grad, out=weight, **kw)
        else:
            nd.nag_mom_update(weight, grad, state, momentum=self.momentum,
                              out=[weight, state], **kw)


@register
class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context, dtype=str(weight.dtype)),
                nd.zeros(weight.shape, ctx=weight.context, dtype=str(weight.dtype)))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._t(index)
        kw = self._common_kwargs(index)
        # bias correction folded into lr (reference: Adam.update)
        kw["lr"] *= (1.0 - self.beta2 ** t) ** 0.5 / (1.0 - self.beta1 ** t)
        mean, var = state
        nd.adam_update(weight, grad, mean, var, beta1=self.beta1,
                       beta2=self.beta2, epsilon=self.epsilon,
                       out=[weight, mean, var], **kw)


@register
class AdamW(Optimizer):
    """Decoupled weight decay (reference: contrib adamw.cc + gluonnlp's
    AdamW usage for BERT)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, correct_bias=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.correct_bias = correct_bias

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context, dtype="float32"),
                nd.zeros(weight.shape, ctx=weight.context, dtype="float32"))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._t(index)
        kw = self._common_kwargs(index)
        wd = kw.pop("wd")
        if self.correct_bias:
            kw["lr"] *= (1.0 - self.beta2 ** t) ** 0.5 / (1.0 - self.beta1 ** t)
        mean, var = state
        nd.adamw_update(weight, grad, mean, var, beta1=self.beta1,
                        beta2=self.beta2, epsilon=self.epsilon, wd=wd,
                        eta=1.0, out=[weight, mean, var], **kw)


@register
class LAMB(Optimizer):
    """Layer-wise adaptive moments (reference: optimizer.py::LAMB +
    lamb_update_phase1/2 ops)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, lower_bound=None, upper_bound=None,
                 bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lower_bound, self.upper_bound = lower_bound, upper_bound
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context, dtype="float32"),
                nd.zeros(weight.shape, ctx=weight.context, dtype="float32"))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._t(index)
        kw = self._common_kwargs(index)
        lr = kw.pop("lr")
        wd = kw.pop("wd")
        mean, var = state
        g = nd.lamb_update_phase1(weight, grad, mean, var, beta1=self.beta1,
                                  beta2=self.beta2, epsilon=self.epsilon,
                                  t=t, bias_correction=self.bias_correction,
                                  wd=wd, **kw)
        if isinstance(g, list):
            g, new_mean, new_var = g
            mean._set_data(new_mean.data)
            var._set_data(new_var.data)
        r1 = weight.norm()
        r2 = g.norm()
        nd.lamb_update_phase2(weight, g, r1, r2, lr=lr,
                              lower_bound=self.lower_bound if self.lower_bound is not None else -1.0,
                              upper_bound=self.upper_bound if self.upper_bound is not None else -1.0,
                              out=weight)


@register
class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1, self.gamma2 = gamma1, gamma2
        self.epsilon = epsilon
        self.centered = centered
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        z = lambda: nd.zeros(weight.shape, ctx=weight.context, dtype=str(weight.dtype))
        if self.centered:
            return (z(), z(), z())
        return (z(),)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        cw = self.clip_weights if self.clip_weights is not None else -1.0
        if self.centered:
            n, g_acc, delta = state
            nd.rmspropalex_update(weight, grad, n, g_acc, delta,
                                  gamma1=self.gamma1, gamma2=self.gamma2,
                                  epsilon=self.epsilon, clip_weights=cw,
                                  out=[weight, n, g_acc, delta], **kw)
        else:
            (n,) = state
            nd.rmsprop_update(weight, grad, n, gamma1=self.gamma1,
                              epsilon=self.epsilon, clip_weights=cw,
                              out=[weight, n], **kw)


@register
class AdaGrad(Optimizer):
    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, ctx=weight.context, dtype=str(weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        nd.adagrad_update(weight, grad, state, epsilon=self.float_stable_eps,
                          out=[weight, state], **kw)


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho, self.epsilon = rho, epsilon

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context, dtype=str(weight.dtype)),
                nd.zeros(weight.shape, ctx=weight.context, dtype=str(weight.dtype)))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        kw.pop("lr", None)  # AdaDelta has no learning rate
        acc_g, acc_d = state
        nd.adadelta_update(weight, grad, acc_g, acc_d, rho=self.rho,
                           epsilon=self.epsilon, out=[weight, acc_g, acc_d],
                           **kw)


@register
class Ftrl(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1, self.beta = lamda1, beta

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context, dtype=str(weight.dtype)),
                nd.zeros(weight.shape, ctx=weight.context, dtype=str(weight.dtype)))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        z, n = state
        nd.ftrl_update(weight, grad, z, n, lamda1=self.lamda1, beta=self.beta,
                       out=[weight, z, n], **kw)


@register
class Signum(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd.zeros(weight.shape, ctx=weight.context, dtype=str(weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        if state is None:
            nd.signsgd_update(weight, grad, out=weight, **kw)
        else:
            nd.signum_update(weight, grad, state, momentum=self.momentum,
                             wd_lh=self.wd_lh, out=[weight, state], **kw)


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (reference: optimizer.py::SGLD)."""

    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = g.clip(-self.clip_gradient, self.clip_gradient)
        noise = nd.random.normal(0, math.sqrt(lr), shape=weight.shape,
                                 ctx=weight.context)
        weight._set_data(
            (weight - lr / 2 * (g + wd * weight) + noise).data)


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference: optimizer.py::DCASGD)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lamda = lamda

    def create_state(self, index, weight):
        mom = None
        if self.momentum != 0.0:
            mom = nd.zeros(weight.shape, ctx=weight.context, dtype=str(weight.dtype))
        return (mom, weight.copy())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = g.clip(-self.clip_gradient, self.clip_gradient)
        mom, prev_w = state
        delta = g + wd * weight + self.lamda * g * g * (weight - prev_w)
        if mom is not None:
            mom._set_data((self.momentum * mom - lr * delta).data)
            upd = mom
        else:
            upd = -lr * delta
        new_w = weight + upd
        # previous_weight tracks the weight AFTER this update (reference:
        # DCASGD — in synchronous training the compensation term is zero)
        prev_w._set_data(new_w.data)
        weight._set_data(new_w.data)


class Updater:
    """State manager mapping param index -> optimizer state
    (reference: optimizer.py::Updater — also what KVStore server-side
    optimizers run)."""

    def __init__(self, optimizer: Optimizer):
        self.optimizer = optimizer
        self.states: Dict[int, object] = {}
        self.states_synced: Dict[int, bool] = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state_multi_precision(index, weight)
        self.optimizer.update_multi_precision(index, weight, grad, self.states[index])

    # envelope marker for the versioned state pickle: v2 adds the
    # optimizer's update counters (num_update / per-index counts), which
    # Adam-family bias correction depends on — without them a resumed
    # run restarts t at 1 and silently diverges from the uninterrupted
    # run. Legacy payloads (bare dict / (dict, Optimizer)) still load.
    _STATES_V2 = "mxnet_tpu_updater_states_v2"

    def get_states(self, dump_optimizer=False):
        def to_np(s):
            if s is None:
                return None
            if isinstance(s, tuple):
                return tuple(to_np(x) for x in s)
            if isinstance(s, NDArray):
                return s.asnumpy()
            return s

        payload = {k: to_np(v) for k, v in self.states.items()}
        counters = {
            "num_update": self.optimizer.num_update,
            "index_update_count": dict(self.optimizer._index_update_count),
        }
        return pickle.dumps(
            (self._STATES_V2, payload, counters,
             self.optimizer if dump_optimizer else None))

    def set_states(self, states):
        data = pickle.loads(states)
        counters = None
        if isinstance(data, tuple) and len(data) == 4 and \
                data[0] == self._STATES_V2:
            _, data, counters, opt_obj = data
            if opt_obj is not None:
                self.optimizer = opt_obj
        elif isinstance(data, tuple) and len(data) == 2 and \
                isinstance(data[1], Optimizer):
            data, self.optimizer = data

        def to_nd(s):
            if s is None:
                return None
            if isinstance(s, tuple):
                return tuple(to_nd(x) for x in s)
            if isinstance(s, _np.ndarray):
                from ..ndarray import array

                return array(s, dtype=s.dtype)
            return s

        self.states = {k: to_nd(v) for k, v in data.items()}
        if counters is not None:
            self.optimizer.num_update = counters["num_update"]
            self.optimizer._restore_update_counts(
                counters["index_update_count"])




@register
class FTML(Optimizer):
    """reference: optimizer.py::FTML (Follow The Moving Leader; states
    d/v/z driven by the ftml_update op)."""

    def __init__(self, learning_rate=0.0025, beta1=0.6, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        z = nd.zeros(weight.shape, ctx=weight.context, dtype=str(weight.dtype))
        return (nd.zeros_like(z), nd.zeros_like(z), z)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        clip = kw.pop("clip_gradient", -1.0)
        d, v, z = state
        nd.ftml_update(weight, grad, d, v, z, t=self._t(index),
                       beta1=self.beta1, beta2=self.beta2,
                       epsilon=self.epsilon, clip_grad=clip,
                       out=[weight, d, v, z], **kw)


@register
class Adamax(Optimizer):
    """reference: optimizer.py::Adamax — Adam with the infinity norm."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2

    def create_state(self, index, weight):
        z = nd.zeros(weight.shape, ctx=weight.context, dtype=str(weight.dtype))
        return (nd.zeros_like(z), nd.zeros_like(z))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._t(index)
        kw = self._common_kwargs(index)
        lr = kw["lr"] / (1.0 - self.beta1 ** t)
        wd = kw["wd"]
        g = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            g = nd.clip(g, -self.clip_gradient, self.clip_gradient)
        m, u = state
        m_new = self.beta1 * m + (1.0 - self.beta1) * g
        u_new = nd.maximum(self.beta2 * u, nd.abs(g))
        m._set_data(m_new.data)
        u._set_data(u_new.data)
        weight._set_data((weight - lr * m_new / (u_new + 1e-8)).data)


@register
class Nadam(Optimizer):
    """reference: optimizer.py::Nadam — Adam with Nesterov momentum
    (Dozat 2016 schedule)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.0

    def create_state(self, index, weight):
        z = nd.zeros(weight.shape, ctx=weight.context, dtype=str(weight.dtype))
        return (nd.zeros_like(z), nd.zeros_like(z))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._t(index)
        kw = self._common_kwargs(index)
        lr, wd = kw["lr"], kw["wd"]
        g = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            g = nd.clip(g, -self.clip_gradient, self.clip_gradient)
        momentum_t = self.beta1 * (1.0 - 0.5 * 0.96 ** (t * self.schedule_decay))
        momentum_t_1 = self.beta1 * (1.0 - 0.5 * 0.96 ** ((t + 1) * self.schedule_decay))
        self.m_schedule = self.m_schedule * momentum_t
        m_schedule_next = self.m_schedule * momentum_t_1
        m, v = state
        m_new = self.beta1 * m + (1.0 - self.beta1) * g
        v_new = self.beta2 * v + (1.0 - self.beta2) * g * g
        g_prime = g / (1.0 - self.m_schedule)
        m_prime = m_new / (1.0 - m_schedule_next)
        v_prime = v_new / (1.0 - self.beta2 ** t)
        m_bar = (1.0 - momentum_t) * g_prime + momentum_t_1 * m_prime
        m._set_data(m_new.data)
        v._set_data(v_new.data)
        weight._set_data(
            (weight - lr * m_bar / (nd.sqrt(v_prime) + self.epsilon)).data)


@register
class LBSGD(Optimizer):
    """reference: optimizer.py::LBSGD — large-batch SGD with LARS-style
    layer-wise adaptive rate scaling (warmup strategies collapse to the
    'lars' trust-ratio core; momentum + multi-precision supported)."""

    def __init__(self, learning_rate=0.01, momentum=0.9, eta=0.001,
                 epsilon=1e-8, warmup_strategy="linear", warmup_epochs=5,
                 batch_scale=1, updates_per_epoch=32, begin_epoch=0,
                 num_epochs=60, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.eta = eta
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, ctx=weight.context,
                        dtype=str(weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        lr, wd = kw["lr"], kw["wd"]
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = nd.clip(g, -self.clip_gradient, self.clip_gradient)
        # LARS trust ratio: ||w|| / (||g|| + wd*||w|| + eps), computed
        # ON DEVICE (a 0-d tensor broadcasting into the update) so the
        # fused/jitted step can trace it and eager mode never syncs
        wnorm = nd.sqrt((weight.astype("float32") ** 2).sum())
        gnorm = nd.sqrt((g.astype("float32") ** 2).sum())
        lars = nd.where(
            (wnorm > 0) * (gnorm > 0),
            self.eta * wnorm / (gnorm + wd * wnorm + self.epsilon),
            nd.ones_like(wnorm))
        eff_lr = lr * lars.astype(str(weight.dtype))
        mom = state
        mom_new = self.momentum * mom - eff_lr * (g + wd * weight)
        mom._set_data(mom_new.data)
        weight._set_data((weight + mom_new).data)


def get_updater(optimizer: Optimizer) -> Updater:
    return Updater(optimizer)
