"""Horizontally-fused multi-tensor optimizer sweeps.

Reference: MXNet's ``multi_sgd_update`` / ``multi_mp_sgd_mom_update`` /
``mp_lamb_update_*`` family (``src/operator/optimizer_op.cc``) — one
kernel launch updating a whole parameter list instead of one per
parameter. The round-5 roofline (PERF.md) put the Adam elementwise sweep
in the top-5 HBM buckets precisely because it ran as O(params) separate
dispatches; this module is the TPU-native answer:

* **bucket planning** — all (param, grad, optimizer-state) leaves of like
  dtype/precision are grouped into buckets (:func:`plan_buckets`), each
  bucket packed into coalesced flat buffers;
* **packed sweep** (:func:`packed_apply`) — the whole bucket's update is
  ONE elementwise pass over the flat buffers: a Pallas VMEM sweep on TPU
  (``pallas_kernels/fused_optimizer.py``, behind the same
  ``MXNET_PALLAS_FUSED`` + platform gates as the layer kernels) with a
  pure-``lax`` fallback that is the CPU oracle. LAMB's two-phase
  trust-ratio runs its per-tensor norms as a single fused
  ``multi_sum_sq``-style pass over the packed buffer
  (:func:`segment_sumsq`);
* **bit-identity with the per-param path** — every formula transcribes
  the single-tensor op math (``ops/optimizer_op.py``) exactly: the same
  f32 casts, the same scalar-broadcast multiply order, per-param norms
  reduced over the ORIGINAL param shape. A fused step is bit-identical
  to the per-param reference, which is the test gate
  (``tests/test_optimizer.py::TestFusedSweep*``).

Three consumers:

* ``parallel/step.py`` — :func:`traced_fused_update` replaces the
  per-ordinal ``update_multi_precision`` loop inside the jitted step
  (donation preserved; row-sparse lazy-update params stay excluded);
* ``gluon/trainer.py`` — :func:`eager_fused_update` collapses the eager
  ``step()`` optimizer phase from O(params) dispatches to one jitted
  sweep per dtype bucket, cached through the compilation service
  (``SiteCache("optimizer_sweep")``), journaled to the signature
  manifest and replayed by ``compiler.warm_start`` with no provider
  (:func:`warm_sweep_spec` rebuilds the sweep from the spec alone);
* ``ops/optimizer_op.py`` — the ``multi_sgd_*`` / ``multi_lamb_*`` ops
  are re-expressed on the same packed layout.

Opt out with ``MXNET_FUSED_OPTIMIZER=0`` (a trace-time routing knob —
it keys every jit cache via ``compiler.keys.routing_knobs``).
"""
from __future__ import annotations

import os
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as _np

__all__ = [
    "fused_sweep_enabled", "family_of", "family_static", "state_roles",
    "collect_scalars", "plan_buckets", "packed_apply", "segment_sumsq",
    "plan_eager", "apply_eager_plan", "eager_fused_update",
    "traced_fused_update", "warm_sweep_spec", "sweep_cache", "Bucket",
]

# the families the packed sweep reproduces bit-exactly; keyed by EXACT
# class (a subclass overriding update() must keep the per-param path)
_FAMILIES = ("sgd", "adam", "adamw", "lamb")


def fused_sweep_enabled() -> bool:
    """The routing knob: ``MXNET_FUSED_OPTIMIZER=0`` opts out of the
    fused sweep everywhere (TrainStep, Trainer, warm replay). Default on.
    Read per call so tests can toggle it; it participates in
    ``compiler.keys.routing_knobs`` so a toggle re-traces instead of
    replaying the other body."""
    return os.environ.get("MXNET_FUSED_OPTIMIZER", "1") != "0"


def family_of(optimizer) -> Optional[str]:
    """The packed-sweep family for this optimizer, or None when it must
    stay on the per-param path (unknown class, or a SUBCLASS of a known
    one — an overridden update() would silently not run)."""
    from .optimizer import SGD, Adam, AdamW, LAMB

    t = type(optimizer)
    if t is SGD:
        return "sgd"
    if t is Adam:
        return "adam"
    if t is AdamW:
        return "adamw"
    if t is LAMB:
        return "lamb"
    return None


def family_static(optimizer, family: str) -> tuple:
    """The optimizer hyperparameters baked into the traced sweep body,
    as a sorted item tuple (part of the cache signature)."""
    clip = optimizer.clip_gradient
    if family == "sgd":
        items = {"momentum": float(optimizer.momentum)}
    elif family in ("adam", "adamw"):
        items = {"beta1": float(optimizer.beta1),
                 "beta2": float(optimizer.beta2),
                 "epsilon": float(optimizer.epsilon)}
    elif family == "lamb":
        items = {"beta1": float(optimizer.beta1),
                 "beta2": float(optimizer.beta2),
                 "epsilon": float(optimizer.epsilon),
                 "bias_correction": bool(optimizer.bias_correction),
                 "lower_bound": optimizer.lower_bound,
                 "upper_bound": optimizer.upper_bound,
                 # eager mode matches the reference's constant-folded
                 # reciprocal-multiply; dynamic mode its true division
                 # (see collect_scalars)
                 "bc_recip": optimizer._dyn is None}
    else:
        raise ValueError(f"unknown sweep family {family!r}")
    items["clip_gradient"] = clip
    return tuple(sorted(items.items()))


def traceable_state(optimizer, family: str, param, n_live: int) -> bool:
    """True when a param's live optimizer-state leaf count matches the
    family's expected layout — the TrainStep guard that keeps a
    foreign/custom state tree on the per-param path."""
    static = dict(family_static(optimizer, family))
    mp = optimizer.multi_precision \
        and str(param.dtype) in ("float16", "bfloat16")
    return n_live == (1 if mp else 0) + len(state_roles(family, static))


def state_roles(family: str, static: dict) -> Tuple[str, ...]:
    """Names of the family's optimizer-state leaves, in the flatten order
    ``create_state`` produces (the fp32 master of a multi-precision param
    is handled separately as the ``w32`` role)."""
    if family == "sgd":
        return ("mom",) if static["momentum"] != 0.0 else ()
    return ("mean", "var")


def collect_scalars(optimizer, family: str, ks: Sequence[int]) -> Dict[str, list]:
    """Per-param runtime scalars for the sweep, computed with EXACTLY the
    per-family ``Optimizer.update`` scalar prep (same expressions, same
    evaluation order) so the packed multiply reproduces the per-param
    result bit-for-bit. Values are python floats on the eager path and
    traced 0-d scalars under ``optimizer.dynamic`` — both feed
    :func:`packed_apply` unchanged.
    """
    lrs, wds, bc1s, bc2s = [], [], [], []
    for k in ks:
        lr = optimizer._get_lr(k)
        wd = optimizer._get_wd(k)
        if family == "adam":
            t = optimizer._t(k)
            # reference: Adam.update folds bias correction into lr
            lr = lr * ((1.0 - optimizer.beta2 ** t) ** 0.5
                       / (1.0 - optimizer.beta1 ** t))
        elif family == "adamw":
            if optimizer.correct_bias:
                t = optimizer._t(k)
                lr = lr * ((1.0 - optimizer.beta2 ** t) ** 0.5
                           / (1.0 - optimizer.beta1 ** t))
        elif family == "lamb" and optimizer.bias_correction:
            t = optimizer._t(k)
            if optimizer._dyn is None:
                # eager reference: t is BAKED into the phase1 op, and
                # XLA constant-folds `m / (1 - beta**t)` into a
                # reciprocal MULTIPLY (f32 reciprocal of the f32
                # constant). Ship that exact f32 inverse so the packed
                # multiply reproduces the reference bit-for-bit — and
                # the sweep compiles ONCE while the reference op
                # retraces per t
                bc1s.append(float(_np.float32(1.0)
                                  / _np.float32(1.0 - optimizer.beta1 ** t)))
                bc2s.append(float(_np.float32(1.0)
                                  / _np.float32(1.0 - optimizer.beta2 ** t)))
            else:
                # traced reference: bc is a runtime scalar -> true
                # division on both paths
                bc1s.append(1.0 - optimizer.beta1 ** t)
                bc2s.append(1.0 - optimizer.beta2 ** t)
        lrs.append(lr)
        wds.append(wd)
    out = {"lr": lrs, "wd": wds}
    if family == "lamb" and optimizer.bias_correction:
        out["bc1"] = bc1s
        out["bc2"] = bc2s
    return out


# ---------------------------------------------------------------------------
# bucket planning
# ---------------------------------------------------------------------------


class Bucket(NamedTuple):
    """One dtype/precision bucket of the parameter set.

    ``members``: positions into the caller's entry list; ``shapes``:
    per-member param shapes; ``wdtype``/``gdtype``: weight/grad dtypes;
    ``mp``: True when the update runs on an fp32 master copy (the
    ``w32`` role) with the low-precision weight downcast at the end.
    """

    members: Tuple[int, ...]
    shapes: Tuple[tuple, ...]
    wdtype: str
    gdtype: str
    mp: bool


def _bucket_cap_bytes() -> int:
    mb = float(os.environ.get("MXNET_OPT_BUCKET_MB", "0"))
    return int(mb * (1 << 20)) if mb > 0 else 0


def plan_buckets(entries, multi_precision: bool) -> List[Bucket]:
    """Group entries into dtype buckets.

    ``entries``: sequence of ``(shape, wdtype, gdtype)``. One bucket per
    (wdtype, gdtype) pair by default — the "one kernel per dtype bucket"
    contract — optionally size-capped via ``MXNET_OPT_BUCKET_MB`` so
    giant models split into fixed total-size classes that the compile
    cache can reuse across param-set growth.
    """
    cap = _bucket_cap_bytes()
    groups: Dict[tuple, list] = {}
    for pos, (shape, wdtype, gdtype) in enumerate(entries):
        groups.setdefault((str(wdtype), str(gdtype)), []).append(
            (pos, tuple(int(s) for s in shape)))
    buckets = []
    for (wdtype, gdtype), mem in groups.items():
        mp = multi_precision and wdtype in ("float16", "bfloat16")
        itemsize = _np.dtype(wdtype).itemsize
        cur, cur_bytes = [], 0
        for pos, shape in mem:
            n_bytes = int(_np.prod(shape or (1,))) * itemsize
            if cap and cur and cur_bytes + n_bytes > cap:
                buckets.append(Bucket(tuple(p for p, _ in cur),
                                      tuple(s for _, s in cur),
                                      wdtype, gdtype, mp))
                cur, cur_bytes = [], 0
            cur.append((pos, shape))
            cur_bytes += n_bytes
        if cur:
            buckets.append(Bucket(tuple(p for p, _ in cur),
                                  tuple(s for _, s in cur),
                                  wdtype, gdtype, mp))
    return buckets


# ---------------------------------------------------------------------------
# the packed sweep
# ---------------------------------------------------------------------------


def _sizes_offsets(shapes):
    sizes = [int(_np.prod(s)) if s else 1 for s in shapes]
    offsets = _np.concatenate([[0], _np.cumsum(sizes)]).tolist()
    return sizes, offsets


def segment_sumsq(flat, shapes, offsets, dtype=None):
    """Per-member sum of squares over the packed buffer — the fused
    ``multi_sum_sq`` norm pass (the LAMB/LARS trust-ratio building
    block). Each segment is reshaped back to its ORIGINAL param shape
    before the reduction so the result is bit-identical to the
    per-param ``jnp.sum(jnp.square(w))``; the optimization barrier
    stops XLA folding the reshape into the reduce (a folded reduce
    accumulates in flat order, which differs from the native-shape
    order at the ULP level)."""
    import jax
    import jax.numpy as jnp

    outs = []
    for shape, off, off2 in zip(shapes, offsets[:-1], offsets[1:]):
        seg = jax.lax.optimization_barrier(
            flat[off:off2].reshape(shape if shape else ()))
        outs.append(jnp.sum(jnp.square(seg)))
    return jnp.stack(outs) if dtype is None \
        else jnp.stack(outs).astype(dtype)


def _pack(arrs):
    """Members -> one flat buffer, in member order. The SINGLE packing
    convention — offsets from :func:`_sizes_offsets` index into exactly
    this concatenation, and every packer (packed_apply, _LambSweep)
    must share it or the per-member slices silently misalign."""
    import jax.numpy as jnp

    if len(arrs) == 1:
        return jnp.reshape(arrs[0], (-1,))
    return jnp.concatenate([jnp.reshape(a, (-1,)) for a in arrs])


def _as_vec(values):
    """(n,) f32 per-member vector from python floats or traced scalars."""
    import jax.numpy as jnp

    if all(isinstance(v, (int, float)) for v in values):
        return _np.asarray(values, _np.float32)
    return jnp.stack([jnp.asarray(v, jnp.float32) for v in values])


def _expand(vec, sizes, total):
    """Per-member scalars -> per-element vector over the packed layout."""
    import jax.numpy as jnp

    return jnp.repeat(jnp.asarray(vec), _np.asarray(sizes, _np.int64),
                      total_repeat_length=total)


# -- elementwise stage formulas ---------------------------------------------
# Each operates on FLAT arrays (any shape — the Pallas kernel calls them
# on (block, 128) tiles, the lax fallback on the 1-D buffer) and
# transcribes the single-tensor op math exactly. ``env`` carries the
# packed tensors + per-element scalar vectors + 0-d scalars.


def _rescale_clip(env, static):
    import jax.numpy as jnp

    g = env["g"].astype(jnp.float32) * env["rescale"]
    clip = static["clip_gradient"]
    if clip is not None and clip >= 0:
        g = jnp.clip(g, -clip, clip)
    return g


def _sgd_elem(env, static):
    import jax.numpy as jnp

    g = _rescale_clip(env, static)
    g = g + env["wd"] * env["w"].astype(jnp.float32)
    if "mom" not in env:
        new_w = env["w"].astype(jnp.float32) - env["lr"] * g
        return {"w": new_w}
    # momentum may be 0.0 here: sgd_mom_update with momentum=0 still
    # rewrites the momentum buffer to -lr*g (the op contract)
    momentum = static["momentum"]
    new_mom = momentum * env["mom"].astype(jnp.float32) - env["lr"] * g
    new_w = env["w"].astype(jnp.float32) + new_mom
    return {"w": new_w, "mom": new_mom}


def _adam_elem(env, static):
    import jax.numpy as jnp

    b1, b2, eps = static["beta1"], static["beta2"], static["epsilon"]
    g = _rescale_clip(env, static)
    g = g + env["wd"] * env["w"].astype(jnp.float32)
    new_mean = b1 * env["mean"].astype(jnp.float32) + (1 - b1) * g
    new_var = b2 * env["var"].astype(jnp.float32) \
        + (1 - b2) * jnp.square(g)
    new_w = env["w"].astype(jnp.float32) \
        - env["lr"] * new_mean / (jnp.sqrt(new_var) + eps)
    return {"w": new_w, "mean": new_mean, "var": new_var}


def _adamw_elem(env, static):
    import jax.numpy as jnp

    b1, b2, eps = static["beta1"], static["beta2"], static["epsilon"]
    g = _rescale_clip(env, static)
    new_mean = b1 * env["mean"] + (1 - b1) * g
    new_var = b2 * env["var"] + (1 - b2) * jnp.square(g)
    w32 = env["w"].astype(jnp.float32)
    new_w = w32 - 1.0 * (env["lr"] * new_mean / (jnp.sqrt(new_var) + eps)
                         + env["wd"] * env["lr"] * w32)
    # per-param AMP overflow guard (reference adamw.cc): `ok` arrives as
    # a per-element 0/1 vector reduced per member OUTSIDE the kernel
    ok = env["ok"] > 0
    new_w = jnp.where(ok, new_w, w32)
    new_mean = jnp.where(ok, new_mean, env["mean"])
    new_var = jnp.where(ok, new_var, env["var"])
    return {"w": new_w, "mean": new_mean, "var": new_var}


def _lamb_phase1_elem(env, static):
    import jax.numpy as jnp

    b1, b2, eps = static["beta1"], static["beta2"], static["epsilon"]
    g = _rescale_clip(env, static)
    new_mean = b1 * env["mean"] + (1 - b1) * g
    new_var = b2 * env["var"] + (1 - b2) * jnp.square(g)
    m, v = new_mean, new_var
    if static["bias_correction"]:
        if static.get("bc_recip"):
            # bc1/bc2 carry f32 INVERSES (see collect_scalars)
            m = m * env["bc1"]
            v = v * env["bc2"]
        else:
            m = m / env["bc1"]
            v = v / env["bc2"]
    upd = m / (jnp.sqrt(v) + eps) + env["wd"] * env["w"].astype(jnp.float32)
    return {"upd": upd, "mean": new_mean, "var": new_var}


def _lamb_phase2_elem(env, static):
    import jax.numpy as jnp

    new_w = env["w"].astype(jnp.float32) - env["lr_ratio"] * env["upd"]
    return {"w": new_w}


def _kernel_routed(platform) -> bool:
    from ..pallas_kernels import fused_optimizer as fopt

    return fopt.fused_opt_supported(platform)


def _run_elementwise(fn, static, flats, vec_el, scalars, out_specs,
                     platform, interpret):
    """One elementwise stage: the Pallas sweep kernel when routed (TPU +
    ``MXNET_PALLAS_FUSED``, or ``interpret`` for the CPU oracle tests),
    else the identical jnp math on the flat buffers."""
    from ..pallas_kernels import fused_optimizer as fopt

    if interpret or fopt.fused_opt_supported(platform):
        from .. import telemetry

        telemetry.record_pallas_dispatch("fused_opt_sweep")
        return fopt.sweep_pallas(fn, static, flats, vec_el, scalars,
                                 out_specs, interpret=interpret)
    env = dict(flats)
    env.update(vec_el)
    env.update(scalars)
    outs = fn(env, static)
    import jax.numpy as jnp

    return {name: outs[name].astype(dtype)
            for name, dtype in out_specs}


def packed_apply(family, static, shapes, ins, vecs, rescale,
                 low_dtype=None, platform=None, interpret=False):
    """Apply one fused sweep over one bucket.

    ``ins``: role -> list of per-member arrays. Roles: ``w`` (the update
    target — the fp32 master in a multi-precision bucket, the weight
    itself otherwise), ``g``, and the family's state roles. ``vecs``:
    name -> per-member scalars (floats or traced 0-d). ``rescale``:
    the grad rescale scalar (float, np, or traced). ``low_dtype``: the
    low-precision weight dtype of a multi-precision bucket — adds a
    ``w_low`` output holding the downcast weights.

    Returns role -> list of updated per-member arrays (original shapes).
    """
    import jax.numpy as jnp

    static = dict(static)
    sizes, offsets = _sizes_offsets(shapes)
    total = offsets[-1]
    if platform is None:
        from ..base import current_execution_platform

        platform = current_execution_platform(
            ins["w"][0] if ins["w"] else None)

    flats = {role: _pack(arrs) for role, arrs in ins.items()}
    vec_el = {name: _expand(_as_vec(v), sizes, total)
              for name, v in vecs.items()}
    scalars = {"rescale": rescale if isinstance(rescale, (int, float))
               else jnp.asarray(rescale, jnp.float32)}

    wdt = flats["w"].dtype
    if family == "sgd":
        out_specs = [("w", wdt)]
        if "mom" in flats:
            out_specs.append(("mom", flats["mom"].dtype))
        new = _run_elementwise(_sgd_elem, static, flats, vec_el, scalars,
                              out_specs, platform, interpret)
    elif family == "adam":
        out_specs = [("w", wdt), ("mean", flats["mean"].dtype),
                     ("var", flats["var"].dtype)]
        new = _run_elementwise(_adam_elem, static, flats, vec_el, scalars,
                              out_specs, platform, interpret)
    elif family == "adamw":
        # the per-param overflow scan (isfinite over the rescaled+clipped
        # grad) is a per-member reduction — computed on the packed buffer
        # segment-wise, then broadcast back as a 0/1 vector
        g32 = flats["g"].astype(jnp.float32) * scalars["rescale"]
        clip = static["clip_gradient"]
        if clip is not None and clip >= 0:
            g32 = jnp.clip(g32, -clip, clip)
        oks = [jnp.isfinite(
                   g32[off:off2].reshape(shape if shape else ())).all()
               for shape, off, off2 in zip(shapes, offsets[:-1],
                                           offsets[1:])]
        vec_el["ok"] = _expand(
            jnp.stack(oks).astype(jnp.float32), sizes, total)
        out_specs = [("w", wdt), ("mean", flats["mean"].dtype),
                     ("var", flats["var"].dtype)]
        new = _run_elementwise(_adamw_elem, static, flats, vec_el,
                              scalars, out_specs, platform, interpret)
    elif family == "lamb":
        import jax

        # phase1 never reads lr (it enters later as the per-member
        # lr*ratio) — don't stream an unused (L,) operand through the
        # kernel on the HBM-bound pass
        p1_vec = {k: v for k, v in vec_el.items() if k != "lr"}
        p1 = _run_elementwise(
            _lamb_phase1_elem, static, flats, p1_vec, scalars,
            [("upd", jnp.float32), ("mean", flats["mean"].dtype),
             ("var", flats["var"].dtype)], platform, interpret)
        # materialization boundary mirroring the reference's op edge
        # (phase1 is ONE op there): without it XLA fuses phase1 into the
        # norm/phase2 consumers and contracts the chain differently than
        # the op-at-a-time reference (ULP drift breaks bit-identity).
        # ONE joint barrier — separate barriers would let XLA duplicate
        # the phase1 chain per consumer, re-opening the drift
        keys_ = sorted(p1)
        vals = jax.lax.optimization_barrier(tuple(p1[k] for k in keys_))
        p1 = dict(zip(keys_, vals))
        # trust-ratio norm pass: one fused multi_sum_sq-style sweep per
        # buffer, per-member reductions over the ORIGINAL shapes. The
        # norms are op outputs in the reference (weight.norm()), so they
        # get the same materialization boundary
        r1 = jax.lax.optimization_barrier(
            jnp.sqrt(segment_sumsq(flats["w"], shapes, offsets)))
        r2 = jax.lax.optimization_barrier(
            jnp.sqrt(segment_sumsq(p1["upd"], shapes, offsets)))
        lo, hi = static["lower_bound"], static["upper_bound"]
        if lo is not None and lo >= 0:
            r1 = jnp.maximum(r1, lo)
        if hi is not None and hi >= 0:
            r1 = jnp.minimum(r1, hi)
        ratio = jnp.where((r1 > 0) & (r2 > 0), r1 / r2, 1.0)
        # materialize the per-member multiplier before it broadcasts into
        # the phase2 loop (same boundary class as the norms above)
        lr_ratio = jax.lax.optimization_barrier(
            _as_vec(vecs["lr"]) * ratio)
        p2_vec = {"lr_ratio": _expand(lr_ratio, sizes, total)}
        new = _run_elementwise(
            _lamb_phase2_elem, static,
            {"w": flats["w"], "upd": p1["upd"]}, p2_vec, {},
            [("w", wdt)], platform, interpret)
        new["mean"], new["var"] = p1["mean"], p1["var"]
    else:
        raise ValueError(f"unknown sweep family {family!r}")

    out: Dict[str, list] = {}
    for role, flat in new.items():
        out[role] = [flat[off:off2].reshape(shape if shape else ())
                     for shape, off, off2 in zip(shapes, offsets[:-1],
                                                 offsets[1:])]
    if low_dtype is not None:
        out["w_low"] = [w.astype(low_dtype) for w in out["w"]]
    return out


# ---------------------------------------------------------------------------
# traced consumer: the TrainStep update phase
# ---------------------------------------------------------------------------


def traced_sweep_routed(platform) -> bool:
    """Whether a jitted TrainStep should route its update phase through
    the packed sweep: only when the Pallas kernel engages (TPU +
    ``MXNET_PALLAS_FUSED``). Off-kernel the per-param loop is kept — it
    already compiles into the one step executable, and replacing it
    with a packed-lax variant would change ULP-level results for zero
    dispatch win (inside one program there is nothing to collapse)."""
    return _kernel_routed(platform)


def traced_fused_update(optimizer, family, items, platform=None):
    """Fused update inside a jitted step (``optimizer.dynamic`` active).

    ``items``: list of ``(k, w_val, g_val, state_leaves)`` with raw jax
    values; ``state_leaves`` in the flatten order of
    ``create_state_multi_precision`` (fp32 master first for mp params).
    Returns ``{k: (new_w, new_state_leaves)}`` — new_w in the PARAM's
    dtype; state leaves in their input order/dtypes.
    """
    static = dict(family_static(optimizer, family))
    roles = state_roles(family, static)
    entries = [(tuple(w.shape), str(w.dtype), str(g.dtype))
               for _, w, g, _ in items]
    buckets = plan_buckets(entries, optimizer.multi_precision)
    results = {}
    for b in buckets:
        ks = [items[pos][0] for pos in b.members]
        ws = [items[pos][1] for pos in b.members]
        gs = [items[pos][2] for pos in b.members]
        leaves = [items[pos][3] for pos in b.members]
        ins = {"g": gs}
        if b.mp:
            # update_multi_precision: the sweep runs on the fp32 master
            # with the grad pre-cast to f32; weight downcasts at the end
            ins["w"] = [lv[0] for lv in leaves]
            ins["g"] = [g.astype("float32") for g in gs]
            base = [lv[1:] for lv in leaves]
        else:
            ins["w"] = ws
            base = leaves
        for ri, role in enumerate(roles):
            ins[role] = [lv[ri] for lv in base]
        vecs = collect_scalars(optimizer, family, ks)
        new = packed_apply(family, static, b.shapes, ins, vecs,
                           optimizer.rescale_grad,
                           low_dtype=b.wdtype if b.mp else None,
                           platform=platform)
        for j, pos in enumerate(b.members):
            k = items[pos][0]
            if b.mp:
                new_leaves = [new["w"][j]] + [new[r][j] for r in roles]
                results[k] = (new["w_low"][j], new_leaves)
            else:
                results[k] = (new["w"][j], [new[r][j] for r in roles])
    return results


# ---------------------------------------------------------------------------
# eager consumer: Trainer.step's optimizer phase
# ---------------------------------------------------------------------------

_SWEEP_SITE = "optimizer_sweep"


def sweep_cache():
    """The process-global compile cache for eager fused sweeps (shared by
    every Trainer and by warm-start replay)."""
    from ..compiler import service as _csvc

    return _csvc.shared_cache(_SWEEP_SITE)


def _sweep_key(family, static, bucket, state_dtypes, vec_names, n,
               platform):
    from ..compiler import signature

    return signature(
        _SWEEP_SITE, (family, bucket.wdtype, bucket.gdtype, bucket.mp),
        avals=tuple(bucket.shapes) + (tuple(state_dtypes), n),
        attrs=tuple(static), platform=platform,
        extra=(tuple(vec_names),))


class _LambSweep:
    """Eager LAMB bucket sweep as THREE jitted dispatches — the
    reference's own kernel granularity (``lamb_update_phase1`` /
    ``multi_sum_sq`` norms / ``lamb_update_phase2``).

    One fused program would be one dispatch, but XLA may recompute a
    value shared by two in-program consumers with different FMA
    contraction (measured on XLA:CPU: the trust-ratio reduce fused into
    the phase2 loop re-accumulates per member), so bit-identity with
    the op-at-a-time reference REQUIRES real program boundaries at the
    reference's op edges. Elementwise-only families stay at one
    dispatch; LAMB's reduce forces the same three launches MXNet's
    fused LAMB makes.
    """

    n_dispatches = 3

    def __init__(self, static_items, shapes, wdtype, mp, vec_names):
        import jax
        import jax.numpy as jnp

        static = dict(static_items)
        self._vec_names = tuple(vec_names)
        self._mp = mp
        self._n = n = len(shapes)
        sizes, offsets = _sizes_offsets(shapes)
        has_bc = static["bias_correction"]

        def phase1(ws, gs, ms, vs, vecs, rescale):
            # outputs stay FLAT: slicing the state outputs per member
            # HERE would let XLA recompute the shared moment chain per
            # output buffer with different contraction (measured —
            # `upd` drifts 1 ULP); the per-member views are taken in
            # the norms program, where these are materialized inputs
            total = offsets[-1]
            env = {"w": _pack(ws), "g": _pack(gs), "mean": _pack(ms),
                   "var": _pack(vs), "rescale": rescale}
            for name in ("wd",) + (("bc1", "bc2") if has_bc else ()):
                env[name] = _expand(vecs[name], sizes, total)
            p1 = _lamb_phase1_elem(env, static)
            return p1["upd"], p1["mean"], p1["var"]

        def norms(ws, upd, fmean, fvar):
            # the fused multi_sum_sq pass: per-member reductions over
            # the ORIGINAL shapes (bit-identical to weight.norm());
            # state slicing rides along — pure views of inputs
            fw = _pack(ws)
            means = [fmean[o:o2].reshape(s) for s, o, o2
                     in zip(shapes, offsets[:-1], offsets[1:])]
            vars_ = [fvar[o:o2].reshape(s) for s, o, o2
                     in zip(shapes, offsets[:-1], offsets[1:])]
            return (jnp.sqrt(segment_sumsq(fw, shapes, offsets)),
                    jnp.sqrt(segment_sumsq(upd, shapes, offsets)),
                    means, vars_)

        lo, hi = static["lower_bound"], static["upper_bound"]

        def phase2(ws, upd, r1, r2, lr):
            if lo is not None and lo >= 0:
                r1 = jnp.maximum(r1, lo)
            if hi is not None and hi >= 0:
                r1 = jnp.minimum(r1, hi)
            ratio = jnp.where((r1 > 0) & (r2 > 0), r1 / r2, 1.0)
            new_w, new_low = [], []
            for j, (s, o, o2) in enumerate(zip(shapes, offsets[:-1],
                                               offsets[1:])):
                w32 = (ws[j].astype(jnp.float32)
                       - lr[j] * ratio[j] * upd[o:o2].reshape(s))
                if mp:
                    new_w.append(w32)
                    new_low.append(w32.astype(wdtype))
                else:
                    new_w.append(w32.astype(ws[j].dtype))
            return new_w, new_low

        self._phase1 = jax.jit(phase1)
        self._norms = jax.jit(norms)
        self._phase2 = jax.jit(phase2)

    def __call__(self, *args):
        n, mp = self._n, self._mp
        pos = 0
        ws = args[pos:pos + n]
        pos += n
        gs = args[pos:pos + n]
        pos += n
        if mp:
            w32 = args[pos:pos + n]
            pos += n
        ms = args[pos:pos + n]
        pos += n
        vs = args[pos:pos + n]
        pos += n
        vecs = {}
        for name in self._vec_names:
            vecs[name] = args[pos]
            pos += 1
        rescale = args[pos]
        tgt = w32 if mp else ws
        # no host-side grad cast: _rescale_clip's astype(f32) inside
        # phase1 reproduces the reference's g32 pre-cast exactly
        upd, fmean, fvar = self._phase1(list(tgt), list(gs), list(ms),
                                        list(vs), vecs, rescale)
        r1, r2, means, vars_ = self._norms(list(tgt), upd, fmean, fvar)
        new_w, new_low = self._phase2(list(tgt), upd, r1, r2,
                                      vecs["lr"])
        if mp:
            return tuple(new_low) + tuple(new_w) + tuple(means) \
                + tuple(vars_)
        return tuple(new_w) + tuple(means) + tuple(vars_)

    def warm_lower(self, sds):
        """AOT-compile all three stage programs at the recorded avals
        (warm_start's replay hook; mirrors ``jit.lower().compile()``)."""
        import jax
        import numpy as _np_

        n, mp = self._n, self._mp
        pos = 0
        ws = list(sds[pos:pos + n])
        pos += n
        gs = list(sds[pos:pos + n])
        pos += n
        if mp:
            w32 = list(sds[pos:pos + n])
            pos += n
        ms = list(sds[pos:pos + n])
        pos += n
        vs = list(sds[pos:pos + n])
        pos += n
        vecs = {}
        for name in self._vec_names:
            vecs[name] = sds[pos]
            pos += 1
        rescale = sds[pos]
        tgt = w32 if mp else ws
        fsum = sum(int(_np.prod(s.shape or (1,))) for s in tgt)
        upd = jax.ShapeDtypeStruct((fsum,), _np_.float32)
        flat_m = jax.ShapeDtypeStruct((fsum,), ms[0].dtype)
        flat_v = jax.ShapeDtypeStruct((fsum,), vs[0].dtype)
        rsd = jax.ShapeDtypeStruct((n,), _np_.float32)
        self._phase1.lower(tgt, gs, ms, vs, vecs, rescale).compile()
        self._norms.lower(tgt, upd, flat_m, flat_v).compile()
        self._phase2.lower(tgt, upd, rsd, rsd, vecs["lr"]).compile()


def _build_sweep_fn(family, static_items, shapes, wdtype, gdtype, mp,
                    state_dtypes, vec_names, platform):
    """The jit-able eager sweep: positional args are
    ``w..., g..., [w32...,] state_role0..., ..., vec..., rescale`` and
    outputs mirror the inputs (updated weights first).

    LAMB routes to the three-dispatch :class:`_LambSweep` when the
    Pallas kernel is not engaged — the trust-ratio reduce needs real
    program boundaries for bit-identity (see _LambSweep). The kernel
    path keeps the single packed program (kernel boundaries give the
    same materialization; identity there is the documented
    FMA-tolerance class of every Pallas kernel)."""
    import jax

    static = dict(static_items)
    roles = state_roles(family, static)
    n = len(shapes)
    if family == "lamb" and not _kernel_routed(platform):
        return _LambSweep(static_items, shapes, wdtype, mp, vec_names)

    def sweep(*args):
        pos = 0
        ws = args[pos:pos + n]
        pos += n
        gs = args[pos:pos + n]
        pos += n
        if mp:
            w32 = args[pos:pos + n]
            pos += n
        state = {}
        for role in roles:
            state[role] = args[pos:pos + n]
            pos += n
        vec = {}
        for name in vec_names:
            vec[name] = args[pos]
            pos += 1
        rescale = args[pos]
        ins = dict(state)
        if mp:
            ins["w"] = list(w32)
            ins["g"] = [g.astype("float32") for g in gs]
        else:
            ins["w"] = list(ws)
            ins["g"] = list(gs)
        new = packed_apply(family, static, shapes, ins, vec, rescale,
                           low_dtype=wdtype if mp else None,
                           platform=platform)
        outs = list(new["w_low"] if mp else new["w"])
        if mp:
            outs += list(new["w"])
        for role in roles:
            outs += list(new[role])
        return tuple(outs)

    return jax.jit(sweep)


def _sweep_jitted(family, static_items, bucket, state_dtypes, vec_names,
                  platform, record=True):
    """Cache-spine lookup for one bucket signature: hit returns the live
    jitted sweep; miss builds it and journals the signature so
    ``warm_start`` can replay it in a fresh process with no provider."""
    cache = sweep_cache()
    key = _sweep_key(family, static_items, bucket, state_dtypes,
                     vec_names, len(bucket.members), platform)
    fn = cache.lookup(key, record=record)
    if fn is not cache.MISS:
        return fn
    fn = _build_sweep_fn(family, static_items, bucket.shapes,
                         bucket.wdtype, bucket.gdtype, bucket.mp,
                         state_dtypes, vec_names, platform)
    cache.insert(key, fn)
    from .. import compiler

    compiler.record_signature(_SWEEP_SITE, {
        "family": family, "static": tuple(static_items),
        "shapes": tuple(bucket.shapes), "wdtype": bucket.wdtype,
        "gdtype": bucket.gdtype, "mp": bucket.mp,
        "state_dtypes": tuple(state_dtypes),
        "vec_names": tuple(vec_names), "platform": platform,
        "routing": compiler.routing_knobs()})
    return fn


class _EagerPlan(NamedTuple):
    """A validated per-updater sweep plan: the family, its static
    hyperparam items, and per-bucket ``(Bucket, state_nds)`` pairs
    (``state_nds``: per member, ``[w32?] + live role leaf NDArrays``)."""

    family: str
    static_items: tuple
    buckets: tuple


def plan_eager(optimizer, updater, items):
    """Build the validated sweep plan for one context's updater, or
    None when the per-param loop must run (unknown family, knob off,
    foreign state layout).

    Creates missing updater states (the lazy ``Updater.__call__``
    contract — save/load_states payloads unchanged) but mutates NOTHING
    else: no counts advance, no weights move. The Trainer pre-flights
    EVERY context through this before :func:`apply_eager_plan` touches
    any of them — a mid-loop fallback after context 0 already swept
    would double-apply context 0's update in the per-param retry, so
    validation and application share THIS one plan structure.
    """
    family = family_of(optimizer)
    if family is None or not fused_sweep_enabled() or not items:
        return None
    import jax

    from ..ndarray import NDArray

    for i, w, _ in items:
        if i not in updater.states:
            updater.states[i] = \
                optimizer.create_state_multi_precision(i, w)
    static_items = family_static(optimizer, family)
    roles = state_roles(family, dict(static_items))
    entries = [(tuple(w.shape), str(w.dtype), str(g.dtype))
               for _, w, g in items]
    is_leaf = lambda x: x is None or isinstance(x, NDArray)
    plans = []
    for b in plan_buckets(entries, optimizer.multi_precision):
        state_nds = []   # per member: [w32?] + live role leaves
        for pos in b.members:
            leaves = jax.tree_util.tree_flatten(
                updater.states[items[pos][0]], is_leaf=is_leaf)[0]
            state_nds.append([lv for lv in leaves if lv is not None])
        expect = (1 if b.mp else 0) + len(roles)
        if any(len(lv) != expect for lv in state_nds):
            return None     # foreign state layout — per-param path
        plans.append((b, state_nds))
    return _EagerPlan(family, static_items, tuple(plans))


def eager_fused_update(optimizer, updater, items) -> bool:
    """Fused optimizer phase for the eager Trainer path: plan + apply.

    ``items``: list of ``(index, weight_nd, grad_nd)`` — one context's
    view of every dense trainable param. Returns False (caller falls
    back to the per-param loop) when :func:`plan_eager` rejects.
    Multi-context callers should plan every context first and then
    apply (see Trainer._fused_update).
    """
    plan = plan_eager(optimizer, updater, items)
    if plan is None:
        return False
    apply_eager_plan(optimizer, plan, items)
    return True


def apply_eager_plan(optimizer, plan, items) -> None:
    """Apply a validated :func:`plan_eager` plan: advance the update
    counts, then ONE jitted packed sweep per dtype bucket."""
    from .. import telemetry

    family = plan.family
    static_items = plan.static_items
    roles = state_roles(family, dict(static_items))

    # count advance for ALL indices before scalar prep; with the
    # standard every-param-every-step loop this is order-identical to
    # the per-param path (each index's t is its own count either way)
    for i, _, _ in items:
        optimizer._update_count(i)

    state_bytes = 0
    for b, state_nds in plan.buckets:
        ks = [items[pos][0] for pos in b.members]
        ws = [items[pos][1] for pos in b.members]
        gs = [items[pos][2] for pos in b.members]
        vecs = collect_scalars(optimizer, family, ks)
        vec_names = sorted(vecs)
        state_dtypes = tuple(str(lv.dtype)
                             for lv in (state_nds[0] if state_nds else ()))
        from ..base import current_execution_platform

        platform = current_execution_platform(ws[0].data)
        fn = _sweep_jitted(family, static_items, b, state_dtypes,
                           vec_names, platform)
        args = [w.data for w in ws] + [g.data for g in gs]
        if b.mp:
            args += [lv[0].data for lv in state_nds]
            base = [lv[1:] for lv in state_nds]
        else:
            base = state_nds
        for ri in range(len(roles)):
            args += [lv[ri].data for lv in base]
        args += [_as_vec(vecs[name]) for name in vec_names]
        args.append(_np.float32(optimizer.rescale_grad))
        outs = fn(*args)
        n = len(b.members)
        pos = 0
        for j, w in enumerate(ws):
            w._set_data(outs[pos + j])
        pos += n
        if b.mp:
            for j, lv in enumerate(state_nds):
                lv[0]._set_data(outs[pos + j])
            pos += n
        for ri in range(len(roles)):
            for j, lv in enumerate(base):
                lv[ri]._set_data(outs[pos + j])
            pos += n
        nbytes = sum(int(_np.prod(s or (1,))) for s in b.shapes) \
            * _np.dtype(b.wdtype).itemsize
        state_bytes += len(roles) * nbytes
        telemetry.record_optimizer_dispatch(
            "fused_sweep", getattr(fn, "n_dispatches", 1))
        telemetry.record_optimizer_bucket(nbytes, len(b.members))
    # per-rank optimizer-state footprint of the replicated sweep — the
    # baseline the ZeRO gauge (mode="zero1"/"zero2") is compared against
    telemetry.record_optimizer_state_bytes("replicated", state_bytes)


# ---------------------------------------------------------------------------
# warm-start replay (compiler.warm_start's optimizer_sweep hook)
# ---------------------------------------------------------------------------


def warm_sweep_spec(spec: dict) -> str:
    """Rebuild + AOT-compile one recorded sweep signature so the first
    real ``Trainer.step`` in this process is a pure cache hit. Needs no
    provider — the spec fully determines the traced body."""
    import jax

    family = spec.get("family")
    if family not in _FAMILIES:
        return "skipped"
    if not fused_sweep_enabled():
        # knob off in THIS process: the consumers will never look these
        # executables up — don't pay their compiles at cold start
        return "skipped"
    shapes = tuple(tuple(s) for s in spec["shapes"])
    static_items = tuple(tuple(kv) for kv in spec["static"])
    vec_names = tuple(spec.get("vec_names", ()))
    state_dtypes = tuple(spec.get("state_dtypes", ()))
    platform = spec.get("platform")
    b = Bucket(tuple(range(len(shapes))), shapes, spec["wdtype"],
               spec["gdtype"], bool(spec["mp"]))
    cache = sweep_cache()
    key = _sweep_key(family, static_items, b, state_dtypes, vec_names,
                     len(shapes), platform)
    hit = cache.lookup(key, record=False)
    if hit is not cache.MISS:
        return "deduped"
    fn = _build_sweep_fn(family, static_items, shapes, spec["wdtype"],
                         spec["gdtype"], bool(spec["mp"]), state_dtypes,
                         vec_names, platform)
    # drive the compile at the recorded avals (zero-filled structs)
    n = len(shapes)
    roles = state_roles(family, dict(static_items))
    sds = []
    for dt in (spec["wdtype"], spec["gdtype"]):
        sds += [jax.ShapeDtypeStruct(s, _np.dtype(dt)) for s in shapes]
    if spec["mp"]:
        sds += [jax.ShapeDtypeStruct(s, _np.float32) for s in shapes]
        sd_states = state_dtypes[1:]
    else:
        sd_states = state_dtypes
    for ri, _ in enumerate(roles):
        dt = sd_states[ri] if ri < len(sd_states) else "float32"
        sds += [jax.ShapeDtypeStruct(s, _np.dtype(dt)) for s in shapes]
    for _ in vec_names:
        sds.append(jax.ShapeDtypeStruct((n,), _np.float32))
    sds.append(jax.ShapeDtypeStruct((), _np.float32))
    try:
        from ..base import execution_platform

        with execution_platform(platform):
            if hasattr(fn, "warm_lower"):
                fn.warm_lower(sds)
            else:
                fn.lower(*sds).compile()
    except Exception:
        return "failed"
    cache.insert(key, fn)
    return "replayed"
