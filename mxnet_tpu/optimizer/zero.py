"""ZeRO-sharded optimizer state over the bucketed collective seam.

The replicated data-parallel step keeps a full copy of every optimizer
state tensor on every rank, so the largest trainable model is capped by
one chip's HBM (ROADMAP item 2; Rajbhandari et al., "ZeRO: Memory
Optimizations Toward Training Trillion Parameter Models", SC'20). This
module shards that state across data-parallel ranks on the EXISTING
seams — the kvstore bucket planner (``plan_buckets(partition=...)``)
and the multi-tensor fused sweep's elementwise formulas — instead of
introducing a new trainer:

* **zero1** — optimizer state is sharded; the fused allreduce becomes
  ``lax.psum_scatter`` (each rank reduces only its contiguous shard of
  the flat bucket), the sweep updates the local shard, and
  ``lax.all_gather`` broadcasts the updated weights back. The fully
  reduced gradient is also gathered and written back into ``p.grad()``
  so post-step gradient inspection matches the replicated path.
* **zero2** — same, but the gathered gradient write-back is skipped:
  each rank keeps only its reduced shard (gradients outside the local
  shard are never materialized reduced).

Bit-identity contract: XLA's ``psum_scatter`` + ``all_gather`` produce
the same bits as the fused ``psum`` (same reduction tree — asserted
empirically by ``tests/test_zero.py`` and ``tools/comms_bench.py``
stage 5), the shard carve is pure indexing, and the shard update runs
the *same* elementwise formulas (``_sgd_elem`` / ``_adam_elem`` /
``_adamw_elem``) the replicated fused sweep runs — elementwise math on
a contiguous slice is bit-equal to the same slice of the full-buffer
sweep. So zero1/zero2 training trajectories are bit-identical to the
replicated baseline.

Hierarchical composition: the collective axes come from the kvstore's
``_mesh_over`` factorization — under ``set_topology(hosts)`` /
``MXNET_KV_HOSTS`` the same ``psum_scatter``/``all_gather`` run as
multi-axis collectives over the ("dcn", "ici") mesh, and multi-axis
reduce keeps the combined-psum bit pattern (shard order follows the
linearized mesh index).

Two execution modes:

* **mesh mode** — more than one in-process gradient copy (multi-context
  trainer on a collective ``tpu_sync`` store): world = number of
  copies, real reduce-scatter over the device mesh.
* **virtual mode** — single context with an explicit (rank, world)
  identity (``reconfigure``, ``MXNET_ZERO_RANK``/``MXNET_ZERO_WORLD``):
  the update itself is local full-buffer (elementwise ⇒ bit-equal to
  shard-wise), but *serialization* is sharded — ``export_state`` emits
  only the owned shard, so checkpoint bundles carry per-rank shard
  files and rejoin must gather + re-shard. This is the mode
  ``ElasticRunner`` exercises, and ``import_state`` re-shards a payload
  saved at world N into a trainer running at world M (member-level
  remap through the flat-bucket layout).
"""
from __future__ import annotations

import os
import warnings
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as _np

from .. import telemetry
from ..base import MXNetError
from ..kvstore.bucketing import (PARTITION_MODES, ShardPlan,
                                 bucket_cap_bytes, plan_buckets)
from . import multi_tensor as mt

__all__ = ["PartitionMismatchError", "ZeroEngine", "supported_family",
           "FALLBACK_FAMILY", "FALLBACK_MULTI_PRECISION", "FALLBACK_SPARSE"]

STATE_VERSION = 1

# fallback-counter reasons (mxnet_kvstore_bucket_fallback_total{reason})
FALLBACK_FAMILY = "zero_family"
FALLBACK_MULTI_PRECISION = "zero_multi_precision"
FALLBACK_SPARSE = "zero_sparse"

_ELEM_FNS = {"sgd": mt._sgd_elem, "adam": mt._adam_elem,
             "adamw": mt._adamw_elem}


class PartitionMismatchError(MXNetError):
    """Sharded optimizer state loaded at an incompatible partition plan
    (wrong mode/world/bucket layout, or sharded↔replicated mismatch).
    The message names both plans; use ``Trainer.load_states_resharded``
    / elastic rejoin to re-shard across world sizes on purpose."""


def supported_family(optimizer) -> Optional[str]:
    """The fused-sweep family name if this optimizer's update can run
    sharded, else None. LAMB is excluded: its trust-ratio norms are
    cross-member reductions over the whole bucket, which a shard-local
    sweep cannot reproduce bit-identically."""
    family = mt.family_of(optimizer)
    if family in ("sgd", "adam", "adamw"):
        return family
    return None


def _plan_digest(plan_table, mode, world) -> str:
    nparams = sum(len(b["members"]) for b in plan_table)
    return f"{mode}@world={world}:{len(plan_table)}buckets/{nparams}params"


def _sizes_offsets(shapes):
    sizes = []
    for s in shapes:
        n = 1
        for d in s:
            n *= int(d)
        sizes.append(n)
    offsets = [0]
    for n in sizes:
        offsets.append(offsets[-1] + n)
    return sizes, offsets


class _BucketState:
    """One planned ZeRO bucket: layout + persistent sharded state."""

    __slots__ = ("indices", "shapes", "sizes", "offsets", "wdtype",
                 "gdtype", "plan", "nbytes", "states", "fn", "unstitch")

    def __init__(self, indices, shapes, wdtype, gdtype, plan, nbytes):
        self.indices: List[int] = list(indices)
        self.shapes: List[Tuple[int, ...]] = [tuple(s) for s in shapes]
        self.sizes, self.offsets = _sizes_offsets(self.shapes)
        self.wdtype = wdtype
        self.gdtype = gdtype
        self.plan: ShardPlan = plan
        self.nbytes = int(nbytes)
        self.states: Dict[str, object] = {}      # role -> jax array
        self.fn = None                           # jitted sweep
        self.unstitch = None                     # jitted flat->members

    @property
    def total(self):
        return self.offsets[-1]


class ZeroEngine:
    """Shard-partitioned optimizer sweep bound to one Trainer.

    Owns the partitioned buckets' persistent state arrays, the jitted
    reduce-scatter/update/allgather dispatch, and the sharded
    serialization (:meth:`export_state` / :meth:`import_state`).
    """

    def __init__(self, trainer, mode: str, rank: Optional[int] = None,
                 world: Optional[int] = None):
        if mode not in PARTITION_MODES:
            raise MXNetError(
                f"unknown partition mode {mode!r}; expected one of "
                f"{PARTITION_MODES}")
        self._trainer = trainer
        self._mode = mode
        self._family = supported_family(trainer._optimizer)
        if self._family is None:
            raise MXNetError(
                f"partition={mode!r} requires a fused-sweep optimizer "
                f"family (sgd/adam/adamw); got "
                f"{type(trainer._optimizer).__name__}")
        self._explicit_rank = rank
        self._explicit_world = world
        self._ready = False
        self._mesh_mode = False
        self._rank = 0
        self._world = 1
        self._mesh = None
        self._devs: Tuple = ()
        self._buckets: List[_BucketState] = []
        self._fallback: Dict[int, str] = {}      # param idx -> reason
        self._virtual_fns: Dict[Tuple, object] = {}

    # -- identity ----------------------------------------------------------

    @property
    def mode(self) -> str:
        return self._mode

    @property
    def rank(self) -> int:
        return self._rank

    @property
    def world(self) -> int:
        return self._world

    @property
    def fallback_reasons(self) -> Dict[int, str]:
        """param index -> reason for params outside the sharded sweep."""
        self.ensure_ready()
        return dict(self._fallback)

    def eligible_indices(self) -> List[int]:
        self.ensure_ready()
        out: List[int] = []
        for b in self._buckets:
            out.extend(b.indices)
        return sorted(out)

    # -- planning ----------------------------------------------------------

    def _resolve_identity(self):
        """Pick mesh vs virtual mode and the (rank, world) identity."""
        import jax

        trainer = self._trainer
        ncopies = len(trainer._contexts)
        if jax.process_count() > 1:
            raise MXNetError(
                "multi-process ZeRO partitioning is not supported yet; "
                "run one context per process and re-shard through the "
                "elastic virtual mode")
        if ncopies > 1:
            store = trainer._kvstore
            if store is None or not hasattr(store, "_mesh_over"):
                raise MXNetError(
                    f"partition={self._mode!r} with {ncopies} contexts "
                    "requires a collective kvstore (tpu_sync); got "
                    f"{type(store).__name__ if store else None}")
            if self._explicit_world not in (None, ncopies):
                raise MXNetError(
                    f"explicit partition world {self._explicit_world} "
                    f"conflicts with {ncopies} gradient copies (mesh "
                    "mode shards across the copies)")
            self._mesh_mode = True
            self._world = ncopies
            self._rank = 0           # all shards are process-local
            return
        # virtual: explicit args > env > single-rank default
        world = self._explicit_world
        rank = self._explicit_rank
        if world is None:
            world = int(os.environ.get("MXNET_ZERO_WORLD", "1") or 1)
        if rank is None:
            rank = int(os.environ.get("MXNET_ZERO_RANK", "0") or 0)
        world = int(world)
        rank = int(rank)
        if world < 1 or not (0 <= rank < world):
            raise MXNetError(
                f"invalid partition identity rank={rank} world={world}")
        self._mesh_mode = False
        self._world = world
        self._rank = rank

    def _classify(self):
        """Split trainer params into sharded-sweep members and fallback
        (reason-tagged) leftovers. Mirrors the fused-sweep eligibility
        gates in ``multi_tensor.plan_eager``."""
        trainer = self._trainer
        opt = trainer._optimizer
        eligible: List[int] = []
        fallback: Dict[int, str] = {}
        for i, p in enumerate(trainer._params):
            if p.grad_req == "null":
                continue
            stype = getattr(p, "_stype", "default")
            gstype = getattr(p, "grad_stype", "default")
            if stype != "default" or gstype != "default":
                fallback[i] = FALLBACK_SPARSE
                continue
            if getattr(opt, "multi_precision", False) and \
                    str(p.dtype) in ("float16", "bfloat16"):
                fallback[i] = FALLBACK_MULTI_PRECISION
                continue
            eligible.append(i)
        return eligible, fallback

    def ensure_ready(self) -> None:
        """Plan buckets, allocate sharded state, build dispatch fns.
        Idempotent; called lazily once params are initialized."""
        if self._ready:
            return
        import jax

        self._resolve_identity()
        trainer = self._trainer
        eligible, self._fallback = self._classify()
        if self._fallback:
            by_reason: Dict[str, int] = {}
            for reason in self._fallback.values():
                by_reason[reason] = by_reason.get(reason, 0) + 1
            for reason, n in sorted(by_reason.items()):
                telemetry.record_kv_bucket_fallback(reason, n)
            warnings.warn(
                f"{len(self._fallback)} parameter(s) fell outside the "
                f"ZeRO sharded sweep "
                f"({', '.join(f'{r}:{n}' for r, n in sorted(by_reason.items()))}) "
                "— they update replicated through the per-param path",
                stacklevel=3)

        params = trainer._params
        ctxs = trainer._contexts
        if eligible:
            dev_src = params[eligible[0]].list_data()
            self._devs = tuple(next(iter(a.data.devices()))
                               for a in dev_src)
        if self._mesh_mode:
            store = trainer._kvstore
            self._mesh = store._mesh_over(list(self._devs))

        store = trainer._kvstore
        cap = getattr(store, "_bucket_bytes", None) if store else None
        if not cap:
            cap = bucket_cap_bytes()
        entries = []
        for i in eligible:
            p = params[i]
            shape = tuple(int(d) for d in p.shape)
            wdt = _np.dtype(p.dtype)
            gdt = _np.dtype(p.list_grad()[0].dtype)
            n = 1
            for d in shape:
                n *= d
            entries.append((i, shape, str(wdt),
                            (str(wdt), str(gdt)), n * gdt.itemsize))
        raw = plan_buckets(entries, cap, partition=self._mode,
                           world=self._world)
        self._buckets = []
        for b in raw:
            wdt = _np.dtype(b.group[0])
            gdt = _np.dtype(b.group[1])
            self._buckets.append(_BucketState(
                b.indices, b.shapes, wdt, gdt, b.shard_plan, b.nbytes))
        for bs in self._buckets:
            self._init_states(bs)
        self._record_state_bytes()
        self._ready = True

    def _record_state_bytes(self) -> None:
        roles = self._roles()
        per_rank = 0
        replicated = 0
        for bs in self._buckets:
            isz = bs.wdtype.itemsize
            per_rank += len(roles) * bs.plan.shard_len * isz
            replicated += len(roles) * bs.total * isz
        telemetry.record_optimizer_state_bytes(self._mode, per_rank)
        telemetry.record_optimizer_state_bytes("replicated", replicated)
        self._state_bytes = (per_rank, replicated)

    def _roles(self) -> Tuple[str, ...]:
        static = dict(mt.family_static(self._trainer._optimizer,
                                       self._family))
        return mt.state_roles(self._family, static)

    def _static_items(self) -> tuple:
        return mt.family_static(self._trainer._optimizer, self._family)

    def _init_states(self, bs: _BucketState) -> None:
        import jax

        roles = self._roles()
        if not roles:
            return
        if self._mesh_mode:
            from jax.sharding import NamedSharding, PartitionSpec as P

            axes = tuple(self._mesh.axis_names)
            sharding = NamedSharding(self._mesh, P(axes))
            zero = _np.zeros(bs.plan.shard_len, bs.wdtype)
            for role in roles:
                shards = [jax.device_put(zero, d)
                          for d in self._mesh.devices.flat]
                bs.states[role] = \
                    jax.make_array_from_single_device_arrays(
                        (bs.plan.padded,), sharding, shards)
        else:
            dev = self._devs[0] if self._devs else None
            zero = _np.zeros(bs.plan.padded, bs.wdtype)
            for role in roles:
                bs.states[role] = jax.device_put(zero, dev) \
                    if dev is not None else jax.numpy.asarray(zero)

    # -- jitted dispatch ---------------------------------------------------

    def _unstitch_fn(self, bs: _BucketState):
        """Jitted padded-flat -> per-member arrays (pad dropped)."""
        if bs.unstitch is None:
            import jax

            segs = list(zip(bs.shapes, bs.offsets[:-1], bs.offsets[1:]))

            def unstitch(flat):
                return tuple(
                    flat[o:o2].reshape(shape if shape else ())
                    for shape, o, o2 in segs)

            bs.unstitch = jax.jit(unstitch)
        return bs.unstitch

    def _build_mesh_fn(self, bs: _BucketState, vec_names):
        import jax
        import jax.numpy as jnp
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        mesh = self._mesh
        axes = tuple(mesh.axis_names)
        ax_sizes = [mesh.shape[a] for a in axes]
        family = self._family
        static = dict(self._static_items())
        roles = self._roles()
        elem = _ELEM_FNS[family]
        shard_len = bs.plan.shard_len
        padded = bs.plan.padded
        total = bs.total
        wdt = bs.wdtype
        sizes = _np.asarray(bs.sizes, _np.int64)
        segs = list(zip(bs.offsets[:-1], bs.offsets[1:]))
        gather_grads = (self._mode == "zero1")
        nr, nv = len(roles), len(vec_names)

        def body(gstk, wstk, *ops):
            states = ops[:nr]
            vecs = ops[nr:nr + nv]
            rescale = jnp.asarray(ops[-1], jnp.float32)
            # reduce-scatter: each rank sums only its shard (tiled
            # multi-axis psum_scatter keeps the combined-psum bits —
            # the load-bearing bit-identity fact, see module docstring)
            g_shard = jax.lax.psum_scatter(
                gstk[0], axes, scatter_dimension=0, tiled=True)
            idx = 0
            for a, s in zip(axes, ax_sizes):
                idx = idx * s + jax.lax.axis_index(a)
            off = idx * shard_len
            w_shard = jax.lax.dynamic_slice(wstk[0], (off,), (shard_len,))
            env = {"w": w_shard, "g": g_shard, "rescale": rescale}
            for role, s in zip(roles, states):
                env[role] = s
            for name, v in zip(vec_names, vecs):
                env[name] = v
            g_full = None
            if family == "adamw" or gather_grads:
                # all_gather of the scattered shards == the fused psum
                # bits (verified), so the gathered grad is exactly the
                # replicated reduced gradient
                g_full = jax.lax.all_gather(
                    g_shard, axes, axis=0, tiled=True)
            if family == "adamw":
                # per-member AMP overflow scan needs the FULL reduced
                # grad (isfinite is a cross-shard member reduction)
                g32 = g_full.astype(jnp.float32) * rescale
                clip = static["clip_gradient"]
                if clip is not None and clip >= 0:
                    g32 = jnp.clip(g32, -clip, clip)
                oks = [jnp.isfinite(g32[o:o2]).all() for o, o2 in segs]
                ok_el = jnp.repeat(jnp.stack(oks).astype(jnp.float32),
                                   sizes, total_repeat_length=total)
                if padded > total:
                    ok_el = jnp.concatenate(
                        [ok_el, jnp.zeros(padded - total, jnp.float32)])
                env["ok"] = jax.lax.dynamic_slice(
                    ok_el, (off,), (shard_len,))
            new = elem(env, static)
            new_w = new["w"].astype(wdt)
            w_full = jax.lax.all_gather(new_w, axes, axis=0, tiled=True)
            outs = [w_full] + [new[r].astype(wdt) for r in roles]
            if gather_grads:
                outs.append(g_full)
            return tuple(outs)

        in_specs = (P(axes), P(axes)) + (P(axes),) * (nr + nv) + (P(),)
        out_specs = (P(),) + (P(axes),) * nr
        if gather_grads:
            out_specs = out_specs + (P(),)
        return jax.jit(shard_map(
            body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False))

    def _build_virtual_fn(self, bs: _BucketState, vec_names):
        import jax
        import jax.numpy as jnp

        family = self._family
        static = dict(self._static_items())
        roles = self._roles()
        elem = _ELEM_FNS[family]
        padded = bs.plan.padded
        total = bs.total
        wdt = bs.wdtype
        sizes = _np.asarray(bs.sizes, _np.int64)
        segs = list(zip(bs.offsets[:-1], bs.offsets[1:]))
        nr, nv = len(roles), len(vec_names)

        def body(g, w, *ops):
            states = ops[:nr]
            vecs = ops[nr:nr + nv]
            rescale = jnp.asarray(ops[-1], jnp.float32)
            env = {"w": w, "g": g, "rescale": rescale}
            for role, s in zip(roles, states):
                env[role] = s
            for name, v in zip(vec_names, vecs):
                env[name] = v
            if family == "adamw":
                g32 = g.astype(jnp.float32) * rescale
                clip = static["clip_gradient"]
                if clip is not None and clip >= 0:
                    g32 = jnp.clip(g32, -clip, clip)
                oks = [jnp.isfinite(g32[o:o2]).all() for o, o2 in segs]
                ok_el = jnp.repeat(jnp.stack(oks).astype(jnp.float32),
                                   sizes, total_repeat_length=total)
                if padded > total:
                    ok_el = jnp.concatenate(
                        [ok_el, jnp.zeros(padded - total, jnp.float32)])
                env["ok"] = ok_el
            new = elem(env, static)
            outs = [new["w"].astype(wdt)] + \
                [new[r].astype(wdt) for r in roles]
            return tuple(outs)

        return jax.jit(body)

    def _pad_fn(self, total, padded, dtype):
        import jax
        import jax.numpy as jnp

        key = ("pad", total, padded, str(dtype))
        fn = self._virtual_fns.get(key)
        if fn is None:
            if padded > total:
                fn = jax.jit(lambda x: jnp.concatenate(
                    [x, jnp.zeros(padded - total, x.dtype)]))
            else:
                fn = jax.jit(lambda x: x)
            self._virtual_fns[key] = fn
        return fn

    # -- the step ----------------------------------------------------------

    def step(self) -> None:
        """Run the sharded sweep over every partitioned bucket. Advances
        the optimizer's per-index update clock exactly once per step
        (the engine replaces BOTH the allreduce and the per-context
        update loop for its members)."""
        self.ensure_ready()
        opt = self._trainer._optimizer
        params = self._trainer._params
        # clock first, then scalar collection — mirrors apply_eager_plan.
        # Tick EVERY device stream (leftover per-param members tick
        # theirs in the trainer loop): streams stay pairwise equal, so
        # a later state dump reads the same clock from any of them.
        nstreams = max(1, len(self._trainer._updaters or ()))
        for ci in range(nstreams):
            opt._set_current_context(ci)
            for bs in self._buckets:
                for i in bs.indices:
                    opt._update_count(i)
        opt._set_current_context(0)
        for bs in self._buckets:
            vecs = mt.collect_scalars(opt, self._family, bs.indices)
            vec_names = sorted(vecs)
            if self._mesh_mode:
                self._step_mesh(bs, vecs, vec_names, params)
            else:
                self._step_virtual(bs, vecs, vec_names, params)
            telemetry.record_optimizer_dispatch("zero_sweep", 1)
            telemetry.record_optimizer_bucket(bs.nbytes, len(bs.indices))

    def _vec_el(self, bs: _BucketState, vecs, vec_names):
        out = []
        for name in vec_names:
            v = _np.repeat(_np.asarray(vecs[name], _np.float32),
                           bs.sizes)
            if bs.plan.padded > bs.total:
                v = _np.concatenate(
                    [v, _np.zeros(bs.plan.padded - bs.total,
                                  _np.float32)])
            out.append(v)
        return out

    def _step_mesh(self, bs, vecs, vec_names, params) -> None:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..kvstore.bucketing import pack

        mesh = self._mesh
        axes = tuple(mesh.axis_names)
        devs = list(mesh.devices.flat)
        pad = self._pad_fn(bs.total, bs.plan.padded, bs.gdtype)
        padw = self._pad_fn(bs.total, bs.plan.padded, bs.wdtype)
        gslots = []
        wslots = []
        for ci in range(len(devs)):
            garrs = [params[i].list_grad()[ci].data for i in bs.indices]
            warrs = [params[i].list_data()[ci].data for i in bs.indices]
            gslots.append(pad(pack(garrs)).reshape(1, bs.plan.padded))
            wslots.append(padw(pack(warrs)).reshape(1, bs.plan.padded))
        sharding = NamedSharding(mesh, P(axes))
        gstk = jax.make_array_from_single_device_arrays(
            (len(devs), bs.plan.padded), sharding, gslots)
        wstk = jax.make_array_from_single_device_arrays(
            (len(devs), bs.plan.padded), sharding, wslots)
        if bs.fn is None:
            bs.fn = self._build_mesh_fn(bs, vec_names)
        roles = self._roles()
        args = [gstk, wstk] + [bs.states[r] for r in roles] + \
            self._vec_el(bs, vecs, vec_names) + \
            [_np.float32(self._trainer._optimizer.rescale_grad)]
        outs = bs.fn(*args)
        w_full = outs[0]
        for k, role in enumerate(roles):
            bs.states[role] = outs[1 + k]
        telemetry.record_kv_collective("zero")
        unstitch = self._unstitch_fn(bs)
        self._scatter(bs, w_full, devs,
                      lambda i, ci: params[i].list_data()[ci], unstitch)
        if self._mode == "zero1":
            g_full = outs[-1]
            self._scatter(bs, g_full, devs,
                          lambda i, ci: params[i].list_grad()[ci],
                          unstitch)

    def _scatter(self, bs, arr, devs, nd_of, unstitch) -> None:
        """Write a replicated (padded,) result back into the per-context
        NDArrays — per-device shard data in, so outputs stay committed
        to the right device."""
        by_dev = {s.device: s.data for s in arr.addressable_shards}
        for ci, d in enumerate(devs):
            pieces = unstitch(by_dev[d])
            for i, piece in zip(bs.indices, pieces):
                nd_of(i, ci)._set_data(piece)

    def _step_virtual(self, bs, vecs, vec_names, params) -> None:
        from ..kvstore.bucketing import pack

        pad = self._pad_fn(bs.total, bs.plan.padded, bs.gdtype)
        padw = self._pad_fn(bs.total, bs.plan.padded, bs.wdtype)
        g = pad(pack([params[i].list_grad()[0].data
                      for i in bs.indices]))
        w = padw(pack([params[i].list_data()[0].data
                       for i in bs.indices]))
        if bs.fn is None:
            bs.fn = self._build_virtual_fn(bs, vec_names)
        roles = self._roles()
        args = [g, w] + [bs.states[r] for r in roles] + \
            self._vec_el(bs, vecs, vec_names) + \
            [_np.float32(self._trainer._optimizer.rescale_grad)]
        outs = bs.fn(*args)
        for k, role in enumerate(roles):
            bs.states[role] = outs[1 + k]
        pieces = self._unstitch_fn(bs)(outs[0])
        for i, piece in zip(bs.indices, pieces):
            params[i].list_data()[0]._set_data(piece)

    # -- elastic re-identity ----------------------------------------------

    def reconfigure(self, rank: int, world: int) -> None:
        """Adopt a new (rank, world) identity — virtual mode only (the
        state is full locally; only the serialization carve changes).
        Used by elastic rejoin when membership changes."""
        self.ensure_ready()
        rank, world = int(rank), int(world)
        if self._mesh_mode:
            if world != self._world:
                raise MXNetError(
                    f"cannot reconfigure a mesh-mode partition (world "
                    f"{self._world}) to world {world}")
            return
        if world < 1 or not (0 <= rank < world):
            raise MXNetError(
                f"invalid partition identity rank={rank} world={world}")
        if world == self._world and rank == self._rank:
            return
        self._rank, self._world = rank, world
        from ..kvstore.bucketing import shard_layout

        for bs in self._buckets:
            old = bs.plan
            bs.plan = shard_layout(self._mode, bs.total, world)
            if bs.plan.padded != old.padded:
                # padded length changed: re-pad the full state buffers
                # (tail is zeros — inert) and drop layout-bound jits
                import jax
                import numpy as np

                for role in list(bs.states):
                    full = np.asarray(bs.states[role])[:bs.total]
                    buf = np.zeros(bs.plan.padded, bs.wdtype)
                    buf[:bs.total] = full
                    dev = self._devs[0] if self._devs else None
                    bs.states[role] = jax.device_put(buf, dev) \
                        if dev is not None else jax.numpy.asarray(buf)
                bs.fn = None
        self._record_state_bytes()

    # -- serialization -----------------------------------------------------

    def describe(self) -> str:
        self.ensure_ready()
        return _plan_digest(self._plan_table(), self._mode, self._world)

    def _plan_table(self):
        table = []
        for bs in self._buckets:
            table.append({
                "members": list(bs.indices),
                "shapes": [list(s) for s in bs.shapes],
                "wdtype": str(bs.wdtype),
                "total": bs.total,
                "padded": bs.plan.padded,
                "shard_len": bs.plan.shard_len,
            })
        return table

    def partition_manifest(self) -> dict:
        """Plan metadata (no tensors) for checkpoint manifests."""
        self.ensure_ready()
        return {
            "version": STATE_VERSION,
            "mode": self._mode,
            "world": self._world,
            "rank": self._rank,
            "family": self._family,
            "digest": self.describe(),
            "plan": self._plan_table(),
        }

    def _owned_ranks(self) -> List[int]:
        if self._mesh_mode or self._world == 1:
            return list(range(self._world))
        return [self._rank]

    def export_state(self, all_ranks: bool = False) -> dict:
        """Sharded state payload. Mesh mode owns every rank's shard
        (they are all process-local); virtual mode emits only the owned
        rank's shard unless ``all_ranks`` (possible because the virtual
        state buffer is full) — elastic bundles stay 1/world sized."""
        self.ensure_ready()
        roles = self._roles()
        owned = list(range(self._world)) if all_ranks \
            else self._owned_ranks()
        shards: Dict[int, Dict[int, Dict[str, object]]] = {}
        for bid, bs in enumerate(self._buckets):
            per_rank: Dict[int, Dict[str, object]] = {r: {}
                                                      for r in owned}
            for role in roles:
                arr = bs.states[role]
                if self._mesh_mode:
                    by_dev = {s.device: _np.asarray(s.data)
                              for s in arr.addressable_shards}
                    flat_devs = list(self._mesh.devices.flat)
                    for r in owned:
                        per_rank[r][role] = by_dev[flat_devs[r]]
                else:
                    full = _np.asarray(arr)
                    for r in owned:
                        lo, hi = bs.plan.shard_range(r)
                        per_rank[r][role] = full[lo:hi].copy()
            shards[bid] = per_rank
        opt = self._trainer._optimizer
        clock = {
            "num_update": int(opt.num_update),
            "index_update_count": {
                int(i): int(opt._index_update_count[i])
                for bs in self._buckets for i in bs.indices
                if i in opt._index_update_count},
        }
        return {
            "version": STATE_VERSION,
            "mode": self._mode,
            "world": self._world,
            "family": self._family,
            "roles": list(roles),
            "plan": self._plan_table(),
            "owned": owned,
            "clock": clock,
            "shards": shards,
        }

    def check_compatible(self, payload: dict) -> None:
        """Raise :class:`PartitionMismatchError` unless ``payload`` was
        exported at exactly this engine's partition plan (strict
        ``Trainer.load_states`` contract — re-sharding is the explicit
        ``import_state``/elastic path, never an accident)."""
        self.ensure_ready()
        src = _plan_digest(payload.get("plan", []),
                           payload.get("mode"), payload.get("world"))
        cur = self.describe()
        if payload.get("mode") != self._mode or \
                int(payload.get("world", -1)) != self._world or \
                payload.get("plan") != self._plan_table():
            raise PartitionMismatchError(
                f"sharded optimizer state was saved under partition "
                f"plan [{src}] but this trainer runs plan [{cur}]; "
                "use Trainer.load_states_resharded / elastic rejoin to "
                "re-shard across plans")

    def import_state(self, payloads: Sequence[dict]) -> None:
        """Merge per-rank payloads (possibly saved at a DIFFERENT world
        size or bucket layout) and re-shard into the current plan.

        Requires full coverage of the source world: every rank
        0..src_world-1 must appear in some payload, else a typed error
        names the missing ranks. The remap runs at *member* level
        (param index -> flat vector) so any world/bucket-layout change
        re-shards losslessly; trailing pad is rebuilt as zeros.
        """
        self.ensure_ready()
        if not payloads:
            raise MXNetError("import_state: no payloads given")
        head = payloads[0]
        roles = self._roles()
        if head.get("family") != self._family:
            raise PartitionMismatchError(
                f"sharded state family {head.get('family')!r} does not "
                f"match this trainer's optimizer family "
                f"{self._family!r}")
        src_plan = head.get("plan")
        src_world = int(head.get("world", 0))
        for p in payloads[1:]:
            if p.get("plan") != src_plan or \
                    int(p.get("world", 0)) != src_world:
                raise PartitionMismatchError(
                    "import_state payloads disagree on the source "
                    "partition plan — they must all come from the same "
                    "checkpoint step")
        # source member map must cover exactly the current members
        src_members: Dict[int, Tuple[Tuple[int, ...], str]] = {}
        for b in src_plan:
            for i, s in zip(b["members"], b["shapes"]):
                src_members[int(i)] = (tuple(int(d) for d in s),
                                       b["wdtype"])
        cur_members = {int(i): (tuple(s), str(bs.wdtype))
                       for bs in self._buckets
                       for i, s in zip(bs.indices, bs.shapes)}
        if src_members != cur_members:
            raise PartitionMismatchError(
                f"sharded state members do not match this trainer: "
                f"saved {len(src_members)} member(s), trainer has "
                f"{len(cur_members)} — shapes/dtypes/indices must agree "
                "(same model) to re-shard")
        # merge shard fragments across payloads
        merged: Dict[int, Dict[int, Dict[str, object]]] = {}
        for p in payloads:
            for bid, per_rank in p.get("shards", {}).items():
                dst = merged.setdefault(int(bid), {})
                for r, role_map in per_rank.items():
                    dst.setdefault(int(r), role_map)
        # stitch each source bucket back to full member vectors
        member_state: Dict[int, Dict[str, object]] = {}
        for bid, b in enumerate(src_plan):
            per_rank = merged.get(bid, {})
            missing = [r for r in range(src_world) if r not in per_rank]
            if missing:
                raise PartitionMismatchError(
                    f"cannot re-shard optimizer state: source world "
                    f"{src_world} but shard(s) for rank(s) {missing} "
                    f"of bucket {bid} are missing — gather every "
                    "rank's bundle before rejoin")
            sizes, offsets = _sizes_offsets(
                [tuple(s) for s in b["shapes"]])
            for role in roles:
                full = _np.concatenate(
                    [_np.asarray(per_rank[r][role])
                     for r in range(src_world)])[:b["total"]]
                for i, o, o2 in zip(b["members"], offsets[:-1],
                                    offsets[1:]):
                    member_state.setdefault(int(i), {})[role] = \
                        full[o:o2]
        # repack into the current plan
        import jax

        for bs in self._buckets:
            for role in roles:
                full = _np.zeros(bs.plan.padded, bs.wdtype)
                off = 0
                for i, n in zip(bs.indices, bs.sizes):
                    full[off:off + n] = \
                        member_state[i][role].astype(bs.wdtype)
                    off += n
                if self._mesh_mode:
                    from jax.sharding import (NamedSharding,
                                              PartitionSpec as P)

                    axes = tuple(self._mesh.axis_names)
                    sl = bs.plan.shard_len
                    shards = [jax.device_put(full[r * sl:(r + 1) * sl],
                                             d)
                              for r, d in enumerate(
                                  self._mesh.devices.flat)]
                    bs.states[role] = \
                        jax.make_array_from_single_device_arrays(
                            (bs.plan.padded,),
                            NamedSharding(self._mesh, P(axes)), shards)
                else:
                    dev = self._devs[0] if self._devs else None
                    bs.states[role] = jax.device_put(full, dev) \
                        if dev is not None else jax.numpy.asarray(full)
        clock = head.get("clock") or {}
        opt = self._trainer._optimizer
        if clock:
            opt.num_update = max(int(opt.num_update),
                                 int(clock.get("num_update", 0)))
            for i, c in (clock.get("index_update_count") or {}).items():
                # mirror into the baseline so device streams created
                # after this restore resume the same clock
                opt._index_update_count[int(i)] = int(c)
                opt._count_baseline[int(i)] = int(c)
