"""Optimizers (reference: python/mxnet/optimizer/).

``multi_tensor`` holds the horizontally-fused multi-tensor sweep engine
(dtype-bucketed packed updates — reference: the ``multi_sgd_*`` /
``mp_lamb_*`` fused op family); imported lazily by its consumers
(Trainer, TrainStep, the multi_* ops), not at package import.
"""
from .optimizer import *  # noqa: F401,F403
from .optimizer import Optimizer, Updater, create, register, get_updater  # noqa: F401
