"""``mx.init`` alias for the initializer namespace
(reference: python/mxnet/initializer.py is exposed as both)."""
from .initializer import *  # noqa: F401,F403
from .initializer import Initializer, create, register  # noqa: F401
