"""mx.mod — legacy symbolic trainer API.

Reference: ``python/mxnet/module/`` — ``BaseModule.fit`` (the classic MXNet
training loop), ``Module`` (bind/init_params/init_optimizer/
forward/backward/update over per-device executors), ``BucketingModule``
(per-bucket executors sharing params — the variable-length answer).
TPU-native: one Executor (= one jitted fwd+bwd graph); the
DataParallelExecutorGroup's batch slicing collapses into mesh sharding
(mxnet_tpu.parallel), and buckets map onto the jit shape-cache.
"""
from .module import Module, BucketingModule, BaseModule, save_checkpoint, \
    load_checkpoint

__all__ = ["Module", "BucketingModule", "BaseModule", "save_checkpoint",
           "load_checkpoint"]
