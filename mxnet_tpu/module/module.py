"""Module / BucketingModule (reference: python/mxnet/module/)."""
from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional

import numpy as _np

from .. import initializer as init_mod
from .. import metric as metric_mod
from .. import optimizer as opt_mod
from ..base import MXNetError
from ..context import Context, cpu, current_context
from ..ndarray import NDArray, array as nd_array, zeros as nd_zeros
from ..ndarray.serialization import save as nd_save, load as nd_load
from ..symbol import Symbol
from ..symbol import load as sym_load


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params):
    """reference: python/mxnet/model.py::save_checkpoint — writes
    prefix-symbol.json + prefix-%04d.params (the deployment artifact)."""
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    payload = {}
    payload.update({f"arg:{k}": v for k, v in (arg_params or {}).items()})
    payload.update({f"aux:{k}": v for k, v in (aux_params or {}).items()})
    nd_save(f"{prefix}-{epoch:04d}.params", payload)


def load_checkpoint(prefix, epoch):
    """reference: model.py::load_checkpoint."""
    symbol = sym_load(f"{prefix}-symbol.json")
    payload = nd_load(f"{prefix}-{epoch:04d}.params")
    arg_params, aux_params = {}, {}
    for k, v in payload.items():
        if k.startswith("arg:"):
            arg_params[k[4:]] = v
        elif k.startswith("aux:"):
            aux_params[k[4:]] = v
        else:
            arg_params[k] = v
    return symbol, arg_params, aux_params


class BaseModule:
    """reference: module/base_module.py::BaseModule — fit/score/predict."""

    def __init__(self, logger=None):
        self.logger = logger or logging.getLogger(__name__)
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False

    # subclass surface: bind, init_params, init_optimizer, forward,
    # backward, update, get_outputs, update_metric

    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def install_monitor(self, mon):
        """reference: base_module.py::BaseModule.install_monitor — each
        module type registers its own executor(s) with the Monitor."""
        raise NotImplementedError()

    def score(self, eval_data, eval_metric, num_batch=None, reset=True,
              epoch=0):
        if reset:
            eval_data.reset()
        if isinstance(eval_metric, str):
            eval_metric = metric_mod.create(eval_metric)
        eval_metric.reset()
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(batch, is_train=False)
            self.update_metric(eval_metric, batch.label)
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, reset=True):
        if reset:
            eval_data.reset()
        outputs = []
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(batch, is_train=False)
            outs = self.get_outputs()
            pad = batch.pad or 0
            n = outs[0].shape[0] - pad
            outputs.append([o[:n] for o in outs])
        if not outputs:
            return []
        from ..ndarray import concat

        n_out = len(outputs[0])
        merged = []
        for i in range(n_out):
            parts = [row[i] for row in outputs]
            merged.append(concat(*parts, dim=0) if len(parts) > 1
                          else parts[0])
        return merged if n_out > 1 else merged[0]

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None):
        """reference: base_module.py::BaseModule.fit — the classic loop."""
        if num_epoch is None:
            raise MXNetError("num_epoch is required for fit")
        initializer = initializer or init_mod.Uniform(0.01)
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        if isinstance(eval_metric, str):
            eval_metric = metric_mod.create(eval_metric)
        validation_metric = validation_metric or eval_metric
        if monitor is not None:
            self.install_monitor(monitor)

        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            train_data.reset()
            for nbatch, data_batch in enumerate(train_data):
                if monitor is not None:
                    monitor.tic()
                self.forward_backward(data_batch)
                self.update()
                if monitor is not None:
                    monitor.toc_print()
                self.update_metric(eval_metric, data_batch.label)
                if batch_end_callback is not None:
                    param = _BatchEndParam(epoch=epoch, nbatch=nbatch,
                                           eval_metric=eval_metric,
                                           locals=locals())
                    for cb in _as_list(batch_end_callback):
                        cb(param)
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                             time.time() - tic)
            if epoch_end_callback is not None:
                arg_p, aux_p = self.get_params()
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg_p, aux_p)
            if eval_data is not None:
                res = self.score(eval_data, validation_metric, epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch,
                                     name, val)


class _BatchEndParam:
    def __init__(self, epoch, nbatch, eval_metric, locals):
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = eval_metric
        self.locals = locals


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


class Module(BaseModule):
    """reference: module/module.py::Module — a Symbol bound for training.

    TPU-native: ONE executor over the whole fwd+bwd graph; device lists
    collapse into the mesh (use mxnet_tpu.parallel for multi-chip)."""

    def __init__(self, symbol: Symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=None, context=None,
                 work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None,
                 compression_params=None):
        super().__init__(logger)
        self._symbol = symbol
        self._data_names = list(data_names or [])
        self._label_names = list(label_names or [])
        if isinstance(context, (list, tuple)):
            context = context[0]  # DP via ctx lists → use parallel.TrainStep
        self._context = context or current_context()
        self._fixed_param_names = set(fixed_param_names or [])
        arg_names = symbol.list_arguments()
        self._param_names = [
            n for n in arg_names
            if n not in self._data_names and n not in self._label_names]
        self._exec = None
        self._optimizer = None
        self._updater = None
        self._kvstore = None
        self._data_shapes = None
        self._label_shapes = None
        # set by Module.load: checkpointed params applied at init_params
        # time, optimizer states applied at init_optimizer time
        self._preloaded = None
        self._preloaded_states = None
        self._compression = None
        if compression_params is not None:
            # single-context Module has no wire, but the semantics (2-bit
            # quantized grads + error feedback) are honored in update()
            from ..kvstore.gradient_compression import create_compression

            self._compression = create_compression(compression_params)

    @property
    def symbol(self):
        return self._symbol

    @property
    def data_names(self):
        return self._data_names

    @property
    def output_names(self):
        return self._symbol.list_outputs()

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return list(zip(self.output_names,
                        [o.shape for o in self._exec.outputs]))

    # -- bind -----------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return
        shape_kwargs = {}
        self._data_shapes = data_shapes
        self._label_shapes = label_shapes
        for desc in data_shapes:
            name, shape = (desc[0], desc[1]) if isinstance(desc, tuple) \
                else (desc.name, desc.shape)
            shape_kwargs[name] = shape
        for desc in (label_shapes or []):
            name, shape = (desc[0], desc[1]) if isinstance(desc, tuple) \
                else (desc.name, desc.shape)
            shape_kwargs[name] = shape
        req = {}
        for n in self._symbol.list_arguments():
            if n in self._data_names or n in self._label_names or \
                    n in self._fixed_param_names:
                req[n] = "null"
            else:
                req[n] = grad_req if for_training else "null"
        self._exec = self._symbol.simple_bind(ctx=self._context,
                                              grad_req=req, **shape_kwargs)
        self.binded = True
        if self._preloaded is not None and not self.params_initialized:
            # Module.load semantics (reference: module.py::Module.load):
            # after load()+bind() the checkpointed params are live even if
            # the user never calls init_params explicitly. allow_missing
            # because a legacy checkpoint may lack aux entries — absent
            # entries keep their default init, as in the reference.
            self.init_params(allow_missing=True)

    # -- params ---------------------------------------------------------
    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False,
                    allow_extra=False):
        if self.params_initialized and not force_init:
            return
        if not self.binded:
            raise MXNetError("call bind before init_params")
        if self._preloaded is not None:
            # Module.load semantics (reference: module.py::Module.load):
            # the checkpointed params take effect at init_params time;
            # either half may be overridden by an explicit argument.
            pre_arg, pre_aux = self._preloaded
            if arg_params is None:
                arg_params = pre_arg
            if aux_params is None:
                aux_params = pre_aux
        initializer = initializer or init_mod.Uniform(0.01)
        for name in self._param_names:
            arr = self._exec.arg_dict[name]
            if arg_params is not None and name in arg_params:
                src = arg_params[name]
                arr._set_data(src.data if isinstance(src, NDArray)
                              else nd_array(src).data)
            elif arg_params is not None and not allow_missing:
                raise MXNetError(
                    f"parameter {name} missing from arg_params "
                    "(pass allow_missing=True to initialize it instead)")
            else:
                desc = init_mod.InitDesc(name, global_init=initializer)
                initializer(desc, arr)
        for name in self._symbol.list_auxiliary_states():
            arr = self._exec.aux_dict[name]
            if aux_params is not None and name in aux_params:
                src = aux_params[name]
                arr._set_data(src.data if isinstance(src, NDArray)
                              else nd_array(src).data)
            elif aux_params is not None and not allow_missing:
                raise MXNetError(
                    f"auxiliary state {name} missing from aux_params "
                    "(pass allow_missing=True to initialize it instead)")
            else:
                # variance-like stats start at 1, means at 0 (reference
                # behaviour from per-op init attrs)
                if "var" in name:
                    arr[:] = 1.0
                else:
                    arr[:] = 0.0
        self.params_initialized = True

    def get_params(self):
        arg = {n: self._exec.arg_dict[n].copy() for n in self._param_names}
        aux = {n: v.copy() for n, v in self._exec.aux_dict.items()}
        return arg, aux

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)

    # -- optimizer ------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        if self.optimizer_initialized and not force_init:
            return
        if isinstance(optimizer, str):
            idx2name = {i: n for i, n in enumerate(self._param_names)}
            optimizer = opt_mod.create(
                optimizer, param_idx2name=idx2name,
                **dict(optimizer_params or {}))
        self._optimizer = optimizer
        self._updater = opt_mod.get_updater(optimizer)
        if self._preloaded_states is not None:
            # Module.load(..., load_optimizer_states=True): apply the
            # checkpointed updater states now that the updater exists.
            from ..checkpoint import apply_state_bytes, read_state_bytes

            fname = self._preloaded_states
            states = read_state_bytes(fname, "Module.load")
            apply_state_bytes(states, self._updater.set_states, fname,
                              "Module.load")
            self._preloaded_states = None
        self.optimizer_initialized = True

    # -- step -----------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        if is_train is None:
            is_train = False
        feeds = {}
        for name, arr in zip(self._data_names, data_batch.data or []):
            feeds[name] = arr
        if data_batch.label:
            for name, arr in zip(self._label_names, data_batch.label):
                if name in self._exec.arg_dict:
                    feeds[name] = arr
        self._exec.forward(is_train=is_train, **feeds)

    def backward(self, out_grads=None):
        self._exec.backward(out_grads)

    def update(self):
        for i, name in enumerate(self._param_names):
            grad = self._exec.grad_dict.get(name)
            if grad is None:
                continue
            if self._compression is not None:
                grad = self._compression.compress(name, 0, grad)
            self._updater(i, grad, self._exec.arg_dict[name])

    def get_outputs(self, merge_multi_context=True):
        return self._exec.outputs

    def get_input_grads(self, merge_multi_context=True):
        return [self._exec.grad_dict.get(n) for n in self._data_names]

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        eval_metric.update_dict(
            dict(zip(self._label_names, labels or [])),
            dict(zip(self.output_names, self._exec.outputs)))

    def install_monitor(self, mon):
        if self._exec is None:
            raise MXNetError("install_monitor requires bind()")
        # a rebind creates a fresh executor — swap it in the Monitor so a
        # second fit(force_rebind=True) doesn't report stale arrays
        prev = getattr(self, "_monitored_exec", None)
        if prev is not None and prev is not self._exec and prev in mon.exes:
            mon.exes.remove(prev)
        mon.install(self._exec)
        self._monitored_exec = self._exec

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        # every artifact commits through the atomic writer (temp + fsync
        # + rename): symbol json and .params via their own savers, the
        # optimizer states here — a killed process never leaves a
        # truncated checkpoint file behind
        arg, aux = self.get_params()
        save_checkpoint(prefix, epoch, self._symbol, arg, aux)
        if save_optimizer_states:
            from ..checkpoint import atomic_write

            atomic_write(f"{prefix}-{epoch:04d}.states",
                         self._updater.get_states())

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        mod = Module(symbol, **kwargs)
        mod._preloaded = (arg_params, aux_params)
        mod._preloaded_states = f"{prefix}-{epoch:04d}.states" \
            if load_optimizer_states else None
        return mod


class BucketingModule(BaseModule):
    """reference: module/bucketing_module.py — per-bucket executors sharing
    parameters; here each bucket is one jit cache entry and parameters are
    shared through a common arg/aux store."""

    def __init__(self, sym_gen, default_bucket_key=None, logger=None,
                 context=None, **kwargs):
        super().__init__(logger)
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._kwargs = kwargs
        self._modules: Dict = {}
        self._curr_module: Optional[Module] = None
        self._curr_key = None
        self._shared_args: Dict[str, NDArray] = {}
        self._shared_aux: Dict[str, NDArray] = {}
        self._optimizer_conf = None

    @property
    def symbol(self):
        return self._curr_module.symbol if self._curr_module else None

    def _get_module(self, bucket_key, data_shapes, label_shapes,
                    for_training=True):
        if bucket_key in self._modules:
            return self._modules[bucket_key]
        sym, data_names, label_names = self._sym_gen(bucket_key)
        mod = Module(sym, data_names, label_names, logger=self.logger,
                     context=self._context, **self._kwargs)
        mod.bind(data_shapes, label_shapes, for_training=for_training)
        # share parameter storage across buckets (the BucketingModule
        # contract): same NDArray objects in every executor
        for n in mod._param_names:
            if n in self._shared_args:
                mod._exec.arg_dict[n] = self._shared_args[n]
            else:
                self._shared_args[n] = mod._exec.arg_dict[n]
        for n in mod.symbol.list_auxiliary_states():
            if n in self._shared_aux:
                mod._exec.aux_dict[n] = self._shared_aux[n]
            else:
                self._shared_aux[n] = mod._exec.aux_dict[n]
        if getattr(self, "_monitor", None) is not None:
            mod.install_monitor(self._monitor)
        self._modules[bucket_key] = mod
        return mod

    def install_monitor(self, mon):
        self._monitor = mon
        for mod in self._modules.values():
            mod.install_monitor(mon)

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             force_rebind=False, **kwargs):
        self._curr_module = self._get_module(
            self._default_bucket_key, data_shapes, label_shapes,
            for_training)
        self._curr_key = self._default_bucket_key
        self.binded = True

    def init_params(self, **kwargs):
        self._curr_module.init_params(**kwargs)
        self.params_initialized = True

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        self._curr_module.init_optimizer(kvstore, optimizer,
                                         optimizer_params, force_init)
        self._optimizer_conf = (kvstore, optimizer, optimizer_params)
        # all buckets share one updater (shared parameter state)
        for mod in self._modules.values():
            mod._optimizer = self._curr_module._optimizer
            mod._updater = self._curr_module._updater
            mod.optimizer_initialized = True
        self.optimizer_initialized = True

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        mod = self._get_module(bucket_key, data_shapes, label_shapes)
        if not mod.params_initialized and self.params_initialized:
            mod.params_initialized = True
        if self.optimizer_initialized and not mod.optimizer_initialized:
            mod._optimizer = self._curr_module._optimizer
            mod._updater = self._curr_module._updater
            mod.optimizer_initialized = True
        self._curr_module = mod
        self._curr_key = bucket_key

    def forward(self, data_batch, is_train=None):
        key = data_batch.bucket_key
        if key is None:
            key = self._default_bucket_key
        if key != self._curr_key:
            self.switch_bucket(key, data_batch.provide_data,
                               data_batch.provide_label)
        self._curr_module.forward(data_batch, is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads)

    def update(self):
        self._curr_module.update()

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs()

    def get_params(self):
        return self._curr_module.get_params()

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._curr_module.update_metric(eval_metric, labels)
