// Native recordio container engine (reference roles:
// src/io/iter_image_recordio_2.cc record scanning +
// dmlc-core recordio split reading).
//
// The hot path of a recordio-backed input pipeline is scanning the
// container: magic/flag/length framing, 4-byte padding, multi-part
// record reassembly, and index construction over multi-GB files. That
// work is branchy byte-level C++ in the reference and stays C++ here;
// Python (ctypes) orchestrates and PIL/jax handle decode/augment.
//
// Format (dmlc-core recordio + MXNet):
//   uint32 magic = 0xced7230a
//   uint32 lrec: upper 3 bits cflag (0 whole, 1 first, 2 middle, 3 last),
//                lower 29 bits payload length
//   payload, zero-padded to a multiple of 4 bytes
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cstdlib>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xced7230a;

struct Reader {
    FILE* f = nullptr;
    std::vector<uint8_t> buf;
};

inline uint32_t dec_flag(uint32_t x) { return (x >> 29u) & 7u; }
inline uint32_t dec_len(uint32_t x) { return x & ((1u << 29u) - 1u); }

}  // namespace

extern "C" {

void* rio_open(const char* path) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return nullptr;
    auto* r = new Reader();
    r->f = f;
    return r;
}

void rio_close(void* h) {
    if (!h) return;
    auto* r = static_cast<Reader*>(h);
    if (r->f) std::fclose(r->f);
    delete r;
}

void rio_seek(void* h, uint64_t pos) {
    auto* r = static_cast<Reader*>(h);
    std::fseek(r->f, static_cast<long>(pos), SEEK_SET);
}

uint64_t rio_tell(void* h) {
    auto* r = static_cast<Reader*>(h);
    return static_cast<uint64_t>(std::ftell(r->f));
}

// Read the next logical record (reassembling multi-part records).
// Returns length, or 0 on EOF, or UINT64_MAX on corruption.
// The payload pointer is valid until the next rio_* call on this handle.
uint64_t rio_next(void* h, const uint8_t** out) {
    auto* r = static_cast<Reader*>(h);
    r->buf.clear();
    while (true) {
        uint32_t magic = 0, lrec = 0;
        if (std::fread(&magic, 4, 1, r->f) != 1) return 0;  // EOF
        if (magic != kMagic) return UINT64_MAX;
        if (std::fread(&lrec, 4, 1, r->f) != 1) return UINT64_MAX;
        const uint32_t flag = dec_flag(lrec);
        const uint32_t len = dec_len(lrec);
        const size_t off = r->buf.size();
        r->buf.resize(off + len);
        if (len && std::fread(r->buf.data() + off, 1, len, r->f) != len)
            return UINT64_MAX;
        const uint32_t pad = (4u - (len & 3u)) & 3u;
        if (pad) std::fseek(r->f, pad, SEEK_CUR);
        if (flag == 0 || flag == 3) break;  // whole record or last part
    }
    *out = r->buf.data();
    return r->buf.size();
}

// Scan the whole container, returning every logical record's byte offset
// (caller frees with rio_free_index). Returns count, UINT64_MAX on
// corruption.
uint64_t rio_build_index(const char* path, uint64_t** offsets_out) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return UINT64_MAX;
    std::vector<uint64_t> offs;
    while (true) {
        const long pos = std::ftell(f);
        uint32_t magic = 0, lrec = 0;
        if (std::fread(&magic, 4, 1, f) != 1) break;  // EOF
        if (magic != kMagic) { std::fclose(f); return UINT64_MAX; }
        if (std::fread(&lrec, 4, 1, f) != 1) { std::fclose(f); return UINT64_MAX; }
        const uint32_t flag = dec_flag(lrec);
        const uint32_t len = dec_len(lrec);
        if (flag == 0 || flag == 1) offs.push_back(static_cast<uint64_t>(pos));
        const uint32_t pad = (4u - (len & 3u)) & 3u;
        std::fseek(f, static_cast<long>(len + pad), SEEK_CUR);
    }
    std::fclose(f);
    auto* arr = static_cast<uint64_t*>(std::malloc(offs.size() * 8));
    std::memcpy(arr, offs.data(), offs.size() * 8);
    *offsets_out = arr;
    return offs.size();
}

void rio_free_index(uint64_t* offsets) { std::free(offsets); }

// Writer ---------------------------------------------------------------

void* rio_create(const char* path) {
    FILE* f = std::fopen(path, "wb");
    if (!f) return nullptr;
    auto* r = new Reader();
    r->f = f;
    return r;
}

// Write one logical record (splitting is not needed for len < 2^29).
// Returns the record's start offset, or UINT64_MAX on error.
uint64_t rio_write(void* h, const uint8_t* data, uint64_t len) {
    auto* r = static_cast<Reader*>(h);
    const uint64_t start = static_cast<uint64_t>(std::ftell(r->f));
    const uint32_t kMax = (1u << 29u) - 1u;
    uint64_t off = 0;
    uint32_t part = 0;
    do {
        const uint64_t remain = len - off;
        const uint32_t n = remain > kMax ? kMax : static_cast<uint32_t>(remain);
        uint32_t flag;
        if (part == 0 && n == remain) flag = 0;
        else if (part == 0) flag = 1;
        else if (n == remain) flag = 3;
        else flag = 2;
        const uint32_t lrec = (flag << 29u) | n;
        if (std::fwrite(&kMagic, 4, 1, r->f) != 1) return UINT64_MAX;
        if (std::fwrite(&lrec, 4, 1, r->f) != 1) return UINT64_MAX;
        if (n && std::fwrite(data + off, 1, n, r->f) != n) return UINT64_MAX;
        const uint32_t pad = (4u - (n & 3u)) & 3u;
        const uint32_t zero = 0;
        if (pad && std::fwrite(&zero, 1, pad, r->f) != pad) return UINT64_MAX;
        off += n;
        ++part;
    } while (off < len);
    return start;
}

void rio_flush(void* h) {
    auto* r = static_cast<Reader*>(h);
    std::fflush(r->f);
}

}  // extern "C"
