"""Native (C++) components, built on demand with the system toolchain.

The reference implements its IO hot paths in C++ (recordio container
scanning, image record iterators — ``src/io/``); this package holds the
TPU-native equivalents. Each .so is compiled lazily from the checked-in
source on first use and cached next to it; every consumer has a pure-
Python fallback so a missing toolchain degrades gracefully.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_lock = threading.Lock()
_libs = {}


def load(name: str):
    """Compile (once) and dlopen _native/<name>.cpp. None if unavailable."""
    with _lock:
        if name in _libs:
            return _libs[name]
        here = os.path.dirname(os.path.abspath(__file__))
        src = os.path.join(here, f"{name}.cpp")
        so = os.path.join(here, f"lib{name}.so")
        lib = None
        try:
            if (not os.path.exists(so)
                    or os.path.getmtime(so) < os.path.getmtime(src)):
                # per-process temp name: concurrent first-use from several
                # worker processes must not clobber each other's output
                import tempfile

                fd, tmp = tempfile.mkstemp(suffix=".so", dir=here)
                os.close(fd)
                cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                       src, "-o", tmp]
                subprocess.run(cmd, check=True, capture_output=True,
                               timeout=120)
                os.replace(tmp, so)
            lib = ctypes.CDLL(so)
        except Exception:
            lib = None
        _libs[name] = lib
        return lib


def recordio_lib():
    lib = load("recordio")
    if lib is None:
        return None
    if not getattr(lib, "_sigs_set", False):
        u64, p = ctypes.c_uint64, ctypes.c_void_p
        lib.rio_open.restype = p
        lib.rio_open.argtypes = [ctypes.c_char_p]
        lib.rio_create.restype = p
        lib.rio_create.argtypes = [ctypes.c_char_p]
        lib.rio_close.argtypes = [p]
        lib.rio_seek.argtypes = [p, u64]
        lib.rio_tell.argtypes = [p]
        lib.rio_tell.restype = u64
        lib.rio_next.argtypes = [p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))]
        lib.rio_next.restype = u64
        lib.rio_write.argtypes = [p, ctypes.c_char_p, u64]
        lib.rio_write.restype = u64
        lib.rio_flush.argtypes = [p]
        lib.rio_build_index.argtypes = [ctypes.c_char_p,
                                        ctypes.POINTER(ctypes.POINTER(u64))]
        lib.rio_build_index.restype = u64
        lib.rio_free_index.argtypes = [ctypes.POINTER(u64)]
        lib._sigs_set = True
    return lib
