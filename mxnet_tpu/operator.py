"""``mx.operator`` — user-defined Python operators (the ``Custom`` op).

Reference: ``src/operator/custom/custom.cc`` (the C++ trampoline that calls
back into Python for forward/backward) + ``python/mxnet/operator.py``
(``CustomOp`` / ``CustomOpProp`` / ``register``). Upstream routes each
forward through the engine to a Python callback on a dedicated thread; the
TPU-native equivalent routes it through ``jax.pure_callback`` — the op
participates in traced/jitted graphs (Symbol executors, hybridized blocks)
as a host call with statically inferred output shapes, and a
``jax.custom_vjp`` wires the user's ``backward`` into autograd, since XLA
cannot differentiate through an opaque host callback.

Semantic deltas from upstream, by design:

* ``aux`` states are read-only inside the op (functional XLA graphs have
  no side-channel mutation; upstream lets ``forward`` write aux).
* The host callback always runs on CPU NDArrays regardless of the graph's
  device — data round-trips device->host->device at the callback boundary,
  which is also true upstream (``custom.cc`` copies to CPU unless the op
  declares device support).
"""
from __future__ import annotations

from typing import Dict, List, Tuple, Type

from .base import MXNetError

__all__ = ["CustomOp", "CustomOpProp", "register", "get_prop_cls"]

_PROPS: Dict[str, Type["CustomOpProp"]] = {}


class CustomOp:
    """Base class for the imperative body of a custom operator
    (reference: python/mxnet/operator.py::CustomOp)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError(
            "backward not implemented — required to train through this op")

    def assign(self, dst, req, src):
        """Write ``src`` into ``dst`` honoring the req mode."""
        if req == "null":
            return
        if req == "add":
            dst[:] = dst + src
        else:  # "write" / "inplace"
            dst[:] = src


class CustomOpProp:
    """Shape/type inference + operator factory
    (reference: python/mxnet/operator.py::CustomOpProp)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self) -> List[str]:
        return ["data"]

    def list_outputs(self) -> List[str]:
        return ["output"]

    def list_auxiliary_states(self) -> List[str]:
        return []

    def infer_shape(self, in_shape):
        """Default: all outputs shaped like the first input; override for
        anything else. Returns (arg_shapes, out_shapes, aux_shapes)."""
        return (in_shape,
                [in_shape[0]] * len(self.list_outputs()),
                [])

    def infer_type(self, in_type):
        return (in_type,
                [in_type[0]] * len(self.list_outputs()),
                [in_type[0]] * len(self.list_auxiliary_states()))

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        """Upstream trims the residuals the backward needs; the functional
        custom_vjp keeps (inputs, outputs) alive regardless, so this is
        advisory here and kept only for API parity."""
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes) -> CustomOp:
        raise NotImplementedError


def register(reg_name: str):
    """Register a CustomOpProp subclass under ``op_type=reg_name``
    (reference: mx.operator.register). Usable afterwards as
    ``mx.nd.Custom(..., op_type=reg_name)`` / ``mx.sym.Custom(...)``."""

    def deco(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError(
                f"register({reg_name!r}) requires a CustomOpProp subclass")
        _PROPS[reg_name] = prop_cls
        prop_cls._register_name = reg_name
        return prop_cls

    return deco


def get_prop_cls(op_type: str) -> Type[CustomOpProp]:
    try:
        return _PROPS[op_type]
    except KeyError:
        raise MXNetError(
            f"Custom op type {op_type!r} is not registered; decorate its "
            "CustomOpProp with @mx.operator.register(name)") from None
