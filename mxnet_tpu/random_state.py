"""Global PRNG state.

Reference: ``src/resource.cc :: ResourceManagerImpl`` kRandom resources +
``python/mxnet/random.py :: seed``. MXNet keeps stateful per-device
generators; the TPU-native equivalent is a counter-based splittable key:

* eager mode: every random op splits a fresh subkey off the global state;
* traced mode (hybridize / Symbol executor / jitted train step): the trace
  scope installs a *traced* base key (an executable input), and subkeys are
  split deterministically from it — so one compiled executable yields fresh
  randomness per call by feeding a new base key, with zero recompilation.
"""
from __future__ import annotations

import contextlib
import threading

__all__ = ["seed", "next_key", "scoped_key", "get_state_key",
           "checkpoint_state", "restore_checkpoint_state"]

_state = threading.local()
_DEFAULT_SEED = 0


def _global():
    if not hasattr(_state, "keys"):
        _state.keys = {}            # (dev_type, dev_id) -> PRNGKey
        _state.base_seed = _DEFAULT_SEED
        _state.host_rng = None      # numpy RandomState for host-side init
    return _state


def host_rng():
    """Host-side numpy RandomState for initializers (reference: the CPU
    sampling behind Initializer). Derived from the mx.random seed so
    ``mx.random.seed(n)`` makes parameter initialization reproducible —
    including ACROSS PROCESSES of a dist job, where each process's
    ``numpy.random`` global state would otherwise start from independent
    OS entropy and data-parallel replicas would silently begin from
    different weights (found live via the 2-process dryrun, round 5)."""
    import numpy as np

    st = _global()
    if getattr(st, "host_rng", None) is None:
        st.host_rng = np.random.RandomState(st.base_seed & 0x7FFFFFFF)
    return st.host_rng


def _ctx_sig(ctx=None):
    from .context import current_context

    c = ctx if ctx is not None else current_context()
    return (c.device_type, c.device_id)


def _stream(st, sig):
    """Per-device stream (reference: resource.cc kRandom is PER-DEVICE).
    Lazily derived from the base seed folded with the device id, so
    devices draw independent streams from one logical seed."""
    key = st.keys.get(sig)
    if key is None:
        import zlib

        import jax

        # crc32, NOT hash(): str hashing is salted per process, which
        # would break run-to-run reproducibility of mx.random.seed
        fold = zlib.crc32(repr(sig).encode()) & 0x7FFFFFFF
        key = jax.random.fold_in(
            jax.random.PRNGKey(int(st.base_seed)), fold)
        st.keys[sig] = key
    return key


def seed(seed_state, ctx="all") -> None:
    """Seed the generator(s) (reference: mx.random.seed(seed, ctx) —
    ctx='all' reseeds every device's stream; a Context reseeds one)."""
    import jax

    st = _global()
    if isinstance(ctx, str) and ctx == "all":
        import numpy as np

        st.base_seed = int(seed_state)
        st.keys = {}
        # host-side initializer stream reseeds with the devices
        st.host_rng = np.random.RandomState(st.base_seed & 0x7FFFFFFF)
    else:
        st.keys[_ctx_sig(ctx)] = jax.random.PRNGKey(int(seed_state))


def next_key():
    """Return a fresh subkey. Inside a trace scope, split from the scoped
    (traced) key; otherwise split the current device's stateful stream."""
    import jax

    st = _global()
    scoped = getattr(st, "scoped", None)
    if scoped is not None:
        key, sub = jax.random.split(scoped[-1])
        scoped[-1] = key
        return sub
    sig = _ctx_sig()
    key, sub = jax.random.split(_stream(st, sig))
    st.keys[sig] = key
    return sub


def get_state_key():
    """Fresh key drawn from the stateful global generator (for feeding a
    compiled executable's rng input)."""
    return next_key()


def checkpoint_state() -> dict:
    """Serializable (picklable) snapshot of the global PRNG: base seed,
    every materialized per-device key stream, and the host-side
    initializer RandomState. The crash-safe checkpoint contract
    (``mxnet_tpu/checkpoint.py``) stores this so a resumed run draws the
    SAME random sequence the uninterrupted run would have — bit-exact
    resume requires the RNG, not just params and optimizer state.

    Thread-scoped like the state itself: snapshots the calling thread's
    streams (the training loop's, in practice).
    """
    import numpy as np

    st = _global()
    keys = {}
    for sig, k in st.keys.items():
        try:
            raw = np.asarray(k)          # old-style uint32 key array
            typed = False
        except TypeError:
            import jax

            raw = np.asarray(jax.random.key_data(k))   # new-style typed
            typed = True
        keys[sig] = (raw, typed)
    host = None
    if getattr(st, "host_rng", None) is not None:
        host = st.host_rng.get_state()
    return {"version": 1, "base_seed": st.base_seed, "keys": keys,
            "host_rng": host}


def restore_checkpoint_state(state: dict) -> None:
    """Restore a :func:`checkpoint_state` snapshot into the calling
    thread's global PRNG (inverse of the snapshot; see there)."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    st = _global()
    st.base_seed = int(state["base_seed"])
    keys = {}
    for sig, (raw, typed) in state["keys"].items():
        arr = jnp.asarray(np.asarray(raw))
        keys[sig] = jax.random.wrap_key_data(arr) if typed else arr
    st.keys = keys
    if state.get("host_rng") is not None:
        rng = np.random.RandomState()
        rng.set_state(state["host_rng"])
        st.host_rng = rng
    else:
        st.host_rng = None


@contextlib.contextmanager
def preserved_stream():
    """Snapshot the stateful key streams and restore them on exit.

    For shape probes / AOT compiles that must not advance the program's
    random sequence (reproducibility) or leak traced keys into the
    global state when run under a live trace.
    """
    st = _global()
    saved = dict(st.keys)
    try:
        yield
    finally:
        st.keys = saved


@contextlib.contextmanager
def scoped_key(key):
    """Install a traced base key: all next_key() calls inside derive from it."""
    st = _global()
    prev = getattr(st, "scoped", None)
    if prev is None:
        st.scoped = [key]
    else:
        st.scoped.append(key)
    stack = st.scoped
    depth = len(stack)
    try:
        yield
    finally:
        # pop our frame (it may have been advanced by splits)
        del stack[depth - 1 :]
        if not stack:
            st.scoped = None
