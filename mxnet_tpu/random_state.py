"""Global PRNG state.

Reference: ``src/resource.cc :: ResourceManagerImpl`` kRandom resources +
``python/mxnet/random.py :: seed``. MXNet keeps stateful per-device
generators; the TPU-native equivalent is a counter-based splittable key:

* eager mode: every random op splits a fresh subkey off the global state;
* traced mode (hybridize / Symbol executor / jitted train step): the trace
  scope installs a *traced* base key (an executable input), and subkeys are
  split deterministically from it — so one compiled executable yields fresh
  randomness per call by feeding a new base key, with zero recompilation.
"""
from __future__ import annotations

import contextlib
import threading

__all__ = ["seed", "next_key", "scoped_key", "get_state_key"]

_state = threading.local()
_DEFAULT_SEED = 0


def _global():
    if not hasattr(_state, "key"):
        import jax

        _state.key = jax.random.PRNGKey(_DEFAULT_SEED)
    return _state


def seed(seed_state, ctx="all") -> None:
    """Seed the global generator (reference: mx.random.seed)."""
    import jax

    _global().key = jax.random.PRNGKey(int(seed_state))


def next_key():
    """Return a fresh subkey. Inside a trace scope, split from the scoped
    (traced) key; otherwise split the stateful global key."""
    import jax

    st = _global()
    scoped = getattr(st, "scoped", None)
    if scoped is not None:
        key, sub = jax.random.split(scoped[-1])
        scoped[-1] = key
        return sub
    key, sub = jax.random.split(st.key)
    st.key = key
    return sub


def get_state_key():
    """Fresh key drawn from the stateful global generator (for feeding a
    compiled executable's rng input)."""
    return next_key()


@contextlib.contextmanager
def scoped_key(key):
    """Install a traced base key: all next_key() calls inside derive from it."""
    st = _global()
    prev = getattr(st, "scoped", None)
    if prev is None:
        st.scoped = [key]
    else:
        st.scoped.append(key)
    stack = st.scoped
    depth = len(stack)
    try:
        yield
    finally:
        # pop our frame (it may have been advanced by splits)
        del stack[depth - 1 :]
        if not stack:
            st.scoped = None
