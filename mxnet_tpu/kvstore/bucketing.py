"""Gradient bucketing for the kvstore's fused ``pushpull``.

The reference KVStore (``dist_device_sync`` / ``nccl``) reduces every
gradient key as its own collective; a ResNet-50 step pays ~160 separate
dispatches and a transformer one per weight tensor. The proven fix
(PyTorch DDP's 25 MB gradient buckets, Li et al. VLDB'20; Horovod tensor
fusion) is to coalesce gradients into large flat buffers and run ONE
collective per bucket. This module holds the mechanics shared by every
store type:

* :func:`plan_buckets` — greedy, order-preserving partition of keys into
  dtype-segregated buckets capped at ``MXNET_KV_BUCKET_MB`` (default 25)
  payload bytes. Keys arrive already sorted by priority (descending), so
  bucket *dispatch order* is the priority order. A single tensor larger
  than the cap gets a bucket of its own — it is never split (the
  collective is one dispatch either way) and never silently dropped.
* :func:`pack` / :func:`unpacker` — jitted flatten-and-concatenate of a
  bucket's member gradients into one flat buffer and the inverse
  scatter. One XLA dispatch each; the unpacker executable is cached per
  bucket signature (member shapes), and ``jax.jit``'s own
  signature-keyed cache makes repeated steps replay compiled code.

Bit-identity contract: packing is pure reshape/concatenate and the
reduction over a flat bucket applies the same elementwise sum (same
operand order, same reduction arity) each member would see in its own
per-key collective — so the bucketed *uncompressed* exchange is
bit-identical to the per-key path, which the tests and
``tools/comms_bench.py`` assert.
"""
from __future__ import annotations

import os
from typing import Dict, List, Sequence, Tuple

__all__ = ["Bucket", "bucket_cap_bytes", "pack", "plan_buckets",
           "unpacker"]

DEFAULT_BUCKET_MB = 25.0  # PyTorch DDP's default gradient-bucket size


def bucket_cap_bytes() -> int:
    """Resolve ``MXNET_KV_BUCKET_MB`` (float MB; 0 disables bucketing)."""
    mb = float(os.environ.get("MXNET_KV_BUCKET_MB", str(DEFAULT_BUCKET_MB)))
    return int(mb * (1 << 20))


class Bucket:
    """One planned bucket: member positions (indices into the caller's
    key list), their shapes, and the flat-buffer layout."""

    __slots__ = ("indices", "shapes", "dtype", "nbytes", "group")

    def __init__(self, dtype, group):
        self.indices: List[int] = []
        self.shapes: List[Tuple[int, ...]] = []
        self.dtype = dtype
        self.group = group          # (dtype_str, nslots, slot device sig)
        self.nbytes = 0

    def add(self, index: int, shape: Tuple[int, ...],
            nbytes: int) -> None:
        self.indices.append(index)
        self.shapes.append(tuple(shape))
        self.nbytes += int(nbytes)

    def __len__(self):
        return len(self.indices)

    def __repr__(self):
        return (f"Bucket(keys={len(self.indices)}, dtype={self.dtype}, "
                f"bytes={self.nbytes})")


def plan_buckets(entries: Sequence[Tuple[int, Tuple[int, ...], object,
                                         object, int]],
                 cap_bytes: int) -> List[Bucket]:
    """Partition ``entries`` into buckets, preserving the given order.

    ``entries``: ``(index, shape, dtype, group, nbytes)`` tuples in
    dispatch (priority) order. ``group`` segregates members that cannot
    share a flat buffer — different dtypes, different device-copy counts
    or placements. Greedy: an entry joins the open bucket of its group
    unless that would exceed ``cap_bytes``; an entry alone larger than
    the cap still gets (and fills) its own bucket.
    """
    buckets: List[Bucket] = []
    open_by_group: Dict[object, Bucket] = {}
    for index, shape, dtype, group, nbytes in entries:
        b = open_by_group.get(group)
        if b is None or (len(b) > 0 and b.nbytes + nbytes > cap_bytes):
            b = Bucket(dtype, group)
            buckets.append(b)
            open_by_group[group] = b
        b.add(index, shape, nbytes)
    return buckets


# --------------------------------------------------------------------------
# jitted pack / unpack
# --------------------------------------------------------------------------

_PACK = None                       # lazily-built jitted variadic packer
_UNPACKERS: Dict[Tuple, object] = {}


def pack(arrs):
    """Flatten + concatenate a bucket's member arrays (one dispatch).

    ``jax.jit`` caches per (arity, shapes, dtype) signature, so every
    step after the first replays a compiled executable. All members must
    be committed to the same device (the planner's ``group`` guarantees
    it); the flat buffer lands on that device.
    """
    global _PACK
    if _PACK is None:
        import jax
        import jax.numpy as jnp

        _PACK = jax.jit(
            lambda *xs: jnp.concatenate([x.reshape(-1) for x in xs]))
    return _PACK(*arrs)


def unpacker(shapes: Sequence[Tuple[int, ...]]):
    """Jitted inverse of :func:`pack` for a bucket signature: flat buffer
    -> tuple of member arrays (one dispatch). Cached per shapes tuple."""
    sig = tuple(tuple(s) for s in shapes)
    fn = _UNPACKERS.get(sig)
    if fn is None:
        import jax

        offsets = []
        off = 0
        for s in sig:
            n = 1
            for d in s:
                n *= int(d)
            offsets.append((off, n, s))
            off += n

        def unpack(flat):
            return tuple(flat[o:o + n].reshape(s) for o, n, s in offsets)

        fn = jax.jit(unpack)
        _UNPACKERS[sig] = fn
    return fn
