"""Gradient bucketing for the kvstore's fused ``pushpull``.

The reference KVStore (``dist_device_sync`` / ``nccl``) reduces every
gradient key as its own collective; a ResNet-50 step pays ~160 separate
dispatches and a transformer one per weight tensor. The proven fix
(PyTorch DDP's 25 MB gradient buckets, Li et al. VLDB'20; Horovod tensor
fusion) is to coalesce gradients into large flat buffers and run ONE
collective per bucket. This module holds the mechanics shared by every
store type:

* :func:`plan_buckets` — greedy, order-preserving partition of keys into
  dtype-segregated buckets capped at ``MXNET_KV_BUCKET_MB`` (default 25)
  payload bytes. Keys arrive already sorted by priority (descending), so
  bucket *dispatch order* is the priority order. A single tensor larger
  than the cap gets a bucket of its own — it is never split (the
  collective is one dispatch either way) and never silently dropped.
* :func:`pack` / :func:`unpacker` — jitted flatten-and-concatenate of a
  bucket's member gradients into one flat buffer and the inverse
  scatter. One XLA dispatch each; the unpacker executable is cached per
  bucket signature (member shapes), and ``jax.jit``'s own
  signature-keyed cache makes repeated steps replay compiled code.

Bit-identity contract: packing is pure reshape/concatenate and the
reduction over a flat bucket applies the same elementwise sum (same
operand order, same reduction arity) each member would see in its own
per-key collective — so the bucketed *uncompressed* exchange is
bit-identical to the per-key path, which the tests and
``tools/comms_bench.py`` assert.

ZeRO partitioning (``partition="zero1"|"zero2"``) is a *layout* the
planner can attach to every bucket: the flat buffer, zero-padded to a
multiple of ``world``, is carved into ``world`` equal contiguous
per-rank shards (:class:`ShardPlan`). Rank ``r`` reduces only elements
``[r*shard_len, (r+1)*shard_len)`` (reduce-scatter), updates its shard,
and the updated weights are allgathered back. The carve is pure
indexing — it never crosses the reduction, so the sharded exchange
stays bit-identical to the fused allreduce (asserted by
``tests/test_zero.py`` and comms_bench stage 5).
"""
from __future__ import annotations

import os
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

__all__ = ["Bucket", "PARTITION_MODES", "ShardPlan", "bucket_cap_bytes",
           "pack", "plan_buckets", "shard_layout", "unpacker"]

DEFAULT_BUCKET_MB = 25.0  # PyTorch DDP's default gradient-bucket size

# the ZeRO stages the planner knows how to lay out: "zero1" shards
# optimizer state only (full gradients still materialize on every
# rank), "zero2" also leaves gradients reduce-scattered (each rank
# keeps only its reduced shard)
PARTITION_MODES = ("zero1", "zero2")


class ShardPlan(NamedTuple):
    """Per-rank carve of one flat bucket under ZeRO partitioning.

    ``total``: unpadded flat element count; ``padded``: total rounded up
    to a multiple of ``world`` (the tail is zero-filled — zeros are
    inert through sum-reduction and are dropped before scatter);
    ``shard_len``: ``padded // world`` elements owned per rank.
    """

    mode: str
    world: int
    total: int
    padded: int
    shard_len: int

    def shard_range(self, rank: int) -> Tuple[int, int]:
        """[start, stop) of ``rank``'s shard in the padded flat buffer."""
        if not (0 <= rank < self.world):
            raise ValueError(
                f"rank {rank} outside partition world {self.world}")
        return rank * self.shard_len, (rank + 1) * self.shard_len


def shard_layout(mode: str, total: int, world: int) -> ShardPlan:
    """The :class:`ShardPlan` for a flat buffer of ``total`` elements
    partitioned across ``world`` ranks."""
    if mode not in PARTITION_MODES:
        raise ValueError(
            f"unknown partition mode {mode!r}; expected one of "
            f"{PARTITION_MODES}")
    world = int(world)
    if world < 1:
        raise ValueError(f"partition world must be >= 1, got {world}")
    shard_len = -(-int(total) // world)          # ceil div
    return ShardPlan(mode, world, int(total), shard_len * world,
                     shard_len)


def bucket_cap_bytes() -> int:
    """Resolve ``MXNET_KV_BUCKET_MB`` (float MB; 0 disables bucketing)."""
    mb = float(os.environ.get("MXNET_KV_BUCKET_MB", str(DEFAULT_BUCKET_MB)))
    return int(mb * (1 << 20))


class Bucket:
    """One planned bucket: member positions (indices into the caller's
    key list), their shapes, and the flat-buffer layout."""

    __slots__ = ("indices", "shapes", "dtype", "nbytes", "group",
                 "shard_plan")

    def __init__(self, dtype, group):
        self.indices: List[int] = []
        self.shapes: List[Tuple[int, ...]] = []
        self.dtype = dtype
        self.group = group          # (dtype_str, nslots, slot device sig)
        self.nbytes = 0
        self.shard_plan: Optional[ShardPlan] = None   # set by partition=

    def elements(self) -> int:
        n = 0
        for s in self.shapes:
            m = 1
            for d in s:
                m *= int(d)
            n += m
        return n

    def add(self, index: int, shape: Tuple[int, ...],
            nbytes: int) -> None:
        self.indices.append(index)
        self.shapes.append(tuple(shape))
        self.nbytes += int(nbytes)

    def __len__(self):
        return len(self.indices)

    def __repr__(self):
        return (f"Bucket(keys={len(self.indices)}, dtype={self.dtype}, "
                f"bytes={self.nbytes})")


def plan_buckets(entries: Sequence[Tuple[int, Tuple[int, ...], object,
                                         object, int]],
                 cap_bytes: int,
                 partition: Optional[str] = None,
                 world: int = 1) -> List[Bucket]:
    """Partition ``entries`` into buckets, preserving the given order.

    ``entries``: ``(index, shape, dtype, group, nbytes)`` tuples in
    dispatch (priority) order. ``group`` segregates members that cannot
    share a flat buffer — different dtypes, different device-copy counts
    or placements. Greedy: an entry joins the open bucket of its group
    unless that would exceed ``cap_bytes``; an entry alone larger than
    the cap still gets (and fills) its own bucket.

    ``partition``: when ``"zero1"`` / ``"zero2"``, every planned bucket
    additionally gets a :class:`ShardPlan` carving its flat buffer into
    ``world`` per-rank shards (the reduce-scatter / shard-update /
    allgather layout the ZeRO engine dispatches on).
    """
    if partition is not None and partition not in PARTITION_MODES:
        raise ValueError(
            f"unknown partition mode {partition!r}; expected one of "
            f"{PARTITION_MODES}")
    buckets: List[Bucket] = []
    open_by_group: Dict[object, Bucket] = {}
    for index, shape, dtype, group, nbytes in entries:
        b = open_by_group.get(group)
        if b is None or (len(b) > 0 and b.nbytes + nbytes > cap_bytes):
            b = Bucket(dtype, group)
            buckets.append(b)
            open_by_group[group] = b
        b.add(index, shape, nbytes)
    if partition is not None:
        for b in buckets:
            b.shard_plan = shard_layout(partition, b.elements(), world)
    return buckets


# --------------------------------------------------------------------------
# jitted pack / unpack
# --------------------------------------------------------------------------

_PACK = None                       # lazily-built jitted variadic packer
_UNPACKERS: Dict[Tuple, object] = {}


def pack(arrs):
    """Flatten + concatenate a bucket's member arrays (one dispatch).

    ``jax.jit`` caches per (arity, shapes, dtype) signature, so every
    step after the first replays a compiled executable. All members must
    be committed to the same device (the planner's ``group`` guarantees
    it); the flat buffer lands on that device.
    """
    global _PACK
    if _PACK is None:
        import jax
        import jax.numpy as jnp

        _PACK = jax.jit(
            lambda *xs: jnp.concatenate([x.reshape(-1) for x in xs]))
    return _PACK(*arrs)


def unpacker(shapes: Sequence[Tuple[int, ...]]):
    """Jitted inverse of :func:`pack` for a bucket signature: flat buffer
    -> tuple of member arrays (one dispatch). Cached per shapes tuple."""
    sig = tuple(tuple(s) for s in shapes)
    fn = _UNPACKERS.get(sig)
    if fn is None:
        import jax

        offsets = []
        off = 0
        for s in sig:
            n = 1
            for d in s:
                n *= int(d)
            offsets.append((off, n, s))
            off += n

        def unpack(flat):
            return tuple(flat[o:o + n].reshape(s) for o, n, s in offsets)

        fn = jax.jit(unpack)
        _UNPACKERS[sig] = fn
    return fn
