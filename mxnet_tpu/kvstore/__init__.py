"""KVStore — parameter synchronization for data parallelism.

Reference: ``src/kvstore/kvstore.cc :: KVStore::Create`` and
``python/mxnet/kvstore.py`` — types 'local', 'device' (single-process
multi-device reduce, ``src/kvstore/comm.h::CommCPU/CommDevice``),
'dist_sync'/'dist_async' (ps-lite parameter server), 'nccl'
(``kvstore_nccl.h``).

TPU-native replacement (SURVEY.md §5.8): the **'tpu_sync'** type drives XLA
collectives over the device mesh — push/pull become a compiled psum; the
'nccl', 'dist_device_sync' and 'dist_sync' names alias onto it so reference
scripts run unchanged. Parameter-server 'dist_async' has no TPU analogue
and raises with guidance. Multi-host rendezvous uses jax.distributed
(see mxnet_tpu.parallel) instead of dmlc_tracker env bootstrap.
"""
from .bucketing import Bucket, bucket_cap_bytes, plan_buckets  # noqa: F401
from .kvstore import (KVStore, KVStoreDistAsyncEmu, KVStoreLocal,  # noqa: F401
                      KVStoreTPUSync, create)
