"""KVStore — parameter synchronization for data parallelism.

Reference: ``src/kvstore/kvstore.cc :: KVStore::Create`` and
``python/mxnet/kvstore.py`` — types 'local', 'device' (single-process
multi-device reduce, ``src/kvstore/comm.h::CommCPU/CommDevice``),
'dist_sync'/'dist_async' (ps-lite parameter server), 'nccl'
(``kvstore_nccl.h``).

TPU-native replacement (SURVEY.md §5.8): the **'tpu_sync'** type drives XLA
collectives over the device mesh — push/pull become a compiled psum; the
'nccl', 'dist_device_sync' and 'dist_sync' names alias onto it so reference
scripts run unchanged. Parameter-server 'dist_async' has no TPU analogue
and raises with guidance. Multi-host rendezvous uses jax.distributed
(see mxnet_tpu.parallel) instead of dmlc_tracker env bootstrap.

Dist modes are SUPERVISED (replacing what ps-lite's tracker gave the
reference): ``tools/launch.py`` polls every worker and fail-fasts or
restarts dead ranks (``--max-restarts``); ``barrier()`` and the
``jax.distributed`` bootstrap are bounded by ``MXNET_KV_BARRIER_TIMEOUT``
and raise a typed :class:`~mxnet_tpu.kvstore.kvstore.BarrierTimeoutError`
naming the site and the missing ranks instead of blocking forever; ranks
leave through a bounded exit barrier; and
``mxnet_tpu.parallel.elastic.ElasticRunner`` adds heartbeat liveness +
epoch-versioned membership with bit-exact checkpoint hand-off, so a
SIGKILLed worker rejoins and the loss stays bit-identical.
"""
from .bucketing import (Bucket, PARTITION_MODES, ShardPlan,  # noqa: F401
                        bucket_cap_bytes, plan_buckets, shard_layout)
from .kvstore import (BarrierTimeoutError, KVStore,  # noqa: F401
                      KVStoreDistAsyncEmu, KVStoreLocal,
                      KVStoreTPUSync, create, reset_barrier_epoch)
