"""KVStore implementations (see package docstring for the design map)."""
from __future__ import annotations

import os
import pickle
import threading
import time
import warnings
import weakref
from typing import Dict, List, Optional, Tuple

import numpy as _np

from .. import fault
from .. import optimizer as opt
from .. import telemetry
from ..base import MXNetError
from ..fault import _state as _fault_state
from ..ndarray import NDArray
from ..ndarray import array as nd_array
from ..telemetry import _state as _telemetry_state
from .bucketing import bucket_cap_bytes, pack, plan_buckets, unpacker

_FUSED_SUM = None


def _fused_sum(arrs):
    """One jitted stack-and-sum over N same-shape arrays (one XLA
    dispatch; jit caches per (N, shape, dtype) signature)."""
    global _FUSED_SUM
    if _FUSED_SUM is None:
        import jax
        import jax.numpy as jnp

        _FUSED_SUM = jax.jit(lambda *xs: jnp.sum(jnp.stack(xs), axis=0))
    return _FUSED_SUM(*arrs)


def _nd_bytes(v) -> int:
    """Payload size of one NDArray (shape x dtype itemsize)."""
    try:
        d = v.dtype
        itemsize = getattr(d, "itemsize", None) or _np.dtype(d).itemsize
        return int(v.size) * int(itemsize)
    except Exception:
        return 0


def _payload_bytes(vals) -> int:
    return sum(_nd_bytes(v) for v in vals)

__all__ = ["BarrierTimeoutError", "KVStore", "KVStoreDistAsyncEmu",
           "KVStoreLocal", "KVStoreTPUSync", "create",
           "reset_barrier_epoch"]


# ---------------------------------------------------------------------------
# Bounded barriers — a dead worker must surface as a typed error naming
# the site and the missing ranks, never as an unbounded hang.
# ---------------------------------------------------------------------------

# SPMD-consistent store-creation ordinal (every process creates its
# stores in the same program order) — namespaces each store's
# cross-process barrier keys so two stores can never alias rendezvous.
_STORE_ORDINAL = 0

# Elastic membership epoch the barrier keyspace is based on. Per-site
# barrier sequence numbers live in process memory, so a restarted rank
# would re-count from zero while survivors kept counting — the ranks
# would announce under different key prefixes and every post-restart
# barrier would time out. The elastic runtime calls
# :func:`reset_barrier_epoch` at every membership transition (and at a
# rejoiner's start), which re-bases EVERY rank's counters to zero under
# an epoch-tagged namespace: survivors and the restarted rank meet at
# seq 1 of the new epoch.
_BARRIER_EPOCH = 0


def reset_barrier_epoch(epoch: int) -> None:
    """Re-base cross-process barrier sequence numbering to an elastic
    membership ``epoch``. Called by ``parallel.elastic`` at each epoch
    transition on every surviving rank (a restarted rank's counters are
    fresh anyway), so all ranks' barriers rendezvous under the same
    ``e{epoch}`` key namespace starting from sequence 1."""
    global _BARRIER_EPOCH
    _BARRIER_EPOCH = int(epoch)


class BarrierTimeoutError(MXNetError):
    """A kvstore barrier (local drain or cross-process rendezvous) did
    not complete within ``MXNET_KV_BARRIER_TIMEOUT`` — the typed signal
    the elastic runtime and exit paths branch on instead of wedging."""


def _barrier_timeout_s() -> float:
    """Default barrier bound (seconds). <= 0 disables the bound (the
    pre-supervision behavior, for jobs that want to block forever)."""
    try:
        return float(os.environ.get("MXNET_KV_BARRIER_TIMEOUT", "300"))
    except ValueError as e:
        raise MXNetError(
            "MXNET_KV_BARRIER_TIMEOUT="
            f"{os.environ['MXNET_KV_BARRIER_TIMEOUT']!r} is not a "
            "number") from e


def _bootstrap_timeout_s() -> int:
    """The ``jax.distributed.initialize`` rendezvous bound (seconds):
    ``MXNET_KV_BOOTSTRAP_TIMEOUT`` falling back to the barrier knob.
    jax wants a positive integer and has no unbounded mode, so <= 0
    (the documented bound opt-out) maps to ~24 days, and fractions
    round UP so 0.5 never truncates to instant failure. Shared by
    ``_maybe_init_distributed`` and the elastic re-bootstrap so the
    opt-out means the same thing at both sites."""
    try:
        t = float(os.environ.get(
            "MXNET_KV_BOOTSTRAP_TIMEOUT", "") or _barrier_timeout_s())
    except ValueError as e:
        raise MXNetError(
            "MXNET_KV_BOOTSTRAP_TIMEOUT="
            f"{os.environ['MXNET_KV_BOOTSTRAP_TIMEOUT']!r} is not a "
            "number") from e
    import math

    return 2**31 // 1000 if t <= 0 else max(1, math.ceil(t))


def _bounded_waitall(site: str, timeout: float) -> None:
    """Drain local async device work, bounded: ``waitall`` runs on a
    daemon thread joined with ``timeout``. On expiry the caller gets
    :class:`BarrierTimeoutError` naming the site — the wedged device
    work stays wedged (nothing can cancel it), but the *process* regains
    control to checkpoint, report, or exit."""
    from .. import ndarray as _nd

    if timeout <= 0:
        _nd.waitall()
        return
    done = threading.Event()
    err: List[BaseException] = []

    def _drain():
        try:
            _nd.waitall()
        except BaseException as e:  # noqa: BLE001 - re-raised below
            err.append(e)
        finally:
            done.set()

    threading.Thread(target=_drain, name="mxnet-kv-barrier-wait",
                     daemon=True).start()
    if not done.wait(timeout):
        raise BarrierTimeoutError(
            f"kvstore.barrier[{site}]: local device drain did not "
            f"complete within {timeout:g}s (MXNET_KV_BARRIER_TIMEOUT) — "
            "outstanding async work is wedged (dead collective peer?)")
    if err:
        raise err[0]


def _coord_client():
    """The jax coordination-service KV client, or None when this process
    was not bootstrapped through ``jax.distributed``."""
    try:
        from jax._src import distributed

        return distributed.global_state.client
    except Exception:
        return None


def dist_initialized() -> bool:
    """Is ``jax.distributed`` bootstrapped in this process?
    ``jax.distributed.is_initialized`` only exists in newer jax; older
    containers (this one included) fall back to the coordination-service
    client handle, which is set by ``initialize`` and cleared by
    ``shutdown`` on every version in support."""
    import jax

    fn = getattr(jax.distributed, "is_initialized", None)
    if fn is not None:
        return bool(fn())
    return _coord_client() is not None


def _kv_set_once(client, key: str, value: str) -> None:
    """``key_value_set`` tolerating re-announcement (a retried barrier
    attempt re-sets its own key; ALREADY_EXISTS is success)."""
    try:
        client.key_value_set(key, value)
    except Exception as e:  # noqa: BLE001 - status string filtered
        # only ALREADY_EXISTS is success; "does not exist" / NOT_FOUND
        # style failures must surface (a swallowed announcement would
        # make every PEER's timeout blame this healthy rank)
        msg = str(e).lower()
        if not ("already" in msg and "exist" in msg):
            raise


def _cross_process_barrier(client, site: str, seq: int, rank: int,
                           num_workers: int, timeout: float,
                           poll_interval: float = 0.05,
                           key_ns: str = "",
                           time_fn=time.monotonic,
                           sleep_fn=time.sleep) -> List[int]:
    """Rendezvous ``num_workers`` ranks through the coordination-service
    KV store: announce ``.../{site}/{seq}/{rank}``, poll the directory
    until every rank announced or the deadline passes. On expiry raises
    :class:`BarrierTimeoutError` naming the site AND the missing ranks —
    the diagnostic a hung ``psum`` can never give. Announcements are
    idempotent, so the surrounding ``fault.retry_call`` is safe."""
    prefix = f"mxnet_tpu/barrier/{key_ns}{site}/{int(seq)}"
    _kv_set_once(client, f"{prefix}/{int(rank)}", str(int(rank)))
    deadline = time_fn() + timeout
    while True:
        if _fault_state.enabled:
            fault.check("kvstore.barrier", f"{site} seq {seq}")
        present = set()
        for item in client.key_value_dir_get(prefix):
            key = item[0] if isinstance(item, (tuple, list)) else item
            tail = str(key).rsplit("/", 1)[-1]
            if tail.isdigit():
                present.add(int(tail))
        if len(present) >= num_workers:
            return sorted(present)
        if timeout > 0 and time_fn() >= deadline:
            missing = sorted(set(range(num_workers)) - present)
            raise BarrierTimeoutError(
                f"kvstore.barrier[{site}] (seq {seq}) timed out after "
                f"{timeout:g}s: missing ranks {missing} of "
                f"{num_workers} (arrived: {sorted(present)}) — restart "
                "the dead worker (tools/launch.py --max-restarts) or "
                "tear the job down; MXNET_KV_BARRIER_TIMEOUT bounds "
                "this wait")
        sleep_fn(poll_interval)


def _register_exit_barrier(store: "KVStore") -> None:
    """Run the store's bounded exit barrier at interpreter exit so a
    multi-process job's ranks leave together when they can — and leave
    ANYWAY (with a warning) when a peer is already gone."""
    import atexit

    ref = weakref.ref(store)

    def _hook():
        s = ref()
        if s is not None:
            s._barrier_before_exit()

    atexit.register(_hook)


def create(name="local") -> "KVStore":
    """reference: mx.kv.create / KVStore::Create."""
    name = str(name).lower()
    if name in ("local", "local_update_cpu", "local_allreduce_cpu",
                "local_allreduce_device", "device"):
        return KVStoreLocal(name)
    if name in ("tpu_sync", "nccl", "dist_device_sync", "dist_sync"):
        return KVStoreTPUSync(name)
    if name in ("dist_async",):
        import os

        if os.environ.get("MXNET_KVSTORE_DIST_ASYNC_EMU") == "1":
            return KVStoreDistAsyncEmu(name)
        raise MXNetError(
            "kvstore 'dist_async' (parameter-server async mode) has no "
            "TPU-native equivalent; use 'tpu_sync' (synchronous in-graph "
            "allreduce over the mesh), or opt into the bounded-staleness "
            "emulation with MXNET_KVSTORE_DIST_ASYNC_EMU=1 "
            "(MXNET_KVSTORE_ASYNC_STALENESS bounds the drift) — "
            "SURVEY.md §5.8, ADR-002")
    if name in ("horovod", "byteps"):
        raise MXNetError(
            f"kvstore '{name}' plugin is replaced by 'tpu_sync' on TPU")
    raise MXNetError(f"unknown kvstore type {name!r}")


class KVStore:
    """Base interface (reference: include/mxnet/kvstore.h)."""

    def __init__(self, type_name):
        self._type = type_name
        self._updater = None
        self._optimizer = None
        self._compression = None

    @property
    def type(self):
        return self._type

    @property
    def rank(self) -> int:
        return 0

    @property
    def num_workers(self) -> int:
        return 1

    def init(self, key, value):
        raise NotImplementedError

    def push(self, key, value, priority=0):
        raise NotImplementedError

    def pull(self, key, out, priority=0, ignore_sparse=True):
        raise NotImplementedError

    def pushpull(self, key, value, out=None, priority=0):
        """Fused push+pull (reference: kvstore.py::pushpull).

        The batched form — ``pushpull(keys, values, outs, priorities)``
        with parallel lists — is the REAL fused entry: stores that
        support it coalesce the keys into flat dtype-segregated buckets
        of ``MXNET_KV_BUCKET_MB`` (default 25) MB and run ONE collective
        per bucket instead of one per key. The scalar form is a thin
        wrapper over a one-key batch.

        Priority contract (previously accepted and ignored, now
        honored): keys are exchanged in DESCENDING priority order,
        stable for ties. The Gluon trainer passes ``priority=-i``, so
        parameter 0's bucket is dispatched first and its reduced
        gradient reaches the optimizer soonest; bucket *i+1*'s
        collective is dispatched before bucket *i*'s scatter, so via
        JAX async dispatch the collective overlaps the previous
        bucket's scatter + optimizer update.
        """
        if isinstance(key, (list, tuple)):
            keys = list(key)
            values = list(value)
            if len(values) != len(keys):
                raise MXNetError(
                    f"batched pushpull: {len(keys)} keys but "
                    f"{len(values)} values")
            if out is None:
                outs = values
            else:
                outs = list(out) if isinstance(out, (list, tuple)) \
                    else [out]
                if len(outs) != len(keys):
                    raise MXNetError(
                        f"batched pushpull: {len(keys)} keys but "
                        f"{len(outs)} outs")
            if isinstance(priority, (list, tuple)):
                if len(priority) != len(keys):
                    raise MXNetError(
                        f"batched pushpull: {len(keys)} keys but "
                        f"{len(priority)} priorities")
                priorities = [int(p) for p in priority]
            else:
                priorities = [int(priority)] * len(keys)
            return self._pushpull_batched(keys, values, outs, priorities)
        return self._pushpull_batched(
            [key], [value], [out if out is not None else value],
            [int(priority)])

    def _pushpull_batched(self, keys, values, outs, priorities):
        """Per-key decomposition — the fallback for stores without a
        fused bucketed path and for the server-side-optimizer mode
        (the updater applies per key). Still honors the priority order
        (descending, stable)."""
        for i in sorted(range(len(keys)), key=lambda j: -priorities[j]):
            self.push(keys[i], values[i], priorities[i])
            self.pull(keys[i], outs[i], priorities[i])

    def row_sparse_pull(self, key, out, priority=0, row_ids=None):
        """Pull ONLY the requested rows (reference: kvstore.py::
        row_sparse_pull for RowSparseNDArray weights).

        ``row_ids``: int NDArray of row indices (duplicates fine). The
        pulled rows are gathered server-side — the traffic and the
        ``out`` payload are O(len(row_ids) x dim), never the full table.
        ``out`` RowSparseNDArrays get a factored (indices, values)
        payload; dense NDArrays get rows written in place.
        """
        if row_ids is None:
            return self.pull(key, out, priority)
        if isinstance(key, (list, tuple)):
            rids = row_ids if isinstance(row_ids, (list, tuple)) \
                else [row_ids] * len(key)
            for k, o, r in zip(key, out, rids):
                self.row_sparse_pull(k, o, priority, r)
            return
        import jax.numpy as jnp

        from ..ndarray.sparse import RowSparseNDArray

        key = self._canon(key)
        self._check_init(key)
        src = self._store[key]
        outs = out if isinstance(out, (list, tuple)) else [out]
        rows = row_ids.data.astype(jnp.int32) \
            if isinstance(row_ids, NDArray) else jnp.asarray(
                row_ids, dtype=jnp.int32)
        # pad/dedupe slots park on an OUT-OF-RANGE sentinel so they can
        # never alias a real table row; scatter drops them, factored
        # getters compress them out
        rows = jnp.unique(rows, size=rows.size, fill_value=src.shape[0])
        vals = src.data[rows]            # sentinel reads clamp (ignored)
        for o in outs:
            if isinstance(o, RowSparseNDArray):
                o.set_rows(rows, vals, src.shape)
            else:
                o._set_data(o.data.at[rows].set(vals, mode="drop"))

    def set_optimizer(self, optimizer):
        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    def set_gradient_compression(self, compression_params):
        """2-bit threshold quantization with error feedback on every
        pushed gradient (reference: kvstore.py::set_gradient_compression
        → gradient_compression.cc)."""
        from .gradient_compression import create_compression

        self._compression = create_compression(compression_params)

    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("no optimizer set on this kvstore")
        from ..checkpoint import atomic_write

        atomic_write(fname, self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("no optimizer set on this kvstore")
        from ..checkpoint import apply_state_bytes, read_state_bytes

        states = read_state_bytes(fname, "load_optimizer_states")
        apply_state_bytes(states, self._updater.set_states, fname,
                          "load_optimizer_states")

    def barrier(self, site: str = "user", timeout: Optional[float] = None):
        """Synchronization barrier, BOUNDED (reference: kvstore.py::
        barrier — an unbounded ``waitall``). Drains local async device
        work within ``timeout`` seconds (default
        ``MXNET_KV_BARRIER_TIMEOUT``, 300; <= 0 restores the unbounded
        wait); distributed stores additionally rendezvous every process.
        On expiry raises :class:`BarrierTimeoutError` naming ``site``
        (and, cross-process, the missing ranks) instead of wedging the
        job on a dead worker. Fault site ``kvstore.barrier``."""
        timeout = _barrier_timeout_s() if timeout is None \
            else float(timeout)
        if _fault_state.enabled:
            fault.check("kvstore.barrier", site)
        _bounded_waitall(site, timeout)

    def _barrier_before_exit(self) -> bool:
        """Bounded exit drain (was a no-op): let a multi-process job's
        ranks leave together, but NEVER wedge teardown — a barrier
        timeout (dead peer) is reported as a warning carrying the typed
        error and exit proceeds. Returns True when the barrier
        completed. ``MXNET_KV_EXIT_BARRIER_TIMEOUT`` (default 10 s,
        capped by the main barrier knob) bounds the wait."""
        try:
            cap = _barrier_timeout_s()
            timeout = float(os.environ.get(
                "MXNET_KV_EXIT_BARRIER_TIMEOUT", "10"))
            if cap > 0:
                timeout = min(timeout, cap)
        except Exception:  # noqa: BLE001 - incl. MXNetError from a
            # malformed knob: this runs from atexit, never raise
            timeout = 10.0
        try:
            self.barrier(site="exit", timeout=timeout)
            return True
        except Exception as e:  # noqa: BLE001 - exit path: warn, never
            # raise (incl. a coordination client already torn down by
            # interpreter shutdown — this runs from atexit)
            warnings.warn(
                f"kvstore exit barrier abandoned (exit continues): {e}",
                RuntimeWarning, stacklevel=2)
            return False


class KVStoreLocal(KVStore):
    """Single-process aggregation across device copies
    (reference: src/kvstore/kvstore_local.h + comm.h::CommCPU/CommDevice).

    'local' reduces via a host-side sum, 'device' sums on the first device —
    with XLA both are a single fused add chain; the distinction is kept for
    API parity."""

    def __init__(self, type_name="local"):
        super().__init__(type_name)
        self._store: Dict = {}
        # fused-pushpull bucket cap (bytes); 0 disables bucketing.
        # Mutable attribute so benches/dryruns can force the per-key
        # path on one store without touching the environment.
        self._bucket_bytes = bucket_cap_bytes()
        # key sets already warned about falling off the fused path (one
        # warning per distinct set, not one per step)
        self._warned_fallback: set = set()

    def init(self, key, value):
        key = self._canon(key)
        if isinstance(value, (list, tuple)):
            value = value[0]
        self._store[key] = value.copy()

    def _canon(self, key):
        return key if isinstance(key, (int, str)) else int(key)

    def _check_init(self, key):
        if key not in self._store:
            raise MXNetError(f"kvstore key {key!r} was not initialized")

    def push(self, key, value, priority=0):
        if isinstance(key, (list, tuple)):
            for k, v in zip(key, value):
                self.push(k, v, priority)
            return
        _tel = _telemetry_state.enabled
        t0 = time.perf_counter() if _tel else 0.0
        key = self._canon(key)
        self._check_init(key)
        vals = list(value) if isinstance(value, (list, tuple)) else [value]
        if self._compression is not None:
            # quantize each worker-slot's gradient before the reduce —
            # the same point the reference compresses before the wire.
            # NOT inside the retry: compression carries error-feedback
            # state, so re-compressing on retry would double-apply it.
            vals = [self._compression.compress(key, i, v)
                    for i, v in enumerate(vals)]

        def _reduce():
            if _fault_state.enabled:
                fault.check("kvstore.push", f"key {key!r}")
            return self._aggregate(vals)

        # bounded exponential-backoff retry around the device work only
        # (the reduce); the updater/store application below runs once —
        # retrying a half-applied optimizer update is not idempotent
        agg = fault.retry_call("kvstore.push", _reduce,
                               detail=f"key {key!r}")
        if self._updater is not None:
            # server-side optimizer path (update_on_kvstore=True). The key
            # itself indexes updater state: ints and strings are both
            # stable across processes/restarts (hash() is neither).
            self._updater(key, agg, self._store[key])
        else:
            self._store_reduced(key, agg)
        if _tel:
            telemetry.record_kv("push", _payload_bytes(vals),
                                time.perf_counter() - t0)
            telemetry.record_kv_collective("per_key")

    def _aggregate(self, vals: List[NDArray]) -> NDArray:
        """Reduce per-device copies to one value (subclass hook).

        ONE fused stack-and-sum dispatch instead of N-1 sequential
        in-place adds (each of which was its own XLA dispatch); copies
        living on other devices are staged onto the first copy's device
        first. The reduction order over the N copies is fixed by the
        stack, so results are deterministic across calls."""
        if len(vals) == 1:
            return vals[0]
        import jax

        dev = next(iter(vals[0].data.devices()))
        arrs = [v.data if next(iter(v.data.devices())) == dev
                else jax.device_put(v.data, dev) for v in vals]
        return NDArray(data=_fused_sum(arrs), ctx=vals[0].context)

    def _store_reduced(self, key, agg: NDArray):
        # snapshot the (immutable) payload — never alias the caller's
        # NDArray, which it may keep mutating in place
        dst = self._store[key]
        dst._set_data(agg.as_in_context(dst.context).data
                      if dst.context != agg.context else agg.data)

    def pull(self, key, out, priority=0, ignore_sparse=True):
        if isinstance(key, (list, tuple)):
            for k, o in zip(key, out):
                self.pull(k, o, priority)
            return
        _tel = _telemetry_state.enabled
        t0 = time.perf_counter() if _tel else 0.0
        key = self._canon(key)
        self._check_init(key)
        outs = out if isinstance(out, (list, tuple)) else [out]
        src = self._store[key]

        def _copy_out():
            if _fault_state.enabled:
                fault.check("kvstore.pull", f"key {key!r}")
            for o in outs:
                o._set_data(src.as_in_context(o.context).data
                            if o.context != src.context else src.data)

        # idempotent (plain overwrite of the outs) — safe to retry whole
        fault.retry_call("kvstore.pull", _copy_out, detail=f"key {key!r}")
        if _tel:
            telemetry.record_kv("pull", _nd_bytes(src) * len(outs),
                                time.perf_counter() - t0)

    # -- bucketed fused pushpull ---------------------------------------
    def _pushpull_batched(self, keys, values, outs, priorities):
        """The fused entry: keys are coalesced into dtype-segregated flat
        buckets (``MXNET_KV_BUCKET_MB``) and each bucket is reduced by
        ONE dispatch (`_bucket_reduce` — a fused stack-and-sum here, one
        compiled psum in ``tpu_sync``), then scattered back into the
        per-param store entries and out views.

        Pipelining: buckets are processed in descending-priority order
        and bucket *i+1*'s reduce is dispatched BEFORE bucket *i*'s
        scatter, so the collective runs while the host enqueues the
        previous bucket's unpack (JAX async dispatch — nothing here
        blocks on device work).

        Falls back to the per-key decomposition when the fused path
        cannot apply: server-side optimizer installed (the updater
        applies per key), bucketing disabled (``MXNET_KV_BUCKET_MB=0``
        or ``store._bucket_bytes = 0``), or — per key — a payload that
        is not a dense NDArray (row-sparse gradients keep their
        specialized path).
        """
        if self._updater is not None or self._bucket_bytes <= 0:
            return KVStore._pushpull_batched(
                self, keys, values, outs, priorities)
        _tel = _telemetry_state.enabled
        t0 = time.perf_counter() if _tel else 0.0
        order = sorted(range(len(keys)), key=lambda j: -priorities[j])
        entries = []          # planner input, in dispatch order
        fallback = set()      # positions exchanged per-key
        vals_by_pos: Dict = {}
        outs_by_pos: Dict = {}
        total_bytes = 0
        for pos in order:
            key = self._canon(keys[pos])
            self._check_init(key)
            vals = list(values[pos]) if isinstance(
                values[pos], (list, tuple)) else [values[pos]]
            outs_i = list(outs[pos]) if isinstance(
                outs[pos], (list, tuple)) else [outs[pos]]
            vals_by_pos[pos] = (key, vals)
            outs_by_pos[pos] = outs_i
            entry = self._bucket_entry(pos, vals, outs_i)
            if entry is None:
                fallback.add(pos)
                continue
            entries.append(entry)
            # pushed copies in + pulled outs back, matching what the
            # per-key path records under push+pull — the two paths'
            # byte counters must stay comparable
            total_bytes += entry[4] * (len(vals) + len(outs_i))
        if fallback:
            # the coverage gap is OBSERVABLE (ISSUE 19 satellite): count
            # every per-key fallback and warn once per distinct key set —
            # a model quietly paying O(keys) dispatches (or training
            # un-sharded under ZeRO) should not be a mystery
            if _tel:
                telemetry.record_kv_bucket_fallback("row_sparse",
                                                    len(fallback))
            keyset = frozenset(vals_by_pos[pos][0] for pos in fallback)
            if keyset not in self._warned_fallback:
                self._warned_fallback.add(keyset)
                shown = sorted(map(str, keyset))
                more = "" if len(shown) <= 8 else f" (+{len(shown) - 8})"
                warnings.warn(
                    f"{len(keyset)} key(s) fell back to per-key pushpull "
                    f"(non-default storage, e.g. row_sparse): "
                    f"{shown[:8]}{more} — these keys are outside the "
                    "fused-bucket (and ZeRO) path",
                    stacklevel=3)
        buckets = plan_buckets(entries, self._bucket_bytes)
        # one dispatch plan in global priority order: a bucket is issued
        # at its FIRST member's slot, per-key fallbacks (sparse payloads)
        # at their own slot — not banished behind every bucket
        bucket_at = {b.indices[0]: b for b in buckets}
        pending = None
        for pos in order:
            b = bucket_at.get(pos)
            if b is not None:
                reduced = self._bucket_exchange_reduce(b, vals_by_pos)
                if _tel:
                    telemetry.record_kv_bucket(b.nbytes, len(b))
                    telemetry.record_kv_collective(
                        self._bucket_path_label(b))
                if pending is not None:
                    self._bucket_scatter(pending[0], pending[1],
                                         vals_by_pos, outs_by_pos)
                pending = (b, reduced)
            elif pos in fallback:
                if pending is not None:
                    self._bucket_scatter(pending[0], pending[1],
                                         vals_by_pos, outs_by_pos)
                    pending = None
                key, vals = vals_by_pos[pos]
                self.push(key, vals, priorities[pos])
                self.pull(key, outs_by_pos[pos], priorities[pos])
        if pending is not None:
            self._bucket_scatter(pending[0], pending[1],
                                 vals_by_pos, outs_by_pos)
        if _tel:
            telemetry.record_kv("pushpull", total_bytes,
                                time.perf_counter() - t0)

    def _bucket_path_label(self, bucket) -> str:
        """Telemetry ``path`` label for one fused-bucket dispatch —
        ``bucketed`` here; ``tpu_sync`` reports ``hierarchical`` when a
        host topology factors its mesh (the label then counts INTER-HOST
        collectives: exactly one per bucket)."""
        return "bucketed"

    @staticmethod
    def _bucket_entry(pos, vals, outs_i):
        """Planner entry for one key's payload, or None for the per-key
        fallback (any non-dense val OR out). The single eligibility/
        grouping rule shared by ``_pushpull_batched`` and
        ``plan_pushpull`` — the dry-run must never predict a bucket the
        batched path would not form. Group: members of one bucket must
        share dtype, copy count and per-slot device placement so each
        slot packs into one same-device flat buffer."""
        if not all(getattr(a, "stype", "default") == "default"
                   for a in vals + outs_i):
            return None
        v0 = vals[0]
        devsig = tuple(str(next(iter(v.data.devices()))) for v in vals)
        return (pos, tuple(v0.shape), v0.dtype,
                (str(v0.dtype), len(vals), devsig), _nd_bytes(v0))

    def plan_pushpull(self, keys, values, priorities=None, outs=None):
        """Dry-run of ``_pushpull_batched``'s bucket plan: the key GROUPS
        a batched call with these arguments would coalesce, as lists of
        positions into ``keys``, in dispatch (descending-priority) order.

        The overlapped-comms Trainer uses this to dispatch each group as
        its own ``pushpull`` the moment its members' gradients finalize
        during backward: a group re-planned alone reproduces exactly the
        batched call's bucket (same members, same flat-buffer layout,
        same reduce arity), so the overlapped exchange stays bit-identical
        to the one-shot batched path. Per-key fallbacks (sparse payloads,
        bucketing disabled, server-side optimizer) come back as singleton
        groups. ``outs`` defaults to ``values`` (the Trainer's in-place
        exchange); pass the real outs when they differ — eligibility
        depends on both.
        """
        n = len(keys)
        priorities = [0] * n if priorities is None else \
            [int(p) for p in priorities]
        order = sorted(range(n), key=lambda j: -priorities[j])
        if self._updater is not None or self._bucket_bytes <= 0:
            return [[pos] for pos in order]
        if outs is None:
            outs = values
        entries = []
        fallback = set()
        for pos in order:
            vals = list(values[pos]) if isinstance(
                values[pos], (list, tuple)) else [values[pos]]
            outs_i = list(outs[pos]) if isinstance(
                outs[pos], (list, tuple)) else [outs[pos]]
            entry = self._bucket_entry(pos, vals, outs_i)
            if entry is None:
                fallback.add(pos)
                continue
            entries.append(entry)
        buckets = plan_buckets(entries, self._bucket_bytes)
        bucket_at = {b.indices[0]: b for b in buckets}
        groups = []
        for pos in order:
            b = bucket_at.get(pos)
            if b is not None:
                groups.append(list(b.indices))
            elif pos in fallback:
                groups.append([pos])
        return groups

    def _bucket_exchange_reduce(self, bucket, vals_by_pos):
        """Pack each device slot's member gradients into one flat buffer
        (one jitted dispatch per slot), compress per bucket when a
        compressor is set, and reduce the slots. Returns the reduced
        flat jax array."""
        nslots = bucket.group[1]
        flats = []
        for s in range(nslots):
            flat = pack([vals_by_pos[pos][1][s].data
                         for pos in bucket.indices])
            if self._compression is not None:
                # per-BUCKET quantize: one jitted kernel over the flat
                # buffer, residual keyed by the bucket's member keys —
                # compression cost stops scaling with parameter count.
                # NOT inside the retry below: error-feedback state, so a
                # retry must not re-apply it (same rule as push()).
                bkey = tuple(vals_by_pos[pos][0]
                             for pos in bucket.indices)
                flat = self._compression.compress_flat(bkey, s, flat)
            flats.append(flat)

        def _reduce():
            if _fault_state.enabled:
                fault.check("kvstore.push",
                            f"bucket[{len(bucket)} keys]")
            return self._bucket_reduce(flats)

        return fault.retry_call("kvstore.push", _reduce,
                                detail=f"bucket[{len(bucket)} keys]")

    def _bucket_reduce(self, flats):
        """Reduce per-slot flat buffers to one (subclass hook): fused
        stack-and-sum on the first slot's device — the flat-buffer twin
        of `_aggregate`, elementwise-identical to reducing each member
        in its own per-key call."""
        if len(flats) == 1:
            return flats[0]
        import jax

        dev = next(iter(flats[0].devices()))
        arrs = [f if next(iter(f.devices())) == dev
                else jax.device_put(f, dev) for f in flats]
        return _fused_sum(arrs)

    def _bucket_scatter(self, bucket, reduced, vals_by_pos, outs_by_pos):
        """Unpack the reduced flat buffer back into the store entries and
        every out view — ONE jitted unpack dispatch per target device
        (replicated tpu_sync results scatter from each device's local
        shard; other devices get one whole-flat transfer, not one per
        key)."""
        import jax

        unpack = unpacker(bucket.shapes)
        shard_by_dev = {s.device: s.data
                        for s in getattr(reduced, "addressable_shards", [])} \
            if hasattr(reduced, "sharding") \
            and len(reduced.sharding.device_set) > 1 else {}
        pieces_by_dev: Dict = {}

        def pieces_for(dev):
            p = pieces_by_dev.get(dev)
            if p is None:
                f = shard_by_dev.get(dev)
                if f is None:
                    if shard_by_dev:
                        f = jax.device_put(
                            next(iter(shard_by_dev.values())), dev)
                    else:
                        f = reduced \
                            if next(iter(reduced.devices())) == dev \
                            else jax.device_put(reduced, dev)
                p = unpack(f)
                pieces_by_dev[dev] = p
            return p

        def _copy_out():
            if _fault_state.enabled:
                fault.check("kvstore.pull",
                            f"bucket[{len(bucket)} keys]")
            for j, pos in enumerate(bucket.indices):
                key = vals_by_pos[pos][0]
                dst = self._store[key]
                dst._set_data(pieces_for(dst.context.jax_device())[j])
                for o in outs_by_pos[pos]:
                    o._set_data(pieces_for(o.context.jax_device())[j])

        # idempotent overwrite — safe to retry whole, like pull()
        fault.retry_call("kvstore.pull", _copy_out,
                         detail=f"bucket[{len(bucket)} keys]")


class KVStoreTPUSync(KVStoreLocal):
    """Collective data-parallel sync over the device mesh.

    Reference roles replaced: ``kvstore_nccl.h::KVStoreNCCL`` (intra-node
    collectives) and ``kvstore_dist.h`` sync mode (multi-host). A push of
    per-device gradient copies lowers to ONE compiled XLA all-reduce
    (``shard_map`` + ``lax.psum`` over a device mesh). Single-process: the
    mesh is the devices holding the copies (psum rides ICI). Multi-process
    (``dist_sync`` after the ``jax.distributed`` bootstrap): the mesh is
    ALL processes' devices — each process contributes its local copies and
    the psum crosses hosts over DCN. The reduced value is a replicated
    ``jax.Array``, so ``pull`` into any participating device's context is
    a local view, not a transfer.
    """

    def __init__(self, type_name="tpu_sync"):
        super().__init__(type_name)
        if type_name in ("dist_sync", "dist_device_sync"):
            _maybe_init_distributed()
            # dist modes are SUPERVISED: ranks leave through a bounded
            # exit barrier (never wedging on a dead peer)
            _register_exit_barrier(self)
        self._mesh = None
        self._reducers: Dict = {}
        # topology-aware hierarchical collectives: number of (virtual)
        # hosts the mesh slots factor into, or None to resolve from
        # MXNET_KV_HOSTS ("auto" = one host per process). When a
        # topology is active the reduce mesh is 2-D ("dcn" x "ici") and
        # every bucket reduction is ONE collective over the factored
        # mesh — XLA's lowering runs the intra-host phase on ICI and
        # crosses DCN once per host pair, and the combined-axes psum is
        # bit-identical to the flat 1-D psum (tests/test_zero.py).
        self._hier_hosts: Optional[int] = None
        # cross-process barrier namespace: (store creation ordinal, per-
        # site sequence). The ordinal is SPMD-consistent (every process
        # creates its stores in the same program order), and keeps two
        # stores' barriers from aliasing each other's rendezvous keys.
        global _STORE_ORDINAL
        _STORE_ORDINAL += 1
        self._barrier_ns = _STORE_ORDINAL
        self._barrier_seq: Dict[str, int] = {}
        self._barrier_epoch = _BARRIER_EPOCH

    def _next_barrier_seq(self, site: str) -> Tuple[int, str]:
        """Allocate this barrier's (sequence, key namespace). Sequences
        count per site IN process memory, so they are re-based whenever
        the elastic membership epoch advanced (``reset_barrier_epoch``):
        every survivor clears its counters at the transition and a
        restarted rank's counters are fresh anyway, so all ranks meet at
        seq 1 under the epoch-tagged namespace instead of the survivors
        announcing seq k+1 against a rejoiner's seq 1 forever."""
        if self._barrier_epoch != _BARRIER_EPOCH:
            self._barrier_epoch = _BARRIER_EPOCH
            self._barrier_seq.clear()
        seq = self._barrier_seq.get(site, 0) + 1
        self._barrier_seq[site] = seq
        return seq, f"e{self._barrier_epoch}/s{self._barrier_ns}/"

    def barrier(self, site: str = "user", timeout: Optional[float] = None):
        """Local drain + cross-process rendezvous, both bounded. The
        rendezvous rides the coordination-service KV store (one
        announce + a poll loop — per-site sequence numbers keep repeated
        barriers distinct under the SPMD contract that every process
        calls them in the same order, re-based at each elastic epoch so
        restarted ranks re-converge), so a timeout can name exactly
        which ranks never arrived — the diagnostic a hung psum cannot
        give. Wrapped in ``fault.retry_call`` at ``kvstore.barrier``
        (announcements are idempotent)."""
        timeout = _barrier_timeout_s() if timeout is None \
            else float(timeout)
        t0 = time.monotonic()
        super().barrier(site, timeout)
        import jax

        if jax.process_count() <= 1:
            return
        client = _coord_client()
        if client is None:       # bootstrapped out-of-band (TPU pod rt)
            return
        # ONE budget for the whole barrier: the rendezvous gets what the
        # local drain left (floored so an instant drain cannot zero it),
        # not a fresh timeout — callers rely on the documented bound
        remaining = timeout if timeout <= 0 else \
            max(0.05, timeout - (time.monotonic() - t0))
        seq, key_ns = self._next_barrier_seq(site)
        fault.retry_call(
            "kvstore.barrier",
            lambda: _cross_process_barrier(
                client, site, seq, self.rank, self.num_workers,
                remaining, key_ns=key_ns),
            detail=f"site {site!r} seq {seq}")

    def attach_mesh(self, mesh):
        """Pin the reduction mesh (default: pushed copies' own devices in
        single-process mode, all global devices in multi-process mode)."""
        self._mesh = mesh

    def set_topology(self, hosts) -> None:
        """Declare the host topology for hierarchical collectives.

        ``hosts``: how many (virtual) hosts the mesh slots split into —
        the mesh becomes ``(hosts, slots_per_host)`` with axes
        ``("dcn", "ici")`` and every bucket reduce is one psum over the
        factored mesh. ``"auto"`` derives one host per process;
        ``None``/``0``/``1`` restores the flat 1-D mesh. Slots group
        contiguously in device-id order, matching how
        ``--xla_force_host_platform_device_count`` virtualizes hosts and
        how real pods enumerate chips per host."""
        if hosts in (None, 0, 1):
            self._hier_hosts = 0          # explicit flat (skip the env)
        elif hosts == "auto":
            import jax

            self._hier_hosts = max(jax.process_count(), 1)
        else:
            h = int(hosts)
            if h < 1:
                raise MXNetError(f"set_topology: hosts must be >= 1 or "
                                 f"'auto', got {hosts!r}")
            self._hier_hosts = h
        self._reducers.clear()

    def _topology_hosts(self, nslots: int) -> int:
        """Resolved host count for an ``nslots``-slot mesh; 0 = flat.
        A topology that does not divide the slot count is rejected
        loudly — a silently-flat mesh would fake the DCN savings."""
        h = self._hier_hosts
        if h is None:
            raw = os.environ.get("MXNET_KV_HOSTS", "").strip()
            if not raw:
                return 0
            if raw == "auto":
                import jax

                h = max(jax.process_count(), 1)
            else:
                h = int(raw)
        if h in (0, 1) or nslots <= 1:
            return 0
        if nslots % h != 0:
            raise MXNetError(
                f"hierarchical topology: {h} hosts do not evenly divide "
                f"{nslots} mesh slots — fix MXNET_KV_HOSTS/set_topology "
                "or the per-key copy count")
        return h

    def _bucket_path_label(self, bucket) -> str:
        """``hierarchical`` when this bucket's reduce ran over a factored
        ("dcn" x "ici") mesh, else ``bucketed`` — mirrors the
        ``_needs_collective`` gate + ``_reduce_mesh`` factoring the
        exchange itself just used (the label is recorded after the
        reduce, so an invalid topology has already raised)."""
        import jax

        nslots = bucket.group[1]
        devsig = bucket.group[2]
        needs = (jax.process_count() > 1 or self._mesh is not None
                 or (nslots > 1 and len(set(devsig)) == nslots))
        if not needs:
            return "bucketed"
        if self._mesh is not None:
            total = int(self._mesh.devices.size)
        elif jax.process_count() > 1:
            total = nslots * jax.process_count()
        else:
            total = nslots
        return "hierarchical" if self._topology_hosts(total) \
            else "bucketed"

    @property
    def num_workers(self):
        import jax

        return jax.process_count()

    @property
    def rank(self):
        import jax

        return jax.process_index()

    # -- the collective ------------------------------------------------
    def _reduce_mesh(self, vals):
        """The mesh a push's psum runs over, and the devices expected to
        contribute one copy each from THIS process."""
        import jax
        import numpy as np
        from jax.sharding import Mesh

        if self._mesh is not None:
            mesh = self._mesh
            local = [d for d in mesh.devices.flat
                     if d.process_index == jax.process_index()]
            return mesh, local
        if jax.process_count() > 1:
            # one mesh slot per PUSHED COPY per process, not per device:
            # a single-context worker (one model replica per process, the
            # common deployment) pushes one copy even when the process
            # exposes several devices. The mesh depends ONLY on the copy
            # COUNT (slot i -> every process's i-th local device in id
            # order), never on which local devices this rank's copies
            # happen to sit on — per-rank placement must not produce
            # per-rank meshes (a disagreeing device set deadlocks the
            # collective). SPMD contract: every process pushes the same
            # number of copies per key; _collective_sum's device check
            # surfaces placement mismatches loudly.
            k = len(vals)
            by_proc = {}
            for d in jax.devices():       # same order on every process
                by_proc.setdefault(d.process_index, []).append(d)
            chosen = []
            for p in sorted(by_proc):
                proc_devs = sorted(by_proc[p], key=lambda d: d.id)
                chosen.extend(proc_devs[:k])
            local = [d for d in chosen
                     if d.process_index == jax.process_index()]
            return self._mesh_over(chosen), local
        devs = [next(iter(v.data.devices())) for v in vals]
        return self._mesh_over(devs), devs

    def _mesh_over(self, devs):
        """Mesh over an ordered flat device list: 1-D ``("kv",)`` by
        default; with a host topology, 2-D ``("dcn", "ici")`` — device
        order is preserved (row-major flattening of the 2-D mesh is the
        flat list), so the factored psum reduces the same operands."""
        import numpy as np
        from jax.sharding import Mesh

        hosts = self._topology_hosts(len(devs))
        if hosts:
            arr = np.array(devs).reshape(hosts, len(devs) // hosts)
            return Mesh(arr, ("dcn", "ici"))
        return Mesh(np.array(devs), ("kv",))

    def _reducer(self, mesh, ndev, shape, dtype):
        """jit(shard_map(psum)) per (mesh, ndev, shape, dtype) — compiled
        once, reused for every push of this signature (the reference
        pre-creates one NCCL reduction per key; here the executable is the
        bucket)."""
        # Mesh hashes by devices+axes, so equal meshes share the entry
        sig = (mesh, ndev, tuple(shape), str(dtype))
        fn = self._reducers.get(sig)
        if fn is None:
            import jax
            from jax.sharding import PartitionSpec as P
            from jax.experimental.shard_map import shard_map

            # all mesh axes at once: on the 2-D hierarchical mesh this is
            # ONE collective whose lowering factors into intra-host (ici)
            # + inter-host (dcn) phases, and a combined-axes psum is
            # bit-identical to the flat 1-D psum (sequential two-stage
            # psums are NOT — measured ULP drift — which is why the
            # policy factors the mesh instead of chaining collectives)
            axes = tuple(mesh.axis_names)

            def allreduce(stacked):
                # each shard is one device's (1, *shape) copy; psum over
                # the mesh and drop the stack dim
                red = shard_map(
                    lambda x: jax.lax.psum(x[0], axes), mesh=mesh,
                    in_specs=P(axes), out_specs=P())
                return red(stacked)

            fn = jax.jit(allreduce)
            self._reducers[sig] = fn
        return fn

    def _collective_sum(self, vals: List[NDArray]):
        """All-reduce per-device copies: one XLA psum over the mesh.

        The collective is wrapped in the bounded retry
        (``fault.retry_call``, site ``kvstore.allreduce``): a psum is
        stateless, so re-dispatching after a transient collective
        failure is safe. Exhaustion raises MXNetError naming the site
        and attempt count."""

        def _reduce():
            if _fault_state.enabled:
                fault.check(
                    "kvstore.allreduce",
                    f"{tuple(vals[0].shape)} x {len(vals)} copies")
            return self._collective_sum_impl(vals)

        if not _telemetry_state.enabled:
            return fault.retry_call("kvstore.allreduce", _reduce)
        t0 = time.perf_counter()
        reduced = fault.retry_call("kvstore.allreduce", _reduce)
        # payload entering the psum: one copy per mesh slot — the reduced
        # array is replicated over the mesh (out_specs=P()), so its device
        # set IS the mesh; a failed collective records nothing
        telemetry.record_kv(
            "allreduce", _nd_bytes(vals[0]) * len(reduced.sharding.device_set),
            time.perf_counter() - t0)
        return reduced

    def _collective_sum_impl(self, vals: List[NDArray]):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh, local_devs = self._reduce_mesh(vals)
        ndev = mesh.devices.size
        spec = P(tuple(mesh.axis_names))   # leading dim over ALL axes
        shape = tuple(vals[0].shape)
        by_dev = {next(iter(v.data.devices())): v for v in vals}
        if set(by_dev) != set(local_devs):
            if jax.process_count() > 1 and len(by_dev) == len(local_devs):
                # multi-process slot mesh (see _reduce_mesh): the mesh
                # slots are position-derived, so a copy pinned to a
                # different local device is relocated onto its slot
                # (deterministic: copies ordered by source device id)
                ordered = [by_dev[d] for d in
                           sorted(by_dev, key=lambda d: d.id)]
                by_dev = {ld: jax.device_put(v.data, ld)
                          for ld, v in zip(local_devs, ordered)}
                shards = [by_dev[d].reshape((1,) + shape)
                          for d in local_devs]
                stacked = jax.make_array_from_single_device_arrays(
                    (ndev,) + shape, NamedSharding(mesh, spec), shards)
                return self._reducer(mesh, ndev, shape,
                                     vals[0].dtype)(stacked)
            raise MXNetError(
                f"tpu_sync push expects one gradient copy per local mesh "
                f"device ({len(local_devs)}); got copies on "
                f"{sorted(str(d) for d in by_dev)}")
        # stack the copies as a global array sharded over 'kv' — each
        # device contributes its local shard in place (across processes,
        # make_array assembles the global view from addressable shards)
        shards = [by_dev[d].data.reshape((1,) + shape) for d in local_devs]
        stacked = jax.make_array_from_single_device_arrays(
            (ndev,) + shape, NamedSharding(mesh, spec), shards)
        return self._reducer(mesh, ndev, shape, vals[0].dtype)(stacked)

    def _needs_collective(self, arrs) -> bool:
        """Whether these per-copy jax arrays must reduce via the mesh
        collective. ONE gate shared by the per-key (`_aggregate`) and
        bucketed (`_bucket_reduce`) paths — if they disagreed, the two
        paths could pick different reduction mechanisms in the same
        configuration and the bucketed-equals-per-key bit-identity
        guarantee would silently break."""
        import jax

        return (jax.process_count() > 1 or self._mesh is not None
                or (len(arrs) > 1
                    and len({next(iter(a.devices())) for a in arrs})
                    == len(arrs)))

    def _aggregate(self, vals: List[NDArray]) -> NDArray:
        if self._needs_collective([v.data for v in vals]):
            return NDArray(data=self._collective_sum(vals),
                           ctx=vals[0].context)
        return super()._aggregate(vals)

    def _bucket_reduce(self, flats):
        """ONE compiled psum over the mesh per bucket. The reducer cache
        keys by the flat shape, so every same-layout step replays one
        executable per bucket — O(params·bytes / bucket_cap) collectives
        per step instead of O(params)."""
        if not self._needs_collective(flats):
            return super()._bucket_reduce(flats)
        wrapped = [NDArray(data=f) for f in flats]
        return self._collective_sum(wrapped)

    def _store_reduced(self, key, agg: NDArray):
        data = agg.data
        if hasattr(data, "sharding") and len(data.sharding.device_set) > 1:
            # keep the replicated multi-device array: pulls become local
            # per-device views
            self._store[key]._set_data(data)
        else:
            super()._store_reduced(key, agg)

    def pull(self, key, out, priority=0, ignore_sparse=True):
        import jax

        if isinstance(key, (list, tuple)):
            for k, o in zip(key, out):
                self.pull(k, o, priority)
            return
        _tel = _telemetry_state.enabled
        t0 = time.perf_counter() if _tel else 0.0
        key = self._canon(key)
        self._check_init(key)
        outs = out if isinstance(out, (list, tuple)) else [out]
        src = self._store[key]
        data = src.data
        # replicated jax.Array: per-device shards are local views of the
        # reduced value (works even when the array spans other processes'
        # devices, where a whole-array device_put would be illegal)
        shard_by_dev = {s.device: s.data
                        for s in getattr(data, "addressable_shards", [])} \
            if hasattr(data, "sharding") \
            and len(data.sharding.device_set) > 1 else {}

        def _copy_out():
            if _fault_state.enabled:
                fault.check("kvstore.pull", f"key {key!r}")
            for o in outs:
                dev = o.context.jax_device()
                if dev in shard_by_dev:
                    o._set_data(shard_by_dev[dev])
                else:
                    o._set_data(src.as_in_context(o.context).data
                                if o.context != src.context else data)

        fault.retry_call("kvstore.pull", _copy_out, detail=f"key {key!r}")
        if _tel:
            telemetry.record_kv("pull", _nd_bytes(src) * len(outs),
                                time.perf_counter() - t0)


class KVStoreDistAsyncEmu(KVStoreTPUSync):
    """Bounded-staleness emulation of the reference's ``dist_async`` mode
    (reference: kvstore_dist.h server mode over ps-lite — workers push
    gradients, servers apply the optimizer immediately, no cross-worker
    barrier, unbounded staleness).

    TPU pods have no parameter server, and XLA collectives are
    synchronous by construction — true unbounded-async cannot exist
    in this execution model. The emulation keeps the convergence-relevant
    property (each worker trains on locally-stale weights, applying its
    own updates without waiting for peers) with a BOUND instead: the
    server-side optimizer runs on the process-local replica at every
    push, and every ``MXNET_KVSTORE_ASYNC_STALENESS`` pushes per key
    (default 4) the replicas are averaged with one psum across processes.
    ``staleness=1`` degenerates to per-step synchronous weight averaging.

    Opt-in via ``MXNET_KVSTORE_DIST_ASYNC_EMU=1`` because the semantics
    are an approximation of the reference's, not a match — ADR-002
    records the decision (SURVEY.md §5.8 "deprecated with emulation
    shim").

    **Lockstep push-count contract.** The replica sync triggers every
    ``staleness`` pushes per key, counted process-locally, and runs a
    collective — so every process must push every key the SAME number
    of times (the natural shape: identical training loops over equal
    step counts). Uneven per-key push counts would leave the fast
    processes inside a psum the slow ones never join; the sync
    therefore runs a bounded rendezvous first
    (``MXNET_KV_BARRIER_TIMEOUT``, default 300 s) and raises
    :class:`BarrierTimeoutError` naming the key and the missing ranks
    instead of deadlocking. ADR-002 records the contract.
    """

    def __init__(self, type_name="dist_async"):
        import os

        super().__init__(type_name)
        _maybe_init_distributed()
        self._staleness = max(1, int(os.environ.get(
            "MXNET_KVSTORE_ASYNC_STALENESS", "4")))
        self._push_count: Dict = {}

    @property
    def staleness(self) -> int:
        return self._staleness

    def push(self, key, value, priority=0):
        if isinstance(key, (list, tuple)):
            for k, v in zip(key, value):
                self.push(k, v, priority)
            return
        _tel = _telemetry_state.enabled
        t0 = time.perf_counter() if _tel else 0.0
        key = self._canon(key)
        self._check_init(key)
        if self._updater is None:
            raise MXNetError(
                "dist_async requires the server-side optimizer "
                "(set_optimizer / Trainer with update_on_kvstore=True), "
                "matching the reference's async server mode")
        vals = list(value) if isinstance(value, (list, tuple)) else [value]
        if self._compression is not None:
            vals = [self._compression.compress(key, i, v)
                    for i, v in enumerate(vals)]
        # LOCAL aggregation only — the async property: no cross-process
        # barrier on the push path

        def _reduce():
            if _fault_state.enabled:
                fault.check("kvstore.push", f"key {key!r}")
            return KVStoreLocal._aggregate(self, vals)

        agg = fault.retry_call("kvstore.push", _reduce,
                               detail=f"key {key!r}")
        self._updater(key, agg, self._store[key])
        n = self._push_count[key] = self._push_count.get(key, 0) + 1
        if n % self._staleness == 0:
            self._sync_replicas(key)
        if _tel:
            telemetry.record_kv("push", _payload_bytes(vals),
                                time.perf_counter() - t0)
            telemetry.record_kv_collective("per_key")

    def _pushpull_batched(self, keys, values, outs, priorities):
        # Server-side optimizer semantics: the updater (and the
        # bounded-staleness replica sync) applies per KEY, so the
        # batched form decomposes here; the per-push local slot
        # aggregation is already one fused stack-and-sum dispatch.
        return KVStore._pushpull_batched(self, keys, values, outs,
                                         priorities)

    def _sync_replicas(self, key):
        """Average the process-local replicas: one psum over all
        processes' devices (each local device contributes replica /
        n_local, so every process has unit weight regardless of its
        device count), then divide by the process count.

        LOCKSTEP CONTRACT (see the class docstring and ADR-002): the
        sync fires every ``staleness`` pushes *per key*, counted
        process-locally — so every process must push each key the same
        number of times. Uneven per-key push counts leave some
        processes inside this collective and others never arriving,
        which would wedge the psum forever; a bounded rendezvous runs
        first (``MXNET_KV_BARRIER_TIMEOUT``) and raises
        :class:`BarrierTimeoutError` NAMING the key and the missing
        ranks instead."""
        import jax

        if jax.process_count() == 1:
            return
        client = _coord_client()
        if client is not None:
            # pre-collective rendezvous, bounded: the psum itself can
            # give no diagnostic when a peer never joins
            timeout = _barrier_timeout_s()
            # ONE site string for both the sequence counter and the
            # rendezvous keys: allocating under one name but announcing
            # under another would let an identically-named user barrier
            # (independent counter) alias this rendezvous's KV prefix
            # and release ranks that never actually met
            site = f"async_sync/{key}"
            seq, key_ns = self._next_barrier_seq(site)
            try:
                # tight poll: this runs per key every `staleness` pushes
                # on a throughput path — the default 50 ms tick would
                # quantize every sync by up to a tick per rank
                _cross_process_barrier(
                    client, site, seq, self.rank,
                    self.num_workers, timeout, poll_interval=0.003,
                    key_ns=key_ns)
            except BarrierTimeoutError as e:
                raise BarrierTimeoutError(
                    f"dist_async replica sync for key {key!r} (sync "
                    f"#{seq}) timed out: not every process reached "
                    f"push-count multiple {self._staleness} for this "
                    "key — dist_async requires LOCKSTEP per-key push "
                    "counts across processes (see ADR-002); underlying: "
                    f"{e}") from e
        src = self._store[key]
        local = jax.local_devices()
        scaled = src.data / float(len(local))
        copies = [NDArray(data=jax.device_put(scaled, d), ctx=src.context)
                  for d in local]
        total = self._collective_sum(copies)
        # materialize the mean as a process-LOCAL array on the replica's
        # own device: async pulls are local by contract, and the next
        # push's updater keeps applying to a single-device replica
        mean = total.addressable_data(0) / float(jax.process_count())
        dev = next(iter(src.data.devices()))
        src._set_data(jax.device_put(mean, dev))


def _maybe_init_distributed():
    """Bootstrap ``jax.distributed`` for multi-host dist_sync.

    Env contract (SURVEY.md §5.6.4): the reference launcher exports
    ``DMLC_PS_ROOT_URI``/``DMLC_PS_ROOT_PORT``/``DMLC_NUM_WORKER``/
    ``DMLC_WORKER_ID``; the TPU-native launcher (tools/launch.py) exports
    the same names, mapped here onto the JAX coordination service. When
    DMLC_* vars are set they win (they are passed explicitly, overriding
    JAX's own env); a job already initialized by the user or a TPU-pod
    runtime is left untouched.
    """
    import os

    uri = os.environ.get("DMLC_PS_ROOT_URI")
    n = int(os.environ.get("DMLC_NUM_WORKER", "1"))
    if not uri or n <= 1:
        return
    if dist_initialized():
        return  # coordination service already up (launcher or user)
    port = os.environ.get("DMLC_PS_ROOT_PORT", "9091")
    rank = int(os.environ.get("DMLC_WORKER_ID", "0"))
    # the rendezvous is BOUNDED: a worker that never comes up must
    # surface as a typed error naming the site, not an eternal hang
    # (MXNET_KV_BOOTSTRAP_TIMEOUT, falling back to the barrier knob)
    timeout_s = _bootstrap_timeout_s()
    import jax

    try:
        jax.distributed.initialize(
            coordinator_address=f"{uri}:{port}",
            num_processes=n, process_id=rank,
            initialization_timeout=timeout_s)
    except Exception as e:
        raise MXNetError(
            f"kvstore.bootstrap: jax.distributed rendezvous at "
            f"{uri}:{port} failed for rank {rank}/{n} within "
            f"{timeout_s}s: {e} — check that all {n} workers launched "
            "(tools/launch.py supervises and restarts them) and that "
            "the coordinator address/port is reachable") from e
