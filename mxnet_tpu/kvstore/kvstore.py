"""KVStore implementations (see package docstring for the design map)."""
from __future__ import annotations

import pickle
from typing import Dict, List, Optional

from .. import optimizer as opt
from ..base import MXNetError
from ..ndarray import NDArray
from ..ndarray import array as nd_array

__all__ = ["KVStore", "KVStoreLocal", "KVStoreTPUSync", "create"]


def create(name="local") -> "KVStore":
    """reference: mx.kv.create / KVStore::Create."""
    name = str(name).lower()
    if name in ("local", "local_update_cpu", "local_allreduce_cpu",
                "local_allreduce_device", "device"):
        return KVStoreLocal(name)
    if name in ("tpu_sync", "nccl", "dist_device_sync", "dist_sync"):
        return KVStoreTPUSync(name)
    if name in ("dist_async",):
        raise MXNetError(
            "kvstore 'dist_async' (parameter-server async mode) has no "
            "TPU-native equivalent; use 'tpu_sync' (synchronous in-graph "
            "allreduce over the mesh) — SURVEY.md §5.8")
    if name in ("horovod", "byteps"):
        raise MXNetError(
            f"kvstore '{name}' plugin is replaced by 'tpu_sync' on TPU")
    raise MXNetError(f"unknown kvstore type {name!r}")


class KVStore:
    """Base interface (reference: include/mxnet/kvstore.h)."""

    def __init__(self, type_name):
        self._type = type_name
        self._updater = None
        self._optimizer = None

    @property
    def type(self):
        return self._type

    @property
    def rank(self) -> int:
        return 0

    @property
    def num_workers(self) -> int:
        return 1

    def init(self, key, value):
        raise NotImplementedError

    def push(self, key, value, priority=0):
        raise NotImplementedError

    def pull(self, key, out, priority=0, ignore_sparse=True):
        raise NotImplementedError

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        self.pull(key, out if out is not None else value, priority)

    def row_sparse_pull(self, key, out, priority=0, row_ids=None):
        # sparse is dense-backed (SURVEY.md §7.3.5)
        self.pull(key, out, priority)

    def set_optimizer(self, optimizer):
        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    def set_gradient_compression(self, compression_params):
        raise MXNetError(
            "gradient compression is a PS-path feature; not applicable to "
            "the XLA-collective backend (planned for DCN in a later round)")

    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("no optimizer set on this kvstore")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("no optimizer set on this kvstore")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    def barrier(self):
        from ..ndarray import waitall

        waitall()

    def _barrier_before_exit(self):
        pass


class KVStoreLocal(KVStore):
    """Single-process aggregation across device copies
    (reference: src/kvstore/kvstore_local.h + comm.h::CommCPU/CommDevice).

    'local' reduces via a host-side sum, 'device' sums on the first device —
    with XLA both are a single fused add chain; the distinction is kept for
    API parity."""

    def __init__(self, type_name="local"):
        super().__init__(type_name)
        self._store: Dict = {}

    def init(self, key, value):
        key = self._canon(key)
        if isinstance(value, (list, tuple)):
            value = value[0]
        self._store[key] = value.copy()

    def _canon(self, key):
        return key if isinstance(key, (int, str)) else int(key)

    def _check_init(self, key):
        if key not in self._store:
            raise MXNetError(f"kvstore key {key!r} was not initialized")

    def push(self, key, value, priority=0):
        if isinstance(key, (list, tuple)):
            for k, v in zip(key, value):
                self.push(k, v, priority)
            return
        key = self._canon(key)
        self._check_init(key)
        vals = value if isinstance(value, (list, tuple)) else [value]
        agg = vals[0]
        if len(vals) > 1:
            acc = vals[0].copyto(vals[0].context)
            for v in vals[1:]:
                acc += v.as_in_context(acc.context)
            agg = acc
        if self._updater is not None:
            # server-side optimizer path (update_on_kvstore=True)
            self._updater(key if isinstance(key, int) else hash(key),
                          agg, self._store[key])
        else:
            self._store[key]._set_data(agg.as_in_context(
                self._store[key].context).data)

    def pull(self, key, out, priority=0, ignore_sparse=True):
        if isinstance(key, (list, tuple)):
            for k, o in zip(key, out):
                self.pull(k, o, priority)
            return
        key = self._canon(key)
        self._check_init(key)
        outs = out if isinstance(out, (list, tuple)) else [out]
        src = self._store[key]
        for o in outs:
            o._set_data(src.as_in_context(o.context).data
                        if o.context != src.context else src.data)


class KVStoreTPUSync(KVStoreLocal):
    """Collective data-parallel sync over the device mesh.

    Reference roles replaced: ``kvstore_nccl.h::KVStoreNCCL`` (intra-node
    collectives) and ``kvstore_dist.h`` sync mode (multi-host). Push/pull on
    sharded arrays lower to ONE XLA allreduce riding ICI; on replicated
    single-device arrays it degenerates to the local sum. The real
    multi-chip path is exercised through ``mxnet_tpu.parallel`` (pjit'd
    train step with psum) — this object keeps the kvstore API contract so
    Module/Trainer code runs unchanged.
    """

    def __init__(self, type_name="tpu_sync"):
        super().__init__(type_name)
        self._mesh = None

    def attach_mesh(self, mesh):
        """Associate a parallel.Mesh; cross-host reduces use its axis."""
        self._mesh = mesh

    @property
    def num_workers(self):
        import jax

        return jax.process_count()

    @property
    def rank(self):
        import jax

        return jax.process_index()

    def push(self, key, value, priority=0):
        # per-process aggregation is the local sum; cross-device reduction
        # happens in-graph via psum when arrays are mesh-sharded
        super().push(key, value, priority)
