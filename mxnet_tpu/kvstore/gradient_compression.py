"""2-bit gradient compression with error feedback (reference:
``src/kvstore/gradient_compression.cc`` :: ``GradientCompression``,
python surface ``kvstore.set_gradient_compression`` /
``Trainer(compression_params={'type': '2bit', 'threshold': t})``).

The reference quantizes each gradient element to 2 bits —
``{-threshold, 0, +threshold}`` — before the wire, keeping the
quantization error in a per-key residual that is added to the next
gradient (error feedback), so the sum of transmitted values converges to
the true gradient sum. TPU-native: the compress step is a tiny jitted
elementwise kernel; the collective then runs on the compressed values.
Residuals live per (key, worker-slot), matching the reference's
per-worker residual buffers.
"""
from __future__ import annotations

from typing import Dict

from ..base import MXNetError
from ..ndarray import NDArray

__all__ = ["GradientCompression", "create_compression"]


class GradientCompression:
    """Threshold 2-bit quantizer with residual error feedback."""

    def __init__(self, threshold=0.5):
        import jax
        import jax.numpy as jnp

        threshold = float(threshold)
        if threshold <= 0:
            raise MXNetError("gradient compression threshold must be > 0")
        self.threshold = threshold
        self._residual: Dict = {}

        t = threshold

        # ONE jitted kernel per instance: jax caches per (shape, dtype),
        # so steady-state pushes hit the compile cache
        @jax.jit
        def _q(g, r):
            g2 = g.astype(jnp.float32) + r
            out = jnp.where(g2 >= t, jnp.float32(t),
                            jnp.where(g2 <= -t, jnp.float32(-t),
                                      jnp.float32(0.0)))
            return out.astype(g.dtype), g2 - out

        self._q = _q

    def compress(self, key, slot, grad: NDArray) -> NDArray:
        """Quantize ``grad + residual`` to {-t, 0, +t}; update residual."""
        import jax.numpy as jnp

        rkey = (key, slot)
        res = self._residual.get(rkey)
        if res is None:
            res = jnp.zeros(grad.shape, jnp.float32)
        out, new_res = self._q(grad.data, res)
        self._residual[rkey] = new_res
        return NDArray(data=out, ctx=grad.context)


def create_compression(params) -> GradientCompression:
    """Build from a ``compression_params`` dict (reference:
    kvstore.py::set_gradient_compression argument contract)."""
    params = dict(params or {})
    ctype = params.pop("type", None)
    if ctype != "2bit":
        raise MXNetError(
            f"unsupported gradient compression type {ctype!r} "
            "(supported: '2bit')")
    comp = GradientCompression(threshold=params.pop("threshold", 0.5))
    if params:
        raise MXNetError(
            f"unknown compression_params keys: {sorted(params)}")
    return comp
