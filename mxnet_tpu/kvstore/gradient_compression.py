"""2-bit gradient compression with error feedback (reference:
``src/kvstore/gradient_compression.cc`` :: ``GradientCompression``,
python surface ``kvstore.set_gradient_compression`` /
``Trainer(compression_params={'type': '2bit', 'threshold': t})``).

The reference quantizes each gradient element to 2 bits —
``{-threshold, 0, +threshold}`` — before the wire, keeping the
quantization error in a per-key residual that is added to the next
gradient (error feedback), so the sum of transmitted values converges to
the true gradient sum. TPU-native: the compress step is a tiny jitted
elementwise kernel; the collective then runs on the compressed values.

Two granularities share the one kernel:

* per key (:meth:`GradientCompression.compress`) — the scalar
  ``push()`` path, residual per ``(key, worker-slot)`` matching the
  reference's per-worker residual buffers;
* per BUCKET (:meth:`GradientCompression.compress_flat`) — the fused
  ``pushpull`` path quantizes a whole packed bucket in one jitted call
  with one residual per ``(bucket members, slot)``, so compression cost
  scales with bucket count, not parameter count.

The two residual namespaces are independent (scalar keys vs member-key
tuples): a store driven through BOTH paths for the same keys keeps two
error-feedback streams — pick one path per key per training run (the
trainer does).

Only floating-point gradients are quantizable; an integer-dtype payload
raises :class:`MXNetError` instead of silently casting the ±threshold
grid into garbage. Residual state is checkpointable
(:meth:`get_state` / :meth:`set_state`) and rides in
``Trainer.save_states``, so a resumed run's error feedback continues
bit-exactly.
"""
from __future__ import annotations

from typing import Dict

from .. import telemetry
from ..base import MXNetError
from ..ndarray import NDArray
from ..telemetry import _state as _telemetry_state

__all__ = ["GradientCompression", "create_compression"]

_SUPPORTED_DTYPES = ("float32", "float16", "bfloat16")


class GradientCompression:
    """Threshold 2-bit quantizer with residual error feedback."""

    def __init__(self, threshold=0.5):
        import jax
        import jax.numpy as jnp

        threshold = float(threshold)
        if threshold <= 0:
            raise MXNetError("gradient compression threshold must be > 0")
        self.threshold = threshold
        self._residual: Dict = {}

        t = threshold

        # ONE jitted kernel per instance: jax caches per (shape, dtype),
        # so steady-state pushes hit the compile cache — and the bucketed
        # path compiles per BUCKET shape, not per parameter
        @jax.jit
        def _q(g, r):
            g2 = g.astype(jnp.float32) + r
            out = jnp.where(g2 >= t, jnp.float32(t),
                            jnp.where(g2 <= -t, jnp.float32(-t),
                                      jnp.float32(0.0)))
            return out.astype(g.dtype), g2 - out

        self._q = _q

    def _check_dtype(self, dtype, what):
        if str(dtype) not in _SUPPORTED_DTYPES:
            raise MXNetError(
                f"2-bit gradient compression supports float gradients "
                f"only ({', '.join(_SUPPORTED_DTYPES)}); {what} has "
                f"dtype {dtype} — refusing to silently cast")

    def _quantize(self, rkey, data):
        import jax.numpy as jnp

        res = self._residual.get(rkey)
        if res is None:
            res = jnp.zeros(data.shape, jnp.float32)
        out, new_res = self._q(data, res)
        self._residual[rkey] = new_res
        if _telemetry_state.enabled:
            bits = getattr(data.dtype, "itemsize", 4) * 8
            telemetry.record_kv_compression(bits / 2.0, int(data.size))
        return out

    def compress(self, key, slot, grad: NDArray) -> NDArray:
        """Quantize ``grad + residual`` to {-t, 0, +t}; update residual."""
        self._check_dtype(grad.dtype, f"gradient for key {key!r}")
        return NDArray(data=self._quantize((key, slot), grad.data),
                       ctx=grad.context)

    def compress_flat(self, bucket_key, slot, flat):
        """Quantize a packed gradient bucket (a flat jax array) in one
        jitted kernel call; the error-feedback residual is keyed by the
        bucket's member keys + slot. Bucket composition is stable across
        steps for a fixed model, so the residual stream is continuous.
        """
        self._check_dtype(flat.dtype,
                          f"gradient bucket {tuple(bucket_key)!r}")
        return self._quantize((tuple(bucket_key), slot), flat)

    # -- checkpointing -------------------------------------------------
    def get_state(self) -> Dict:
        """Pickleable snapshot of the error-feedback residuals (numpy) —
        what ``Trainer.save_states`` embeds so a resumed run's
        transmitted-gradient stream continues bit-exactly."""
        import numpy as np

        return {"threshold": self.threshold,
                "residual": {k: np.asarray(v)
                             for k, v in self._residual.items()}}

    def set_state(self, state: Dict) -> None:
        """Inverse of :meth:`get_state`. A threshold mismatch raises —
        residuals accumulated under a different quantization grid would
        silently corrupt error feedback."""
        import jax.numpy as jnp

        thr = state.get("threshold")
        if thr is not None and float(thr) != self.threshold:
            raise MXNetError(
                f"gradient-compression state was saved with threshold "
                f"{thr} but this store is configured with "
                f"{self.threshold}")
        self._residual = {k: jnp.asarray(v, jnp.float32)
                          for k, v in state.get("residual", {}).items()}


def create_compression(params) -> GradientCompression:
    """Build from a ``compression_params`` dict (reference:
    kvstore.py::set_gradient_compression argument contract)."""
    params = dict(params or {})
    ctype = params.pop("type", None)
    if ctype != "2bit":
        raise MXNetError(
            f"unsupported gradient compression type {ctype!r} "
            "(supported: '2bit')")
    comp = GradientCompression(threshold=params.pop("threshold", 0.5))
    if params:
        raise MXNetError(
            f"unknown compression_params keys: {sorted(params)}")
    return comp
