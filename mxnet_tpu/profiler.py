"""``mx.profiler`` — tracing/profiling over ``jax.profiler``.

Reference surface: ``python/mxnet/profiler.py`` (``set_config``, ``set_state``
``start``/``stop``, ``dumps``, scoped annotation objects ``Task``/``Frame``/
``Event``/``Counter``/``Marker``) backed by ``src/profiler/profiler.cc``'s
chrome://tracing dump. TPU-native design: the device-side trace comes from
XLA/XProf via ``jax.profiler.start_trace`` (TensorBoard-viewable, includes
per-HLO device timelines — strictly more than the reference's per-op spans);
host-side scoped annotations lower to ``jax.profiler.TraceAnnotation`` /
``StepTraceAnnotation`` so they appear on the same timeline. ``dumps()``
returns an aggregate table of host-recorded spans, mirroring
``profiler.dumps()``'s aggregate-stats mode (``aggregate_stats.cc``).

Env: ``MXNET_PROFILER_AUTOSTART=1`` starts profiling at import, like the
reference.
"""
from __future__ import annotations

import os
import threading
import time
from collections import defaultdict
from typing import Dict, List, Optional

__all__ = [
    "set_config", "set_state", "start", "stop", "pause", "resume", "dumps",
    "dump", "state", "record_span", "Task", "Frame", "Event", "Counter",
    "Marker",
]

_lock = threading.Lock()
_config: Dict = {
    "filename": "profile.json",       # chrome-trace-style output dir/file
    "profile_all": False,
    "profile_symbolic": True,
    "profile_imperative": True,
    "profile_memory": False,
    "profile_api": False,
    "aggregate_stats": True,
    "continuous_dump": False,
}
_state = "stop"            # 'run' | 'stop' | 'pause'
_trace_dir: Optional[str] = None
_jax_trace_active = False
# host-side span aggregation: name -> [count, total_s, min_s, max_s]
_spans: Dict[str, List[float]] = defaultdict(lambda: [0, 0.0, float("inf"), 0.0])
_counters: Dict[str, float] = {}
_markers: List[tuple] = []
# pause/resume bookkeeping: cumulative excluded wall time + open pause start
_paused_total = 0.0
_pause_started: Optional[float] = None


def set_config(**kwargs):
    """Configure the profiler (reference: profiler.py::set_config).

    Accepts the reference's kwargs (``profile_all``, ``profile_symbolic``,
    ``profile_imperative``, ``profile_memory``, ``profile_api``,
    ``filename``, ``aggregate_stats``, ``continuous_dump``). ``filename``'s
    directory is where the XProf trace is written.
    """
    unknown = set(kwargs) - set(_config)
    if unknown:
        raise ValueError(f"unknown profiler config keys: {sorted(unknown)}")
    with _lock:
        _config.update(kwargs)


def state():
    return _state


def set_state(new_state="stop"):
    """'run' starts the device trace; 'stop' ends it (reference semantics)."""
    global _state, _jax_trace_active, _trace_dir, _paused_total, _pause_started
    if new_state not in ("run", "stop", "pause"):
        raise ValueError(f"bad profiler state {new_state!r}")
    with _lock:
        now = time.perf_counter()
        if new_state == "pause" and _state == "run":
            _pause_started = now
        elif _pause_started is not None and new_state in ("run", "stop"):
            # leaving pause: accumulate the excluded window
            _paused_total += now - _pause_started
            _pause_started = None
        if new_state == "run" and _state != "run":
            import jax

            _trace_dir = os.path.splitext(_config["filename"])[0] + "_xprof"
            os.makedirs(_trace_dir, exist_ok=True)
            try:
                jax.profiler.start_trace(_trace_dir)
                _jax_trace_active = True
            except RuntimeError:
                # a trace is already running (nested start) — keep host spans
                _jax_trace_active = False
        elif new_state in ("stop", "pause") and _state == "run":
            if _jax_trace_active:
                import jax

                jax.profiler.stop_trace()
                _jax_trace_active = False
        _state = new_state


def start():
    set_state("run")


def stop():
    set_state("stop")


def pause(profile_process="worker"):
    set_state("pause")


def resume(profile_process="worker"):
    set_state("run")


def dumps(reset=False, format="table"):
    """Aggregate stats of host-recorded spans, counters and markers.

    ``format="table"`` (default) mirrors ``profiler.dumps()``'s aggregate
    mode: timed spans, ``Counter`` values, ``Marker`` entries (count + last
    timestamp), with pause/resume-excluded time in the header. The
    device-side XProf trace lives in ``<filename stem>_xprof/``.

    ``format="chrome_trace"`` returns a chrome://tracing JSON string:
    aggregate span events, profiler counters as ``ph:"C"`` counter events,
    markers as instant events — with ``mx.telemetry``'s counters merged
    onto the same timeline when telemetry has data.
    """
    global _paused_total, _pause_started
    if format == "chrome_trace":
        return _dumps_chrome_trace(reset)
    if format != "table":
        raise ValueError(f"unknown dumps format {format!r}")
    mem_lines = _memory_lines()     # outside _lock: touches jax/devices
    with _lock:
        now = time.perf_counter()
        paused = _paused_total
        if _pause_started is not None:  # still paused at dump time
            paused += now - _pause_started
        lines = ["Profile Statistics:"]
        if paused > 0:
            lines.append(f"(excluded paused time: {paused * 1e3:.3f} ms)")
        lines.extend(mem_lines)
        lines.append(f"{'Name':<40}{'Calls':>8}{'Total(ms)':>12}"
                     f"{'Min(ms)':>10}{'Max(ms)':>10}{'Avg(ms)':>10}")
        for name in sorted(_spans):
            cnt, tot, mn, mx = _spans[name]
            lines.append(
                f"{name:<40}{cnt:>8}{tot * 1e3:>12.3f}{mn * 1e3:>10.3f}"
                f"{mx * 1e3:>10.3f}{tot / max(cnt, 1) * 1e3:>10.3f}")
        for name in sorted(_counters):
            lines.append(f"{name:<40}{'':>8}{_counters[name]:>12.3f}")
        by_marker: Dict[str, int] = {}
        for name, scope, ts in _markers:
            key = f"Marker::{name} ({scope})"
            by_marker[key] = by_marker.get(key, 0) + 1
        for name in sorted(by_marker):
            lines.append(f"{name:<40}{by_marker[name]:>8}")
        if reset:
            _spans.clear()
            _counters.clear()
            _markers.clear()
            _paused_total = 0.0
            if _pause_started is not None:
                # an open pause window was just reported — rebase it so
                # resume() doesn't re-account the reset portion
                _pause_started = now
        out = "\n".join(lines)
    if _trace_dir:
        out += f"\n(XProf device trace: {_trace_dir})"
    return out


def _memory_lines():
    """Per-device allocator lines for ``dumps()`` when
    ``set_config(profile_memory=True)`` — the reference's memory
    profiling view, backed by ``storage.pool_stats()`` (PjRt's BFC pool
    counters). Platforms with no stats (CPU) report zeros rather than
    vanishing, so the flag's effect is always visible."""
    if not _config["profile_memory"]:
        return []
    try:
        import jax

        from . import storage
        from .context import Context

        lines = []
        for dev in jax.local_devices():
            st = storage.pool_stats(Context(dev.platform, dev.id))
            lines.append(
                f"Memory::{dev.platform}({dev.id})"
                f"  bytes_in_use={st['bytes_in_use']}"
                f"  peak_bytes_in_use={st['peak_bytes_in_use']}"
                f"  bytes_limit={st['bytes_limit']}"
                f"  num_allocs={st['num_allocs']}")
        return lines
    except Exception:  # pragma: no cover - stats are best-effort
        return ["Memory:: (device stats unavailable)"]


def _dumps_chrome_trace(reset=False):
    import json

    from . import telemetry

    global _paused_total, _pause_started
    events = []
    with _lock:
        now = time.perf_counter()
        for name in sorted(_spans):
            cnt, tot, mn, mx = _spans[name]
            events.append({
                "name": name, "ph": "X", "pid": 0, "tid": 0, "ts": 0,
                "dur": tot * 1e6,
                "args": {"calls": cnt, "min_ms": mn * 1e3,
                         "max_ms": mx * 1e3,
                         "avg_ms": tot / max(cnt, 1) * 1e3}})
        for name in sorted(_counters):
            events.append({"name": name, "ph": "C", "pid": 0, "tid": 0,
                           "ts": now * 1e6,
                           "args": {"value": _counters[name]}})
        for name, scope, ts in _markers:
            events.append({"name": name, "ph": "i", "pid": 0, "tid": 0,
                           "ts": ts * 1e6, "s": "p",
                           "args": {"scope": scope}})
        paused = _paused_total
        if _pause_started is not None:  # still paused at dump time
            paused += now - _pause_started
        if reset:
            _spans.clear()
            _counters.clear()
            _markers.clear()
            _paused_total = 0.0
            if _pause_started is not None:
                _pause_started = now
    # merge telemetry's counter series onto the same timeline
    events.extend(telemetry.chrome_counter_events())
    # ... and the request-tracing spans (serving traces + flow-linked
    # batch spans + flight-recorder instants) when tracing is on
    from . import tracing as _req_tracing

    if _req_tracing.enabled():
        events.extend(_req_tracing.chrome_trace_events())
    doc = {"traceEvents": events, "displayTimeUnit": "ms",
           "otherData": {"excluded_paused_ms": paused * 1e3}}
    if _trace_dir:
        doc["otherData"]["xprof_trace_dir"] = _trace_dir
    return json.dumps(doc)


def record_span(name: str, seconds: float) -> None:
    """Record one already-measured span into the aggregate table.

    For runtime-internal spans whose start/stop straddle internal locks
    (e.g. ``Bulk::flush`` — the engine measures a flush while holding the
    segment lock, so a scoped ``Event`` would be misleading to users who
    ``Event(...)`` around their own code). Shows in ``dumps()`` exactly
    like a ``_Scope``-recorded span.
    """
    with _lock:
        rec = _spans[name]
        rec[0] += 1
        rec[1] += seconds
        rec[2] = min(rec[2], seconds)
        rec[3] = max(rec[3], seconds)


def dump(finished=True, profile_process="worker"):
    """Write the aggregate table next to the configured filename."""
    path = _config["filename"]
    with open(path, "w") as f:
        f.write(dumps())
    return path


class _Scope:
    """Scoped annotation: context manager + start/stop object API.

    Lowered to ``jax.profiler.TraceAnnotation`` so the span shows on the
    XProf host timeline, and recorded in the host aggregate table.
    """

    _kind = "Event"

    def __init__(self, name):
        self.name = name
        self._t0 = None
        self._ann = None

    def start(self):
        import jax

        self._t0 = time.perf_counter()
        self._ann = jax.profiler.TraceAnnotation(
            f"{self._kind}::{self.name}")
        self._ann.__enter__()
        return self

    def stop(self):
        if self._t0 is None:
            return
        dt = time.perf_counter() - self._t0
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            self._ann = None
        with _lock:
            rec = _spans[f"{self._kind}::{self.name}"]
            rec[0] += 1
            rec[1] += dt
            rec[2] = min(rec[2], dt)
            rec[3] = max(rec[3], dt)
        self._t0 = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


class Task(_Scope):
    _kind = "Task"


class Frame(_Scope):
    _kind = "Frame"


class Event(_Scope):
    _kind = "Event"


class Counter:
    """Named counter (reference: profiler.Counter): set/increment/decrement."""

    def __init__(self, name, value=0):
        self.name = name
        self.set_value(value)

    def set_value(self, value):
        with _lock:
            _counters[self.name] = float(value)

    def increment(self, delta=1):
        with _lock:
            _counters[self.name] = _counters.get(self.name, 0.0) + delta

    def decrement(self, delta=1):
        self.increment(-delta)

    def __iadd__(self, v):
        self.increment(v)
        return self

    def __isub__(self, v):
        self.decrement(v)
        return self


class Marker:
    """Instant event (reference: profiler.Marker.mark)."""

    def __init__(self, name):
        self.name = name

    def mark(self, scope="process"):
        with _lock:
            _markers.append((self.name, scope, time.perf_counter()))


if os.environ.get("MXNET_PROFILER_AUTOSTART", "0") == "1":
    set_state("run")
