"""Base utilities: errors, dtype registry, naming.

TPU-native re-implementation of the roles played by the reference's
``python/mxnet/base.py`` (ctypes plumbing, ``MXNetError``, ``check_call``)
and mshadow's dtype switch machinery (``mshadow/base.h :: kFloat32`` etc.).
There is no C ABI boundary here yet: the compute core is JAX/XLA, so the
"library handle" is the in-process JAX runtime.
"""
from __future__ import annotations

import threading

import numpy as _np

__all__ = [
    "MXNetError",
    "NotSupportedForSparseNDArray",
    "string_types",
    "numeric_types",
    "integer_types",
    "dtype_np_to_id",
    "dtype_id_to_np",
    "name_manager",
]


class MXNetError(RuntimeError):
    """Framework-level error (reference: ``python/mxnet/base.py :: MXNetError``)."""


class NotSupportedForSparseNDArray(MXNetError):
    def __init__(self, function, alias, *args):
        super().__init__(
            f"Function {function.__name__}"
            f" (alias: {alias}) is not supported for SparseNDArray."
        )


string_types = (str,)
numeric_types = (float, int, _np.generic)
integer_types = (int, _np.integer)

# dtype id table mirrors mshadow's TypeFlag ordering so that serialized
# .params files and symbol.json attrs keep the same integer codes
# (reference: mshadow/base.h :: kFloat32=0, kFloat64=1, kFloat16=2,
# kUint8=3, kInt32=4, kInt8=5, kInt64=6, kBool=7, plus bf16 extension).
_DTYPE_NP_TO_ID = {
    _np.dtype("float32"): 0,
    _np.dtype("float64"): 1,
    _np.dtype("float16"): 2,
    _np.dtype("uint8"): 3,
    _np.dtype("int32"): 4,
    _np.dtype("int8"): 5,
    _np.dtype("int64"): 6,
    _np.dtype("bool"): 7,
    _np.dtype("int16"): 8,
    _np.dtype("uint16"): 9,
    _np.dtype("uint32"): 10,
    _np.dtype("uint64"): 11,
    # bfloat16 is TPU-first-class; id 12 matches mshadow's bfloat16 slot.
    "bfloat16": 12,
}

_DTYPE_ID_TO_NP = {v: k for k, v in _DTYPE_NP_TO_ID.items()}


def dtype_np_to_id(dtype) -> int:
    import ml_dtypes

    if dtype == ml_dtypes.bfloat16 or str(dtype) == "bfloat16":
        return 12
    return _DTYPE_NP_TO_ID[_np.dtype(dtype)]


def dtype_id_to_np(type_id: int):
    if type_id == 12:
        import ml_dtypes

        return _np.dtype(ml_dtypes.bfloat16)
    return _DTYPE_ID_TO_NP[type_id]


class _NameManager(threading.local):
    """Automatic unique-name assignment.

    Reference: ``python/mxnet/name.py :: NameManager``.
    """

    def __init__(self):
        super().__init__()
        self._counter = {}

    def get(self, name, hint):
        if name is not None:
            return name
        idx = self._counter.get(hint, 0)
        self._counter[hint] = idx + 1
        return f"{hint}{idx}"

    def reset(self):
        self._counter = {}


name_manager = _NameManager()


def classproperty(func):
    class _Descriptor:
        def __get__(self, obj, owner):
            return func(owner)

    return _Descriptor()


# ---------------------------------------------------------------------------
# execution-platform plumbing
# ---------------------------------------------------------------------------
import contextlib as _contextlib
import contextvars as _contextvars

_exec_platform = _contextvars.ContextVar("mxnet_tpu_exec_platform",
                                         default=None)


@_contextlib.contextmanager
def execution_platform(platform):
    """Declare the platform ops are being traced/lowered for.

    The framework's jit entry points (per-op eager cache, CachedOp,
    TrainStep) set this from the devices they will actually run on, so
    kernel-eligibility checks inside a trace (e.g. the Pallas flash
    attention dispatch) don't have to guess from the default backend — a
    CPU-context op must not take the Pallas path just because a TPU exists
    in the process.
    """
    token = _exec_platform.set(platform)
    try:
        yield
    finally:
        _exec_platform.reset(token)


def current_execution_platform(sample=None):
    """Execution platform for `sample` (concrete array, tracer, or None)."""
    override = _exec_platform.get()
    if override is not None:
        return override
    import jax

    if sample is not None and not isinstance(sample, jax.core.Tracer):
        try:
            return next(iter(sample.devices())).platform
        except Exception:
            pass
    try:
        return jax.devices()[0].platform
    except Exception:
        return "none"
