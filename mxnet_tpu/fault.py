"""``mx.fault`` — deterministic, seeded fault injection + bounded retry.

The reference stack survives production because its failure paths are
exercised constantly: the dependency engine propagates op failures
deterministically (ThreadedVar ``ExceptionRef``), the distributed KVStore
tolerates flaky workers, and checkpoints are the resume contract. A
reproduction with only happy paths cannot claim those properties — this
module makes the failure paths *testable*:

* **Named injection sites.** Instrumented layers call
  :func:`check` at a named point — ``engine.dispatch`` (every imperative
  op dispatch), ``kvstore.push`` / ``kvstore.pull`` /
  ``kvstore.allreduce`` (comms), ``checkpoint.write`` /
  ``checkpoint.read`` (every atomic file commit / checkpoint load),
  ``kvstore.barrier`` (every bounded cross-process rendezvous),
  ``datafeed.put`` (each batch staged by the async input pipeline —
  ``io.DeviceFeedIter``), ``serving.dispatch`` (every inference batch
  the model server dispatches), ``serving.reload`` (every model
  hot-reload — ``serving.Server``), ``serving.replica`` (every batch a
  Router-managed replica dispatches; the dotted sub-sites
  ``serving.replica.<i>`` target one replica — kill or wedge exactly
  one instance of the fleet), ``serving.route`` (every routing
  decision the serving Router makes), ``serving.ingress`` (every
  submit frame the socket ingress handles), ``worker.spawn`` (every
  replica worker process launch — dotted ``worker.spawn.<i>``
  sub-sites target one worker's spawn path), ``elastic.heartbeat`` (every
  liveness touch of the elastic runtime) and ``elastic.rejoin`` (every
  epoch-transition restore — ``parallel.elastic.ElasticRunner``).
  Like telemetry, every call site guards on one module-level flag
  (``_state.enabled`` — a single attribute load + branch), so the
  disabled fast path costs one branch and allocates nothing.

* **Policies.** ``MXNET_FAULT_SPEC`` (or :func:`inject` /
  :func:`install`) maps sites to policies::

      site=policy[;site=policy...]

      once        raise FaultInjected on the first hit, pass afterwards
      nth:N       raise on exactly the Nth hit (fail "mid-write")
      every:N     raise on every Nth hit (N, 2N, 3N, ...)
      p:F         raise each hit with probability F (seeded RNG)
      latency:S   sleep S seconds on every hit (slow, not broken)

  ``site`` may be ``*`` to match every instrumented point. All
  randomness comes from one ``random.Random(MXNET_FAULT_SEED)`` so a
  chaos run is reproducible bit-for-bit (``tools/chaos_check.py``).

* **Bounded retry.** :func:`retry_call` is the comms retry/backoff
  primitive the KVStore wraps its device work in: bounded attempts
  (``MXNET_COMM_RETRY_ATTEMPTS``), exponential backoff from
  ``MXNET_COMM_RETRY_DELAY`` with jitter drawn from the injector RNG,
  and a clear ``MXNetError`` naming the site, detail (key) and attempt
  count on exhaustion. Only *transient* failures are retried —
  injected faults and XLA runtime errors with transient status codes —
  so deterministic bugs still fail fast.

Telemetry (``MXNET_TELEMETRY=1``): ``mxnet_fault_injected_total{site}``,
``mxnet_retry_total{site,outcome}``.
"""
from __future__ import annotations

import contextlib
import os
import random
import threading
import time
from typing import Dict, Optional, Tuple

from .base import MXNetError

__all__ = [
    "FaultInjected", "check", "inject", "install", "clear",
    "enable", "disable", "active", "stats", "parse_spec",
    "retry_call", "is_transient", "has_policy", "SITES",
]

# The instrumented points (documentation + spec validation). check() with
# an unlisted name still works — the list is the contract, not a cage.
SITES = (
    "engine.dispatch",
    "kvstore.push",
    "kvstore.pull",
    "kvstore.allreduce",
    "kvstore.barrier",
    "checkpoint.write",
    "checkpoint.read",
    "datafeed.put",
    "serving.dispatch",
    "serving.reload",
    "serving.replica",
    "serving.route",
    "serving.upgrade",
    "serving.ingress",
    "controller.scale",
    "worker.spawn",
    "elastic.heartbeat",
    "elastic.rejoin",
)

# Site families whose instrumented points check dotted per-instance
# sub-sites (``<family>.<i>``) in addition to the family name.
_SUBSITE_FAMILIES = ("serving.replica", "worker.spawn")


class FaultInjected(MXNetError):
    """An error raised by the fault injector (always retry-transient)."""

    def __init__(self, site: str, hit: int, detail: str = ""):
        self.site = site
        self.hit = hit
        self.detail = detail
        extra = f" ({detail})" if detail else ""
        super().__init__(
            f"injected fault at {site}{extra} [hit #{hit}]")


class _State:
    __slots__ = ("enabled",)

    def __init__(self, enabled: bool):
        self.enabled = enabled


# THE fast-path guard: instrumented modules read `_state.enabled` directly
# (one attribute load + branch; never swap the _State instance, callers
# cache a reference to it) — same pattern as telemetry._state.
_state = _State(False)

_lock = threading.Lock()
_sites: Dict[str, "_Policy"] = {}
_rng = random.Random(int(os.environ.get("MXNET_FAULT_SEED", "0")))


class _Policy:
    """One site's policy: decides per hit whether to fire, thread-safely."""

    __slots__ = ("kind", "arg", "hits", "injected")

    def __init__(self, kind: str, arg: float = 0.0):
        self.kind = kind
        self.arg = arg
        self.hits = 0
        self.injected = 0

    def hit(self) -> Tuple[str, int]:
        """Count one hit; return ("fail"|"sleep"|"pass", hit_number)."""
        with _lock:
            self.hits += 1
            n = self.hits
            kind = self.kind
            if kind == "once":
                fire = n == 1
            elif kind == "nth":
                fire = n == int(self.arg)
            elif kind == "every":
                fire = n % int(self.arg) == 0
            elif kind == "p":
                fire = _rng.random() < self.arg
            elif kind == "latency":
                self.injected += 1
                return "sleep", n
            else:  # pragma: no cover - parse_spec rejects unknown kinds
                fire = False
            if fire:
                self.injected += 1
                return "fail", n
            return "pass", n

    def describe(self) -> str:
        return self.kind if self.kind in ("once",) else \
            f"{self.kind}:{self.arg:g}"


def parse_spec(spec: str) -> Dict[str, _Policy]:
    """Parse an ``MXNET_FAULT_SPEC`` string into ``{site: policy}``.

    Raises :class:`MXNetError` on malformed grammar — a chaos run that
    silently injects nothing is worse than one that fails to start.
    """
    out: Dict[str, _Policy] = {}
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise MXNetError(
                f"fault spec entry {part!r} is not site=policy "
                f"(spec grammar: site=once|nth:N|every:N|p:F|latency:S)")
        site, policy = part.split("=", 1)
        site = site.strip()
        policy = policy.strip()
        # dotted SUB-sites name one instance of a replicated layer —
        # allowed ONLY for families whose instrumented points actually
        # check per-instance sub-sites (currently serving.replica.<i>,
        # the Router's replica targeting); accepting them under every
        # site would let kvstore.push.0=once install and silently
        # never fire, defeating the typo-catching point of SITES
        if site != "*" and site not in SITES and not any(
                site.startswith(fam + ".")
                and site[len(fam) + 1:].isdigit()
                for fam in _SUBSITE_FAMILIES):
            raise MXNetError(
                f"unknown fault site {site!r}; known sites: "
                f"{', '.join(SITES)} (or '*' for all, or a per-instance "
                "sub-site of " + "/".join(_SUBSITE_FAMILIES)
                + " like serving.replica.0 — the suffix is the integer "
                "instance index)")
        kind, _, arg = policy.partition(":")
        kind = kind.strip()
        try:
            if kind == "once":
                if arg:
                    raise ValueError("'once' takes no argument")
                pol = _Policy("once")
            elif kind in ("nth", "every"):
                n = int(arg)
                if n < 1:
                    raise ValueError(f"'{kind}' needs N >= 1")
                pol = _Policy(kind, n)
            elif kind == "p":
                f = float(arg)
                if not 0.0 <= f <= 1.0:
                    raise ValueError("'p' needs 0 <= F <= 1")
                pol = _Policy("p", f)
            elif kind == "latency":
                s = float(arg)
                if s < 0:
                    raise ValueError("'latency' needs S >= 0")
                pol = _Policy("latency", s)
            else:
                raise ValueError(
                    "policy must be once | nth:N | every:N | p:F | "
                    "latency:S")
        except ValueError as e:
            raise MXNetError(
                f"bad fault policy {policy!r} for site {site!r}: {e}") \
                from e
        out[site] = pol
    return out


def install(spec, seed: Optional[int] = None) -> None:
    """Install a fault spec (string or ``{site: policy}``) and enable
    injection. ``seed`` reseeds the injector RNG (default: keep)."""
    global _sites
    policies = parse_spec(spec) if isinstance(spec, str) else dict(spec)
    with _lock:
        _sites = policies
        if seed is not None:
            _rng.seed(int(seed))
    _state.enabled = bool(policies)


def clear() -> None:
    """Disable injection and drop all site policies."""
    global _sites
    _state.enabled = False
    with _lock:
        _sites = {}


def enable() -> None:
    _state.enabled = True


def disable() -> None:
    _state.enabled = False


def active() -> bool:
    return _state.enabled


def has_policy(site: str) -> bool:
    """Is a policy installed for exactly ``site`` (no ``*`` fallback)?

    For replicated layers whose instances check dotted sub-sites
    (``serving.replica.<i>``): the family check already honours ``*``,
    so instance checks guard on this to avoid double-counting the
    wildcard policy's hits."""
    with _lock:
        return site in _sites


def stats() -> Dict[str, Dict[str, int]]:
    """Per-site ``{"hits": n, "injected": k}`` for the installed spec."""
    with _lock:
        return {site: {"hits": p.hits, "injected": p.injected,
                       "policy": p.describe()}
                for site, p in _sites.items()}


@contextlib.contextmanager
def inject(spec, seed: Optional[int] = None):
    """Scoped injection: install ``spec``, enable, restore prior state on
    exit (the test-facing entry point)::

        with fault.inject("kvstore.allreduce=once"):
            trainer.step(batch_size)   # first allreduce fails, retry wins
    """
    global _sites
    with _lock:
        prev_sites = _sites
        prev_rng = _rng.getstate()
    prev_enabled = _state.enabled
    install(spec, seed=seed)
    try:
        yield stats
    finally:
        with _lock:
            _sites = prev_sites
            _rng.setstate(prev_rng)
        _state.enabled = prev_enabled


def check(site: str, detail: str = "") -> None:
    """One pass through a named injection point.

    No-op unless injection is enabled AND a policy matches ``site`` (or
    ``*``). Raises :class:`FaultInjected` or sleeps per the policy.
    Call sites on hot paths guard with ``if _state.enabled:`` themselves
    so the disabled cost is a single branch.
    """
    if not _state.enabled:
        return
    pol = _sites.get(site)
    if pol is None:
        pol = _sites.get("*")
        if pol is None:
            return
    action, n = pol.hit()
    if action == "pass":
        return
    from . import telemetry, tracing

    if telemetry._state.enabled:
        telemetry.record_fault_injected(site)
    if tracing._state.enabled:
        # annotate the live span (if any request trace is ambient on
        # this thread): the injected fault becomes part of the story
        # the dumped trace tells
        tracing.note(f"fault injected: {site}"
                     + (f" ({detail})" if detail else ""))
    if action == "sleep":
        time.sleep(pol.arg)
        return
    raise FaultInjected(site, n, detail)


# ---------------------------------------------------------------------------
# Bounded retry with exponential backoff — the comms resilience primitive.
# ---------------------------------------------------------------------------

# Transient-looking XLA/jax runtime status markers. Anything else is a
# deterministic bug: retrying it would only mask the failure N times.
_TRANSIENT_MARKERS = ("UNAVAILABLE", "DEADLINE_EXCEEDED", "ABORTED",
                      "RESOURCE_EXHAUSTED")


def is_transient(exc: BaseException) -> bool:
    """Is ``exc`` worth retrying? Injected faults always; XLA runtime
    errors only with a transient status code in the message."""
    if isinstance(exc, FaultInjected):
        return True
    if type(exc).__name__ == "XlaRuntimeError":
        msg = str(exc)
        return any(m in msg for m in _TRANSIENT_MARKERS)
    return False


def retry_call(site: str, fn, detail: str = "",
               attempts: Optional[int] = None,
               base_delay: Optional[float] = None):
    """Run ``fn()`` with bounded exponential-backoff retry on transient
    failures.

    ``attempts`` (>=1) and ``base_delay`` default to the
    ``MXNET_COMM_RETRY_ATTEMPTS`` (3) / ``MXNET_COMM_RETRY_DELAY``
    (0.05 s) env knobs, read per call so tests can monkeypatch them.
    Delay doubles per retry with up to +25% jitter from the seeded
    injector RNG (deterministic chaos runs stay deterministic). On
    exhaustion raises :class:`MXNetError` naming the site, detail and
    attempt count, chained to the last underlying failure.
    """
    # hot path: the first attempt runs bare — no env parsing, no
    # telemetry import, no loop state. A fault-free call (the only kind
    # a healthy training step makes, per key per step) costs one
    # try/except frame on top of fn() itself.
    try:
        return fn()
    except Exception as e:  # noqa: BLE001 - filtered by is_transient
        if not is_transient(e):
            raise
        last = e

    # failure path: now resolve the knobs and enter the backoff loop
    if attempts is None:
        attempts = int(os.environ.get("MXNET_COMM_RETRY_ATTEMPTS", "3"))
    if attempts < 1:
        raise MXNetError(f"retry attempts must be >= 1, got {attempts}")
    if base_delay is None:
        base_delay = float(os.environ.get("MXNET_COMM_RETRY_DELAY", "0.05"))
    from . import telemetry, tracing

    attempt = 1
    while True:
        if telemetry._state.enabled:
            telemetry.record_retry(site, "retry")
        if tracing._state.enabled:
            tracing.note(f"retry {attempt}/{attempts} at {site}: {last}")
        if attempt >= attempts:
            if telemetry._state.enabled:
                telemetry.record_retry(site, "exhausted")
            if tracing._state.enabled:
                tracing.note(f"retries exhausted at {site}")
            extra = f" ({detail})" if detail else ""
            raise MXNetError(
                f"{site}{extra} failed after {attempts} attempt(s); "
                f"last error: {last}") from last
        delay = base_delay * (2.0 ** (attempt - 1))
        if delay > 0:
            with _lock:
                jitter = _rng.random()
            time.sleep(delay * (1.0 + 0.25 * jitter))
        attempt += 1
        try:
            result = fn()
        except Exception as e:  # noqa: BLE001
            if not is_transient(e):
                raise
            last = e
            continue
        if telemetry._state.enabled:
            telemetry.record_retry(site, "recovered")
        if tracing._state.enabled:
            tracing.note(f"recovered at {site} on attempt {attempt}")
        return result


# MXNET_FAULT_SPEC in the environment: install + enable at import so
# driver-spawned subprocesses (tools/chaos_check.py stages) inject without
# any code changes. A malformed spec fails the import — loudly.
_env_spec = os.environ.get("MXNET_FAULT_SPEC")
if _env_spec:
    install(_env_spec)
