"""Symbol — the declarative graph API (L3/L7 of SURVEY.md §1).

Reference: ``python/mxnet/symbol/symbol.py :: Symbol`` over nnvm's graph IR
(``3rdparty/tvm/nnvm :: Node/NodeEntry/Graph``, serialized by
``SaveJSON/LoadJSON`` — the symbol.json format). TPU-native re-design: the
graph is a lightweight python DAG over the SAME op registry the imperative
API uses; binding compiles the whole graph into ONE XLA executable (the
reference's GraphExecutor memory planning / op bulking are what XLA does
natively). symbol.json stays byte-compatible so reference model artifacts
(`HybridBlock.export`, `model.save_checkpoint`) load unchanged.
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence, Tuple

from ..base import MXNetError, name_manager
from ..ops.registry import get_op, has_op, list_ops

__all__ = ["Symbol", "var", "Variable", "Group", "load", "load_json",
           "AUX_PARAMS"]

# ops whose trailing tensor params are auxiliary states (mutated by the op,
# not gradient targets) — reference: per-op FMutateInputs attr in nnvm
AUX_PARAMS: Dict[str, Tuple[str, ...]] = {
    "BatchNorm": ("moving_mean", "moving_var"),
    "SyncBatchNorm": ("moving_mean", "moving_var"),
}


class _Node:
    """One graph node: a variable (op=None) or an op application."""

    __slots__ = ("op", "name", "attrs", "inputs", "num_outputs", "_attr_dict")

    def __init__(self, op: Optional[str], name: str, attrs: dict,
                 inputs: List[Tuple["_Node", int]], num_outputs: int = 1):
        self.op = op
        self.name = name
        self.attrs = attrs
        self.inputs = inputs
        self.num_outputs = num_outputs
        self._attr_dict = {}


class Symbol:
    """A list of output entries of the graph (reference: Symbol is a
    NodeEntry array; single-output in the common case)."""

    def __init__(self, entries: Sequence[Tuple[_Node, int]]):
        self._entries: List[Tuple[_Node, int]] = list(entries)

    # -- construction helpers ------------------------------------------
    @property
    def name(self):
        if len(self._entries) == 1:
            return self._entries[0][0].name
        return ", ".join(n.name for n, _ in self._entries)

    def __len__(self):
        return len(self._entries)

    def __iter__(self):
        for i in range(len(self._entries)):
            yield Symbol([self._entries[i]])

    def __getitem__(self, idx):
        if isinstance(idx, str):
            names = self.list_outputs()
            if idx not in names:
                raise MXNetError(f"no output named {idx!r}; have {names}")
            idx = names.index(idx)
        return Symbol([self._entries[idx]])

    def attr(self, key):
        return self._entries[0][0]._attr_dict.get(key)

    def _set_attr(self, **kwargs):
        self._entries[0][0]._attr_dict.update(kwargs)

    def optimize_for(self, backend, arg_params=None, aux_params=None,
                     **kwargs) -> "Symbol":
        """Apply a registered subgraph backend's passes (reference:
        Symbol.optimize_for → SubgraphProperty). Param dicts, when given,
        are updated in place (weight-folding passes rewrite them)."""
        from .. import subgraph

        return subgraph.apply_backend(backend, self, arg_params,
                                      aux_params, **kwargs)

    def get_internals(self) -> "Symbol":
        entries = []
        for node in self._topo():
            for i in range(node.num_outputs):
                entries.append((node, i))
        return Symbol(entries)

    def get_children(self) -> Optional["Symbol"]:
        node = self._entries[0][0]
        if not node.inputs:
            return None
        return Symbol(list(node.inputs))

    # -- graph walks ----------------------------------------------------
    def _topo(self) -> List[_Node]:
        seen = {}
        order: List[_Node] = []

        def visit(node):
            if id(node) in seen:
                return
            seen[id(node)] = node
            for parent, _ in node.inputs:
                visit(parent)
            order.append(node)

        for node, _ in self._entries:
            visit(node)
        return order

    def list_arguments(self) -> List[str]:
        out = []
        for node in self._topo():
            if node.op is None and not node.attrs.get("__aux__"):
                out.append(node.name)
        return out

    def list_auxiliary_states(self) -> List[str]:
        out = []
        for node in self._topo():
            if node.op is None and node.attrs.get("__aux__"):
                out.append(node.name)
        return out

    def list_outputs(self) -> List[str]:
        out = []
        for node, idx in self._entries:
            if node.num_outputs > 1:
                out.append(f"{node.name}_output{idx}")
            else:
                out.append(f"{node.name}_output")
        return out

    def list_inputs(self):
        return self.list_arguments() + self.list_auxiliary_states()

    # -- shape/type inference ------------------------------------------
    def infer_shape(self, *args, **kwargs):
        try:
            return self._infer_shape_impl(False, *args, **kwargs)
        except MXNetError:
            raise

    def infer_shape_partial(self, *args, **kwargs):
        return self._infer_shape_impl(True, *args, **kwargs)

    def _infer_shape_impl(self, partial, *args, **kwargs):
        import jax
        import jax.numpy as jnp
        import numpy as np

        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        known: Dict[str, tuple] = {}
        if args:
            for n, s in zip(arg_names, args):
                if s is not None:
                    known[n] = tuple(s)
        known.update({k: tuple(v) for k, v in kwargs.items() if v is not None})

        # forward-propagate shapes; parameter shapes of param-bearing ops
        # (weights/biases/norm stats) are back-filled from the data shape —
        # the bidirectional FInferShape behaviour simple_bind relies on
        shapes: Dict[Tuple[int, int], tuple] = {}
        try:
            for node in self._topo():
                if node.op is None:
                    shp = known.get(node.name)
                    if shp is None:
                        declared = node.attrs.get("__shape__")
                        if declared:
                            shp = tuple(declared)
                    shapes[(id(node), 0)] = tuple(shp) if shp else None
                    continue
                _backfill_param_shapes(node, shapes)
                in_shapes = [shapes.get((id(p), i)) for p, i in node.inputs]
                if any(s is None for s in in_shapes):
                    if not partial:
                        missing = [p.name for (p, i), s in
                                   zip(node.inputs, in_shapes) if s is None]
                        raise MXNetError(
                            f"cannot infer shape at op {node.name!r} "
                            f"({node.op}): inputs {missing} unknown")
                    for i in range(node.num_outputs):
                        shapes[(id(node), i)] = None
                    continue
                out_shapes = _abstract_op(node, in_shapes)
                for i, s in enumerate(out_shapes):
                    shapes[(id(node), i)] = s
        except NotImplementedError as e:
            raise MXNetError(str(e))

        arg_shapes = []
        for node in self._topo():
            if node.op is None and not node.attrs.get("__aux__"):
                arg_shapes.append(shapes.get((id(node), 0)))
        aux_shapes = []
        for node in self._topo():
            if node.op is None and node.attrs.get("__aux__"):
                aux_shapes.append(shapes.get((id(node), 0)))
        out_shapes = [shapes.get((id(n), i)) for n, i in self._entries]
        return arg_shapes, out_shapes, aux_shapes

    def infer_type(self, *args, **kwargs):
        arg_names = self.list_arguments()
        dt = kwargs.get("data", "float32") if kwargs else \
            (args[0] if args else "float32")
        import numpy as np

        t = np.dtype(dt) if not isinstance(dt, type) else np.dtype("float32")
        return ([t] * len(arg_names), [t] * len(self._entries),
                [t] * len(self.list_auxiliary_states()))

    # -- serialization (symbol.json compat) ----------------------------
    def tojson(self) -> str:
        nodes = self._topo()
        node_idx = {id(n): i for i, n in enumerate(nodes)}
        jnodes = []
        arg_nodes = []
        for i, n in enumerate(nodes):
            if n.op is None:
                arg_nodes.append(i)
            attrs = {k: _attr_str(v) for k, v in n.attrs.items()
                     if not k.startswith("__")}
            jn = {
                "op": n.op if n.op is not None else "null",
                "name": n.name,
                "inputs": [[node_idx[id(p)], oi, 0] for p, oi in n.inputs],
            }
            if attrs:
                jn["attrs"] = attrs
            jnodes.append(jn)
        heads = [[node_idx[id(n)], oi, 0] for n, oi in self._entries]
        return json.dumps({
            "nodes": jnodes,
            "arg_nodes": arg_nodes,
            "node_row_ptr": list(range(len(jnodes) + 1)),
            "heads": heads,
            "attrs": {"mxnet_version": ["int", 10700]},
        }, indent=2)

    def save(self, fname: str) -> None:
        from ..checkpoint import atomic_write

        atomic_write(fname, self.tojson().encode("utf-8"))

    # -- composition sugar ---------------------------------------------
    def __add__(self, other):
        return _binary(self, other, "broadcast_add", "_plus_scalar")

    def __radd__(self, other):
        return self.__add__(other)

    def __sub__(self, other):
        return _binary(self, other, "broadcast_sub", "_minus_scalar")

    def __rsub__(self, other):
        return _binary(self, other, "broadcast_sub", "_rminus_scalar",
                       reverse=True)

    def __mul__(self, other):
        return _binary(self, other, "broadcast_mul", "_mul_scalar")

    def __rmul__(self, other):
        return self.__mul__(other)

    def __truediv__(self, other):
        return _binary(self, other, "broadcast_div", "_div_scalar")

    def __rtruediv__(self, other):
        return _binary(self, other, "broadcast_div", "_rdiv_scalar",
                       reverse=True)

    def __pow__(self, other):
        return _binary(self, other, "broadcast_power", "_power_scalar")

    def __neg__(self):
        return self.__mul__(-1.0)

    def __repr__(self):
        return f"<Symbol {self.name}>"

    # -- binding --------------------------------------------------------
    def simple_bind(self, ctx=None, grad_req="write", type_dict=None,
                    stype_dict=None, group2ctx=None, shared_arg_names=None,
                    shared_exec=None, shared_buffer=None, **kwargs):
        from .executor import Executor

        return Executor._simple_bind(self, ctx, grad_req, kwargs)

    def bind(self, ctx, args, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None):
        from .executor import Executor

        return Executor(self, ctx, args, args_grad, grad_req, aux_states)

    def eval(self, ctx=None, **kwargs):
        ex = self.bind(ctx, kwargs)
        return ex.forward()

    # gradient graph is implicit (jax.vjp in the Executor); provided for
    # API parity
    def __call__(self, *args, **kwargs):
        raise MXNetError("Symbol composition via __call__ (legacy grouping) "
                         "is not supported; apply ops from mx.sym directly")


def _attr_str(v):
    if isinstance(v, bool):
        return "True" if v else "False"
    if isinstance(v, (list, tuple)):
        return "(" + ", ".join(str(x) for x in v) + ")"
    return str(v)


def _backfill_param_shapes(node: _Node, shapes) -> None:
    """Infer unknown VARIABLE input shapes of param-bearing ops from the
    (known) data shape + attrs (reference: per-op FInferShape backward
    direction). Covers the layers simple_bind users declare params for."""
    data_shape = None
    if node.inputs:
        p0, i0 = node.inputs[0]
        data_shape = shapes.get((id(p0), i0))
    if data_shape is None:
        return
    a = node.attrs
    opdef = get_op(node.op)

    def put(pname, shp):
        for (parent, pi), tp in zip(node.inputs, opdef.tensor_params):
            if tp == pname and parent.op is None and                     shapes.get((id(parent), 0)) is None:
                shapes[(id(parent), 0)] = tuple(int(x) for x in shp)

    op = node.op
    if op == "FullyConnected":
        flatten = a.get("flatten", True)
        in_units = 1
        if flatten:
            for d in data_shape[1:]:
                in_units *= d
        else:
            in_units = data_shape[-1]
        nh = a.get("num_hidden", 0)
        put("weight", (nh, in_units))
        put("bias", (nh,))
    elif op == "Convolution":
        kernel = tuple(a.get("kernel", ()))
        nf = a.get("num_filter", 1)
        ng = a.get("num_group", 1)
        put("weight", (nf, data_shape[1] // ng) + kernel)
        put("bias", (nf,))
    elif op == "Deconvolution":
        kernel = tuple(a.get("kernel", ()))
        nf = a.get("num_filter", 1)
        ng = a.get("num_group", 1)
        put("weight", (data_shape[1], nf // ng) + kernel)
        put("bias", (nf,))
    elif op in ("BatchNorm", "SyncBatchNorm", "InstanceNorm"):
        c = data_shape[a.get("axis", 1)]
        for pname in ("gamma", "beta", "moving_mean", "moving_var"):
            put(pname, (c,))
    elif op == "LayerNorm":
        c = data_shape[a.get("axis", -1)]
        put("gamma", (c,))
        put("beta", (c,))
    elif op == "GroupNorm":
        c = data_shape[1]
        put("gamma", (c,))
        put("beta", (c,))
    elif op == "Embedding":
        put("weight", (a.get("input_dim", 0), a.get("output_dim", 0)))
    elif op == "_contrib_rms_norm":
        put("weight", (data_shape[-1],))


def _abstract_op(node: _Node, in_shapes: List[tuple]):
    """Shape inference by abstract evaluation of the registered jax fn."""
    import jax
    import jax.numpy as jnp

    opdef = get_op(node.op)
    specs = [jax.ShapeDtypeStruct(s, jnp.float32) for s in in_shapes]

    def fn(*xs):
        return _apply_opdef(opdef, list(xs), node.attrs, rng=None,
                            training=False)

    out = jax.eval_shape(fn, *specs)
    if isinstance(out, (list, tuple)):
        return [tuple(o.shape) for o in out]
    return [tuple(out.shape)]


def _apply_opdef(opdef, tensors, attrs, rng, training):
    kw = {k: v for k, v in attrs.items() if not k.startswith("__")
          and (opdef.var_attrs or k in opdef.attr_params)}
    if opdef.attr_specs:
        # the typed AttrSpec contract holds on the graph-execution path
        # too, not just eager calls
        from ..ops.registry import validate_attrs

        validate_attrs(opdef, kw)
    if opdef.pass_training_flag:
        kw["_training"] = training
    if opdef.needs_rng:
        if opdef.rng_gate is not None and not opdef.rng_gate(kw):
            return opdef.fn(None, *tensors, **kw)
        import jax

        key = rng if rng is not None else jax.random.PRNGKey(0)
        return opdef.fn(key, *tensors, **kw)
    return opdef.fn(*tensors, **kw)


def _binary(lhs, other, op, scalar_op, reverse=False):
    if isinstance(other, Symbol):
        return _apply_op(op, [lhs, other], {})
    attrs = {"scalar": float(other)}
    return _apply_op(scalar_op, [lhs], attrs)


_name_counters: Dict[str, int] = {}


def _auto_name(hint: str) -> str:
    i = _name_counters.get(hint, 0)
    _name_counters[hint] = i + 1
    return f"{hint}{i}"


def _apply_op(opname: str, inputs: List[Symbol], attrs: dict,
              name: Optional[str] = None) -> Symbol:
    opdef = get_op(opname)
    entries = []
    for s in inputs:
        if len(s._entries) != 1:
            raise MXNetError(
                f"op {opname}: multi-output symbol used directly as input; "
                "select an output first (sym[i])")
        entries.append(s._entries[0])
    node_name = name or _auto_name(opname.lower().lstrip("_"))
    nout = opdef.num_outputs or 1
    node = _Node(opname, node_name, dict(attrs), entries, nout)
    _mark_aux_inputs(node, opdef)
    return Symbol([(node, 0)]) if nout == 1 else \
        Symbol([(node, i) for i in range(nout)])


def _mark_aux_inputs(node, opdef):
    """FMutateInputs-style aux detection: plain vars fed to an op's
    mutated params (AUX_PARAMS) are auxiliary states — applied both when
    composing (`_apply_op`) and when loading JSON (`load_json`)."""
    if node.op not in AUX_PARAMS:
        return
    aux_names = AUX_PARAMS[node.op]
    for pname, (parent, _) in zip(opdef.tensor_params, node.inputs):
        if pname in aux_names and parent.op is None:
            parent.attrs["__aux__"] = True


def var(name, attr=None, shape=None, lr_mult=None, wd_mult=None, dtype=None,
        init=None, stype=None, **kwargs) -> Symbol:
    """Create a variable symbol (reference: symbol.var / sym.Variable)."""
    attrs = {}
    if shape is not None:
        attrs["__shape__"] = tuple(shape)
    if dtype is not None:
        attrs["__dtype__"] = str(dtype)
    if init is not None:
        attrs["__init__"] = str(init)
    node = _Node(None, name, attrs, [])
    s = Symbol([(node, 0)])
    if attr:
        s._set_attr(**attr)
    return s


Variable = var


def Group(symbols: Sequence[Symbol]) -> Symbol:
    entries = []
    for s in symbols:
        entries.extend(s._entries)
    return Symbol(entries)


def load_json(json_str: str) -> Symbol:
    """Parse symbol.json (byte-compatible with nnvm SaveJSON output)."""
    data = json.loads(json_str)
    jnodes = data["nodes"]
    nodes: List[_Node] = []
    for jn in jnodes:
        op = jn["op"]
        attrs_raw = jn.get("attrs", jn.get("param", {})) or {}
        if op == "null":
            node = _Node(None, jn["name"], {}, [])
        else:
            opdef = get_op(op)  # raises NotImplementedError for unknown ops
            attrs = _coerce_attrs(opdef, attrs_raw)
            inputs = [(nodes[i], oi) for i, oi, *_ in jn["inputs"]]
            node = _Node(op, jn["name"], attrs, inputs,
                         opdef.num_outputs or 1)
        nodes.append(node)
    heads = data.get("heads") or [[len(nodes) - 1, 0, 0]]
    for node in nodes:
        if node.op is not None:
            _mark_aux_inputs(node, get_op(node.op))
    return Symbol([(nodes[i], oi) for i, oi, *_ in heads])


def load(fname: str) -> Symbol:
    with open(fname) as f:
        return load_json(f.read())


def _coerce_attrs(opdef, attrs_raw: dict) -> dict:
    """symbol.json stores attrs as strings; coerce back to python values by
    inspecting the op fn's defaults (the dmlc::Parameter round-trip)."""
    import ast
    import inspect

    sig = inspect.signature(opdef.fn)
    out = {}
    for k, v in attrs_raw.items():
        if k not in opdef.attr_params and not opdef.var_attrs:
            continue
        if not isinstance(v, str):
            out[k] = v
            continue
        try:
            out[k] = ast.literal_eval(v)
            continue
        except (ValueError, SyntaxError):
            pass
        low = v.strip()
        if low in ("True", "true", "1"):
            out[k] = True
        elif low in ("False", "false", "0"):
            out[k] = False
        elif low in ("None", "null"):
            out[k] = None
        else:
            out[k] = v  # string-typed attr (e.g. act_type='relu')
    return out
