"""Executor — bound symbolic graph (reference: L3 GraphExecutor).

Reference: ``src/executor/graph_executor.cc :: GraphExecutor::Init`` builds
fwd+bwd nnvm graphs, plans memory, attaches op executors and runs them
through the engine with segment bulking (SURVEY.md §3.4). TPU-native:
binding traces the whole graph into ONE jitted function (memory planning,
bulking, fusion = XLA); backward is ``jax.vjp`` of that function, so the
"full fwd+bwd graph" of the reference is literally one executable here.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..base import MXNetError
from ..context import Context, cpu, current_context
from ..ndarray import NDArray, zeros as nd_zeros
from ..ndarray.ndarray import _wrap_jax
from .symbol import Symbol, _apply_opdef
from ..ops.registry import get_op

__all__ = ["Executor"]


def eval_graph(sym: Symbol, values: Dict[str, object], training: bool,
               rng=None):
    """Topologically evaluate the graph on jax arrays. Returns the list of
    output arrays plus {aux_name: updated_value} for mutated aux states."""
    results: Dict[tuple, object] = {}
    aux_updates: Dict[str, object] = {}
    for node in sym._topo():
        if node.op is None:
            if node.name not in values:
                raise MXNetError(f"executor: missing input {node.name!r}")
            results[(id(node), 0)] = values[node.name]
            continue
        opdef = get_op(node.op)
        ins = [results[(id(p), i)] for p, i in node.inputs]
        out = _apply_opdef(opdef, ins, node.attrs, rng=rng, training=training)
        if isinstance(out, (list, tuple)):
            # training-mode BatchNorm returns (out, batch_mean, batch_var):
            # fold the stat updates back into the aux vars functionally
            if node.op in ("BatchNorm", "SyncBatchNorm") and training:
                momentum = node.attrs.get("momentum", 0.9)
                y, bmean, bvar = out
                for pname, (parent, pi) in zip(opdef.tensor_params,
                                               node.inputs):
                    if parent.op is not None:
                        continue
                    if pname == "moving_mean":
                        prev = results[(id(parent), 0)]
                        aux_updates[parent.name] = \
                            momentum * prev + (1 - momentum) * bmean
                    elif pname == "moving_var":
                        prev = results[(id(parent), 0)]
                        aux_updates[parent.name] = \
                            momentum * prev + (1 - momentum) * bvar
                results[(id(node), 0)] = y
                for i in range(1, node.num_outputs):
                    results[(id(node), i)] = out[i] if i < len(out) else None
            else:
                for i, o in enumerate(out):
                    results[(id(node), i)] = o
                if node.num_outputs == 1:
                    results[(id(node), 0)] = out[0]
        else:
            results[(id(node), 0)] = out
    outs = [results[(id(n), i)] for n, i in sym._entries]
    return outs, aux_updates


class Executor:
    """reference: python/mxnet/executor.py::Executor."""

    def __init__(self, symbol: Symbol, ctx, args, args_grad=None,
                 grad_req="write", aux_states=None):
        self._symbol = symbol
        self._ctx = ctx or current_context()
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        if isinstance(args, (list, tuple)):
            args = dict(zip(arg_names, args))
        self.arg_dict: Dict[str, NDArray] = dict(args)
        missing = [n for n in arg_names if n not in self.arg_dict]
        if missing:
            raise MXNetError(f"bind: missing arguments {missing}")
        if isinstance(aux_states, (list, tuple)):
            aux_states = dict(zip(aux_names, aux_states))
        self.aux_dict: Dict[str, NDArray] = dict(aux_states or {})
        for n in aux_names:
            if n not in self.aux_dict:
                raise MXNetError(f"bind: missing auxiliary state {n}")
        if isinstance(grad_req, str):
            grad_req = {n: grad_req for n in arg_names}
        elif isinstance(grad_req, (list, tuple)):
            grad_req = dict(zip(arg_names, grad_req))
        self._grad_req = grad_req
        if args_grad is None:
            args_grad = {
                n: nd_zeros(self.arg_dict[n].shape, ctx=self._ctx,
                            dtype=str(self.arg_dict[n].dtype))
                for n in arg_names if grad_req.get(n, "null") != "null"}
        elif isinstance(args_grad, (list, tuple)):
            args_grad = dict(zip(arg_names, args_grad))
        self.grad_dict: Dict[str, NDArray] = dict(args_grad)
        self.outputs: List[NDArray] = []
        from ..compiler import service as _csvc

        self._fwd_cache = _csvc.SiteCache("executor")
        self._vjp = None
        self._is_train = False

    # -- compiled forward ----------------------------------------------
    def _compiled(self, training: bool):
        import jax

        from .. import compiler

        # canonical service key: the bound graph is fixed per Executor,
        # so the signature varies only in the train flag (+ the routing
        # knobs every compile cache keys on)
        key = compiler.signature("executor", id(self._symbol),
                                 extra=(training,))
        fn = self._fwd_cache.lookup(key)
        if fn is self._fwd_cache.MISS:
            sym = self._symbol
            arg_names = sym.list_arguments()
            aux_names = sym.list_auxiliary_states()

            def pure(arg_vals, aux_vals, rng):
                values = dict(zip(arg_names, arg_vals))
                values.update(dict(zip(aux_names, aux_vals)))
                outs, aux_updates = eval_graph(sym, values, training, rng)
                new_aux = tuple(
                    aux_updates.get(n, values[n]) for n in aux_names)
                return tuple(outs), new_aux

            fn = jax.jit(pure)
            self._fwd_cache.insert(key, fn)
            compiler.record_signature("executor", {
                "args": {n: tuple(self.arg_dict[n].shape)
                         for n in arg_names},
                "training": training,
                "routing": compiler.routing_knobs()})
        return fn

    def forward(self, is_train=False, **kwargs):
        import jax

        from .. import random_state

        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError(f"forward: unknown argument {k}")
            self.arg_dict[k]._set_data(
                v.data if isinstance(v, NDArray) else v)
        self._is_train = bool(is_train)
        arg_names = self._symbol.list_arguments()
        aux_names = self._symbol.list_auxiliary_states()
        arg_vals = tuple(self.arg_dict[n].data for n in arg_names)
        aux_vals = tuple(self.aux_dict[n].data for n in aux_names)
        rng = random_state.get_state_key()
        from ..base import current_execution_platform, execution_platform

        sample = next((v for v in arg_vals if hasattr(v, "devices")), None)
        if self._is_train:
            # value-and-vjp so backward() can run later without retracing
            def fwd_for_grad(diff_vals):
                vals = list(arg_vals)
                for slot, v in zip(self._diff_slots(), diff_vals):
                    vals[slot] = v
                outs, new_aux = self._compiled(True)(tuple(vals), aux_vals,
                                                     rng)
                return outs, new_aux

            import jax

            diff_vals = tuple(arg_vals[i] for i in self._diff_slots())
            with execution_platform(current_execution_platform(sample)):
                outs, vjp, new_aux = jax.vjp(fwd_for_grad, diff_vals,
                                             has_aux=True)
            self._vjp = vjp
        else:
            with execution_platform(current_execution_platform(sample)):
                outs, new_aux = self._compiled(False)(arg_vals, aux_vals,
                                                      rng)
            self._vjp = None
        for n, v in zip(aux_names, new_aux):
            self.aux_dict[n]._set_data(v)
        self.outputs = [_wrap_jax(o, self._ctx) for o in outs]
        return self.outputs

    def _diff_slots(self):
        arg_names = self._symbol.list_arguments()
        return [i for i, n in enumerate(arg_names)
                if self._grad_req.get(n, "null") != "null"]

    def backward(self, out_grads=None):
        if self._vjp is None:
            raise MXNetError(
                "backward() requires a prior forward(is_train=True)")
        import jax.numpy as jnp

        if out_grads is None:
            grads = tuple(jnp.ones_like(o.data) for o in self.outputs)
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            grads = tuple(
                g.data if isinstance(g, NDArray) else jnp.asarray(g)
                for g in out_grads)
        (dvals,) = self._vjp(grads)
        arg_names = self._symbol.list_arguments()
        for slot, g in zip(self._diff_slots(), dvals):
            name = arg_names[slot]
            garr = self.grad_dict.get(name)
            if garr is None:
                continue
            if self._grad_req.get(name) == "add":
                garr._set_data(garr.data + g)
            else:
                garr._set_data(g.astype(garr.data.dtype))

    # -- simple_bind ----------------------------------------------------
    @classmethod
    def _simple_bind(cls, symbol: Symbol, ctx, grad_req, shape_kwargs):
        from .. import initializer

        arg_shapes, out_shapes, aux_shapes = symbol.infer_shape(
            **shape_kwargs)
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        args = {}
        for n, s in zip(arg_names, arg_shapes):
            args[n] = nd_zeros(s, ctx=ctx)
        aux = {n: nd_zeros(s, ctx=ctx) for n, s in zip(aux_names, aux_shapes)}
        return cls(symbol, ctx, args, None, grad_req, aux)

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for n, v in (arg_params or {}).items():
            if n in self.arg_dict:
                self.arg_dict[n]._set_data(
                    v.data if isinstance(v, NDArray) else v)
            elif not allow_extra_params:
                raise MXNetError(f"unknown parameter {n}")
        for n, v in (aux_params or {}).items():
            if n in self.aux_dict:
                self.aux_dict[n]._set_data(
                    v.data if isinstance(v, NDArray) else v)
            elif not allow_extra_params:
                raise MXNetError(f"unknown aux state {n}")

    @property
    def arg_arrays(self):
        return [self.arg_dict[n] for n in self._symbol.list_arguments()]

    @property
    def grad_arrays(self):
        return [self.grad_dict.get(n)
                for n in self._symbol.list_arguments()]

    @property
    def aux_arrays(self):
        return [self.aux_dict[n]
                for n in self._symbol.list_auxiliary_states()]


def eval_symbol(sym: Symbol, feed: Dict[str, NDArray]):
    """Evaluate a symbol graph on NDArrays through the nd wrappers — the
    SymbolBlock forward. Runs on the autograd tape (eager training works)
    and under hybridize tracing (values may be tracer-backed). Training-mode
    BatchNorm folds its batch stats into the aux NDArrays like the gluon
    block does."""
    from .. import autograd
    from .. import ndarray as nd_mod

    training = autograd.is_training()
    results: Dict[tuple, NDArray] = {}
    for node in sym._topo():
        if node.op is None:
            if node.name not in feed:
                raise MXNetError(f"eval_symbol: missing input {node.name!r}")
            results[(id(node), 0)] = feed[node.name]
            continue
        opdef = get_op(node.op)
        ins = [results[(id(p), i)] for p, i in node.inputs]
        fn = getattr(nd_mod, node.op)
        attrs = {k: v for k, v in node.attrs.items()
                 if not k.startswith("__")}
        out = fn(*ins, **attrs)
        if isinstance(out, (list, tuple)) and \
                node.op in ("BatchNorm", "SyncBatchNorm") and training:
            momentum = node.attrs.get("momentum", 0.9)
            y, bmean, bvar = out
            with autograd.pause():
                for pname, (parent, _pi) in zip(opdef.tensor_params,
                                                node.inputs):
                    if parent.op is not None or parent.name not in feed:
                        continue
                    arr = feed[parent.name]
                    if pname == "moving_mean":
                        arr._set_data(
                            (momentum * arr.data
                             + (1 - momentum) * bmean.data.astype(
                                 arr.data.dtype)))
                    elif pname == "moving_var":
                        arr._set_data(
                            (momentum * arr.data
                             + (1 - momentum) * bvar.data.astype(
                                 arr.data.dtype)))
            results[(id(node), 0)] = y
        elif isinstance(out, (list, tuple)):
            for i, o in enumerate(out):
                results[(id(node), i)] = o
        else:
            results[(id(node), 0)] = out
    outs = [results[(id(n), i)] for n, i in sym._entries]
    return outs[0] if len(outs) == 1 else outs
