"""mx.sym — symbolic API namespace with generated op wrappers.

Reference: ``python/mxnet/symbol/register.py`` generates ``mx.sym.*``
functions from the C op registry at import; here the same
``mxnet_tpu.ops.registry`` drives both nd and sym wrappers, so every
operator is automatically available in both APIs (the nnvm single-registry
property, SURVEY.md §2.1 "Operator library").
"""
from __future__ import annotations

import sys
import types

from ..base import MXNetError
from ..ops.registry import get_op, list_ops
from .symbol import (Symbol, var, Variable, Group, load, load_json,
                     _apply_op)
from .executor import Executor

__all__ = ["Symbol", "var", "Variable", "Group", "load", "load_json",
           "Executor"]


def _make_symbol_function(opname: str):
    opdef = get_op(opname)

    def wrapper(*args, name=None, **kwargs):
        tensors = [None] * len(opdef.tensor_params)
        attrs = {}
        if opdef.tensor_params and not opdef.variadic:
            for i, a in enumerate(args):
                if i < len(tensors):
                    tensors[i] = a
                else:
                    j = i - len(tensors)
                    if j < len(opdef.attr_params):
                        attrs[opdef.attr_params[j]] = a
                    else:
                        raise TypeError(
                            f"{opname}: too many positional arguments")
            for k, v in kwargs.items():
                if k in opdef.tensor_params:
                    tensors[opdef.tensor_params.index(k)] = v
                else:
                    attrs[k] = v
            # auto-create variables for unset inputs (MXNet behaviour:
            # sym.FullyConnected(data=x) creates fc_weight/fc_bias vars).
            # Optional tensors are only auto-created for the bias slot and
            # only when no_bias is unset (conv/fc/deconv convention); other
            # optional inputs (masks, lengths) stay absent.
            syms = []
            base = name or opname.lower().lstrip("_")
            for pname, t in zip(opdef.tensor_params, tensors):
                if isinstance(t, Symbol):
                    syms.append(t)
                elif t is None:
                    if pname in opdef.optional_tensor_params:
                        if pname == "bias" and not attrs.get("no_bias",
                                                             False):
                            syms.append(var(f"{base}_{pname}"))
                        continue
                    v = var(f"{base}_{pname}")
                    from .symbol import AUX_PARAMS

                    if pname in AUX_PARAMS.get(opname, ()):
                        v._entries[0][0].attrs["__aux__"] = True
                    syms.append(v)
                else:
                    raise MXNetError(
                        f"sym.{opname}: input {pname} must be a Symbol, "
                        f"got {type(t)}")
        else:
            if opdef.variadic:
                syms = list(args)
                attrs.update(kwargs)
            else:
                for i, a in enumerate(args):
                    if i < len(opdef.attr_params):
                        attrs[opdef.attr_params[i]] = a
                attrs.update(kwargs)
                syms = []
        return _apply_op(opname, syms, attrs, name=name)

    wrapper.__name__ = opname
    wrapper.__qualname__ = f"sym.{opname}"
    from ..ops.registry import render_attr_docs

    wrapper.__doc__ = (opdef.fn.__doc__ or f"{opname} symbol operator.") \
        + render_attr_docs(opdef)
    return wrapper


_this = sys.modules[__name__]
random = types.ModuleType(__name__ + ".random")
contrib = types.ModuleType(__name__ + ".contrib")
linalg = types.ModuleType(__name__ + ".linalg")
sys.modules[random.__name__] = random
sys.modules[contrib.__name__] = contrib
sys.modules[linalg.__name__] = linalg

def _refresh_ops():
    """(Re)generate sym wrappers from the registry — called at import and
    again by mx.library.load after native ops register."""
    for _name in list_ops():
        _w = _make_symbol_function(_name)
        if not hasattr(_this, _name):
            setattr(_this, _name, _w)
        if _name.startswith("_contrib_"):
            if not hasattr(contrib, _name[len("_contrib_"):]):
                setattr(contrib, _name[len("_contrib_"):], _w)
        if _name.startswith("_linalg_"):
            if not hasattr(linalg, _name[len("_linalg_"):]):
                setattr(linalg, _name[len("_linalg_"):], _w)
        if _name.startswith("_random_"):
            if not hasattr(random, _name[len("_random_"):]):
                setattr(random, _name[len("_random_"):], _w)


_refresh_ops()


# ---------------------------------------------------------------------------
# Symbol sugar methods — MXNet exposes most ops as Symbol methods too
# (reference: symbol/register.py attaches generated methods).
# ---------------------------------------------------------------------------

_SYMBOL_METHODS = {
    "reshape": "reshape", "transpose": "transpose", "flatten": "Flatten",
    "astype": "cast", "cast": "cast", "sum": "sum", "mean": "mean",
    "max": "max", "min": "min", "prod": "prod", "clip": "clip",
    "expand_dims": "expand_dims", "squeeze": "squeeze",
    "slice_axis": "slice_axis", "split": "split", "repeat": "repeat",
    "tile": "tile", "softmax": "softmax", "log_softmax": "log_softmax",
    "exp": "exp", "log": "log", "sqrt": "sqrt", "square": "square",
    "abs": "abs", "norm": "norm", "argmax": "argmax", "argmin": "argmin",
    "sigmoid": "sigmoid", "tanh": "tanh", "relu": "relu",
}


def _attach_symbol_methods():
    from ..ops.registry import has_op

    for meth, opname in _SYMBOL_METHODS.items():
        if not has_op(opname):
            continue
        fn = _make_symbol_function(opname)

        def method(self, *args, _fn=fn, **kwargs):
            return _fn(self, *args, **kwargs)

        method.__name__ = meth
        if not hasattr(Symbol, meth):
            setattr(Symbol, meth, method)


_attach_symbol_methods()
