"""HybridBlock.export — gluon → symbol.json + .params.

Reference: ``python/mxnet/gluon/block.py :: HybridBlock.export`` produces
``prefix-symbol.json`` + ``prefix-%04d.params``, the deployment artifact
re-imported by ``SymbolBlock.imports`` (and by other language bindings).
The trace here runs hybrid_forward with Symbol proxies — the same move the
reference makes with its symbol frontend.
"""
from __future__ import annotations

from ..base import MXNetError
from ..ndarray.serialization import save as nd_save
from .symbol import AUX_PARAMS, Symbol, var
from ..ops.registry import get_op

__all__ = ["export_hybrid_block", "mark_aux_states", "trace_symbol"]


def mark_aux_states(sym: Symbol) -> None:
    """Mark variables feeding aux slots of stateful ops (BatchNorm moving
    stats) with __aux__, mirroring nnvm's FMutateInputs classification."""
    from .symbol import _mark_aux_inputs

    for node in sym._topo():
        if node.op is not None:
            _mark_aux_inputs(node, get_op(node.op))


def trace_symbol(block):
    """Symbolically trace an initialized block. Returns
    ``(sym, arg_params, aux_params)`` with params as ``{name: NDArray}``
    — the in-memory form export and ``optimize_for`` both consume."""
    params = block.collect_params()
    uninitialized = [p.name for p in params.values() if p._data is None]
    if uninitialized:
        raise MXNetError(
            f"export: run a forward pass first; uninitialized params: "
            f"{uninitialized[:3]}...")
    data = var("data")
    try:
        out = block._symbolic_forward(data)
    except Exception as e:
        raise MXNetError(
            f"export: block is not symbolically traceable ({e}); blocks "
            "whose forward depends on concrete shapes/values cannot be "
            "exported — same restriction as the reference's hybridize "
            "tracing") from e
    if isinstance(out, (list, tuple)):
        from .symbol import Group

        flat = []

        def walk(o):
            if isinstance(o, Symbol):
                flat.append(o)
            elif isinstance(o, (list, tuple)):
                for x in o:
                    walk(x)

        walk(out)
        out = Group(flat)
    mark_aux_states(out)
    arg_names = set(out.list_arguments())
    aux_names = set(out.list_auxiliary_states())
    arg_params, aux_params = {}, {}
    for p in params.values():
        if p._data is None:
            continue
        if p.name in aux_names:
            aux_params[p.name] = p.data()
        elif p.name in arg_names:
            arg_params[p.name] = p.data()
    return out, arg_params, aux_params


def export_hybrid_block(block, path: str, epoch: int = 0):
    """Trace ``block`` symbolically and write the deployment artifact.
    Params not reached by the trace (e.g. unused heads) are dropped,
    matching the reference's export behaviour."""
    out, arg_params, aux_params = trace_symbol(block)
    sym_file = f"{path}-symbol.json"
    out.save(sym_file)
    payload = {f"arg:{k}": v for k, v in arg_params.items()}
    payload.update({f"aux:{k}": v for k, v in aux_params.items()})
    params_file = f"{path}-{epoch:04d}.params"
    nd_save(params_file, payload)
    return sym_file, params_file
