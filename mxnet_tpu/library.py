"""``mx.library`` — load external native operator libraries (reference:
``python/mxnet/library.py`` :: ``load``, C side ``include/mxnet/lib_api.h``
:: ``CustomOp`` + ``src/c_api/c_api.cc::MXLoadLib``).

The reference dlopens a user ``.so`` that registers ops through a C ABI.
TPU-native equivalent: the ``.so`` exports the small C ABI below; loaded
ops are registered into the op registry (so they appear as ``mx.nd.*`` /
``mx.sym.*`` like every other op) and execute on the HOST via
``jax.pure_callback`` — callable under ``jit``/``hybridize``, with XLA
treating the call as an opaque host op. This is the honest TPU mapping:
user-native kernels cannot target the MXU (use ``mx.rtc`` Pallas kernels
for that); what a native library provides is host compute plumbed into
the graph.

Required C ABI (all symbols ``extern "C"``):

    int  mxlib_num_ops(void);
    const char* mxlib_op_name(int op);
    int  mxlib_op_num_inputs(int op);
    //  out_shape has room for 8 dims; return 0 on success
    int  mxlib_op_infer_shape(int op, int nin, const int64_t** in_shapes,
                              const int* in_ndims, int64_t* out_shape,
                              int* out_ndim);
    //  f32 buffers, contiguous; return 0 on success
    int  mxlib_op_compute(int op, int nin, const float** in,
                          const int64_t** in_shapes, const int* in_ndims,
                          float* out);
"""
from __future__ import annotations

import ctypes
import os
from typing import List

import numpy as _np

from .base import MXNetError

__all__ = ["load", "loaded_libs"]

_LOADED: List[str] = []


def loaded_libs():
    return list(_LOADED)


def _shape_args(shapes):
    n = len(shapes)
    arrs = [(_np.asarray(s, _np.int64) if len(s) else
             _np.zeros(1, _np.int64)) for s in shapes]
    ptrs = (ctypes.POINTER(ctypes.c_int64) * n)(*[
        a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)) for a in arrs])
    ndims = (ctypes.c_int * n)(*[len(s) for s in shapes])
    return arrs, ptrs, ndims


def load(path, verbose=True):
    """Load a native op library; returns the list of registered op names
    (reference contract: ``mx.library.load`` prints/exposes them)."""
    path = os.path.abspath(path)
    if not os.path.exists(path):
        raise MXNetError(f"library not found: {path}")
    try:
        lib = ctypes.CDLL(path)
    except OSError as e:
        raise MXNetError(f"cannot dlopen {path}: {e}") from e
    for sym, restype in [("mxlib_num_ops", ctypes.c_int),
                         ("mxlib_op_name", ctypes.c_char_p),
                         ("mxlib_op_num_inputs", ctypes.c_int),
                         ("mxlib_op_infer_shape", ctypes.c_int),
                         ("mxlib_op_compute", ctypes.c_int)]:
        if not hasattr(lib, sym):
            raise MXNetError(
                f"{path}: missing ABI symbol {sym!r} — see "
                "mxnet_tpu/library.py for the required C ABI")
        getattr(lib, sym).restype = restype

    from .ops.registry import register

    from .ops.registry import get_op as _get_op

    # validate EVERY name before registering ANY: a collision must not
    # leave earlier ops from the rejected library behind
    all_names = [lib.mxlib_op_name(i).decode()
                 for i in range(lib.mxlib_num_ops())]
    if path not in _LOADED:
        for name in all_names:
            try:
                _get_op(name)
                exists = True
            except Exception:
                exists = False
            if exists:
                raise MXNetError(
                    f"{path}: op {name!r} collides with an already-"
                    "registered op; loading it would silently redirect "
                    "existing graphs")

    names = []
    for op_idx, name in enumerate(all_names):
        nin = lib.mxlib_op_num_inputs(op_idx)

        def make(op_idx=op_idx, name=name, nin=nin):
            def infer_shape(shapes):
                _keep, ptrs, ndims = _shape_args(shapes)
                out_shape = (_np.zeros(8, _np.int64))
                out_ndim = ctypes.c_int(0)
                rc = lib.mxlib_op_infer_shape(
                    op_idx, nin, ptrs, ndims,
                    out_shape.ctypes.data_as(
                        ctypes.POINTER(ctypes.c_int64)),
                    ctypes.byref(out_ndim))
                if rc != 0:
                    raise MXNetError(
                        f"{name}: infer_shape failed (rc={rc}) for input "
                        f"shapes {shapes}")
                return tuple(int(d) for d in out_shape[:out_ndim.value])

            def host_compute(*arrays):
                arrays = [_np.ascontiguousarray(a, _np.float32)
                          for a in arrays]
                shapes = [a.shape for a in arrays]
                out = _np.zeros(infer_shape(shapes), _np.float32)
                _keep, ptrs, ndims = _shape_args(shapes)
                in_ptrs = (ctypes.POINTER(ctypes.c_float) * nin)(*[
                    a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
                    for a in arrays])
                rc = lib.mxlib_op_compute(
                    op_idx, nin, in_ptrs, ptrs, ndims,
                    out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
                if rc != 0:
                    raise MXNetError(f"{name}: compute failed (rc={rc})")
                return out

            def op_fn(*args):
                import jax
                import jax.numpy as jnp

                if len(args) != nin:
                    raise MXNetError(
                        f"{name} expects {nin} inputs, got {len(args)}")
                out_shape = infer_shape([tuple(a.shape) for a in args])
                return jax.pure_callback(
                    host_compute,
                    jax.ShapeDtypeStruct(out_shape, jnp.float32),
                    *args, vmap_method="sequential")

            op_fn.__name__ = name
            op_fn.__doc__ = (f"custom native op {name!r} from {path} "
                             "(host compute via pure_callback)")
            return op_fn

        register(name, variadic=False)(make())
        names.append(name)
    # regenerate the nd/sym wrapper namespaces to pick up the new ops
    from . import ndarray as nd_mod
    from . import symbol as sym_mod

    for mod in (nd_mod, sym_mod):
        refresh = getattr(mod, "_refresh_ops", None)
        if refresh is not None:
            refresh()
    _LOADED.append(path)
    if verbose:
        import logging

        logging.info("loaded library %s: ops %s", path, names)
    return names
