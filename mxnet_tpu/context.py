"""Device contexts.

Reference: ``include/mxnet/base.h :: Context`` — a ``(dev_type, dev_id)``
pair with kCPU / kGPU / kCPUPinned / kCPUShared. The TPU-native build adds
``kTPU`` as the accelerator type and maps every context onto a JAX device:

* ``mx.cpu(i)``        -> i-th XLA:CPU device (also the test oracle)
* ``mx.tpu(i)``        -> i-th TPU chip visible to this process
* ``mx.gpu(i)``        -> alias for the i-th local accelerator, so that
  unmodified MXNet scripts written with ``mx.gpu()`` run on TPU machines
  (the north star is a bare context swap; aliasing makes it barer still).
* ``mx.cpu_pinned()``  -> host memory staging context. XLA:TPU manages its
  own pinned staging buffers, so this is a CPU context tagged pinned; the
  DataLoader uses it as the hand-off point before ``device_put``.
"""
from __future__ import annotations

import threading
from typing import Optional

from .base import MXNetError

__all__ = [
    "Context",
    "cpu",
    "cpu_pinned",
    "cpu_shared",
    "gpu",
    "tpu",
    "current_context",
    "num_gpus",
    "num_tpus",
    "num_devices",
]


class Context:
    """A device context (device type + device id)."""

    # dev_type ids keep the reference's numbering where it exists
    # (include/mxnet/base.h :: kCPU=1, kGPU=2, kCPUPinned=3, kCPUShared=5)
    # and add kTPU=6.
    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 5: "cpu_shared", 6: "tpu"}
    devstr2type = {v: k for k, v in devtype2str.items()}

    _default_ctx = threading.local()

    def __init__(self, device_type, device_id: int = 0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        else:
            if isinstance(device_type, str):
                device_type = Context.devstr2type[device_type]
            self.device_typeid = device_type
            self.device_id = device_id
        self._old_ctx = None

    @property
    def device_type(self) -> str:
        return Context.devtype2str[self.device_typeid]

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_typeid == other.device_typeid
            and self.device_id == other.device_id
        )

    def __str__(self):
        return f"{self.device_type}({self.device_id})"

    def __repr__(self):
        return self.__str__()

    def __enter__(self):
        if not hasattr(Context._default_ctx, "value"):
            Context._default_ctx.value = Context("cpu", 0)
        self._old_ctx = Context._default_ctx.value
        Context._default_ctx.value = self
        return self

    def __exit__(self, ptype, value, trace):
        Context._default_ctx.value = self._old_ctx

    # -- JAX mapping ---------------------------------------------------
    def jax_device(self):
        """Resolve this context to a concrete ``jax.Device``."""
        import jax

        dt = self.device_type
        if dt in ("cpu", "cpu_pinned", "cpu_shared"):
            # THIS process's devices: in a multi-controller job (dist_sync)
            # cpu(i)/tpu(i) is rank-local, like the reference's per-worker
            # gpu(i) — other ranks' devices are not addressable anyway
            devs = jax.local_devices(backend="cpu")
        elif dt == "tpu":
            devs = _accelerator_devices("tpu")
        elif dt == "gpu":
            # gpu(i) aliases the local accelerator so mx.gpu() scripts run
            # unchanged on TPU hosts; raises only if no accelerator at all.
            devs = _accelerator_devices(None)
        else:
            raise MXNetError(f"unknown device type {dt}")
        if self.device_id >= len(devs):
            raise MXNetError(
                f"context {self} out of range: only {len(devs)} {dt} device(s)"
            )
        return devs[self.device_id]

    def empty_cache(self):
        """Release cached device memory (reference: Context::empty_cache →
        storage pool release). PjRt owns pooling; this is best-effort."""
        import gc

        gc.collect()


def _accelerator_devices(kind: Optional[str]):
    """Non-CPU jax devices of THIS process, most-specific first (rank-local
    numbering in multi-controller jobs — see Context.jax_device)."""
    import jax

    try:
        all_devs = jax.local_devices()
    except RuntimeError:
        return []
    accel = [d for d in all_devs if d.platform != "cpu"]
    if kind == "tpu":
        tpus = [d for d in accel if "tpu" in d.platform.lower() or "axon" in d.platform.lower()]
        # Under forced-CPU test runs there is no TPU; fall back to CPU
        # devices so `mx.tpu()` code paths stay testable (oracle device).
        return tpus or accel or jax.local_devices(backend="cpu")
    return accel or jax.local_devices(backend="cpu")


def cpu(device_id: int = 0) -> Context:
    return Context(1, device_id)


def gpu(device_id: int = 0) -> Context:
    return Context(2, device_id)


def cpu_pinned(device_id: int = 0) -> Context:
    return Context(3, device_id)


def cpu_shared(device_id: int = 0) -> Context:
    return Context(5, device_id)


def tpu(device_id: int = 0) -> Context:
    return Context(6, device_id)


def num_gpus() -> int:
    """Number of local accelerators (reference: mx.context.num_gpus)."""
    import jax

    try:
        return len([d for d in jax.devices() if d.platform != "cpu"])
    except RuntimeError:
        return 0


def num_tpus() -> int:
    return num_gpus()


def num_devices() -> int:
    import jax

    return jax.device_count()


def current_context() -> Context:
    if not hasattr(Context._default_ctx, "value"):
        Context._default_ctx.value = Context("cpu", 0)
    return Context._default_ctx.value


def default_accelerator() -> Context:
    """The preferred compute context on this host: tpu if present else cpu."""
    return tpu(0) if num_gpus() > 0 else cpu(0)
