"""Detection image pipeline (reference: ``python/mxnet/image/detection.py``
:: ``DetAugmenter`` zoo, ``CreateDetAugmenter``, ``ImageDetIter``).

Labels ride the recordio header as a flat array
``[header_width, object_width, <extras...>, obj0..., obj1...]`` with each
object ``[cls, xmin, ymin, xmax, ymax, ...]`` in normalized [0, 1]
coordinates — the ``tools/im2rec.py`` detection packing. Augmenters
transform image AND boxes together; the iterator pads each batch's label
block to a fixed object count with -1 (the reference's padding value).
"""
from __future__ import annotations

import random as _pyrandom

import numpy as np

from ..base import MXNetError
from . import (Augmenter, BrightnessJitterAug, CastAug, ColorNormalizeAug,
               ContrastJitterAug, ForceResizeAug, HorizontalFlipAug,
               ImageIter, RandomGrayAug, SaturationJitterAug, imdecode)

__all__ = ["DetAugmenter", "DetBorrowAug", "DetHorizontalFlipAug",
           "DetRandomCropAug", "DetRandomPadAug", "CreateDetAugmenter",
           "ImageDetIter"]


class DetAugmenter:
    """Image+label augmenter base (reference: detection.py::DetAugmenter)."""

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Lift an image-only Augmenter into the detection pipeline
    (reference: DetBorrowAug) — geometry-preserving augs only."""

    def __init__(self, augmenter):
        if not isinstance(augmenter, Augmenter):
            raise MXNetError("DetBorrowAug wraps an image Augmenter")
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetHorizontalFlipAug(DetAugmenter):
    """Mirror image and x-coordinates with probability p."""

    def __init__(self, p=0.5):
        self.p = p

    def __call__(self, src, label):
        if _pyrandom.random() < self.p:
            arr = src.asnumpy() if hasattr(src, "asnumpy") else np.asarray(src)
            src = arr[:, ::-1, :].copy()
            label = label.copy()
            valid = label[:, 0] >= 0
            x1 = label[valid, 1].copy()
            label[valid, 1] = 1.0 - label[valid, 3]
            label[valid, 3] = 1.0 - x1
        return src, label


class DetRandomCropAug(DetAugmenter):
    """SSD-style random crop with object-coverage constraints
    (reference: DetRandomCropAug)."""

    def __init__(self, min_object_covered=0.1, aspect_ratio_range=(0.75, 1.33),
                 area_range=(0.05, 1.0), max_attempts=50):
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts

    def _coverage(self, boxes, crop):
        cx1, cy1, cx2, cy2 = crop
        ix1 = np.maximum(boxes[:, 0], cx1)
        iy1 = np.maximum(boxes[:, 1], cy1)
        ix2 = np.minimum(boxes[:, 2], cx2)
        iy2 = np.minimum(boxes[:, 3], cy2)
        inter = np.clip(ix2 - ix1, 0, None) * np.clip(iy2 - iy1, 0, None)
        area = np.clip(boxes[:, 2] - boxes[:, 0], 1e-12, None) * \
            np.clip(boxes[:, 3] - boxes[:, 1], 1e-12, None)
        return inter / area

    def __call__(self, src, label):
        arr = src.asnumpy() if hasattr(src, "asnumpy") else np.asarray(src)
        h, w = arr.shape[:2]
        valid = label[:, 0] >= 0
        boxes = label[valid, 1:5]
        for _ in range(self.max_attempts):
            scale = _pyrandom.uniform(*self.area_range)
            ratio = _pyrandom.uniform(*self.aspect_ratio_range)
            cw = min(1.0, np.sqrt(scale * ratio))
            ch = min(1.0, np.sqrt(scale / ratio))
            cx = _pyrandom.uniform(0, 1.0 - cw)
            cy = _pyrandom.uniform(0, 1.0 - ch)
            crop = (cx, cy, cx + cw, cy + ch)
            if boxes.size:
                cov = self._coverage(boxes, crop)
                keep = cov >= self.min_object_covered
                if not keep.any():
                    continue
            # crop pixels
            x1p, y1p = int(cx * w), int(cy * h)
            x2p, y2p = int((cx + cw) * w), int((cy + ch) * h)
            out = arr[y1p:y2p, x1p:x2p, :]
            new_label = np.full_like(label, -1.0)
            if boxes.size:
                kept = boxes[keep]
                # re-normalize into crop coords, clipped
                kept = kept.copy()
                kept[:, [0, 2]] = np.clip(
                    (kept[:, [0, 2]] - cx) / cw, 0.0, 1.0)
                kept[:, [1, 3]] = np.clip(
                    (kept[:, [1, 3]] - cy) / ch, 0.0, 1.0)
                rows = label[valid][keep]
                rows[:, 1:5] = kept
                new_label[:len(rows)] = rows
            return out, new_label
        return arr, label


class DetRandomPadAug(DetAugmenter):
    """Expand the canvas and place the image randomly (zoom-out aug,
    reference: DetRandomPadAug)."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33),
                 area_range=(1.0, 3.0), max_attempts=50,
                 pad_val=(127, 127, 127)):
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.pad_val = pad_val

    def __call__(self, src, label):
        arr = src.asnumpy() if hasattr(src, "asnumpy") else np.asarray(src)
        h, w = arr.shape[:2]
        for _ in range(self.max_attempts):
            scale = _pyrandom.uniform(*self.area_range)
            if scale < 1.0:
                continue
            ratio = _pyrandom.uniform(*self.aspect_ratio_range)
            nw, nh = int(w * np.sqrt(scale * ratio)), \
                int(h * np.sqrt(scale / ratio))
            if nw < w or nh < h:
                continue
            ox = _pyrandom.randint(0, nw - w)
            oy = _pyrandom.randint(0, nh - h)
            canvas = np.empty((nh, nw, arr.shape[2]), arr.dtype)
            canvas[...] = np.asarray(self.pad_val, arr.dtype)[:arr.shape[2]]
            canvas[oy:oy + h, ox:ox + w, :] = arr
            label = label.copy()
            valid = label[:, 0] >= 0
            label[valid, 1] = (label[valid, 1] * w + ox) / nw
            label[valid, 3] = (label[valid, 3] * w + ox) / nw
            label[valid, 2] = (label[valid, 2] * h + oy) / nh
            label[valid, 4] = (label[valid, 4] * h + oy) / nh
            return canvas, label
        return arr, label


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_gray=0, rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0, hue=0,
                       pad_val=(127, 127, 127), min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.05, 3.0), max_attempts=50):
    """Standard detection pipeline (reference:
    detection.py::CreateDetAugmenter)."""
    auglist = []
    if rand_crop > 0 and _pyrandom is not None:
        auglist.append(DetRandomCropAug(
            min_object_covered, aspect_ratio_range,
            (area_range[0], min(1.0, area_range[1])), max_attempts))
    if rand_pad > 0:
        auglist.append(DetRandomPadAug(
            aspect_ratio_range, (1.0, max(1.0, area_range[1])),
            max_attempts, pad_val))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    # geometry settles: force to the model's input size
    auglist.append(DetBorrowAug(ForceResizeAug(
        (data_shape[2], data_shape[1]))))
    if brightness:
        auglist.append(DetBorrowAug(BrightnessJitterAug(brightness)))
    if contrast:
        auglist.append(DetBorrowAug(ContrastJitterAug(contrast)))
    if saturation:
        auglist.append(DetBorrowAug(SaturationJitterAug(saturation)))
    if hue:
        from . import HueJitterAug

        auglist.append(DetBorrowAug(HueJitterAug(hue)))
    if rand_gray > 0:
        auglist.append(DetBorrowAug(RandomGrayAug(rand_gray)))
    auglist.append(DetBorrowAug(CastAug()))
    if mean is not None or std is not None:
        # only True substitutes the ImageNet defaults; a component left
        # as None stays IDENTITY (no surprise mean shift on std-only use)
        if mean is True:
            mean = np.array([123.68, 116.28, 103.53])
        elif mean is None:
            mean = np.zeros(3)
        if std is True:
            std = np.array([58.395, 57.12, 57.375])
        elif std is None:
            std = np.ones(3)
        auglist.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter(ImageIter):
    """Detection record iterator (reference: detection.py::ImageDetIter).

    Yields NCHW batches plus ``(batch, max_objects, object_width)``
    labels, -1-padded. Object count/width are estimated by scanning the
    first records (the reference's ``_estimate_label_shape``)."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imgidx=None, shuffle=False, aug_list=None,
                 label_shape=None, **kwargs):
        if aug_list is None:
            aug_list = CreateDetAugmenter(data_shape)
        # ImageDetIter.next() decodes inline (no pool); don't let env
        # MXNET_DATA_WORKERS fork a process pool it would never use
        kwargs.setdefault("worker_mode", "serial")
        super().__init__(batch_size, data_shape, path_imgrec=path_imgrec,
                         path_imgidx=path_imgidx, shuffle=shuffle,
                         aug_list=[], label_width=1, **kwargs)
        self.auglist = aug_list
        if label_shape is None:
            label_shape = self._estimate_label_shape()
        self.label_shape = tuple(label_shape)
        from ..io import DataDesc

        self.provide_label = [DataDesc(
            "label", (batch_size,) + self.label_shape, "float32", "N")]
        self.reset()

    @staticmethod
    def _parse_label(raw):
        raw = np.asarray(raw, np.float32).ravel()
        if raw.size < 2:
            raise MXNetError(
                "detection label must start with [header_width, "
                "object_width, ...]")
        a, b = int(raw[0]), int(raw[1])
        if b < 5:
            raise MXNetError(f"object_width {b} < 5 (cls + 4 coords)")
        body = raw[a:]
        if body.size % b:
            raise MXNetError(
                f"label body size {body.size} not divisible by "
                f"object_width {b}")
        return body.reshape(-1, b)

    def _estimate_label_shape(self):
        """Scan the WHOLE record file (like the reference): estimating
        from a prefix would silently truncate ground-truth boxes of any
        later record with more objects. Pass ``label_shape`` explicitly
        to skip the scan on huge datasets."""
        max_objs, width = 1, 5
        self.reset()
        while True:
            sample = self._next_sample()
            if sample is None:
                break
            label, _payload = sample
            objs = self._parse_label(label)
            max_objs = max(max_objs, objs.shape[0])
            width = max(width, objs.shape[1])
        self.reset()
        return (max_objs, width)

    def next(self):
        from ..io import DataBatch
        from ..ndarray import array as nd_array

        c, h, w = self.data_shape
        mo, lw = self.label_shape
        data = np.zeros((self.batch_size, c, h, w), np.float32)
        labels = np.full((self.batch_size, mo, lw), -1.0, np.float32)
        i = 0
        while i < self.batch_size:
            sample = self._next_sample()
            if sample is None:
                break
            raw_label, payload = sample
            objs = self._parse_label(raw_label)
            padded = np.full((mo, lw), -1.0, np.float32)
            n = min(len(objs), mo)
            padded[:n, :objs.shape[1]] = objs[:n]
            img = imdecode(payload, flag=1 if c == 3 else 0)
            arr = img.asnumpy() if hasattr(img, "asnumpy") else np.asarray(img)
            for aug in self.auglist:
                arr, padded = aug(arr, padded)
                if hasattr(arr, "asnumpy"):
                    arr = arr.asnumpy()
            data[i] = np.asarray(arr, np.float32).transpose(2, 0, 1)
            labels[i] = padded
            i += 1
        if i == 0:
            raise StopIteration
        pad = self.batch_size - i
        for j in range(i, self.batch_size):
            data[j] = data[j % i]
            labels[j] = labels[j % i]
        return DataBatch(data=[nd_array(data)], label=[nd_array(labels)],
                         pad=pad)

    def reshape(self, data_shape=None, label_shape=None):
        """Change batch shapes between epochs (reference:
        ImageDetIter.reshape)."""
        from ..io import DataDesc

        if data_shape is not None:
            self.data_shape = tuple(data_shape)
            self.provide_data = [DataDesc(
                "data", (self.batch_size,) + self.data_shape, "float32",
                "NCHW")]
        if label_shape is not None:
            self.label_shape = tuple(label_shape)
            self.provide_label = [DataDesc(
                "label", (self.batch_size,) + self.label_shape, "float32",
                "N")]
