"""``mx.image`` — image decode + augmentation pipeline (reference:
``python/mxnet/image/image.py``).

The reference wraps OpenCV; here PIL decodes/encodes (the only codec in
this environment) and the augmenters are pure numpy on HWC arrays — they
run in DataLoader / iterator worker threads on host, exactly like the
reference's C++ augmenter zoo runs on CPU, and the device only ever sees
the final batched tensor.
"""
from __future__ import annotations

import contextlib as _contextlib
import io as _io
import os as _os
import random as _pyrandom
import threading as _threading
import zlib as _zlib

import numpy as np

from .. import telemetry
from ..base import MXNetError
from ..ndarray import NDArray, array as nd_array
from ..telemetry import _state as _telemetry_state

__all__ = [
    "imdecode", "imread", "imresize", "resize_short", "fixed_crop",
    "center_crop", "random_crop", "random_size_crop", "color_normalize",
    "Augmenter", "ResizeAug", "ForceResizeAug", "CenterCropAug",
    "RandomCropAug", "RandomSizedCropAug", "HorizontalFlipAug", "CastAug",
    "ColorNormalizeAug", "BrightnessJitterAug", "ContrastJitterAug",
    "SaturationJitterAug", "ColorJitterAug", "LightingAug", "RandomGrayAug",
    "CreateAugmenter", "ImageIter",
]


def _to_np(img):
    if isinstance(img, NDArray):
        return img.asnumpy()
    return np.asarray(img)


# Numpy passthrough mode: inside `_numpy_outputs()` every augmenter /
# decode helper returns plain numpy instead of wrapping into NDArrays.
# Decode WORKER PROCESSES require this — they are forked children whose
# inherited XLA threadpools are dead, so a single nd_array() there would
# hang on the first device_put — and it also drops the per-augmenter
# host->device round trip from the hot decode path.
_out_mode = _threading.local()


def _mkarr(arr):
    """Augmenter output wrapper: NDArray normally; in numpy passthrough
    mode a plain array with nd_array's float64 -> float32 rule applied,
    so both modes produce bit-identical values."""
    if getattr(_out_mode, "numpy", False):
        arr = np.asarray(arr)
        if arr.dtype == np.float64:
            arr = arr.astype(np.float32)
        return arr
    return nd_array(arr)


@_contextlib.contextmanager
def _numpy_outputs():
    prev = getattr(_out_mode, "numpy", False)
    _out_mode.numpy = True
    try:
        yield
    finally:
        _out_mode.numpy = prev


def _wrap(img, out=None):
    if out is not None:
        out._set_data(nd_array(img).data)
        return out
    return _mkarr(img)


def imdecode(buf, flag=1, to_rgb=1, out=None):
    """Decode an encoded image buffer to HWC uint8 (reference: imdecode)."""
    from PIL import Image

    if isinstance(buf, NDArray):
        buf = buf.asnumpy().tobytes()
    img = Image.open(_io.BytesIO(bytes(buf)))
    img = img.convert("RGB" if flag else "L")
    arr = np.asarray(img)
    if not flag:
        arr = arr[:, :, None]
    if flag and not to_rgb:
        arr = arr[:, :, ::-1]  # BGR, the reference's cv2 default
    return _wrap(arr, out)


def imread(filename, flag=1, to_rgb=1):
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag=flag, to_rgb=to_rgb)


def imresize(src, w, h, interp=1):
    from PIL import Image

    arr = _to_np(src).astype(np.uint8)
    resample = {0: Image.NEAREST, 1: Image.BILINEAR, 2: Image.BICUBIC,
                3: Image.NEAREST, 4: Image.LANCZOS}.get(interp,
                                                        Image.BILINEAR)
    squeeze = arr.shape[-1] == 1
    pil = Image.fromarray(arr[..., 0] if squeeze else arr)
    out = np.asarray(pil.resize((w, h), resample))
    if squeeze:
        out = out[:, :, None]
    return _mkarr(out)


def resize_short(src, size, interp=2):
    """Resize so the SHORT side equals size (reference: resize_short)."""
    h, w = _to_np(src).shape[:2]
    if h > w:
        new_w, new_h = size, int(h * size / w)
    else:
        new_w, new_h = int(w * size / h), size
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    arr = _to_np(src)[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        return imresize(arr, size[0], size[1], interp)
    return _mkarr(arr)


def center_crop(src, size, interp=2):
    h, w = _to_np(src).shape[:2]
    new_w, new_h = size
    x0 = max(0, (w - new_w) // 2)
    y0 = max(0, (h - new_h) // 2)
    out = fixed_crop(src, x0, y0, min(new_w, w), min(new_h, h), size, interp)
    return out, (x0, y0, new_w, new_h)


def random_crop(src, size, interp=2):
    h, w = _to_np(src).shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = _pyrandom.randint(0, w - new_w)
    y0 = _pyrandom.randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def random_size_crop(src, size, area, ratio, interp=2, max_attempts=10):
    """Random area+aspect crop (the Inception-style crop)."""
    h, w = _to_np(src).shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(max_attempts):
        target = _pyrandom.uniform(*area) * src_area
        log_ratio = (np.log(ratio[0]), np.log(ratio[1]))
        ar = np.exp(_pyrandom.uniform(*log_ratio))
        new_w = int(round(np.sqrt(target * ar)))
        new_h = int(round(np.sqrt(target / ar)))
        if new_w <= w and new_h <= h:
            x0 = _pyrandom.randint(0, w - new_w)
            y0 = _pyrandom.randint(0, h - new_h)
            return (fixed_crop(src, x0, y0, new_w, new_h, size, interp),
                    (x0, y0, new_w, new_h))
    return center_crop(src, size, interp)


def color_normalize(src, mean, std=None):
    arr = _to_np(src).astype(np.float32)
    arr = arr - _to_np(mean)
    if std is not None:
        arr = arr / _to_np(std)
    return _mkarr(arr)


# ---------------------------------------------------------------------------
# augmenters (reference: image.py Augmenter zoo)
# ---------------------------------------------------------------------------


class Augmenter:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json

        return json.dumps([self.__class__.__name__, self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=2):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size, self.area, self.ratio, self.interp = \
            size, area, ratio, interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio,
                                self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            return _mkarr(_to_np(src)[:, ::-1])
        return src if isinstance(src, NDArray) else _mkarr(src)


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(typ=typ)
        self.typ = typ

    def __call__(self, src):
        return _mkarr(_to_np(src).astype(self.typ))


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__()
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32) if std is not None else None

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.brightness, self.brightness)
        return _mkarr(_to_np(src).astype(np.float32) * alpha)


class ContrastJitterAug(Augmenter):
    _coef = np.array([0.299, 0.587, 0.114], np.float32)

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.contrast, self.contrast)
        arr = _to_np(src).astype(np.float32)
        gray = (arr * self._coef).sum(-1).mean()
        return _mkarr(arr * alpha + gray * (1 - alpha))


class SaturationJitterAug(Augmenter):
    _coef = np.array([0.299, 0.587, 0.114], np.float32)

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.saturation, self.saturation)
        arr = _to_np(src).astype(np.float32)
        gray = (arr * self._coef).sum(-1, keepdims=True)
        return _mkarr(arr * alpha + gray * (1 - alpha))



class SequentialAug(Augmenter):
    """Apply a list of augmenters in order (reference: image.py ::
    SequentialAug)."""

    def __init__(self, ts):
        super().__init__()
        self.ts = list(ts)

    def dumps(self):
        return ["SequentialAug", [t.dumps() for t in self.ts]]

    def __call__(self, src):
        for t in self.ts:
            src = t(src)
        return src


class RandomOrderAug(Augmenter):
    """Apply a list of augmenters in random order (reference: image.py ::
    RandomOrderAug)."""

    def __init__(self, ts):
        super().__init__()
        self.ts = list(ts)

    def dumps(self):
        return ["RandomOrderAug", [t.dumps() for t in self.ts]]

    def __call__(self, src):
        order = list(self.ts)
        _pyrandom.shuffle(order)
        for t in order:
            src = t(src)
        return src

class ColorJitterAug(RandomOrderAug):
    """Random-order brightness/contrast/saturation jitter (reference:
    image.py::ColorJitterAug — a RandomOrderAug over the three jitters,
    with hue available via HueJitterAug in the builder)."""

    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness:
            ts.append(BrightnessJitterAug(brightness))
        if contrast:
            ts.append(ContrastJitterAug(contrast))
        if saturation:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)
        self.brightness = brightness
        self.contrast = contrast
        self.saturation = saturation

    def dumps(self):
        return ["ColorJitterAug", [t.dumps() for t in self.ts]]

    def __call__(self, src):
        src = super().__call__(src)
        return src if isinstance(src, NDArray) else _mkarr(src)


class LightingAug(Augmenter):
    """PCA lighting noise (AlexNet-style)."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = np.asarray(eigval, np.float32)
        self.eigvec = np.asarray(eigvec, np.float32)

    def __call__(self, src):
        alpha = np.random.normal(0, self.alphastd, size=(3,))
        rgb = (self.eigvec * alpha * self.eigval).sum(-1)
        return _mkarr(_to_np(src).astype(np.float32) + rgb)


class RandomGrayAug(Augmenter):
    _coef = np.array([0.299, 0.587, 0.114], np.float32)

    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            arr = _to_np(src).astype(np.float32)
            gray = (arr * self._coef).sum(-1, keepdims=True)
            return _mkarr(np.broadcast_to(gray, arr.shape).copy())
        return src if isinstance(src, NDArray) else _mkarr(src)


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0, rand_gray=0,
                    inter_method=2, dtype="float32"):
    """Standard augmenter list builder (reference: CreateAugmenter;
    ``dtype`` mirrors the upstream parameter — ``"uint8"`` keeps the
    chain cast-free for the quarter-size wire format, in which case the
    float augmenters (jitter/normalize/lighting) must stay off)."""
    auglist = []
    crop_size = (data_shape[2], data_shape[1])
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3 / 4.0, 4 / 3.0), inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    if np.dtype(dtype) != np.uint8:
        # decoded pixels are uint8 already; a cast-to-uint8 would only
        # burn a float intermediate per sample on the decode workers
        auglist.append(CastAug(str(np.dtype(dtype))))
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None and len(np.shape(mean)):
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


def _decode_augment(payload, auglist, channels, dtype, sseed=None,
                    numpy_mode=False):
    """Decode one sample + run the augmenter chain -> CHW numpy.

    ``sseed`` reseeds the global python/numpy RNG streams first, making
    the sample's augmentation draws a function of (seed, ordinal) alone —
    bit-identical across serial and process-worker execution (the
    contract bench.py stage 5 and tests/test_io_pipeline.py assert).
    ``numpy_mode`` keeps every augmenter output plain numpy (decode
    workers are forked children whose inherited XLA threadpools are dead;
    see ``_numpy_outputs``).
    """
    if sseed is not None:
        _pyrandom.seed(sseed)
        np.random.seed(sseed)
    cm = _numpy_outputs() if numpy_mode else _contextlib.nullcontext()
    with cm:
        img = imdecode(payload, flag=1 if channels == 3 else 0)
        for aug in auglist:
            img = aug(img)
    arr = img.asnumpy() if isinstance(img, NDArray) else np.asarray(img)
    arr = arr.transpose(2, 0, 1)
    if arr.dtype == dtype:
        return arr
    if np.issubdtype(dtype, np.integer) and \
            np.issubdtype(arr.dtype, np.floating):
        # an integer astype WRAPS out-of-range floats (normalized pixels
        # become 0/255 garbage) — refuse instead of silently corrupting
        raise MXNetError(
            f"augmenter chain produced {arr.dtype} but ImageIter("
            f"dtype={dtype}) was requested; keep normalization off host "
            "(io.DeviceFeedIter device_transform) or use a float dtype")
    return arr.astype(dtype)


_worker_cfg = None
_ITER_UID = 0


def _image_worker_init(auglist, channels, dtype):
    global _worker_cfg
    _worker_cfg = (list(auglist), int(channels), np.dtype(dtype))


def _image_worker_chunk(payloads, seeds, shape, shm_name=None):
    """Decode+augment one chunk in a forked worker, writing each sample
    STRAIGHT into one shared-memory block (no stack-then-copy
    intermediate); only the descriptor crosses the pipe (gluon
    dataloader's transport). ``shm_name`` is parent-assigned so a block
    whose descriptor never arrives stays sweepable by prefix."""
    from ..gluon.data.dataloader import _alloc_shm, _unlink_shm

    auglist, channels, dtype = _worker_cfg
    desc, dst, done = _alloc_shm((len(payloads),) + tuple(shape), dtype,
                                 name=shm_name)
    try:
        for j, (p, s) in enumerate(zip(payloads, seeds)):
            dst[j] = _decode_augment(p, auglist, channels, dtype, s,
                                     numpy_mode=True)
    except BaseException:
        # no descriptor will reach the parent: the failing worker owns
        # the unlink or the block outlives the run in /dev/shm
        done()
        _unlink_shm(desc)
        raise
    done()
    return desc


class ImageIter:
    """Record-file / list-backed image iterator (reference: ImageIter).

    Feeds NCHW batches; decode + augmentation run on host (worker role of
    the reference's C++ ImageRecordIter), the device sees only the final
    batch.

    Worker model (``worker_mode``):

    * ``"process"`` — a fork pool of ``preprocess_threads`` workers (the
      reference iterator's decode worker pool). Each worker decodes a
      contiguous chunk and ships it back as one shared-memory block;
      Pillow decode + numpy augmenters run truly in parallel (the thread
      pool is GIL-bound on everything but the decode itself). Default
      when ``MXNET_DATA_WORKERS`` is set (its value = worker count).
    * ``"thread"`` (default) / ``"serial"`` — the legacy in-process paths.

    ``seed`` makes augmentation deterministic: sample ordinal ``k`` of
    epoch ``e`` reseeds the RNG streams with ``crc32(base(seed, e), k)``,
    so serial and process execution produce bit-identical batches (thread
    mode shares the global streams across workers and stays
    nondeterministic). ``dtype`` is the batch dtype — ``"uint8"`` with a
    crop/flip-only augmenter list ships quarter-size batches and leaves
    normalization to the device (see io.DeviceFeedIter).
    """

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imgidx=None, shuffle=False, aug_list=None,
                 label_width=1, last_batch_handle="pad",
                 preprocess_threads=4, worker_mode=None, seed=None,
                 dtype="float32", worker_timeout=120, **kwargs):
        from ..io import DataDesc
        from ..recordio import MXIndexedRecordIO, MXRecordIO

        if len(data_shape) != 3:
            raise MXNetError("data_shape must be (channels, height, width)")
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.shuffle = shuffle
        env_workers = _os.environ.get("MXNET_DATA_WORKERS")
        if worker_mode is None:
            worker_mode = "process" if env_workers else "thread"
        if worker_mode not in ("serial", "thread", "process"):
            raise MXNetError(
                f"worker_mode must be 'serial', 'thread' or 'process', "
                f"got {worker_mode!r}")
        n = int(env_workers) if env_workers else int(preprocess_threads)
        self._n_workers = max(1, min(n, _os.cpu_count() or 1))
        if worker_mode == "thread" and self._n_workers == 1:
            worker_mode = "serial"
        self._worker_mode = worker_mode
        self._worker_timeout = worker_timeout
        global _ITER_UID
        _ITER_UID += 1
        # parent-assigned shm namespace: blocks whose descriptor never
        # arrives (worker timeout, terminate) stay findable for close()
        self._shm_prefix = f"mxi{_os.getpid()}u{_ITER_UID}"
        self._pool = None
        self._seed = seed
        self._dtype = np.dtype(dtype)
        self._epoch = -1
        self._drawn = 0
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(data_shape)
        self._rec = None
        self._keys = None
        if path_imgrec is None:
            raise MXNetError("ImageIter requires path_imgrec (use "
                             "gluon.data for folder datasets)")
        if path_imgidx:
            self._rec = MXIndexedRecordIO(path_imgidx, path_imgrec, "r")
            self._keys = list(self._rec.keys)
        else:
            if shuffle:
                raise MXNetError(
                    "ImageIter(shuffle=True) requires path_imgidx — "
                    "sequential record files cannot be reordered")
            self._rec = MXRecordIO(path_imgrec, "r")
        self._order = None
        self._cursor = 0
        self.provide_data = [DataDesc("data",
                                      (batch_size,) + self.data_shape,
                                      self._dtype, "NCHW")]
        lshape = (batch_size,) if label_width == 1 else (batch_size,
                                                         label_width)
        self.provide_label = [DataDesc("softmax_label", lshape, "float32",
                                       "N")]
        self.reset()
        if self._worker_mode == "process":
            # fork the pool NOW, on the constructing (main) thread:
            # forking later from a DeviceFeedIter producer thread while
            # the main thread dispatches XLA work maximizes the
            # fork-while-lock-held hazard window. The augmenter list is
            # captured here; mutate self.auglist before construction,
            # not after.
            self._ensure_pool()

    def reset(self):
        self._cursor = 0
        self._epoch += 1
        self._drawn = 0
        if self._seed is not None:
            self._epoch_base = (self._seed + 1000003 * self._epoch) \
                & 0x7FFFFFFF
        else:
            # process workers fork the parent's RNG state: without a
            # fresh per-epoch base every worker would replay the same
            # augmentation stream; draw one from the global stream (which
            # tests seed, keeping runs reproducible end to end)
            self._epoch_base = _pyrandom.getrandbits(31)
        if self._keys is not None:
            self._order = list(self._keys)
            if self.shuffle:
                if self._seed is not None:
                    # seeded: shuffle from a private RNG so the epoch's
                    # order is a function of (seed, epoch) alone
                    _pyrandom.Random(self._epoch_base).shuffle(self._order)
                else:
                    _pyrandom.shuffle(self._order)
        else:
            self._rec.reset()

    def _sample_seed(self, ordinal):
        """Per-sample augmentation seed, or None for the legacy
        global-stream behavior (unseeded serial/thread modes)."""
        if self._seed is None and self._worker_mode != "process":
            return None
        return _zlib.crc32(f"{self._epoch_base}:{ordinal}".encode()) \
            % (2 ** 31)

    def _next_sample(self):
        from ..recordio import unpack

        if self._keys is not None:
            if self._cursor >= len(self._order):
                return None
            rec = self._rec.read_idx(self._order[self._cursor])
            self._cursor += 1
        else:
            rec = self._rec.read()
            if rec is None:
                return None
        header, payload = unpack(rec)
        label = header.label
        if isinstance(label, (np.ndarray, list)):
            label = np.asarray(label, np.float32)
        else:
            label = np.float32(label)
        return label, payload

    def __iter__(self):
        return self

    def __next__(self):
        return self.next()

    def close(self):
        """Shut down the decode pool (idempotent; also runs on GC).
        Thread pools cancel queued work; process pools are terminated
        without draining, then the iterator's shm namespace is swept —
        a chunk whose descriptor never reached the parent (worker
        timeout, terminate mid-chunk) must not outlive the run."""
        pool, self._pool = self._pool, None
        if pool is None:
            return
        if hasattr(pool, "shutdown"):           # ThreadPoolExecutor
            pool.shutdown(wait=False, cancel_futures=True)
        else:                                   # multiprocessing.Pool
            pool.terminate()
            pool.join()
            import glob as _glob

            for path in _glob.glob(f"/dev/shm/{self._shm_prefix}*"):
                try:
                    _os.unlink(path)
                except OSError:  # pragma: no cover - raced cleanup
                    pass

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass

    def _decode_one(self, payload, sseed=None):
        return _decode_augment(payload, self.auglist, self.data_shape[0],
                               self._dtype, sseed)

    def _ensure_pool(self):
        if self._pool is not None:
            return self._pool
        if self._worker_mode == "process":
            import multiprocessing

            # fork, not spawn: workers inherit the augmenter list without
            # re-importing the framework. The worker path is numpy-only
            # (no jax) — forked XLA threadpools are dead in the child, so
            # touching jax there would hang (see _numpy_outputs).
            ctx = multiprocessing.get_context("fork")
            self._pool = ctx.Pool(
                self._n_workers, initializer=_image_worker_init,
                initargs=(self.auglist, self.data_shape[0],
                          str(self._dtype)))
        else:
            import concurrent.futures as _cf

            self._pool = _cf.ThreadPoolExecutor(self._n_workers)
        return self._pool

    def _decode_chunks_into(self, data, payloads, seeds):
        """Fan one batch out over the process pool in contiguous chunks;
        each comes back as one shm block copied once straight into the
        batch buffer (parent owns the unlink)."""
        from ..gluon.data.dataloader import _from_shm_into, _unlink_shm

        pool = self._ensure_pool()
        n = len(payloads)
        size = -(-n // min(self._n_workers, n))
        results = [(ofs, pool.apply_async(
            _image_worker_chunk,
            (payloads[ofs:ofs + size], seeds[ofs:ofs + size],
             self.data_shape,
             f"{self._shm_prefix}e{self._epoch}d{self._drawn}o{ofs}")))
            for ofs in range(0, n, size)]
        descs = []
        failed = None
        for ofs, res in results:
            try:
                descs.append((ofs, res.get(self._worker_timeout)))
            except Exception as e:  # noqa: BLE001 - rewrapped below
                failed = failed or e
        if failed is not None:
            # unlink the chunks that DID land: the workers unregistered
            # their blocks from the resource tracker, the parent owns
            # cleanup (same contract as the gluon loader)
            for _, d in descs:
                _unlink_shm(d)
            raise MXNetError(
                f"ImageIter decode worker failed: {failed!r}") from failed
        for ofs, desc in descs:
            _from_shm_into(desc, data, ofs)

    def next(self):
        from ..io import DataBatch

        c, h, w = self.data_shape
        data = np.zeros((self.batch_size, c, h, w), self._dtype)
        labels = np.zeros((self.batch_size,) if self.label_width == 1
                          else (self.batch_size, self.label_width),
                          np.float32)
        # record reads are serial (cheap, stateful cursor); decode +
        # augment fan out over the pool
        payloads, lab_list = [], []
        while len(payloads) < self.batch_size:
            sample = self._next_sample()
            if sample is None:
                break
            label, payload = sample
            payloads.append(payload)
            lab_list.append(label)
        i = len(payloads)
        if i == 0:
            raise StopIteration
        seeds = [self._sample_seed(self._drawn + j) for j in range(i)]
        self._drawn += i
        if self._worker_mode == "process":
            self._decode_chunks_into(data, payloads, seeds)
        elif self._worker_mode == "thread":
            decoded = list(self._ensure_pool().map(
                self._decode_one, payloads, seeds))
            for j, arr in enumerate(decoded):
                data[j] = arr
        else:
            for j, (p, s) in enumerate(zip(payloads, seeds)):
                data[j] = self._decode_one(p, s)
        for j, label in enumerate(lab_list):
            labels[j] = label
        if _telemetry_state.enabled:
            telemetry.record_images_decoded(i)
        pad = self.batch_size - i
        if pad:
            # pad by recycling real samples (NDArrayIter's wrap behavior —
            # io.py) so fit() never trains on fabricated zero images; pad
            # rows are discounted by score/predict via DataBatch.pad
            for j in range(i, self.batch_size):
                data[j] = data[j % i]
                labels[j] = labels[j % i]
        return DataBatch(data=[nd_array(data)], label=[nd_array(labels)],
                         pad=pad)


from .detection import (DetAugmenter, DetBorrowAug,  # noqa: E402
                        DetHorizontalFlipAug, DetRandomCropAug,
                        DetRandomPadAug, CreateDetAugmenter, ImageDetIter)

__all__ += ["SequentialAug", "RandomOrderAug", "HueJitterAug",
            "scale_down"]
__all__ += ["DetAugmenter", "DetBorrowAug", "DetHorizontalFlipAug",
            "DetRandomCropAug", "DetRandomPadAug", "CreateDetAugmenter",
            "ImageDetIter"]


class HueJitterAug(Augmenter):
    """Random hue jitter (reference: image.py::HueJitterAug — the YIQ
    rotation formulation)."""

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue
        self.tyiq = np.array([[0.299, 0.587, 0.114],
                              [0.596, -0.274, -0.321],
                              [0.211, -0.523, 0.311]])
        self.ityiq = np.array([[1.0, 0.956, 0.621],
                               [1.0, -0.272, -0.647],
                               [1.0, -1.107, 1.705]])

    def __call__(self, src):
        alpha = _pyrandom.uniform(-self.hue, self.hue)
        u = np.cos(alpha * np.pi)
        w = np.sin(alpha * np.pi)
        bt = np.array([[1.0, 0.0, 0.0],
                       [0.0, u, -w],
                       [0.0, w, u]])
        t = np.dot(np.dot(self.ityiq, bt), self.tyiq).T
        x = _to_np(src).astype(np.float32)
        return _mkarr(np.dot(x, t))


def scale_down(src_size, size):
    """Scale `size` down to fit in `src_size`, keeping aspect ratio
    (reference: image.py::scale_down)."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)
