"""``mx.npx`` — NumPy-extension namespace (reference:
``python/mxnet/numpy_extension/__init__.py`` + ``util.py::set_np``).

Deep-learning operators that plain NumPy lacks (activations, softmax,
one_hot, topk, ...) plus the ``set_np``/``reset_np`` frontend switch. In
the reference, ``set_np`` flips both np_shape (zero-size shape semantics —
native here, jax shapes are numpy shapes) and np_array (Gluon blocks
produce ``mx.np.ndarray``); here it toggles the np_array flag consulted by
``is_np_array``.
"""
from __future__ import annotations

import threading

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from ..numpy import ndarray as np_ndarray, _invoke, _np_wrap, _jnp, _data

_state = threading.local()


def set_np(shape=True, array=True):
    """Activate NumPy semantics (reference: util.py::set_np)."""
    if shape and not array:
        raise ValueError("setting np_shape without np_array is not useful "
                         "here: shapes are always NumPy-semantic on JAX")
    _state.np_array = bool(array)


def reset_np():
    _state.np_array = False


def is_np_array():
    return getattr(_state, "np_array", False)


def is_np_shape():
    # jax/XLA shapes ARE numpy shapes (zero-size dims legal); constant True
    # mirrors the reference's semantic once set_np_shape(True) is active
    return True


def set_np_shape(active=True):
    return True


def use_np(func):
    """Decorator form (reference: util.py::use_np) — runs ``func`` with the
    np-array flag active, restoring it afterwards."""
    import functools

    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        prev = is_np_array()
        set_np()
        try:
            return func(*args, **kwargs)
        finally:
            _state.np_array = prev

    return wrapper


# ---------------------------------------------------------------------------
# nn extension ops (reference: _npx namespace, src/operator/numpy_extension)
# ---------------------------------------------------------------------------


def relu(data):
    return _invoke("npx_relu", lambda d: _jnp().maximum(d, 0), [data])


def sigmoid(data):
    import jax

    return _invoke("npx_sigmoid", jax.nn.sigmoid, [data])


def softmax(data, axis=-1, length=None, temperature=None):
    import jax

    t = temperature or 1.0
    if length is None:
        return _invoke("npx_softmax",
                       lambda d: jax.nn.softmax(d / t, axis=axis), [data])

    def body(d, lens):
        # length-masked softmax (reference: softmax(..., use_length=True)):
        # positions >= length along `axis` get zero probability; lengths
        # are per-batch (leading dim)
        ax = axis % d.ndim
        pshape = [1] * d.ndim
        pshape[ax] = d.shape[ax]
        pos = _jnp().arange(d.shape[ax]).reshape(pshape)
        lshape = [1] * d.ndim
        lshape[0] = lens.shape[0]
        mask = pos < lens.astype("int32").reshape(lshape)
        masked = _jnp().where(mask, d / t, -1e30)
        out = jax.nn.softmax(masked, axis=ax)
        return _jnp().where(mask, out, 0.0)

    return _invoke("npx_softmax_len", body, [data, length])


def log_softmax(data, axis=-1):
    import jax

    return _invoke("npx_log_softmax",
                   lambda d: jax.nn.log_softmax(d, axis=axis), [data])


def leaky_relu(data, act_type="leaky", slope=0.25):
    import jax

    acts = {
        "leaky": lambda d: jax.nn.leaky_relu(d, slope),
        "elu": lambda d: jax.nn.elu(d, slope),
        "selu": jax.nn.selu,
        "gelu": jax.nn.gelu,
    }
    if act_type not in acts:
        raise MXNetError(f"leaky_relu: unsupported act_type {act_type!r} "
                         f"(have {sorted(acts)})")
    return _invoke(f"npx_{act_type}", acts[act_type], [data])


def gelu(data):
    import jax

    return _invoke("npx_gelu", jax.nn.gelu, [data])


def one_hot(data, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    import jax

    def body(d):
        oh = jax.nn.one_hot(d.astype("int32"), depth, dtype=dtype)
        return oh * on_value + (1 - oh) * off_value

    return _invoke("npx_one_hot", body, [data])


def pick(data, index, axis=-1, mode="clip", keepdims=False):
    from ..ops.registry import get_op
    from ..ndarray.ndarray import imperative_invoke

    return _np_wrap(imperative_invoke(
        get_op("pick"), [data, index],
        {"axis": axis, "keepdims": keepdims}))


def topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False):
    import jax

    def body(d):
        dd = _jnp().moveaxis(d, axis, -1)
        neg = -dd if is_ascend else dd
        vals, idx = jax.lax.top_k(neg, k)
        if is_ascend:
            vals = -vals
        vals = _jnp().moveaxis(vals, -1, axis)
        idx = _jnp().moveaxis(idx, -1, axis)
        if ret_typ == "value":
            return vals
        if ret_typ == "both":
            return vals, idx.astype("float32")
        return idx.astype("float32")

    return _invoke("npx_topk", body, [data])


def reshape_like(lhs, rhs):
    return _invoke("npx_reshape_like",
                   lambda a, b: _jnp().reshape(a, b.shape), [lhs, rhs])


def batch_flatten(data):
    return _invoke("npx_batch_flatten",
                   lambda d: _jnp().reshape(d, (d.shape[0], -1)), [data])


def batch_dot(a, b, transpose_a=False, transpose_b=False):
    def body(x, y):
        if transpose_a:
            x = _jnp().swapaxes(x, -1, -2)
        if transpose_b:
            y = _jnp().swapaxes(y, -1, -2)
        return _jnp().matmul(x, y)

    return _invoke("npx_batch_dot", body, [a, b])


def gather_nd(data, indices):
    def body(d, idx):
        return d[tuple(idx.astype("int32"))]

    return _invoke("npx_gather_nd", body, [data, indices])


def sequence_mask(data, sequence_length=None, use_sequence_length=False,
                  value=0.0, axis=0):
    if not use_sequence_length or sequence_length is None:
        return _np_wrap(data if isinstance(data, NDArray)
                        else __import__("mxnet_tpu.numpy",
                                        fromlist=["array"]).array(data))

    def body(d, lens):
        steps = _jnp().arange(d.shape[axis])
        mask = steps[:, None] < lens[None, :] if axis == 0 else \
            steps[None, :] < lens[:, None]
        # the axis distinction is fully handled in the mask construction;
        # both layouts broadcast over the trailing feature dims
        mask = mask.reshape(d.shape[:2] + (1,) * (d.ndim - 2))
        return _jnp().where(mask, d, value)

    return _invoke("npx_sequence_mask", body, [data, sequence_length])


def arange_like(data, start=0.0, step=1.0, axis=None):
    def body(d):
        n = d.size if axis is None else d.shape[axis]
        out = start + step * _jnp().arange(n, dtype="float32")
        return out if axis is not None else out.reshape(d.shape)

    return _invoke("npx_arange_like", body, [data])


# op-backed npx functions (reference: mx.npx.* wrappers over the same
# C-registered kernels the symbol/nd frontends use — here the shared op
# registry). Round 4: the set gluon-numpy models and upstream scripts
# actually call.
def _op_call(opname, tensors, attrs):
    from ..ndarray.ndarray import imperative_invoke
    from ..ops.registry import get_op

    return _np_wrap(imperative_invoke(
        get_op(opname), list(tensors),
        {k: v for k, v in attrs.items() if v is not None}))


def activation(data, act_type="relu", **kwargs):
    return _op_call("Activation", [data], {"act_type": act_type})


def fully_connected(x, weight, bias=None, num_hidden=None, no_bias=False,
                    flatten=True, **kwargs):
    return _op_call("FullyConnected", [x, weight, bias],
                    {"num_hidden": num_hidden or weight.shape[0],
                     "no_bias": bias is None or no_bias,
                     "flatten": flatten})


def convolution(data=None, weight=None, bias=None, kernel=None, stride=None,
                dilate=None, pad=None, num_filter=1, num_group=1,
                no_bias=False, layout=None, **kwargs):
    return _op_call("Convolution", [data, weight, bias],
                    {"kernel": kernel, "stride": stride, "dilate": dilate,
                     "pad": pad, "num_filter": num_filter,
                     "num_group": num_group,
                     "no_bias": bias is None or no_bias, "layout": layout})


def pooling(data, kernel=None, stride=None, pad=None, pool_type="max",
            global_pool=False, pooling_convention="valid", layout=None,
            **kwargs):
    return _op_call("Pooling", [data],
                    {"kernel": kernel, "stride": stride, "pad": pad,
                     "pool_type": pool_type, "global_pool": global_pool,
                     "pooling_convention": pooling_convention,
                     "layout": layout})


def batch_norm(x, gamma, beta, running_mean, running_var, eps=1e-3,
               momentum=0.9, axis=1, use_global_stats=False,
               fix_gamma=True, **kwargs):
    # defaults mirror the BatchNorm op (reference batch_norm-inl.h
    # DMLC_DECLARE_FIELD: eps 1e-3, fix_gamma true) so ported npx scripts
    # see identical semantics
    return _op_call("BatchNorm", [x, gamma, beta, running_mean,
                                  running_var],
                    {"eps": eps, "momentum": momentum, "axis": axis,
                     "use_global_stats": use_global_stats,
                     "fix_gamma": fix_gamma})


def layer_norm(data, gamma, beta, axis=-1, eps=1e-5, **kwargs):
    return _op_call("LayerNorm", [data, gamma, beta],
                    {"axis": axis, "eps": eps})


def dropout(data, p=0.5, axes=(), **kwargs):
    return _op_call("Dropout", [data], {"p": p, "axes": tuple(axes)})


def embedding(data, weight, input_dim=None, output_dim=None,
              dtype="float32", sparse_grad=False, **kwargs):
    return _op_call("Embedding", [data, weight],
                    {"input_dim": input_dim or weight.shape[0],
                     "output_dim": output_dim or weight.shape[1],
                     "dtype": dtype})


def smooth_l1(data, scalar=1.0, **kwargs):
    return _op_call("smooth_l1", [data], {"scalar": scalar})


def rnn(data=None, parameters=None, state=None, state_cell=None, mode=None,
        state_size=None, num_layers=1, bidirectional=False, p=0.0,
        state_outputs=False, **kwargs):
    if mode is None:
        raise ValueError(
            "npx.rnn: 'mode' is required (one of 'rnn_relu', 'rnn_tanh', "
            "'lstm', 'gru') — the RNN op has no default cell type")
    tensors = [data, parameters, state]
    if state_cell is not None:
        tensors.append(state_cell)
    return _op_call("RNN", tensors,
                    {"mode": mode, "state_size": state_size,
                     "num_layers": num_layers,
                     "bidirectional": bidirectional, "p": p,
                     "state_outputs": state_outputs})


# waitall/load/save mirrors (reference exposes them in npx too)
def waitall():
    from ..ndarray import waitall as _w

    _w()


def load(fname):
    from ..ndarray import serialization

    loaded = serialization.load(fname)
    if isinstance(loaded, dict):
        return {k: v.as_np_ndarray() for k, v in loaded.items()}
    return [v.as_np_ndarray() for v in loaded]


def save(fname, data):
    from ..ndarray import serialization

    if isinstance(data, dict):
        data = {k: v.as_nd_ndarray() for k, v in data.items()}
    elif isinstance(data, (list, tuple)):
        data = [v.as_nd_ndarray() for v in data]
    else:
        data = [data.as_nd_ndarray()]
    serialization.save(fname, data)


__all__ = sorted(n for n in globals() if not n.startswith("_")
                 and n not in ("threading", "NDArray", "MXNetError",
                               "np_ndarray"))


def masked_softmax(data, mask=None, axis=-1, temperature=1.0, **kwargs):
    if mask is None:  # reference: mask=None means plain softmax
        return softmax(data, axis=axis, temperature=temperature)
    return _op_call("masked_softmax", [data, mask],
                    {"axis": axis, "temperature": temperature})


def masked_log_softmax(data, mask=None, axis=-1, temperature=1.0, **kwargs):
    if mask is None:
        return log_softmax(data, axis=axis)
    return _op_call("masked_log_softmax", [data, mask],
                    {"axis": axis, "temperature": temperature})


def deconvolution(data, weight, bias=None, *, kernel=(), stride=(),
                  dilate=(), pad=(), adj=(), num_filter=1, num_group=1,
                  no_bias=False, target_shape=(), layout=None, **kwargs):
    tensors = [data, weight] + ([bias] if bias is not None else [])
    return _op_call("Deconvolution", tensors,
                    {"kernel": kernel, "stride": stride, "dilate": dilate,
                     "pad": pad, "adj": adj, "num_filter": num_filter,
                     "num_group": num_group,
                     "no_bias": bias is None or no_bias,
                     "target_shape": target_shape, "layout": layout})


def group_norm(data, gamma, beta, num_groups=1, eps=1e-5, **kwargs):
    return _op_call("GroupNorm", [data, gamma, beta],
                    {"num_groups": num_groups, "eps": eps})


def instance_norm(data, gamma, beta, eps=1e-3, **kwargs):
    return _op_call("InstanceNorm", [data, gamma, beta], {"eps": eps})


def l2_normalization(data, eps=1e-10, mode="instance", **kwargs):
    return _op_call("L2Normalization", [data], {"eps": eps, "mode": mode})


def sequence_last(data, sequence_length=None, use_sequence_length=False,
                  axis=0, **kwargs):
    tensors = [data] + ([sequence_length]
                        if sequence_length is not None else [])
    return _op_call("SequenceLast", tensors,
                    {"use_sequence_length": use_sequence_length
                     or sequence_length is not None, "axis": axis})


def sequence_reverse(data, sequence_length=None, use_sequence_length=False,
                     axis=0, **kwargs):
    tensors = [data] + ([sequence_length]
                        if sequence_length is not None else [])
    return _op_call("SequenceReverse", tensors,
                    {"use_sequence_length": use_sequence_length
                     or sequence_length is not None, "axis": axis})


def ctc_loss(data, label, data_lengths=None, label_lengths=None, **kwargs):
    # the op binds (data, label, data_lengths, label_lengths) POSITIONALLY:
    # when only label_lengths is given, a full-length data_lengths tensor
    # must occupy the third slot
    tensors = [data, label]
    attrs = {}
    if label_lengths is not None and data_lengths is None:
        from ..numpy import full as _np_full

        data_lengths = _np_full((label.shape[0],), data.shape[0])
    if data_lengths is not None:
        tensors.append(data_lengths)
        attrs["use_data_lengths"] = True
    if label_lengths is not None:
        tensors.append(label_lengths)
        attrs["use_label_lengths"] = True
    return _op_call("CTCLoss", tensors, attrs)


def roi_pooling(data, rois, pooled_size=(1, 1), spatial_scale=1.0,
                **kwargs):
    return _op_call("ROIPooling", [data, rois],
                    {"pooled_size": pooled_size,
                     "spatial_scale": spatial_scale})


def scatter_nd(data, indices, shape, **kwargs):
    return _op_call("scatter_nd", [data, indices], {"shape": shape})


def slice(data, begin, end, step=None, **kwargs):
    return _op_call("slice", [data],
                    {"begin": begin, "end": end, "step": step})


def slice_axis(data, axis, begin, end, **kwargs):
    return _op_call("slice_axis", [data],
                    {"axis": axis, "begin": begin, "end": end})


__all__ = sorted(n for n in globals() if not n.startswith("_")
                 and n not in ("threading", "NDArray", "MXNetError",
                               "np_ndarray", "annotations"))
