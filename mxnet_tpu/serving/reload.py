"""Zero-downtime model hot-reload for the serving stack.

Protocol (the "old graph serves until the new one is warmed" contract):

1. the watcher thread ticks ``CheckpointManager.poll_newest(tag)`` — a
   one-``stat`` no-change fast path, full manifest re-validation only
   when a bundle's commit record actually moved;
2. on a new valid bundle it calls ``Server.reload``: the user's
   ``model_factory(bundle_path)`` builds a fresh block (load params,
   optionally ``quantize_net`` it, hybridize), the server AOT-warms it
   for every signature in live use, and only then swaps the model
   attribute — requests dispatched at any point during build/warmup keep
   hitting the OLD compiled graphs, so no request ever waits on a
   reload compile;
3. a failed reload (corrupt bundle, factory bug) is contained: the
   error is recorded (``mxnet_serving_reloads_total{outcome="error"}``),
   the old model keeps serving, and the watcher keeps polling —
   transient failures additionally retry inside ``fault.retry_call``
   at site ``serving.reload``.

``model_factory`` receives the BUNDLE DIRECTORY (not a file): load
whatever the deployment needs from it, typically::

    def factory(path):
        net = build_net()
        net.load_parameters(os.path.join(path, "params.params"))
        net.hybridize()
        return net
"""
from __future__ import annotations

import logging
import threading
from typing import Optional

from ..base import MXNetError

__all__ = ["ReloadWatcher"]

_log = logging.getLogger(__name__)


class ReloadWatcher:
    """Poll a CheckpointManager; hot-reload the server on new bundles.

    The first poll is PRIMED away at :meth:`start`: the bundle the
    server was launched from must not trigger an immediate no-op
    reload — only bundles committed after the watcher starts do.
    """

    def __init__(self, server, manager, model_factory,
                 interval_s: float = 0.5, tag: str = "serve"):
        if interval_s <= 0:
            raise MXNetError(
                f"reload poll interval must be > 0, got {interval_s}")
        self.server = server
        self.manager = manager
        self.model_factory = model_factory
        self.interval_s = float(interval_s)
        self.tag = tag
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ReloadWatcher":
        if self._thread is not None:
            return self
        # prime: the currently-newest bundle is the one already serving
        self.manager.poll_newest(self.tag)
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name=f"{self.server.name}-reload",
            daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: Optional[float] = None) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            if self._thread.is_alive():
                raise MXNetError(
                    f"{self.server.name}: reload watcher did not exit "
                    f"within {timeout}s (model build/warmup in flight?)")
            self._thread = None

    @property
    def is_running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                step = self.manager.poll_newest(self.tag)
            except Exception:  # noqa: BLE001 - keep serving, keep polling
                _log.exception("%s: checkpoint poll failed", self.server.name)
                continue
            if step is None:
                continue
            try:
                self.server.reload(self.manager, self.model_factory,
                                   step=step)
                _log.info("%s: hot-reloaded model from step %d",
                          self.server.name, step)
            except Exception:  # noqa: BLE001 - old model keeps serving
                _log.exception("%s: hot reload of step %d failed; "
                               "previous model keeps serving",
                               self.server.name, step)
                # the poll already consumed this bundle's change event —
                # forget it so the next tick retries instead of serving
                # stale weights until a NEWER bundle happens to land
                self.manager.poll_reset(self.tag)
