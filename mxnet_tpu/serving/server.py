"""``mx.serving.Server`` — continuous-batching model server.

The repo trains fast; this is the piece that *serves* (ROADMAP item 1).
One server wraps one hybridized (optionally int8-quantized) Gluon block
and turns concurrent single-sample requests into bucket-padded batches:

* :meth:`Server.submit` is the thread-safe ingress — any thread hands in
  one sample and gets a ``concurrent.futures.Future`` back;
* a scheduler thread drains the queue into dynamic batches under a
  per-request latency SLO: it keeps filling while the oldest queued
  request is comfortably inside its deadline and dispatches early the
  moment it is not (deadline-aware batch close);
* each batch is padded up to the nearest :class:`~.buckets.BucketGrid`
  entry, so every dispatch lands on one warm ``_CachedGraph`` executable
  (``HybridBlock.warmup`` pre-compiles the whole grid at load time);
* per-request outputs are sliced from the real rows and resolved into
  the futures; padded rows never reach a caller.

Resilience reuses the PR-3 runtime: every dispatch runs under
``fault.retry_call`` at site ``serving.dispatch`` (transient failures
retry with backoff; deterministic ones fail the batch's futures, not the
server), and hot reload (``serving.reload``) swaps a freshly-built,
freshly-WARMED model in behind a lock — the old graph serves every
request that arrives while the new one compiles (see
:mod:`mxnet_tpu.serving.reload`).

Telemetry (``MXNET_TELEMETRY=1`` / ``telemetry.enable()``):
``mxnet_serving_queue_depth``, ``mxnet_serving_batch_occupancy``,
``mxnet_serving_time_in_queue_seconds``, ``mxnet_serving_request_seconds``
(p50/p99 from the fine ``SERVING_BUCKETS``), ``mxnet_serving_requests_total``,
``mxnet_serving_batches_total{reason}``, ``mxnet_serving_reloads_total`` —
all exported via ``telemetry.prom_text()``.
"""
from __future__ import annotations

import contextlib
import threading
import time
import weakref
from concurrent.futures import Future
from typing import Optional, Sequence, Tuple

import numpy as np

from .. import autograd, fault, telemetry, tracing
from ..base import MXNetError
from ..fault import _state as _fault_state
from ..telemetry import _state as _telemetry_state
from ..tracing import _state as _tracing_state
from .buckets import BucketGrid
from .health import Heartbeat

__all__ = ["Server", "live_servers"]

# every running server, for the test-suite leak guard: a test that leaves
# a scheduler (or watcher) thread running would tax every later test
_live_servers = weakref.WeakSet()


def live_servers():
    """Servers whose scheduler thread is currently running."""
    return [s for s in list(_live_servers) if s.is_running]


class _Request:
    __slots__ = ("sample", "shape_key", "future", "t_enqueue", "deadline",
                 "trace", "span", "own_trace")

    def __init__(self, sample, shape_key, deadline_s):
        self.sample = sample
        self.shape_key = shape_key
        self.future = Future()
        self.t_enqueue = time.perf_counter()
        self.deadline = self.t_enqueue + deadline_s
        # tracing (MXNET_TRACING=1): the request's Trace, its live
        # batch.wait span, and whether THIS server minted the trace
        # (a router/worker that handed it in finishes it instead)
        self.trace = None
        self.span = None
        self.own_trace = False


class Server:
    """Serve a Gluon block under a latency SLO with bucketed batching.

    ::

        net.hybridize()
        srv = mx.serving.Server(net, batch_buckets=(1, 4, 16, 32),
                                shape_buckets=[(3, 224, 224)], slo_ms=50)
        srv.start()                       # warms every grid bucket
        fut = srv.submit(image)           # any thread; one sample, no
        probs = fut.result()              # batch dim; numpy out
        srv.stop()                        # drains in-flight requests

    ``block``: the model. A ``HybridBlock`` is hybridized (if it is not
    already) and every grid bucket is AOT-warmed at :meth:`start`; a
    plain ``Block`` serves eagerly (no warmup — useful for tests).

    ``slo_ms`` is the per-request latency objective: a request's batch
    closes no later than ``slo_ms - close_margin_ms`` after its submit,
    however empty the batch is; under load batches close early on
    ``full``. ``deadline_ms=`` at submit overrides per request.

    ``batch_timeout_ms`` caps how long the OLDEST queued request waits
    for co-batching before its batch closes anyway (the TF-Serving
    ``batch_timeout`` knob). ``None`` (default) keeps the legacy
    deadline-keyed patience: the scheduler fills toward the biggest
    bucket until ``deadline - close_margin``. That patience is optimal
    when arrivals come in tight waves (an in-process closed loop
    refills atomically), but an arrival stream SPREAD by a pipeline —
    results trickling back over a socket, clients refilling one by one
    — never quite fills the bucket, so every batch closes at the SLO
    edge and p50 ~= SLO however light the load (measured: 100% of
    worker batches ``deadline``-closed through the ingress). A few ms
    here trades a few points of occupancy for an SLO-independent
    latency floor; out-of-process workers default it on
    (``serving.RemoteReplica(batch_timeout_ms=5)``).

    ``dtype``: samples are cast to it on submit. Futures resolve with
    numpy arrays (or the model's output structure with numpy leaves).
    """

    def __init__(self, block, batch_buckets=(1, 2, 4, 8, 16, 32),
                 shape_buckets=None, slo_ms: float = 100.0,
                 close_margin_ms: float = 5.0, max_queue: int = 4096,
                 dtype: str = "float32", ctx=None, warmup: bool = True,
                 name: Optional[str] = None,
                 batch_timeout_ms: Optional[float] = None):
        if slo_ms <= 0:
            raise MXNetError(f"slo_ms must be > 0, got {slo_ms}")
        if close_margin_ms < 0 or close_margin_ms >= slo_ms:
            raise MXNetError(
                f"close_margin_ms must be in [0, slo_ms), got "
                f"{close_margin_ms} (slo_ms={slo_ms})")
        if batch_timeout_ms is not None and batch_timeout_ms <= 0:
            raise MXNetError(
                f"batch_timeout_ms must be > 0 (or None for the "
                f"deadline-keyed close), got {batch_timeout_ms}")
        if max_queue < 1:
            raise MXNetError(f"max_queue must be >= 1, got {max_queue}")
        self.grid = BucketGrid(batch_buckets, shape_buckets)
        self.slo_s = slo_ms / 1e3
        self.margin_s = close_margin_ms / 1e3
        self.batch_timeout_s = (batch_timeout_ms / 1e3
                                if batch_timeout_ms is not None else None)
        self.max_queue = int(max_queue)
        self.dtype = dtype
        self.ctx = ctx
        self.name = name or f"server_{id(self):x}"
        self._warmup = bool(warmup)
        self._model = block
        self._model_lock = threading.Lock()
        self._cond = threading.Condition()
        self._queue: list = []
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self._watcher = None        # reload.ReloadWatcher, when enabled
        # pre-dispatch hook, set by serving.Router on managed replicas:
        # runs INSIDE run() (the retried dispatch body) so an injected
        # replica fault / latency lands exactly where a real replica
        # failure would — in this scheduler thread, per batch
        self._pre_dispatch = None
        # scheduler-loop liveness beacon: touched once per loop
        # iteration (so between two touches at most ONE dispatch runs).
        # A Router reads it to tell a *hung* dispatch from a scheduler
        # patiently filling a batch toward its deadline close.
        self.hb = Heartbeat()
        self.loaded_step: Optional[int] = None
        # monotonic model-version counter: bumps on every swap_model /
        # reload; a rolling-upgrade rollback restores the OLD number so
        # fleet version agreement is observable (Router/controller read
        # it, never write it)
        self.model_version = 0
        # signatures actually compiled/used — the reload warmup manifest
        self._warm_sigs = set()
        # always-on light counters (telemetry covers the full story)
        self.n_requests = 0
        self.n_batches = 0
        self.n_errors = 0
        self.n_reloads = 0

    # -- lifecycle -----------------------------------------------------
    @property
    def is_running(self) -> bool:
        return self._running or (self._thread is not None
                                 and self._thread.is_alive())

    def start(self) -> "Server":
        """Warm the bucket grid and start the scheduler thread."""
        if self.is_running:
            raise MXNetError(f"{self.name}: already running")
        self._warm_block(self._model, prime=True)
        self._running = True
        self._thread = threading.Thread(
            target=self._scheduler_loop, name=self.name, daemon=True)
        self._thread.start()
        _live_servers.add(self)
        return self

    def stop(self, drain: bool = True, timeout: Optional[float] = None
             ) -> None:
        """Stop the server. ``drain=True`` (default) serves every queued
        request first (dispatching immediately, SLO waits skipped);
        ``drain=False`` fails pending futures with :class:`MXNetError`."""
        with self._cond:
            self._running = False
            if not drain:
                pending, self._queue = self._queue, []
                for r in pending:
                    if not r.future.set_running_or_notify_cancel():
                        continue        # caller already cancelled it
                    r.future.set_exception(
                        MXNetError(f"{self.name}: server stopped before "
                                   "this request was dispatched"))
                    self._count_request(outcome="rejected")
                    self._end_trace_rejected(r)
            self._cond.notify_all()
        if self._watcher is not None:
            self._watcher.stop(timeout)
            self._watcher = None
        if self._thread is not None:
            self._thread.join(timeout)
            if self._thread.is_alive():
                raise MXNetError(
                    f"{self.name}: scheduler thread did not exit within "
                    f"{timeout}s")
            self._thread = None
        _live_servers.discard(self)

    def __enter__(self) -> "Server":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop(drain=not any(exc))

    # -- ingress -------------------------------------------------------
    def submit(self, sample, deadline_ms: Optional[float] = None) -> Future:
        """Enqueue one sample (NO batch dimension); returns a Future that
        resolves to the model output for that sample (numpy leaves).
        Thread-safe. Raises :class:`MXNetError` immediately when the
        server is not running, the queue is full, or no shape bucket
        fits the sample — rejection is synchronous, never a hung future.
        """
        arr = sample.asnumpy() if hasattr(sample, "asnumpy") \
            else np.asarray(sample)
        arr = np.ascontiguousarray(arr, dtype=self.dtype)
        bucket = self.grid.bucket_shape(arr.shape)   # raises if none fits
        arr = self.grid.pad_sample(arr, bucket)
        deadline_s = (deadline_ms / 1e3 if deadline_ms is not None
                      else self.slo_s)
        req = _Request(arr, bucket, deadline_s)
        if _tracing_state.enabled:
            # the span must exist BEFORE the queue append: the scheduler
            # may batch-close this request before submit returns
            amb = tracing.ambient()
            if amb is not None:
                req.trace = amb[0]
                req.span = req.trace.begin(
                    "batch.wait", parent=amb[1], replica=self.name)
            else:
                req.trace = tracing.new_trace("request", replica=self.name)
                req.own_trace = True
                req.span = req.trace.begin("batch.wait", replica=self.name)
        with self._cond:
            if not self._running:
                self._count_request(outcome="rejected")
                self._end_trace_rejected(req)
                raise MXNetError(f"{self.name}: server is not running")
            if len(self._queue) >= self.max_queue:
                self._count_request(outcome="rejected")
                self._end_trace_rejected(req)
                raise MXNetError(
                    f"{self.name}: submission queue full "
                    f"({self.max_queue} requests)")
            self._queue.append(req)
            depth = len(self._queue)
            self._cond.notify_all()
        if _telemetry_state.enabled:
            telemetry.set_serving_queue_depth(depth)
        return req.future

    # -- scheduler -----------------------------------------------------
    def _scheduler_loop(self) -> None:
        try:
            while True:
                self.hb.touch()
                batch, reason = self._next_batch()
                if batch is None:
                    return
                self._dispatch(batch, reason)
        except BaseException:
            # a scheduler death must be LOUD, not a server that accepts
            # requests into a queue nobody drains: stop accepting and
            # fail everything queued
            with self._cond:
                self._running = False
                pending, self._queue = self._queue, []
            for r in pending:
                if r.future.set_running_or_notify_cancel():
                    r.future.set_exception(MXNetError(
                        f"{self.name}: scheduler thread crashed"))
                    self._end_trace_rejected(r, "error")
            raise

    def _next_batch(self):
        """Block until a batch should close; returns (requests, reason)
        or (None, None) on shutdown with an empty queue."""
        with self._cond:
            while True:
                self.hb.touch()
                if not self._queue:
                    if not self._running:
                        return None, None
                    self._cond.wait(0.1)
                    continue
                head = self._queue[0]
                key = head.shape_key
                cap = self.grid.max_batch
                matching = sum(1 for r in self._queue
                               if r.shape_key == key)
                now = time.perf_counter()
                # close on the TIGHTEST deadline in the queue, not just
                # the head's: a short-deadline request behind a lazy head
                # (same key: it rides this batch; different key: it is
                # served right after) must not wait out the head's SLO
                deadline_at = min(r.deadline for r in self._queue) \
                    - self.margin_s
                # batch timeout: the head is the oldest enqueue (submit
                # order is FIFO even when deadline_ms overrides are not)
                # — cap its co-batching wait independently of the SLO
                timeout_at = (head.t_enqueue + self.batch_timeout_s
                              if self.batch_timeout_s is not None
                              else None)
                close_at = deadline_at if timeout_at is None \
                    else min(deadline_at, timeout_at)
                if matching >= cap:
                    reason = "full"
                elif not self._running:
                    reason = "drain"
                elif now >= close_at:
                    reason = ("timeout" if timeout_at is not None
                              and timeout_at <= close_at + 1e-9
                              and now < deadline_at else "deadline")
                else:
                    # fill otherwise: sleep until the head's close time
                    # or the next submit, whichever is first
                    self._cond.wait(min(close_at - now, 0.1))
                    continue
                taken, rest = [], []
                for r in self._queue:
                    if len(taken) < cap and r.shape_key == key:
                        taken.append(r)
                    else:
                        rest.append(r)
                self._queue = rest
                if _telemetry_state.enabled:
                    telemetry.set_serving_queue_depth(len(rest))
                return taken, reason

    def _dispatch(self, batch, reason: str) -> None:
        """Pad, run, slice, resolve — one bucketed inference dispatch."""
        from ..ndarray import array as nd_array

        t_start = time.perf_counter()
        # a caller may have cancelled a still-queued future; drop those
        # rows now — set_result on a cancelled future would raise and
        # kill the scheduler thread
        batch = [r for r in batch
                 if r.future.set_running_or_notify_cancel()]
        if not batch:
            return
        n = len(batch)
        key = batch[0].shape_key
        cap = self.grid.batch_bucket(n)
        payload = np.zeros((cap,) + key, dtype=self.dtype)
        for i, r in enumerate(batch):
            payload[i] = r.sample
        model = self._model          # reload swaps the attribute, not us
        sig = (cap,) + key

        bsp = None
        if _tracing_state.enabled:
            traced = [(r.trace, r.span) for r in batch
                      if r.trace is not None]
            if traced:
                # the N co-batched wait spans end here (flow-linked to
                # the ONE dispatch span that serves them all)
                bsp = tracing.begin_batch(
                    traced, wait_tags={"close_reason": reason},
                    replica=self.name, sig=str(sig), reason=reason)

        def run():
            hook = self._pre_dispatch
            if hook is not None:
                hook(sig)
            if _fault_state.enabled:
                fault.check("serving.dispatch", f"{self.name} batch={sig}")
            x = nd_array(payload, ctx=self.ctx)
            with autograd.pause():
                out = model(x)
            return self._materialize(out)

        # injected faults / retries inside the dispatch annotate the
        # batch span (fault.py calls tracing.note against the ambient)
        amb = (tracing.active(batch[0].trace, bsp) if bsp is not None
               else contextlib.nullcontext())
        try:
            with amb:
                leaves, tree = fault.retry_call(
                    "serving.dispatch", run, detail=self.name)
        except Exception as e:  # noqa: BLE001 - forwarded to the futures
            self.n_errors += 1
            tracing.end_batch(bsp, outcome="error",
                              error=type(e).__name__)
            for r in batch:
                r.future.set_exception(e)
                self._count_request(
                    outcome="error", t_enqueue=r.t_enqueue,
                    trace_id=r.trace.trace_id if r.trace is not None
                    else None)
                if r.own_trace:
                    r.trace.finish(type(e).__name__)
            return
        tracing.end_batch(bsp, outcome="ok")
        self.n_batches += 1
        if self.n_batches == 1:
            from .. import compiler

            # replica cold-start milestone: start() -> first served batch
            compiler.mark_event("first_response")
        if _telemetry_state.enabled:
            telemetry.record_serving_batch(n, cap, reason)
            for r in batch:
                telemetry.record_serving_queue_time(t_start - r.t_enqueue)
        with self._model_lock:      # the reload warmup copies this set
            self._warm_sigs.add(sig)
        from ..gluon.block import nested_unflatten_nd

        try:
            for i, r in enumerate(batch):
                # copy: a row VIEW would pin the whole padded batch
                # array for as long as the caller holds the result
                r.future.set_result(nested_unflatten_nd(
                    tree, [leaf[i].copy() for leaf in leaves]))
                self._count_request(
                    outcome="ok", t_enqueue=r.t_enqueue,
                    trace_id=r.trace.trace_id if r.trace is not None
                    else None)
                if r.own_trace:
                    r.trace.finish("ok")
        except Exception as e:  # noqa: BLE001 - e.g. non-batch-major leaf
            self.n_errors += 1
            for r in batch:
                if not r.future.done():
                    r.future.set_exception(e)
                    self._count_request(outcome="error",
                                        t_enqueue=r.t_enqueue)
                if r.own_trace:
                    r.trace.finish(type(e).__name__)

    @staticmethod
    def _materialize(out):
        """Flatten the model output and pull each leaf to host numpy once
        per batch (futures hand out row slices of these)."""
        from ..gluon.block import nested_flatten_nd

        flat, tree = nested_flatten_nd(out)
        return [leaf.asnumpy() for leaf in flat], tree

    def _count_request(self, outcome: str, t_enqueue: Optional[float] = None,
                       trace_id: Optional[str] = None) -> None:
        self.n_requests += 1
        if _telemetry_state.enabled:
            lat = (time.perf_counter() - t_enqueue
                   if t_enqueue is not None else 0.0)
            telemetry.record_serving_request(lat, outcome,
                                             trace_id=trace_id)

    @staticmethod
    def _end_trace_rejected(req: _Request, status: str = "rejected") -> None:
        """Seal a traced request that never reached a batch."""
        if req.trace is None:
            return
        if req.span is not None:
            req.span.end(outcome=status)
        if req.own_trace:
            req.trace.finish(status)

    # -- model management ----------------------------------------------
    def _warm_block(self, block, prime: bool = False) -> int:
        """AOT-compile ``block`` for every known signature: the full
        grid when it is enumerable (``prime=True`` + shape buckets), and
        always every signature this server has actually served — so a
        hot-reloaded model is warm for live traffic before the swap.

        Warm compiles route through the compilation service: a replica
        (or a reloaded model) whose program another in-process replica
        already compiled is an executable-table hit, not a second XLA
        compile — N replicas of one architecture warm for the price of
        one. When a signature manifest is being recorded, its journal is
        replayed against the block first, so signatures served by a
        PREVIOUS process warm too (the manifest may know more than the
        enumerable grid)."""
        if not self._warmup or not hasattr(block, "warmup"):
            return 0
        from .. import compiler

        man = compiler.recorder()
        if man is not None:
            try:
                compiler.warm_start(man, blocks=[block])
            except Exception:   # noqa: BLE001 - warm is best-effort
                pass
        with self._model_lock:      # the scheduler adds sigs concurrently
            sigs = set(self._warm_sigs)
        if prime and self.grid.shape_buckets is not None:
            sigs.update(self.grid.input_signatures())
        if not sigs:
            return 0
        if getattr(block, "_active", None) is False:
            block.hybridize()
        return block.warmup(sorted(sigs), dtype=self.dtype, ctx=self.ctx)

    def current_model(self):
        """The block currently being served (the rolling-upgrade
        machinery keeps it for rollback)."""
        return self._model

    def swap_model(self, block, version: Optional[int] = None) -> None:
        """Atomically replace the served model with ``block``, warming it
        for every signature in live use first — requests dispatched
        during the warmup keep hitting the old graph. ``version``
        overrides the monotonic bump (a rollback restores the old
        number)."""
        self._warm_block(block, prime=True)
        with self._model_lock:
            self._model = block
            self.model_version = (self.model_version + 1
                                  if version is None else int(version))
        self.n_reloads += 1

    def reload(self, manager, model_factory, step: Optional[int] = None
               ) -> int:
        """Zero-downtime reload from a :class:`CheckpointManager` bundle:
        build a fresh block via ``model_factory(bundle_path)``, warm it,
        swap it in. The old graph serves until the swap. Fault site
        ``serving.reload``; transient failures retry, persistent ones
        raise (the old model keeps serving). Returns the loaded step."""
        t0 = time.perf_counter()
        if step is None:
            step = manager.latest_step()
            if step is None:
                raise MXNetError(
                    f"{self.name}: no checksum-valid checkpoint under "
                    f"{manager.directory!r} to reload from")
        path = manager.path(step)

        def build():
            if _fault_state.enabled:
                fault.check("serving.reload", path)
            return model_factory(path)

        try:
            block = fault.retry_call("serving.reload", build, detail=path)
            self.swap_model(block)
        except Exception:
            if _telemetry_state.enabled:
                telemetry.record_serving_reload(0.0, outcome="error")
            raise
        self.loaded_step = step
        if _telemetry_state.enabled:
            telemetry.record_serving_reload(time.perf_counter() - t0)
        return step

    def enable_hot_reload(self, manager, model_factory,
                          interval_s: float = 0.5,
                          tag: Optional[str] = None):
        """Start a watcher thread that polls ``manager`` (via
        :meth:`CheckpointManager.poll_newest`) and hot-reloads on every
        new valid bundle. See :class:`~.reload.ReloadWatcher`."""
        from .reload import ReloadWatcher

        if self._watcher is not None:
            raise MXNetError(f"{self.name}: hot reload already enabled")
        self._watcher = ReloadWatcher(
            self, manager, model_factory, interval_s=interval_s,
            tag=tag or self.name)
        self._watcher.start()
        return self._watcher

    def stats(self) -> dict:
        """Light always-on counters (telemetry has the full story)."""
        with self._cond:
            depth = len(self._queue)
        return {"requests": self.n_requests, "batches": self.n_batches,
                "errors": self.n_errors, "reloads": self.n_reloads,
                "queue_depth": depth, "loaded_step": self.loaded_step,
                "model_version": self.model_version,
                "running": self.is_running}
