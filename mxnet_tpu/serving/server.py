"""``mx.serving.Server`` — continuous-batching model server.

The repo trains fast; this is the piece that *serves* (ROADMAP item 1).
One server wraps one hybridized (optionally int8-quantized) Gluon block
and turns concurrent single-sample requests into bucket-padded batches:

* :meth:`Server.submit` is the thread-safe ingress — any thread hands in
  one sample and gets a ``concurrent.futures.Future`` back;
* a scheduler thread drains the queue into dynamic batches under a
  per-request latency SLO: it keeps filling while the oldest queued
  request is comfortably inside its deadline and dispatches early the
  moment it is not (deadline-aware batch close);
* each batch is padded up to the nearest :class:`~.buckets.BucketGrid`
  entry, so every dispatch lands on one warm ``_CachedGraph`` executable
  (``HybridBlock.warmup`` pre-compiles the whole grid at load time);
* per-request outputs are sliced from the real rows and resolved into
  the futures; padded rows never reach a caller.

Resilience reuses the PR-3 runtime: every dispatch runs under
``fault.retry_call`` at site ``serving.dispatch`` (transient failures
retry with backoff; deterministic ones fail the batch's futures, not the
server), and hot reload (``serving.reload``) swaps a freshly-built,
freshly-WARMED model in behind a lock — the old graph serves every
request that arrives while the new one compiles (see
:mod:`mxnet_tpu.serving.reload`).

Telemetry (``MXNET_TELEMETRY=1`` / ``telemetry.enable()``):
``mxnet_serving_queue_depth``, ``mxnet_serving_batch_occupancy``,
``mxnet_serving_time_in_queue_seconds``, ``mxnet_serving_request_seconds``
(p50/p99 from the fine ``SERVING_BUCKETS``), ``mxnet_serving_requests_total``,
``mxnet_serving_batches_total{reason}``, ``mxnet_serving_reloads_total`` —
all exported via ``telemetry.prom_text()``.
"""
from __future__ import annotations

import contextlib
import itertools
import threading
import time
import weakref
from concurrent.futures import Future
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from .. import autograd, fault, telemetry, tracing
from ..base import MXNetError
from ..fault import _state as _fault_state
from ..telemetry import _state as _telemetry_state
from ..tracing import _state as _tracing_state
from .buckets import DEFAULT_LEN_BUCKETS, BucketGrid, TokenBucket
from .health import Heartbeat
from .kvcache import CacheFull, PagePool, Preempted

__all__ = ["Server", "GenerateHandle", "TenantThrottled", "live_servers"]

DEFAULT_MODEL = "default"


class TenantThrottled(MXNetError):
    """Typed per-tenant admission shed: this tenant's token bucket is
    empty. Synchronous at submit (never a queued request burning another
    tenant's deadline budget) and scoped to ONE tenant — the fleet is
    not overloaded, this tenant's configured rate is. Crosses
    :mod:`.wire` under the stable name ``throttled``."""


class _Tenant:
    """One registered model sharing this server's replica.

    Tenants share the bucket grid, the scheduler thread, and (when
    decode is on) the ONE :class:`PagePool` — page accounting is the
    multi-tenant contention point priority preemption arbitrates. Each
    tenant owns its block, its decode engine (its own K/V arenas over
    the shared page numbering), its model version, its admission
    token-bucket, and its weighted-fair credit state (credits are only
    ever touched by the scheduler thread)."""

    __slots__ = ("name", "block", "slo_class", "priority", "weight",
                 "slo_s", "bucket", "engine", "engine_version",
                 "model_version", "credit", "dcredit", "warm_sigs",
                 "n_requests", "n_shed", "n_preempted", "n_tokens")

    def __init__(self, name, block, slo_class, priority, weight, slo_s,
                 bucket):
        self.name = name
        self.block = block
        self.slo_class = slo_class
        self.priority = int(priority)
        self.weight = float(weight)
        self.slo_s = float(slo_s)
        self.bucket = bucket            # TokenBucket or None
        self.engine = None
        self.engine_version = -1
        self.model_version = 0
        self.credit = 0.0               # weighted-fair classify pick
        self.dcredit = 0.0              # weighted-fair decode slots
        self.warm_sigs = set()          # sigs THIS tenant has served
        self.n_requests = 0
        self.n_shed = 0
        self.n_preempted = 0            # streams evicted FROM this tenant
        self.n_tokens = 0

# every running server, for the test-suite leak guard: a test that leaves
# a scheduler (or watcher) thread running would tax every later test
_live_servers = weakref.WeakSet()


def live_servers():
    """Servers whose scheduler thread is currently running."""
    return [s for s in list(_live_servers) if s.is_running]


class _Request:
    __slots__ = ("sample", "shape_key", "future", "t_enqueue", "deadline",
                 "trace", "span", "own_trace", "tenant")

    def __init__(self, sample, shape_key, deadline_s, tenant=None):
        self.sample = sample
        self.shape_key = shape_key
        self.tenant = tenant
        self.future = Future()
        self.t_enqueue = time.perf_counter()
        self.deadline = self.t_enqueue + deadline_s
        # tracing (MXNET_TRACING=1): the request's Trace, its live
        # batch.wait span, and whether THIS server minted the trace
        # (a router/worker that handed it in finishes it instead)
        self.trace = None
        self.span = None
        self.own_trace = False


class GenerateHandle:
    """Streaming handle for one autoregressive generate request.

    ``future`` resolves to the full int32 token array when the
    completion finishes (or raises the typed failure — ``CacheFull``,
    ``WorkerCrashed``, ``MXNetError`` — exactly like ``submit``'s
    future: a generate NEVER wedges). Tokens stream as they are
    decoded: ``on_token(index, token)`` fires per token (from the
    scheduler/reader thread — keep it cheap), ``tokens()`` snapshots
    what has arrived, and ``next_token(i)`` blocks until token ``i``
    exists or the stream ends (returns None when it ended first).
    """

    def __init__(self, on_token=None):
        self.future = Future()
        self._on_token = on_token
        self._cond = threading.Condition()
        self._tokens: list = []

    def _push(self, token: int) -> None:
        with self._cond:
            self._tokens.append(int(token))
            i = len(self._tokens) - 1
            self._cond.notify_all()
        cb = self._on_token
        if cb is not None:
            try:
                cb(i, int(token))
            except Exception:   # noqa: BLE001 - user callback stays user's
                pass

    def _seal(self) -> None:
        """Wake every next_token() waiter once the future resolved."""
        with self._cond:
            self._cond.notify_all()

    def tokens(self) -> list:
        with self._cond:
            return list(self._tokens)

    def next_token(self, i: int, timeout: Optional[float] = None):
        """Block until token ``i`` streams in; None when the request
        finished (or failed — check ``future``) before producing it."""
        deadline = (time.perf_counter() + timeout
                    if timeout is not None else None)
        with self._cond:
            while len(self._tokens) <= i:
                if self.future.done():
                    return None
                wait = 0.05 if deadline is None \
                    else min(0.05, deadline - time.perf_counter())
                if wait <= 0:
                    return None
                self._cond.wait(wait)
            return self._tokens[i]

    def result(self, timeout: Optional[float] = None):
        return self.future.result(timeout)


class _GenRequest:
    __slots__ = ("prompt", "max_new", "handle", "pages", "length",
                 "generated", "t_submit", "t_last", "deadline", "trace",
                 "span", "own_trace", "len_bucket", "model_version",
                 "tenant", "priority", "seq")

    def __init__(self, prompt, max_new, handle, deadline_s, tenant=None,
                 priority=0, seq=0):
        self.prompt = prompt                 # 1-D int32 token array
        self.max_new = int(max_new)
        self.handle = handle
        self.tenant = tenant
        self.priority = int(priority)        # preemption rank
        self.seq = int(seq)                  # stream id (preempt events)
        self.pages = None                    # page list once admitted
        self.length = len(prompt)            # tokens written OR known
        self.generated: list = []
        self.t_submit = time.perf_counter()
        self.t_last = self.t_submit          # last token emit (per-token lat)
        self.deadline = (self.t_submit + deadline_s
                         if deadline_s is not None else None)
        self.trace = None
        self.span = None                     # live gen.queue / phase span
        self.own_trace = False
        self.len_bucket = 0
        self.model_version = -1


class Server:
    """Serve a Gluon block under a latency SLO with bucketed batching.

    ::

        net.hybridize()
        srv = mx.serving.Server(net, batch_buckets=(1, 4, 16, 32),
                                shape_buckets=[(3, 224, 224)], slo_ms=50)
        srv.start()                       # warms every grid bucket
        fut = srv.submit(image)           # any thread; one sample, no
        probs = fut.result()              # batch dim; numpy out
        srv.stop()                        # drains in-flight requests

    ``block``: the model. A ``HybridBlock`` is hybridized (if it is not
    already) and every grid bucket is AOT-warmed at :meth:`start`; a
    plain ``Block`` serves eagerly (no warmup — useful for tests).

    ``slo_ms`` is the per-request latency objective: a request's batch
    closes no later than ``slo_ms - close_margin_ms`` after its submit,
    however empty the batch is; under load batches close early on
    ``full``. ``deadline_ms=`` at submit overrides per request.

    ``batch_timeout_ms`` caps how long the OLDEST queued request waits
    for co-batching before its batch closes anyway (the TF-Serving
    ``batch_timeout`` knob). ``None`` (default) keeps the legacy
    deadline-keyed patience: the scheduler fills toward the biggest
    bucket until ``deadline - close_margin``. That patience is optimal
    when arrivals come in tight waves (an in-process closed loop
    refills atomically), but an arrival stream SPREAD by a pipeline —
    results trickling back over a socket, clients refilling one by one
    — never quite fills the bucket, so every batch closes at the SLO
    edge and p50 ~= SLO however light the load (measured: 100% of
    worker batches ``deadline``-closed through the ingress). A few ms
    here trades a few points of occupancy for an SLO-independent
    latency floor; out-of-process workers default it on
    (``serving.RemoteReplica(batch_timeout_ms=5)``).

    ``dtype``: samples are cast to it on submit. Futures resolve with
    numpy arrays (or the model's output structure with numpy leaves).
    """

    def __init__(self, block, batch_buckets=(1, 2, 4, 8, 16, 32),
                 shape_buckets=None, slo_ms: float = 100.0,
                 close_margin_ms: float = 5.0, max_queue: int = 4096,
                 dtype: str = "float32", ctx=None, warmup: bool = True,
                 name: Optional[str] = None,
                 batch_timeout_ms: Optional[float] = None,
                 decode_pages: Optional[int] = None, page_size: int = 16,
                 len_buckets=None,
                 max_generate_tokens: Optional[int] = None,
                 slo_class: str = "standard", priority: int = 0,
                 weight: float = 1.0, rate_limit: Optional[float] = None,
                 burst: Optional[float] = None,
                 defrag_threshold: Optional[float] = 0.25):
        if slo_ms <= 0:
            raise MXNetError(f"slo_ms must be > 0, got {slo_ms}")
        if close_margin_ms < 0 or close_margin_ms >= slo_ms:
            raise MXNetError(
                f"close_margin_ms must be in [0, slo_ms), got "
                f"{close_margin_ms} (slo_ms={slo_ms})")
        if batch_timeout_ms is not None and batch_timeout_ms <= 0:
            raise MXNetError(
                f"batch_timeout_ms must be > 0 (or None for the "
                f"deadline-keyed close), got {batch_timeout_ms}")
        if max_queue < 1:
            raise MXNetError(f"max_queue must be >= 1, got {max_queue}")
        # autoregressive decode: a page pool + a model-provided decode
        # engine turn on submit_generate (see _decode_tick)
        self._decode_pages = decode_pages
        if decode_pages is not None and len_buckets is None:
            len_buckets = DEFAULT_LEN_BUCKETS
        self.grid = BucketGrid(batch_buckets, shape_buckets,
                               len_buckets=len_buckets)
        self._page_size = int(page_size)
        if decode_pages is not None:
            cap = (int(decode_pages) - 1) * self._page_size
            self._max_gen_tokens = int(
                max_generate_tokens if max_generate_tokens is not None
                else min(cap, self.grid.len_buckets[-1] + 256))
            if self._max_gen_tokens > cap:
                raise MXNetError(
                    f"max_generate_tokens={self._max_gen_tokens} exceeds "
                    f"the pool's {cap}-token capacity "
                    f"({decode_pages} pages x {page_size}, scratch "
                    "page excluded)")
        self._pool: Optional[PagePool] = None
        self._gen_table_w = 0
        self._gen_active: list = []
        self.n_tokens = 0
        self.slo_s = slo_ms / 1e3
        self.margin_s = close_margin_ms / 1e3
        self.batch_timeout_s = (batch_timeout_ms / 1e3
                                if batch_timeout_ms is not None else None)
        self.max_queue = int(max_queue)
        self.dtype = dtype
        self.ctx = ctx
        self.name = name or f"server_{id(self):x}"
        self._warmup = bool(warmup)
        self._model_lock = threading.Lock()
        self._cond = threading.Condition()
        # multi-tenant registry: the constructor block IS tenant
        # "default" (single-tenant callers never see the registry);
        # register_model() adds tenants sharing this replica. Per-tenant
        # queues so one tenant's burst cannot push another's requests
        # back in a shared FIFO.
        self._tenants: Dict[str, _Tenant] = {}
        self._queues: Dict[str, list] = {}
        self._gen_pending: Dict[str, list] = {}
        self._seq = itertools.count()       # stream ids (preempt events)
        if weight <= 0:
            raise MXNetError(f"weight must be > 0, got {weight}")
        bucket = (TokenBucket(rate_limit, burst)
                  if rate_limit is not None else None)
        t0 = _Tenant(DEFAULT_MODEL, block, str(slo_class), priority,
                     weight, self.slo_s, bucket)
        self._tenants[DEFAULT_MODEL] = t0
        self._queues[DEFAULT_MODEL] = []
        self._gen_pending[DEFAULT_MODEL] = []
        # automatic defrag trigger: pack the pool when free holes below
        # its high-water mark exceed this many pages (None disables)
        self._defrag_min_pages: Optional[int] = None
        if defrag_threshold is not None and decode_pages is not None:
            if not 0 < float(defrag_threshold) <= 1:
                raise MXNetError(
                    f"defrag_threshold must be in (0, 1] or None, got "
                    f"{defrag_threshold}")
            self._defrag_min_pages = max(
                2, int(float(defrag_threshold) * (int(decode_pages) - 1)))
        self._drain = True
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self._watcher = None        # reload.ReloadWatcher, when enabled
        # pre-dispatch hook, set by serving.Router on managed replicas:
        # runs INSIDE run() (the retried dispatch body) so an injected
        # replica fault / latency lands exactly where a real replica
        # failure would — in this scheduler thread, per batch
        self._pre_dispatch = None
        # scheduler-loop liveness beacon: touched once per loop
        # iteration (so between two touches at most ONE dispatch runs).
        # A Router reads it to tell a *hung* dispatch from a scheduler
        # patiently filling a batch toward its deadline close.
        self.hb = Heartbeat()
        self.loaded_step: Optional[int] = None
        # signatures actually compiled/used — the reload warmup manifest
        # (union across tenants; each tenant also tracks its own)
        self._warm_sigs = set()
        # always-on light counters (telemetry covers the full story)
        self.n_requests = 0
        self.n_batches = 0
        self.n_errors = 0
        self.n_reloads = 0
        self.n_preemptions = 0
        self.n_defrags = 0

    # -- single-tenant compat: the default tenant's block/version are
    # the server's (tests, controller and chaos gates read these) ------
    @property
    def _model(self):
        return self._tenants[DEFAULT_MODEL].block

    @_model.setter
    def _model(self, block) -> None:
        self._tenants[DEFAULT_MODEL].block = block

    @property
    def model_version(self) -> int:
        """The DEFAULT tenant's monotonic model-version counter: bumps
        on every swap_model / reload; a rolling-upgrade rollback
        restores the OLD number so fleet version agreement is
        observable (Router/controller read it, never write it).
        Per-tenant versions: :meth:`model_versions`."""
        return self._tenants[DEFAULT_MODEL].model_version

    @model_version.setter
    def model_version(self, v: int) -> None:
        self._tenants[DEFAULT_MODEL].model_version = int(v)

    def model_versions(self) -> Dict[str, int]:
        """Per-tenant model versions (upgrading tenant A never touches
        tenant B's number — the per-model rolling-upgrade contract)."""
        with self._model_lock:
            return {n: t.model_version for n, t in self._tenants.items()}

    def models(self):
        """Registered tenant names (``"default"`` always present)."""
        return sorted(self._tenants)

    def _tenant(self, model) -> _Tenant:
        name = DEFAULT_MODEL if model is None else str(model)
        t = self._tenants.get(name)
        if t is None:
            raise MXNetError(
                f"{self.name}: unknown model {name!r} (registered: "
                f"{sorted(self._tenants)})")
        return t

    def register_model(self, name: str, block, slo_class: str = "standard",
                       priority: int = 0, weight: float = 1.0,
                       slo_ms: Optional[float] = None,
                       rate_limit: Optional[float] = None,
                       burst: Optional[float] = None) -> "_Tenant":
        """Register a second (third, ...) model to serve from THIS
        replica. Tenants share the scheduler, the bucket grid and — when
        decode is on — the one page pool; through the compilation
        service's signature-keyed executable table an identical-config
        tenant costs a warmup, not a second fleet.

        ``slo_class`` is a label carried into telemetry/trace spans;
        ``priority`` orders preemption (higher preempts lower when the
        page pool is full); ``weight`` sets this tenant's weighted-fair
        share of batch-close picks and decode slots; ``rate_limit``
        (requests/second, with ``burst``) arms a per-tenant admission
        token bucket — an empty bucket sheds synchronously with
        :class:`TenantThrottled`. ``slo_ms`` overrides the server SLO
        for this tenant's default deadline."""
        name = str(name)
        if not name:
            raise MXNetError("tenant name must be non-empty")
        if weight <= 0:
            raise MXNetError(f"weight must be > 0, got {weight}")
        if name in self._tenants:
            raise MXNetError(
                f"{self.name}: model {name!r} is already registered")
        bucket = (TokenBucket(rate_limit, burst)
                  if rate_limit is not None else None)
        t = _Tenant(name, block, str(slo_class), priority, weight,
                    slo_ms / 1e3 if slo_ms is not None else self.slo_s,
                    bucket)
        if self.is_running:
            # warm + build the decode engine BEFORE the tenant is
            # visible to submitters: its first request must not retrace
            self._warm_block(block, prime=True)
            if self._decode_pages is not None:
                t.engine = self._make_engine(block)
                t.engine_version = t.model_version
        with self._cond:
            if name in self._tenants:
                raise MXNetError(
                    f"{self.name}: model {name!r} is already registered")
            self._tenants[name] = t
            self._queues[name] = []
            self._gen_pending[name] = []
            self._cond.notify_all()
        return t

    # -- lifecycle -----------------------------------------------------
    @property
    def is_running(self) -> bool:
        return self._running or (self._thread is not None
                                 and self._thread.is_alive())

    def _make_engine(self, block):
        """Build ``block``'s decode engine over the SHARED page pool.
        The engine dtype is the KV/compute dtype, not the request I/O
        dtype: token servers run dtype="int32" but the cache must hold
        floats (bf16/f32 servers keep their precision)."""
        if not hasattr(block, "decode_engine"):
            raise MXNetError(
                f"{self.name}: decode_pages set but the model has no "
                "decode_engine() seam (paged-KV generate needs a "
                "decode-capable model)")
        eng_dt = (self.dtype
                  if np.issubdtype(np.dtype(self.dtype), np.floating)
                  else "float32")
        return block.decode_engine(self._pool, dtype=eng_dt)

    def start(self) -> "Server":
        """Warm the bucket grid and start the scheduler thread."""
        if self.is_running:
            raise MXNetError(f"{self.name}: already running")
        for t in self._tenants.values():
            self._warm_block(t.block, prime=True)
        if self._decode_pages is not None:
            self._pool = PagePool(self._decode_pages, self._page_size)
            for t in self._tenants.values():
                t.engine = self._make_engine(t.block)
                t.engine_version = t.model_version
            self._gen_table_w = self._pool.pages_for(self._max_gen_tokens)
        self._running = True
        self._thread = threading.Thread(
            target=self._scheduler_loop, name=self.name, daemon=True)
        self._thread.start()
        _live_servers.add(self)
        return self

    def stop(self, drain: bool = True, timeout: Optional[float] = None
             ) -> None:
        """Stop the server. ``drain=True`` (default) serves every queued
        request first (dispatching immediately, SLO waits skipped);
        ``drain=False`` fails pending futures with :class:`MXNetError`."""
        with self._cond:
            self._running = False
            self._drain = bool(drain)
            if not drain:
                pending = [r for q in self._queues.values() for r in q]
                for q in self._queues.values():
                    del q[:]
                for r in pending:
                    if not r.future.set_running_or_notify_cancel():
                        continue        # caller already cancelled it
                    r.future.set_exception(
                        MXNetError(f"{self.name}: server stopped before "
                                   "this request was dispatched"))
                    self._count_request(outcome="rejected",
                                        tenant=r.tenant)
                    self._end_trace_rejected(r)
            self._cond.notify_all()
        if self._watcher is not None:
            self._watcher.stop(timeout)
            self._watcher = None
        if self._thread is not None:
            self._thread.join(timeout)
            if self._thread.is_alive():
                raise MXNetError(
                    f"{self.name}: scheduler thread did not exit within "
                    f"{timeout}s")
            self._thread = None
        _live_servers.discard(self)

    def __enter__(self) -> "Server":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop(drain=not any(exc))

    # -- ingress -------------------------------------------------------
    def _throttle(self, t: _Tenant) -> None:
        """Per-tenant token-bucket admission: raises
        :class:`TenantThrottled` (synchronous, typed, scoped to ONE
        tenant) when ``t``'s bucket is empty."""
        if t.bucket is None or t.bucket.take():
            return
        t.n_shed += 1
        self._count_request(outcome="rejected", tenant=t)
        if _telemetry_state.enabled:
            telemetry.record_serving_shed("throttled", model=t.name)
        raise TenantThrottled(
            f"{self.name}: tenant {t.name!r} over its admission rate "
            f"({t.bucket.rate:g}/s, burst {t.bucket.burst:g})")

    def submit(self, sample, deadline_ms: Optional[float] = None,
               model: Optional[str] = None,
               priority: Optional[int] = None) -> Future:
        """Enqueue one sample (NO batch dimension); returns a Future that
        resolves to the model output for that sample (numpy leaves).
        Thread-safe. Raises :class:`MXNetError` immediately when the
        server is not running, the queue is full, or no shape bucket
        fits the sample — rejection is synchronous, never a hung future.

        ``model=`` selects the tenant (default: the constructor block);
        its SLO class sets the default deadline and its token bucket
        (if armed) may shed with :class:`TenantThrottled`. ``priority``
        is accepted for wire symmetry (classify requests are never
        preempted — only generate streams hold pages).
        """
        t = self._tenant(model)
        self._throttle(t)
        arr = sample.asnumpy() if hasattr(sample, "asnumpy") \
            else np.asarray(sample)
        arr = np.ascontiguousarray(arr, dtype=self.dtype)
        bucket = self.grid.bucket_shape(arr.shape)   # raises if none fits
        arr = self.grid.pad_sample(arr, bucket)
        deadline_s = (deadline_ms / 1e3 if deadline_ms is not None
                      else t.slo_s)
        req = _Request(arr, bucket, deadline_s, tenant=t)
        if _tracing_state.enabled:
            # the span must exist BEFORE the queue append: the scheduler
            # may batch-close this request before submit returns
            amb = tracing.ambient()
            if amb is not None:
                req.trace = amb[0]
                req.span = req.trace.begin(
                    "batch.wait", parent=amb[1], replica=self.name,
                    model=t.name, slo_class=t.slo_class)
            else:
                req.trace = tracing.new_trace(
                    "request", replica=self.name, model=t.name,
                    slo_class=t.slo_class)
                req.own_trace = True
                req.span = req.trace.begin(
                    "batch.wait", replica=self.name, model=t.name,
                    slo_class=t.slo_class)
        with self._cond:
            if not self._running:
                self._count_request(outcome="rejected", tenant=t)
                self._end_trace_rejected(req)
                raise MXNetError(f"{self.name}: server is not running")
            q = self._queues[t.name]
            if len(q) >= self.max_queue:
                self._count_request(outcome="rejected", tenant=t)
                self._end_trace_rejected(req)
                raise MXNetError(
                    f"{self.name}: submission queue full for model "
                    f"{t.name!r} ({self.max_queue} requests)")
            q.append(req)
            depth = sum(len(x) for x in self._queues.values())
            tenant_depth = len(q)
            self._cond.notify_all()
        if _telemetry_state.enabled:
            telemetry.set_serving_queue_depth(depth)
            telemetry.set_tenant_queue_depth(tenant_depth, t.name)
        return req.future

    def submit_generate(self, prompt, max_new_tokens: int,
                        deadline_ms: Optional[float] = None,
                        on_token=None, model: Optional[str] = None,
                        priority: Optional[int] = None) -> GenerateHandle:
        """Enqueue one autoregressive generate request: ``prompt`` is a
        1-D int32 token array, ``max_new_tokens`` the completion budget
        (greedy decode). Returns a :class:`GenerateHandle` streaming
        tokens as the continuous batcher produces them.

        Rejection is synchronous and typed, like :meth:`submit`:
        :class:`~.kvcache.CacheFull` when the request cannot EVER fit
        the cache budget, :class:`MXNetError` when no len bucket fits
        the prompt or the server is not running. A request admitted but
        later starved (deadline blown waiting for pages) fails its
        future typed — a generate never wedges on an exhausted arena.

        ``deadline_ms`` bounds the WHOLE completion (default: none —
        generates outlive the per-request SLO by design).

        ``model=`` selects the tenant; ``priority`` overrides the
        tenant's preemption rank for this stream (higher-priority
        arrivals may reclaim a lower-priority stream's pages — the
        victim resolves typed :class:`~.kvcache.Preempted` with a
        sealed clean-prefix stream).
        """
        if self._decode_pages is None:
            raise MXNetError(f"{self.name}: decode is not enabled "
                             "(construct the server with decode_pages=)")
        t = self._tenant(model)
        self._throttle(t)
        arr = prompt.asnumpy() if hasattr(prompt, "asnumpy") \
            else np.asarray(prompt)
        arr = np.ascontiguousarray(arr, dtype=np.int32).reshape(-1)
        if arr.size < 1:
            raise MXNetError(f"{self.name}: empty prompt")
        if int(max_new_tokens) < 1:
            raise MXNetError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        len_bucket = self.grid.prefill_bucket(arr.size)  # raises: no fit
        total = arr.size + int(max_new_tokens)
        if total > self._max_gen_tokens:
            t.n_shed += 1
            if _telemetry_state.enabled:
                telemetry.record_serving_shed("kvcache_full",
                                              model=t.name)
            raise CacheFull(
                f"{self.name}: prompt {arr.size} + max_new_tokens "
                f"{max_new_tokens} exceeds the {self._max_gen_tokens}-"
                "token per-request cache budget")
        handle = GenerateHandle(on_token)
        req = _GenRequest(arr, max_new_tokens, handle,
                          deadline_ms / 1e3 if deadline_ms is not None
                          else None, tenant=t,
                          priority=(t.priority if priority is None
                                    else priority),
                          seq=next(self._seq))
        req.len_bucket = len_bucket
        if _tracing_state.enabled:
            amb = tracing.ambient()
            if amb is not None:
                req.trace = amb[0]
                req.span = req.trace.begin("gen.queue", parent=amb[1],
                                           replica=self.name,
                                           model=t.name,
                                           slo_class=t.slo_class)
            else:
                req.trace = tracing.new_trace(
                    "generate", replica=self.name,
                    prompt_len=int(arr.size),
                    max_new=int(max_new_tokens), model=t.name,
                    slo_class=t.slo_class)
                req.own_trace = True
                req.span = req.trace.begin("gen.queue", replica=self.name,
                                           model=t.name,
                                           slo_class=t.slo_class)
        with self._cond:
            if not self._running:
                self._count_request(outcome="rejected", tenant=t)
                self._end_gen_rejected(req)
                raise MXNetError(f"{self.name}: server is not running")
            q = self._gen_pending[t.name]
            if len(q) >= self.max_queue:
                self._count_request(outcome="rejected", tenant=t)
                self._end_gen_rejected(req)
                raise MXNetError(
                    f"{self.name}: generate queue full for model "
                    f"{t.name!r} ({self.max_queue} requests)")
            q.append(req)
            self._cond.notify_all()
        return handle

    @staticmethod
    def _end_gen_rejected(req: "_GenRequest",
                          status: str = "rejected") -> None:
        if req.trace is None:
            return
        if req.span is not None:
            req.span.end(outcome=status)
            req.span = None
        if req.own_trace:
            req.trace.finish(status)

    # -- decode phase (continuous batching) ----------------------------
    @staticmethod
    def _wrr_pick(tenants, field: str = "credit") -> _Tenant:
        """Smooth weighted round-robin over ``tenants``: every pick adds
        each tenant's weight to its credit, takes the max, and charges
        the winner the total — long-run pick shares converge to the
        configured weights (scheduler thread only)."""
        total = 0.0
        for t in tenants:
            total += t.weight
            setattr(t, field, getattr(t, field) + t.weight)
        best = max(tenants, key=lambda t: getattr(t, field))
        setattr(best, field, getattr(best, field) - total)
        return best

    def _preempt(self, victim: "_GenRequest",
                 beneficiary: "_GenRequest") -> None:
        """Evict ``victim`` for a higher-priority arrival — AT a decode
        step boundary, so every token it streamed is a clean, sealed
        prefix (never a torn token). The handle resolves typed
        :class:`~.kvcache.Preempted`; the flight recorder names victim
        and beneficiary."""
        victim.tenant.n_preempted += 1
        self.n_preemptions += 1
        if _telemetry_state.enabled:
            telemetry.record_preemption(victim.tenant.name,
                                        beneficiary.tenant.name)
        if _tracing_state.enabled:
            tracing.record_event(
                "preempted", replica=self.name,
                victim=victim.seq, beneficiary=beneficiary.seq,
                victim_model=victim.tenant.name,
                beneficiary_model=beneficiary.tenant.name,
                victim_priority=victim.priority,
                beneficiary_priority=beneficiary.priority,
                victim_tokens=len(victim.generated))
        self._finalize_gen(victim, error=Preempted(
            f"{self.name}: stream preempted at token "
            f"{len(victim.generated)}/{victim.max_new}: pages reclaimed "
            f"for higher-priority {beneficiary.tenant.name!r} arrival "
            f"(priority {beneficiary.priority} > {victim.priority})"))

    def _admit_pages(self, g: "_GenRequest", active: list):
        """All-or-nothing page allocation for ``g``, preempting
        lower-priority active streams (lowest priority first, then the
        one with the least progress to waste) until it fits. Victims
        are removed from ``active`` in place. Raises
        :class:`~.kvcache.CacheFull` when ``g`` cannot fit even with
        every lower-priority stream evicted."""
        while True:
            try:
                return self._pool.alloc(g, g.length + g.max_new)
            except CacheFull:
                lower = [v for v in active if v.priority < g.priority]
                if not lower:
                    raise
                # evict nobody unless eviction actually admits g: a
                # too-big arrival must not waste victims' work
                need = self._pool.pages_for(g.length + g.max_new)
                avail = (self._pool.stats()["free"]
                         + sum(len(self._pool.owned(v)) for v in lower))
                if need > avail:
                    raise
                victim = min(lower,
                             key=lambda v: (v.priority, len(v.generated)))
                self._preempt(victim, beneficiary=g)
                active.remove(victim)

    def _decode_tick(self) -> bool:
        """One continuous-batching turn: admit pending generates
        (prefill), then run ONE decode step round for active requests.
        Requests join and leave the decode batch at any step boundary.
        Multi-tenant: admission interleaves per-tenant pending queues
        weighted-fair, a full pool preempts the lowest-priority active
        stream for a higher-priority arrival, and decode slots are
        assigned weighted-fair per round. Returns False when nothing
        could move (scheduler backs off)."""
        progressed = False
        now = time.perf_counter()
        with self._cond:
            active = list(self._gen_active)
            pending = {n: list(q) for n, q in self._gen_pending.items()
                       if q}
        # deferred per-tenant weight swap: a completion runs entirely on
        # ONE model version, so a hot reload reaches a tenant's decode
        # engine only while that tenant has no active completions —
        # never mid-request (and never another tenant's swap)
        for t in self._tenants.values():
            if (t.engine is not None
                    and t.engine_version != t.model_version
                    and not any(g.tenant is t for g in active)):
                t.engine.refresh_params(t.block)
                t.engine_version = t.model_version
        # -- admission: weighted-fair across tenants, all-or-nothing
        #    page allocation per request, preemption on a full pool
        admitted: list = []
        while pending and len(admitted) < self.grid.max_batch:
            t = self._wrr_pick([self._tenants[n] for n in pending])
            queue = pending[t.name]
            g = queue.pop(0)
            if not queue:
                del pending[t.name]
            if g.deadline is not None and now > g.deadline:
                self._remove_pending(g)
                self._finalize_gen(g, error=MXNetError(
                    f"{self.name}: generate deadline expired before "
                    "prefill (cache/backlog starvation)"))
                progressed = True
                continue
            try:
                g.pages = self._admit_pages(g, active)
            except CacheFull as e:
                if not active and not admitted:
                    # nothing holds pages and it STILL does not fit:
                    # waiting cannot help — shed typed, never wedge
                    t.n_shed += 1
                    if _telemetry_state.enabled:
                        telemetry.record_serving_shed("kvcache_full",
                                                      model=t.name)
                    self._remove_pending(g)
                    self._finalize_gen(g, error=e)
                    progressed = True
                    continue
                # this tenant's head is blocked until actives free
                # pages; other tenants keep admitting this tick
                pending.pop(t.name, None)
                continue
            self._remove_pending(g)
            admitted.append(g)
        if admitted:
            groups: dict = {}
            for g in admitted:
                groups.setdefault((g.tenant.name, g.len_bucket),
                                  []).append(g)
            for key in sorted(groups):
                self._prefill_batch(groups[key], key[1])
            progressed = True
        # -- decode step round (chunked to the grid, never mixing
        #    tenants in one dispatch)
        with self._cond:
            active = list(self._gen_active)
        expired = [g for g in active
                   if g.deadline is not None and now > g.deadline]
        for g in expired:
            self._finalize_gen(g, error=MXNetError(
                f"{self.name}: generate deadline expired at token "
                f"{len(g.generated)}/{g.max_new}"))
        active = [g for g in active if g not in expired]
        if active:
            self._decode_round(active)
        if self._pool is not None:
            self._maybe_defrag()
        return progressed or bool(active) or bool(expired)

    def _decode_round(self, active: list) -> None:
        """One decode step for active streams. Single-tenant: every
        stream steps, chunked to the grid (the legacy path). Multiple
        tenants resident: ``grid.max_batch`` decode slots per round are
        assigned weighted-fair across tenants with live streams, each
        tenant's picks step as its OWN batch (a dispatch runs one
        tenant's executable), and stepped streams rotate to the back of
        the active list so no stream starves within its tenant."""
        by_tenant: dict = {}
        for g in active:
            by_tenant.setdefault(g.tenant.name, []).append(g)
        if len(by_tenant) == 1:
            cap = self.grid.max_batch
            for i in range(0, len(active), cap):
                self._decode_batch(active[i:i + cap])
            return
        tenants = [self._tenants[n] for n in by_tenant]
        remaining = {t.name: len(by_tenant[t.name]) for t in tenants}
        share = {t.name: 0 for t in tenants}
        slots = min(self.grid.max_batch, len(active))
        for _ in range(slots):
            elig = [t for t in tenants if remaining[t.name] > 0]
            if not elig:
                break
            t = self._wrr_pick(elig, field="dcredit")
            share[t.name] += 1
            remaining[t.name] -= 1
        for t in tenants:
            n = share[t.name]
            if n == 0:
                continue
            streams = by_tenant[t.name]
            self._decode_batch(streams[:n])
            if n < len(streams):
                # rotate the stepped streams behind the unstepped ones
                with self._cond:
                    for g in streams[:n]:
                        try:
                            self._gen_active.remove(g)
                        except ValueError:
                            continue    # finalized during the step
                        self._gen_active.append(g)

    def _maybe_defrag(self) -> None:
        """Automatic defrag, checked between decode steps: when the
        free holes below the pool's high-water mark exceed the
        configured threshold, pack live pages down, replay the
        permutation onto EVERY tenant's arenas, and refresh every
        active stream's page snapshot (``defrag`` renumbers the pool in
        place — a ``g.pages`` list taken at admission is stale the
        moment the pool packs)."""
        if self._defrag_min_pages is None:
            return
        n_live, span = self._pool.frag_info()
        if n_live == 0 or span - n_live < self._defrag_min_pages:
            return
        engines = [t.engine for t in self._tenants.values()
                   if t.engine is not None]
        if not engines or not all(hasattr(e, "apply_defrag")
                                  for e in engines):
            return      # an engine cannot replay moves: never corrupt
        moves = self._pool.defrag()
        if not moves:
            return
        for e in engines:
            e.apply_defrag(moves)
        with self._cond:
            for g in self._gen_active:
                g.pages = self._pool.owned(g)
        self.n_defrags += 1
        if _telemetry_state.enabled:
            telemetry.record_kvcache_defrag(len(moves))
        if _tracing_state.enabled:
            tracing.record_event("kvcache.defrag", replica=self.name,
                                 moves=len(moves), live_pages=n_live)

    def _remove_pending(self, g) -> None:
        with self._cond:
            q = self._gen_pending.get(g.tenant.name)
            if q is not None:
                try:
                    q.remove(g)
                except ValueError:
                    pass

    def _prefill_batch(self, group, len_bucket: int) -> None:
        """Prefill one len-bucket group: write the prompts' K/V into
        their pages and emit each request's FIRST token (the
        time-to-first-token dispatch)."""
        tenant = group[0].tenant
        engine = tenant.engine
        cap = self.grid.batch_bucket(len(group))
        w = self._gen_table_w
        tokens = np.zeros((cap, len_bucket), dtype=np.int32)
        lengths = np.zeros((cap,), dtype=np.int32)
        table = np.zeros((cap, w), dtype=np.int32)
        for i, g in enumerate(group):
            tokens[i, :g.prompt.size] = g.prompt
            lengths[i] = g.prompt.size
            table[i, :len(g.pages)] = g.pages
            g.model_version = tenant.engine_version
            if g.span is not None:          # gen.queue ends here
                g.span.end(outcome="ok")
            g.span = (g.trace.begin("prefill", replica=self.name,
                                    len_bucket=len_bucket,
                                    model=tenant.name,
                                    slo_class=tenant.slo_class)
                      if g.trace is not None else None)
        sig = (cap, len_bucket)

        def run():
            hook = self._pre_dispatch
            if hook is not None:
                hook(sig)
            if _fault_state.enabled:
                fault.check("serving.dispatch",
                            f"{self.name} prefill={sig}")
            return engine.prefill(tokens, lengths, table)

        try:
            logits = fault.retry_call("serving.dispatch", run,
                                      detail=self.name)
        except Exception as e:  # noqa: BLE001 - forwarded to handles
            self.n_errors += 1
            for g in group:
                self._finalize_gen(g, error=e)
            return
        self.n_batches += 1
        if _telemetry_state.enabled:
            telemetry.record_serving_batch(len(group), cap, "prefill")
        with self._cond:
            self._gen_active.extend(group)
        t_now = time.perf_counter()
        for i, g in enumerate(group):
            if g.span is not None:
                g.span.end(outcome="ok")
                g.span = None
            self._emit_token(g, int(np.argmax(logits[i])), t_now)

    def _decode_batch(self, chunk) -> None:
        """ONE decode step for up to max_batch active requests of ONE
        tenant — the (batch, 1) executable, whatever depth each request
        is at."""
        tenant = chunk[0].tenant
        engine = tenant.engine
        cap = self.grid.batch_bucket(len(chunk))
        w = self._gen_table_w
        tokens = np.zeros((cap,), dtype=np.int32)
        lengths = np.zeros((cap,), dtype=np.int32)
        table = np.zeros((cap, w), dtype=np.int32)
        spans = []
        for i, g in enumerate(chunk):
            tokens[i] = g.generated[-1]
            lengths[i] = g.length
            table[i, :len(g.pages)] = g.pages
            spans.append(g.trace.begin("decode.step", replica=self.name,
                                       token=len(g.generated),
                                       model=tenant.name)
                         if g.trace is not None else None)
        sig = (cap, 1)

        def run():
            hook = self._pre_dispatch
            if hook is not None:
                hook(sig)
            if _fault_state.enabled:
                fault.check("serving.dispatch", f"{self.name} decode={sig}")
            return engine.decode_step(tokens, lengths, table)

        try:
            logits = fault.retry_call("serving.dispatch", run,
                                      detail=self.name)
        except Exception as e:  # noqa: BLE001 - forwarded to handles
            self.n_errors += 1
            for g, sp in zip(chunk, spans):
                if sp is not None:
                    sp.end(outcome="error", error=type(e).__name__)
            for g in chunk:
                self._finalize_gen(g, error=e)
            return
        if _telemetry_state.enabled:
            telemetry.record_decode_step(len(chunk), model=tenant.name)
        t_now = time.perf_counter()
        for i, (g, sp) in enumerate(zip(chunk, spans)):
            if sp is not None:
                sp.end(outcome="ok")
            self._emit_token(g, int(np.argmax(logits[i])), t_now)

    def _emit_token(self, g, token: int, t_now: float) -> None:
        g.generated.append(token)
        g.length += 1
        self.n_tokens += 1
        g.tenant.n_tokens += 1
        if _telemetry_state.enabled:
            telemetry.record_token(t_now - g.t_last, model=g.tenant.name)
        g.t_last = t_now
        g.handle._push(token)
        if len(g.generated) >= g.max_new:
            self._finalize_gen(g)

    def _finalize_gen(self, g, error: Optional[Exception] = None) -> None:
        """Resolve one generate request: free its pages, leave the
        batch, settle the future (exactly once) and seal the stream."""
        if g.pages is not None:
            self._pool.free(g)
            g.pages = None
        with self._cond:
            try:
                self._gen_active.remove(g)
            except ValueError:
                pass
        fut = g.handle.future
        try:
            if error is None:
                fut.set_result(np.asarray(g.generated, dtype=np.int32))
            else:
                fut.set_exception(error)
        except Exception:   # noqa: BLE001 - already settled (racing stop)
            pass
        g.handle._seal()
        if error is not None:
            self.n_errors += 1
        self._count_request(
            outcome="ok" if error is None else "error",
            t_enqueue=g.t_submit,
            trace_id=g.trace.trace_id if g.trace is not None else None,
            tenant=g.tenant)
        if g.span is not None:
            g.span.end(outcome="ok" if error is None else "error")
            g.span = None
        if g.own_trace and g.trace is not None:
            g.trace.finish("ok" if error is None
                           else type(error).__name__)

    def _fail_generates(self, exc: Exception) -> None:
        with self._cond:
            doomed = [g for q in self._gen_pending.values() for g in q]
            doomed += self._gen_active
            for q in self._gen_pending.values():
                del q[:]
        for g in doomed:
            self._finalize_gen(g, error=exc)

    # -- scheduler -----------------------------------------------------
    def _scheduler_loop(self) -> None:
        try:
            while True:
                self.hb.touch()
                batch, reason = self._next_batch()
                if batch is None:
                    # non-drain shutdown may leave generates behind
                    self._fail_generates(MXNetError(
                        f"{self.name}: server stopped before this "
                        "generate completed"))
                    return
                if batch:
                    self._dispatch(batch, reason)
                if self._gen_pending or self._gen_active:
                    if not self._decode_tick():
                        # nothing admissible this instant (pool full,
                        # actives still hold pages): breathe, retry
                        with self._cond:
                            self._cond.wait(0.005)
        except BaseException:
            # a scheduler death must be LOUD, not a server that accepts
            # requests into a queue nobody drains: stop accepting and
            # fail everything queued
            with self._cond:
                self._running = False
                pending = [r for q in self._queues.values() for r in q]
                for q in self._queues.values():
                    del q[:]
            for r in pending:
                if r.future.set_running_or_notify_cancel():
                    r.future.set_exception(MXNetError(
                        f"{self.name}: scheduler thread crashed"))
                    self._end_trace_rejected(r, "error")
            self._fail_generates(MXNetError(
                f"{self.name}: scheduler thread crashed"))
            raise

    def _next_batch(self):
        """Block until a batch should close; returns (requests, reason),
        ``([], "decode")`` when decode work should run NOW (continuous
        batching never parks the scheduler while generates are live),
        or (None, None) on shutdown with nothing left to serve.

        Multi-tenant: every non-empty tenant queue is evaluated with
        the single-tenant close rules (full / drain / timeout /
        deadline) against ITS OWN requests, so one tenant's burst never
        advances or delays another tenant's close time; when several
        tenants are closeable at once the pick is smooth weighted
        round-robin, and a closed batch never mixes tenants."""
        with self._cond:
            while True:
                self.hb.touch()
                gen_work = (any(self._gen_pending.values())
                            or bool(self._gen_active))
                nonempty = [n for n in self._queues if self._queues[n]]
                if not nonempty:
                    if not self._running:
                        if gen_work and self._drain:
                            return [], "decode"
                        return None, None
                    if gen_work:
                        return [], "decode"
                    self._cond.wait(0.1)
                    continue
                cap = self.grid.max_batch
                now = time.perf_counter()
                full, closeable = [], []
                min_close_at = None
                for name in nonempty:
                    q = self._queues[name]
                    head = q[0]
                    key = head.shape_key
                    matching = sum(1 for r in q if r.shape_key == key)
                    if matching >= cap:
                        full.append(name)
                        continue
                    # close on the TIGHTEST deadline in this tenant's
                    # queue, not just the head's: a short-deadline
                    # request behind a lazy head (same key: it rides
                    # this batch; different key: it is served right
                    # after) must not wait out the head's SLO
                    deadline_at = min(r.deadline for r in q) \
                        - self.margin_s
                    # batch timeout: the head is the oldest enqueue
                    # (submit order is FIFO within a tenant) — cap its
                    # co-batching wait independently of the SLO
                    timeout_at = (head.t_enqueue + self.batch_timeout_s
                                  if self.batch_timeout_s is not None
                                  else None)
                    close_at = deadline_at if timeout_at is None \
                        else min(deadline_at, timeout_at)
                    if now >= close_at:
                        reason = ("timeout" if timeout_at is not None
                                  and timeout_at <= close_at + 1e-9
                                  and now < deadline_at else "deadline")
                        closeable.append((name, reason))
                    elif min_close_at is None or close_at < min_close_at:
                        min_close_at = close_at
                if full:
                    picked = self._wrr_pick(
                        [self._tenants[n] for n in full]).name
                    reason = "full"
                elif not self._running:
                    # drain: oldest head across tenants goes first
                    picked = min(
                        nonempty,
                        key=lambda n: self._queues[n][0].t_enqueue)
                    reason = "drain"
                elif closeable:
                    if len(closeable) == 1:
                        picked, reason = closeable[0]
                    else:
                        picked = self._wrr_pick(
                            [self._tenants[n] for n, _ in closeable]).name
                        reason = dict(closeable)[picked]
                else:
                    if gen_work:
                        # decode steps interleave with the batch fill:
                        # the classic batch keeps its SLO patience, the
                        # scheduler just doesn't SLEEP through it
                        return [], "decode"
                    # fill otherwise: sleep until the earliest close
                    # time or the next submit, whichever is first
                    self._cond.wait(min(min_close_at - now, 0.1))
                    continue
                q = self._queues[picked]
                key = q[0].shape_key
                taken, rest = [], []
                for r in q:
                    if len(taken) < cap and r.shape_key == key:
                        taken.append(r)
                    else:
                        rest.append(r)
                self._queues[picked] = rest
                if _telemetry_state.enabled:
                    telemetry.set_serving_queue_depth(
                        sum(len(x) for x in self._queues.values()))
                    telemetry.set_tenant_queue_depth(len(rest), picked)
                return taken, reason

    def _dispatch(self, batch, reason: str) -> None:
        """Pad, run, slice, resolve — one bucketed inference dispatch."""
        from ..ndarray import array as nd_array

        t_start = time.perf_counter()
        # a caller may have cancelled a still-queued future; drop those
        # rows now — set_result on a cancelled future would raise and
        # kill the scheduler thread
        batch = [r for r in batch
                 if r.future.set_running_or_notify_cancel()]
        if not batch:
            return
        n = len(batch)
        key = batch[0].shape_key
        tenant = batch[0].tenant
        cap = self.grid.batch_bucket(n)
        payload = np.zeros((cap,) + key, dtype=self.dtype)
        for i, r in enumerate(batch):
            payload[i] = r.sample
        model = tenant.block         # reload swaps the attribute, not us
        sig = (cap,) + key

        bsp = None
        if _tracing_state.enabled:
            traced = [(r.trace, r.span) for r in batch
                      if r.trace is not None]
            if traced:
                # the N co-batched wait spans end here (flow-linked to
                # the ONE dispatch span that serves them all)
                bsp = tracing.begin_batch(
                    traced, wait_tags={"close_reason": reason},
                    replica=self.name, sig=str(sig), reason=reason,
                    model=tenant.name)

        def run():
            hook = self._pre_dispatch
            if hook is not None:
                hook(sig)
            if _fault_state.enabled:
                fault.check("serving.dispatch", f"{self.name} batch={sig}")
            x = nd_array(payload, ctx=self.ctx)
            with autograd.pause():
                out = model(x)
            return self._materialize(out)

        # injected faults / retries inside the dispatch annotate the
        # batch span (fault.py calls tracing.note against the ambient)
        amb = (tracing.active(batch[0].trace, bsp) if bsp is not None
               else contextlib.nullcontext())
        try:
            with amb:
                leaves, tree = fault.retry_call(
                    "serving.dispatch", run, detail=self.name)
        except Exception as e:  # noqa: BLE001 - forwarded to the futures
            self.n_errors += 1
            tracing.end_batch(bsp, outcome="error",
                              error=type(e).__name__)
            for r in batch:
                r.future.set_exception(e)
                self._count_request(
                    outcome="error", t_enqueue=r.t_enqueue,
                    trace_id=r.trace.trace_id if r.trace is not None
                    else None, tenant=tenant)
                if r.own_trace:
                    r.trace.finish(type(e).__name__)
            return
        tracing.end_batch(bsp, outcome="ok")
        self.n_batches += 1
        if self.n_batches == 1:
            from .. import compiler

            # replica cold-start milestone: start() -> first served batch
            compiler.mark_event("first_response")
        if _telemetry_state.enabled:
            telemetry.record_serving_batch(n, cap, reason)
            for r in batch:
                telemetry.record_serving_queue_time(t_start - r.t_enqueue)
        with self._model_lock:      # the reload warmup copies this set
            self._warm_sigs.add(sig)
            tenant.warm_sigs.add(sig)
        from ..gluon.block import nested_unflatten_nd

        try:
            for i, r in enumerate(batch):
                # copy: a row VIEW would pin the whole padded batch
                # array for as long as the caller holds the result
                r.future.set_result(nested_unflatten_nd(
                    tree, [leaf[i].copy() for leaf in leaves]))
                self._count_request(
                    outcome="ok", t_enqueue=r.t_enqueue,
                    trace_id=r.trace.trace_id if r.trace is not None
                    else None, tenant=tenant)
                if r.own_trace:
                    r.trace.finish("ok")
        except Exception as e:  # noqa: BLE001 - e.g. non-batch-major leaf
            self.n_errors += 1
            for r in batch:
                if not r.future.done():
                    r.future.set_exception(e)
                    self._count_request(outcome="error",
                                        t_enqueue=r.t_enqueue,
                                        tenant=tenant)
                if r.own_trace:
                    r.trace.finish(type(e).__name__)

    @staticmethod
    def _materialize(out):
        """Flatten the model output and pull each leaf to host numpy once
        per batch (futures hand out row slices of these)."""
        from ..gluon.block import nested_flatten_nd

        flat, tree = nested_flatten_nd(out)
        return [leaf.asnumpy() for leaf in flat], tree

    def _count_request(self, outcome: str, t_enqueue: Optional[float] = None,
                       trace_id: Optional[str] = None,
                       tenant: Optional[_Tenant] = None) -> None:
        self.n_requests += 1
        if tenant is not None:
            tenant.n_requests += 1
        if _telemetry_state.enabled:
            lat = (time.perf_counter() - t_enqueue
                   if t_enqueue is not None else 0.0)
            telemetry.record_serving_request(
                lat, outcome, trace_id=trace_id,
                model=tenant.name if tenant is not None else None)

    @staticmethod
    def _end_trace_rejected(req: _Request, status: str = "rejected") -> None:
        """Seal a traced request that never reached a batch."""
        if req.trace is None:
            return
        if req.span is not None:
            req.span.end(outcome=status)
        if req.own_trace:
            req.trace.finish(status)

    # -- model management ----------------------------------------------
    def _warm_block(self, block, prime: bool = False) -> int:
        """AOT-compile ``block`` for every known signature: the full
        grid when it is enumerable (``prime=True`` + shape buckets), and
        always every signature this server has actually served — so a
        hot-reloaded model is warm for live traffic before the swap.

        Warm compiles route through the compilation service: a replica
        (or a reloaded model) whose program another in-process replica
        already compiled is an executable-table hit, not a second XLA
        compile — N replicas of one architecture warm for the price of
        one. When a signature manifest is being recorded, its journal is
        replayed against the block first, so signatures served by a
        PREVIOUS process warm too (the manifest may know more than the
        enumerable grid)."""
        if not self._warmup or not hasattr(block, "warmup"):
            return 0
        from .. import compiler

        man = compiler.recorder()
        if man is not None:
            try:
                compiler.warm_start(man, blocks=[block])
            except Exception:   # noqa: BLE001 - warm is best-effort
                pass
        with self._model_lock:      # the scheduler adds sigs concurrently
            sigs = set(self._warm_sigs)
        if prime and self.grid.shape_buckets is not None:
            sigs.update(self.grid.input_signatures())
        if not sigs:
            return 0
        if getattr(block, "_active", None) is False:
            block.hybridize()
        return block.warmup(sorted(sigs), dtype=self.dtype, ctx=self.ctx)

    def current_model(self, model: Optional[str] = None):
        """The block currently being served for ``model`` (default
        tenant when None; the rolling-upgrade machinery keeps it for
        rollback)."""
        return self._tenant(model).block

    def swap_model(self, block, version: Optional[int] = None,
                   model: Optional[str] = None) -> None:
        """Atomically replace ONE tenant's served model with ``block``,
        warming it for every signature in live use first — requests
        dispatched during the warmup keep hitting the old graph, and
        other tenants' blocks/versions are untouched (the per-model
        upgrade contract). ``version`` overrides the monotonic bump (a
        rollback restores the old number)."""
        t = self._tenant(model)
        self._warm_block(block, prime=True)
        with self._model_lock:
            t.block = block
            t.model_version = (t.model_version + 1
                               if version is None else int(version))
        self.n_reloads += 1

    def reload(self, manager, model_factory, step: Optional[int] = None
               ) -> int:
        """Zero-downtime reload from a :class:`CheckpointManager` bundle:
        build a fresh block via ``model_factory(bundle_path)``, warm it,
        swap it in. The old graph serves until the swap. Fault site
        ``serving.reload``; transient failures retry, persistent ones
        raise (the old model keeps serving). Returns the loaded step."""
        t0 = time.perf_counter()
        if step is None:
            step = manager.latest_step()
            if step is None:
                raise MXNetError(
                    f"{self.name}: no checksum-valid checkpoint under "
                    f"{manager.directory!r} to reload from")
        path = manager.path(step)

        def build():
            if _fault_state.enabled:
                fault.check("serving.reload", path)
            return model_factory(path)

        try:
            block = fault.retry_call("serving.reload", build, detail=path)
            self.swap_model(block)
        except Exception:
            if _telemetry_state.enabled:
                telemetry.record_serving_reload(0.0, outcome="error")
            raise
        self.loaded_step = step
        if _telemetry_state.enabled:
            telemetry.record_serving_reload(time.perf_counter() - t0)
        return step

    def enable_hot_reload(self, manager, model_factory,
                          interval_s: float = 0.5,
                          tag: Optional[str] = None):
        """Start a watcher thread that polls ``manager`` (via
        :meth:`CheckpointManager.poll_newest`) and hot-reloads on every
        new valid bundle. See :class:`~.reload.ReloadWatcher`."""
        from .reload import ReloadWatcher

        if self._watcher is not None:
            raise MXNetError(f"{self.name}: hot reload already enabled")
        self._watcher = ReloadWatcher(
            self, manager, model_factory, interval_s=interval_s,
            tag=tag or self.name)
        self._watcher.start()
        return self._watcher

    def stats(self) -> dict:
        """Light always-on counters (telemetry has the full story)."""
        with self._cond:
            depth = sum(len(q) for q in self._queues.values())
            gen_pending = sum(len(q)
                              for q in self._gen_pending.values())
            gen_active = len(self._gen_active)
            models = {
                n: {"slo_class": t.slo_class, "priority": t.priority,
                    "weight": t.weight, "version": t.model_version,
                    "requests": t.n_requests, "shed": t.n_shed,
                    "preempted": t.n_preempted, "tokens": t.n_tokens,
                    "queue_depth": len(self._queues[n]),
                    "generates_pending": len(self._gen_pending[n])}
                for n, t in self._tenants.items()}
        out = {"requests": self.n_requests, "batches": self.n_batches,
               "errors": self.n_errors, "reloads": self.n_reloads,
               "queue_depth": depth, "loaded_step": self.loaded_step,
               "model_version": self.model_version,
               "running": self.is_running, "models": models,
               "preemptions": self.n_preemptions}
        if self._decode_pages is not None:
            out.update(tokens=self.n_tokens, generates_pending=gen_pending,
                       generates_active=gen_active,
                       defrags=self.n_defrags,
                       kvcache=self._pool.stats() if self._pool else None)
        return out
